"""``gol trace-report``: summarize a trace file on the terminal.

Accepts both artifacts the obs subsystem writes:

- Chrome trace JSON (``trace.export_chrome`` / ``--trace DIR`` exports):
  an object with ``traceEvents`` of ``ph:"X"`` complete events;
- flight-recorder JSONL (``obs/recorder.py`` dumps): header / span /
  registry records, one JSON object per line.

Three views, built from the same normalized span list:

- **per-phase stats** — count, total, p50, p95 per span name (the
  percentile math is the shared ``obs.registry.quantile``, the same rule
  the serving histograms export);
- **span tree** — the most recent top-level span per thread with its
  nested children, indented by depth;
- **gap analysis** — per thread, untraced wall time between consecutive
  top-level spans (where a run spends time *nobody* instrumented — the
  question phase printfs can never answer).

A stitched FLEET trace (``gol fleet-trace``, multiple pids) additionally
renders **per-process** phase tables (one per pid lane, labeled from the
stitcher's process table) and the **cross-process gap**: per propagated
flow id, the time between the router's forward point (``ph:"s"`` in the
router pid) and the owning worker's claim point (``ph:"t"`` in another
pid) — the fleet-queueing hop no single process can measure.
"""

from __future__ import annotations

import json

from gol_tpu.obs import registry


def load_spans(path: str) -> tuple[list[dict], dict]:
    """Normalize a trace file into (spans, metadata).

    Each span: ``{"name", "start_us", "dur_us", "tid", "depth", "attrs"}``.
    Format is sniffed from content, not the filename: a JSON object with
    ``traceEvents`` is a Chrome trace; otherwise the file is read as
    flight-recorder JSONL (torn lines dropped).
    """
    with open(path, "rb") as f:
        raw = f.read()
    try:
        doc = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        doc = None
    if isinstance(doc, dict) and "traceEvents" in doc:
        spans = [
            {
                "name": e.get("name", "?"),
                "start_us": float(e.get("ts", 0.0)),
                "dur_us": float(e.get("dur", 0.0)),
                "tid": e.get("tid", 0),
                "pid": e.get("pid", 0),
                "depth": (e.get("args") or {}).get("depth", 0),
                "attrs": {k: v for k, v in (e.get("args") or {}).items()
                          if k != "depth"},
            }
            for e in doc["traceEvents"]
            if e.get("ph") == "X"
        ]
        meta = dict(doc.get("otherData") or {})
        flow_events = [
            {
                "id": str(e.get("id", "0")),
                "ph": e["ph"],
                "ts_us": float(e.get("ts", 0.0)),
                "pid": e.get("pid", 0),
                "attrs": dict(e.get("args") or {}),
            }
            for e in doc["traceEvents"]
            if e.get("ph") in ("s", "t", "f")
        ]
        flows = _flow_counts(e["ph"] for e in flow_events)
        if flows:
            meta["flows"] = flows
        if flow_events:
            # The stitched-fleet lane: points keep ts/pid so the
            # cross-process gap analysis below can measure the hop.
            meta["flow_points"] = flow_events
        return spans, meta
    # Flight-recorder JSONL.
    spans, meta, flow_phases = [], {}, []
    for line in raw.split(b"\n"):
        if not line:
            continue
        try:
            rec = json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            continue
        kind = rec.get("record")
        if kind == "header":
            flows = meta.get("flows")
            meta = {k: v for k, v in rec.items() if k != "record"}
            if flows:
                meta["flows"] = flows
        elif kind == "span":
            phase = (rec.get("attrs") or {}).get("flow_phase")
            if phase in ("s", "t", "f"):
                # Flow points ride the span ring but are arrows, not
                # durations — count them instead of polluting the tables.
                flow_phases.append(phase)
                continue
            spans.append({
                "name": rec.get("name", "?"),
                "start_us": float(rec.get("start_s", 0.0)) * 1e6,
                "dur_us": float(rec.get("duration_s", 0.0)) * 1e6,
                "tid": rec.get("tid", 0),
                "pid": 0,  # a flight dump is one process by construction
                "depth": rec.get("depth", 0),
                "attrs": rec.get("attrs") or {},
            })
        elif kind == "registry":
            meta["registry"] = {k: v for k, v in rec.items() if k != "record"}
        elif kind == "state":
            # Live subsystem snapshots (e.g. the async checkpoint writer's
            # queue): folded into the header block so "what was in flight
            # when it died" renders next to the crash reason.
            meta.setdefault("state", {})[rec.get("name", "?")] = {
                k: v for k, v in rec.items() if k not in ("record", "name")
            }
    flows = _flow_counts(flow_phases)
    if flows:
        meta["flows"] = flows
    spans.sort(key=lambda s: s["start_us"])
    return spans, meta


def _flow_counts(phases) -> dict:
    counts = {"s": 0, "t": 0, "f": 0}
    for p in phases:
        counts[p] += 1
    return {k: v for k, v in counts.items() if v}


def _fmt_ms(us: float) -> str:
    return f"{us / 1000:.3f}"


def phase_table(spans: list[dict]) -> list[str]:
    by_name: dict[str, list[float]] = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s["dur_us"])
    lines = ["phase                        count   total_ms      p50_ms      p95_ms",
             "-" * 68]
    for name in sorted(by_name, key=lambda n: -sum(by_name[n])):
        durs = by_name[name]
        lines.append(
            f"{name:<28} {len(durs):>5} {_fmt_ms(sum(durs)):>10} "
            f"{_fmt_ms(registry.quantile(durs, 0.5)):>11} "
            f"{_fmt_ms(registry.quantile(durs, 0.95)):>11}"
        )
    return lines


def span_tree(spans: list[dict], max_roots: int = 5) -> list[str]:
    """The newest ``max_roots`` depth-0 spans per thread, with children
    indented under them (a child = a deeper span starting within the
    parent's [start, start+dur) window on the same thread)."""
    lines = []
    by_tid: dict[int, list[dict]] = {}
    for s in spans:
        by_tid.setdefault(s["tid"], []).append(s)
    for tid, tspans in sorted(by_tid.items(), key=lambda kv: str(kv[0])):
        tspans.sort(key=lambda s: s["start_us"])
        roots = [s for s in tspans if s["depth"] == 0][-max_roots:]
        if not roots:
            continue
        lines.append(f"thread {tid}:")
        for root in roots:
            end = root["start_us"] + root["dur_us"]
            members = [
                s for s in tspans
                if root["start_us"] <= s["start_us"] < max(end, root["start_us"] + 1)
                and s["depth"] >= 0 and (s is root or s["depth"] > 0)
            ]
            for s in members:
                attrs = ""
                if s["attrs"]:
                    attrs = "  " + ", ".join(
                        f"{k}={v}" for k, v in sorted(s["attrs"].items())
                    )
                lines.append(
                    f"  {'  ' * s['depth']}{s['name']} "
                    f"{_fmt_ms(s['dur_us'])} ms{attrs}"
                )
    return lines


def gap_analysis(spans: list[dict]) -> list[str]:
    """Per thread: total traced vs untraced time between top-level spans."""
    lines = []
    by_tid: dict[int, list[dict]] = {}
    for s in spans:
        if s["depth"] == 0:
            by_tid.setdefault(s["tid"], []).append(s)
    for tid, roots in sorted(by_tid.items(), key=lambda kv: str(kv[0])):
        roots.sort(key=lambda s: s["start_us"])
        traced = sum(s["dur_us"] for s in roots)
        gaps = []
        for prev, cur in zip(roots, roots[1:]):
            gap = cur["start_us"] - (prev["start_us"] + prev["dur_us"])
            if gap > 0:
                gaps.append(gap)
        span_wall = (
            roots[-1]["start_us"] + roots[-1]["dur_us"] - roots[0]["start_us"]
        )
        biggest = max(gaps) if gaps else 0.0
        lines.append(
            f"thread {tid}: {len(roots)} top-level span(s), traced "
            f"{_fmt_ms(traced)} ms of {_fmt_ms(span_wall)} ms wall; "
            f"untraced gaps {_fmt_ms(sum(gaps))} ms "
            f"(largest {_fmt_ms(biggest)} ms)"
        )
    return lines


def cross_process_gaps(flow_points: list[dict]) -> dict[str, list[float]]:
    """Per flow id, the router-forward -> worker-claim hop in microseconds.

    A gap exists when a flow id has an ``s`` point in one pid and a ``t``
    point in a DIFFERENT pid (the propagated id's contract: the router
    stamps ``s`` at forward time, the adopting worker steps ``t`` at
    accept/claim). The claim point — ``attrs.state == "claimed"`` — is
    preferred; the first foreign ``t`` (admission) is the fallback, so
    partially-adopted traces still measure the hop. Returns
    ``{"fleet_queueing": [gap_us, ...]}`` (empty when the trace is
    single-process)."""
    by_id: dict[str, list[dict]] = {}
    for p in flow_points:
        by_id.setdefault(p["id"], []).append(p)
    gaps: list[float] = []
    for points in by_id.values():
        starts = [p for p in points if p["ph"] == "s"]
        if not starts:
            continue
        start = min(starts, key=lambda p: p["ts_us"])
        foreign = [p for p in points
                   if p["ph"] == "t" and p["pid"] != start["pid"]]
        if not foreign:
            continue
        claimed = [p for p in foreign
                   if p["attrs"].get("state") == "claimed"]
        target = min(claimed or foreign, key=lambda p: p["ts_us"])
        gaps.append(target["ts_us"] - start["ts_us"])
    return {"fleet_queueing": gaps} if gaps else {}


def render(path: str) -> str:
    spans, meta = load_spans(path)
    lines = [f"# trace report: {path}", ""]
    if meta:
        keys = ("reason", "pid", "anchor_unix_ns", "dropped_spans")
        shown = {k: meta[k] for k in keys if k in meta}
        if shown:
            lines.append("meta: " + ", ".join(f"{k}={v}" for k, v in shown.items()))
            lines.append("")
        for name, state in sorted((meta.get("state") or {}).items()):
            lines.append(
                f"state[{name}]: "
                + ", ".join(f"{k}={v}" for k, v in sorted(state.items()))
            )
            lines.append("")
        flows = meta.get("flows")
        if flows:
            # Job-lifecycle flow arrows (obs.trace.flow): how many jobs the
            # trace saw start / step / finish.
            lines.append(
                "job flows: "
                f"{flows.get('s', 0)} started, {flows.get('t', 0)} step(s), "
                f"{flows.get('f', 0)} finished"
            )
            lines.append("")
    if not spans:
        lines.append("(no spans recorded)")
        return "\n".join(lines) + "\n"
    lines.append(f"{len(spans)} span(s)")
    lines.append("")
    pids = sorted({s["pid"] for s in spans})
    if len(pids) > 1:
        # A stitched fleet trace: one phase table per process lane, the
        # lane labeled from the stitcher's process table when present.
        labels = {}
        for name, info in (meta.get("processes") or {}).items():
            labels[info.get("pid")] = name
        for pid in pids:
            label = labels.get(pid)
            lines.append(f"## per-phase — process {pid}"
                         + (f" ({label})" if label else ""))
            lines.extend(phase_table([s for s in spans if s["pid"] == pid]))
            lines.append("")
    else:
        lines.append("## per-phase")
        lines.extend(phase_table(spans))
        lines.append("")
    gaps = cross_process_gaps(meta.get("flow_points") or [])
    for name, values in sorted(gaps.items()):
        lines.append(f"## cross-process gaps — {name} "
                     "(router forward -> worker claim)")
        lines.append(
            f"  {len(values)} hop(s): p50 "
            f"{_fmt_ms(registry.quantile(values, 0.5))} ms, p95 "
            f"{_fmt_ms(registry.quantile(values, 0.95))} ms, max "
            f"{_fmt_ms(max(values))} ms"
        )
        lines.append("")
    lines.append("## span tree (newest top-level spans)")
    lines.extend(span_tree(spans))
    lines.append("")
    lines.append("## gaps (untraced time between top-level spans)")
    lines.extend(gap_analysis(spans))
    reg = meta.get("registry") or {}
    counters = reg.get("counters")
    if counters:
        lines.append("")
        lines.append("## registry counters at dump time")
        for name in sorted(counters):
            lines.append(f"  {name} = {counters[name]}")
    gauges = reg.get("gauges")
    if gauges:
        lines.append("")
        lines.append("## registry gauges at dump time")
        for name in sorted(gauges):
            lines.append(f"  {name} = {gauges[name]}")
    hists = reg.get("histograms")
    if hists:
        # The serving latency/gap distributions (dispatch_gap_seconds,
        # queue/run latency): the same nearest-rank summaries /metrics
        # exports, rendered so a flight dump answers "was the device
        # idling between drains" on its own.
        lines.append("")
        lines.append("## registry histograms at dump time")
        for name in sorted(hists):
            s = hists[name] or {}
            stats = ", ".join(
                f"{k}={s[k]}" for k in ("count", "sum", "p50", "p95", "p99")
                if k in s
            )
            lines.append(f"  {name}: {stats}")
    return "\n".join(lines) + "\n"
