"""Trace-context propagation: one trace id across the router/worker hop.

PR 4 gave each process a span ring and PR 7 tied a job's lifecycle to its
batch spans with flow events — but every id was process-local, so a fleet
trace stitched from N processes (obs/fleettrace.py) showed N disconnected
lanes. This module defines the ONE wire contract that joins them:

- the router stamps an ``X-Gol-Trace`` header onto every forwarded
  ``POST /jobs`` **while tracing is enabled** (``gol fleet --trace``) and
  records a flow *start* under the carried trace id at forward time;
- a worker whose tracing is enabled adopts the header's trace id as the
  job's flow id (``Job.trace``, process-local like the perf_counter
  stamps), so its claim/finish flow points and batch spans chain onto the
  router's — one Perfetto arrow from the router's placement decision into
  the worker slice that served the job.

Degradation is the contract's other half, pinned by tests:

- tracing disabled (the default): the router adds NO header and allocates
  nothing; the worker never looks past a dict ``.get`` — byte-identical
  requests and responses to the pre-propagation tree;
- new router -> old worker: the unknown header is ignored by stdlib HTTP
  servers; the forwarded body is the client's bytes verbatim either way;
- old client -> new worker: no header, ``extract`` returns None, the job
  flows under its own id exactly as before;
- a malformed header value (anything outside the token grammar below) is
  DROPPED, never an error: propagation is telemetry, and telemetry must
  not be able to 400 a job.

The header value is ``<trace>/<parent>``: ``trace`` the flow id shared by
every process on the job's path, ``parent`` the sender's span label (the
router stamps ``router-<pid>``) — carried as a span attribute on the
adopting side, never parsed further.
"""

from __future__ import annotations

import os
import re
import uuid

TRACE_HEADER = "X-Gol-Trace"

# -- deadline propagation (PR 14) -------------------------------------------
#
# ``X-Gol-Deadline`` carries a job's REMAINING latency budget in seconds —
# stamped by `gol submit --timeout`, decremented by the router for its own
# elapsed time before each forward hop, enforced at router forward, worker
# admission, and batch dispatch (serve/scheduler). It rides this module
# because it is the same kind of contract as X-Gol-Trace: a hop-by-hop
# header whose ABSENCE must be byte-identical to the pre-header tree
# (old client -> new server: no header, no budget, today's behavior;
# new client -> old server: the unknown header is ignored by stdlib HTTP
# servers) and whose malformed values DROP silently — a deadline is an
# optimization contract, and a corrupt header must never 400 a job.
# The value is a plain decimal seconds-remaining (not an absolute time):
# wall clocks across a fleet disagree, but "you have 1.25s left" survives
# any hop unskewed modulo network transit, which only ever shortens it.

DEADLINE_HEADER = "X-Gol-Deadline"


def encode_deadline(seconds: float) -> str:
    """The header value for a remaining budget of ``seconds``."""
    return f"{float(seconds):.6f}"


def decode_deadline(value) -> float | None:
    """Header value -> remaining seconds, or None for anything absent or
    malformed (the degrade-to-nothing rule; negative and zero values are
    VALID — they mean "already expired")."""
    if not value or not isinstance(value, str):
        return None
    try:
        budget = float(value.strip())
    except ValueError:
        return None
    if budget != budget or budget in (float("inf"), float("-inf")):
        return None
    return budget

# Token grammar for each half of the header value. Deliberately tight:
# these strings end up as Perfetto flow ids and span attributes, and a
# hostile/corrupt value must degrade to "no context", not ride into
# exports.
_TOKEN = re.compile(r"[A-Za-z0-9._-]{1,64}")


def new_trace_id() -> str:
    """A fresh fleet-wide trace id (one per routed submit)."""
    return uuid.uuid4().hex[:16]


def encode(trace_id: str, parent: str | None = None) -> str:
    """The header value carrying ``trace_id`` (and the sender label)."""
    if not _TOKEN.fullmatch(trace_id):
        raise ValueError(f"trace id {trace_id!r} is not a valid token")
    if parent is None:
        return trace_id
    if not _TOKEN.fullmatch(parent):
        raise ValueError(f"parent {parent!r} is not a valid token")
    return f"{trace_id}/{parent}"


def decode(value) -> tuple[str, str | None] | None:
    """Parse a header value -> (trace_id, parent), or None for anything
    absent or malformed (the degrade-to-nothing rule)."""
    if not value or not isinstance(value, str):
        return None
    trace_id, sep, parent = value.partition("/")
    if not _TOKEN.fullmatch(trace_id):
        return None
    if not sep:
        return trace_id, None
    if not _TOKEN.fullmatch(parent):
        return None
    return trace_id, parent


def sender_label() -> str:
    """The ``parent`` token a forwarding process stamps (the router)."""
    return f"router-{os.getpid()}"


__all__ = ["DEADLINE_HEADER", "TRACE_HEADER", "decode", "decode_deadline",
           "encode", "encode_deadline", "new_trace_id", "sender_label"]
