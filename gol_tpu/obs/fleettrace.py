"""``gol fleet-trace``: one stitched timeline for the whole fleet.

Each process in a fleet (the router, every worker) keeps its own span ring
over its own ``perf_counter`` — a clock that is monotonic but has an
arbitrary, per-process zero. This module collects every live ring
(``GET /debug/trace``, the same payload PR 4 gave single servers) and
stitches ONE Chrome/Perfetto trace out of them:

- **clock normalization**: each payload's metadata carries the process's
  anchor pair (``anchor_perf_s`` from ``perf_counter``, ``anchor_unix_ns``
  from the one sanctioned wall read at ``trace.enable()``). Every event's
  timestamp becomes *wall microseconds since the earliest anchor in the
  fleet*:

      ts_us = (start_s - anchor_perf_s) * 1e6
              + (anchor_unix_ns - min_anchor_unix_ns) / 1e3

  which applies each process's router-relative clock skew as measured by
  its own anchor (test-pinned on injected skew). Wall time is metadata
  here exactly as in ``trace.py``: it aligns axes across processes and
  never enters any within-process duration.
- **process lanes**: every process keeps its pid (plus a
  ``process_name`` metadata event with its fleet id — ``router``, ``w0``,
  ...), so Perfetto renders one lane group per process. In-process test
  fleets where several "processes" share one pid get synthetic pids (the
  real pid stays in the process table) — lanes must not merge.
- **cross-process flows**: the router's flow *start* and the owning
  worker's *step/finish* points carry the same propagated trace id
  (obs/propagate.py), so Perfetto draws the router→worker arrow per job —
  the fleet-queueing hop ``gol trace-report`` also measures.

Collection degrades per process: an unreachable worker (mid-respawn,
crashed) is skipped with a note in the output's ``otherData`` — a fleet
trace of the survivors beats no trace during exactly the incident that
killed a worker.
"""

from __future__ import annotations

import json
import urllib.error


def collect(base_url: str, http=None, timeout: float = 10.0) -> list[dict]:
    """Fetch ``/debug/trace`` from the router at ``base_url`` and from
    every worker its ``GET /fleet`` lists. Against a plain ``gol serve``
    (no /fleet endpoint) the result is that one process alone.

    Returns ``[{"name", "url", "payload"|None, "error"?}, ...]`` — one
    entry per process, unreachable ones with ``payload=None``.
    """
    if http is None:
        from gol_tpu.fleet.client import http_json as http
    base = base_url.rstrip("/")
    targets = [("router", base)]
    try:
        status, membership = http("GET", base + "/fleet", timeout=timeout)
        if status == 200 and isinstance(membership, dict):
            for w in membership.get("workers", []):
                if w.get("url"):
                    targets.append((str(w.get("id", w["url"])),
                                    str(w["url"]).rstrip("/")))
    except (urllib.error.URLError, ConnectionError, OSError, ValueError):
        pass  # a single server: no membership, trace it alone

    import threading

    out = [{"name": name, "url": url, "payload": None}
           for name, url in targets]
    lock = threading.Lock()

    def fetch(entry: dict) -> None:
        try:
            status, payload = http("GET", entry["url"] + "/debug/trace",
                                   timeout=timeout)
            with lock:
                if status == 200 and isinstance(payload, dict):
                    entry["payload"] = payload
                else:
                    entry["error"] = f"HTTP {status}"
        except (urllib.error.URLError, ConnectionError, OSError,
                ValueError) as err:
            with lock:
                entry["error"] = f"{type(err).__name__}: {err}"

    threads = [threading.Thread(target=fetch, args=(e,), daemon=True)
               for e in out]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout + 5)
    return out


def stitch(processes: list[dict]) -> dict:
    """Merge per-process ``/debug/trace`` payloads into one Chrome trace.

    ``processes``: the ``collect`` shape — entries whose ``payload`` is
    None (unreachable) or whose tracer never enabled (anchor 0: nothing to
    align) are recorded in ``otherData.skipped`` and contribute no events.
    """
    live = []
    skipped = []
    for entry in processes:
        payload = entry.get("payload")
        meta = (payload or {}).get("meta") or {}
        if payload is None:
            skipped.append({"name": entry.get("name", "?"),
                            "reason": entry.get("error", "unreachable")})
        elif not meta.get("anchor_unix_ns"):
            skipped.append({"name": entry.get("name", "?"),
                            "reason": "tracing disabled (no anchor)"})
        else:
            live.append((entry.get("name", "?"), payload, meta))
    if not live:
        return {
            "traceEvents": [],
            "displayTimeUnit": "ms",
            "otherData": {"processes": {}, "skipped": skipped},
        }

    # The fleet's wall origin: the earliest anchor. Every process's events
    # shift by its OWN (anchor_unix_ns - origin) — the per-process skew
    # adjustment (two processes enabled at different wall moments land on
    # one axis; an injected skew shifts exactly its process, test-pinned).
    origin_ns = min(meta["anchor_unix_ns"] for _, _, meta in live)

    events: list[dict] = []
    process_table: dict[str, dict] = {}
    used_pids: set[int] = set()
    for index, (name, payload, meta) in enumerate(live):
        real_pid = int(meta.get("pid") or 0)
        pid = real_pid
        # In-process fleets (tests) report one pid for every lane; a pid
        # collision would weld lanes, so collide into a synthetic pid and
        # keep the real one in the process table. The probe INCREMENTS
        # until free: a recomputed hash of the colliding pid can be its
        # own fixed point (a real pid inside the synthetic block), and a
        # non-advancing loop would hang the stitch.
        if pid == 0 or pid in used_pids:
            pid = 1_000_000 + index * 1_000 + (real_pid % 1_000)
            while pid in used_pids:
                pid += 1
        used_pids.add(pid)
        anchor_perf = float(meta.get("anchor_perf_s") or 0.0)
        offset_us = (meta["anchor_unix_ns"] - origin_ns) / 1e3
        process_table[name] = {
            "pid": pid,
            "real_pid": real_pid,
            "anchor_unix_ns": meta["anchor_unix_ns"],
            "skew_us_vs_origin": offset_us,
            "dropped_spans": meta.get("dropped_spans", 0),
        }
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{name} (pid {real_pid})"},
        })
        for span in payload.get("spans") or []:
            attrs = dict(span.get("attrs") or {})
            phase = attrs.pop("flow_phase", None)
            ts = (float(span.get("start_s", 0.0)) - anchor_perf) * 1e6 \
                + offset_us
            if phase in ("s", "t", "f"):
                ev = {
                    "name": span.get("name", "?"),
                    "cat": "flow",
                    "ph": phase,
                    "id": attrs.pop("flow_id", "0"),
                    "ts": ts,
                    "pid": pid,
                    "tid": span.get("tid", 0),
                }
                if phase == "f":
                    ev["bp"] = "e"
                if attrs:
                    ev["args"] = attrs
                events.append(ev)
                continue
            events.append({
                "name": span.get("name", "?"),
                "ph": "X",
                "ts": ts,
                "dur": float(span.get("duration_s", 0.0)) * 1e6,
                "pid": pid,
                "tid": span.get("tid", 0),
                "args": dict(attrs, depth=span.get("depth", 0)),
            })
    # Metadata events first, then time order — the chrome_events rule.
    events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "origin_unix_ns": origin_ns,
            "processes": process_table,
            "skipped": skipped,
        },
    }


def export(base_url: str, path: str, http=None) -> dict:
    """collect + stitch + write: the ``gol fleet-trace`` body. Returns the
    stitched document (the CLI prints its summary)."""
    doc = stitch(collect(base_url, http=http))
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.write("\n")
    return doc


__all__ = ["collect", "export", "stitch"]
