"""Per-phase device timing and guarded ``jax.profiler`` capture.

Two jobs, previously reimplemented separately by ``--profile DIR`` in the
CLI and by tools/measure.py's xprof path:

- ``device_phase(name)`` — a span (obs/trace.py) whose end is an explicit
  device **fence**: JAX dispatch is async, so a bare ``perf_counter`` pair
  around a dispatched call times the *enqueue*, not the work. The phase's
  exit blocks on the values handed to ``fence()`` (``block_until_ready``
  where available, scalar readback otherwise — the only dependable barrier
  over remote-attach tunnels, tools/measure.py's hard-won rule) before the
  span closes, so the recorded duration is the device time the reference's
  phase printfs *meant* to measure.

- ``capture(dir)`` — ``jax.profiler`` trace capture with guarded start AND
  stop. The raw ``jax.profiler.trace`` context the CLI used let two
  failure shapes leak to users: a start that throws (no device work yet,
  profiler backend unavailable) killed an otherwise-fine run, and a body
  that crashed mid-capture left a torn trace directory that looks like
  evidence but loads as garbage. Here, a failing start logs and the run
  proceeds unprofiled; a crashing body stops the profiler and sweeps the
  partial capture away before re-raising. ``--profile DIR`` and the tuner
  both ride this one implementation now.
"""

from __future__ import annotations

import contextlib
import logging
import os
import shutil

from gol_tpu.obs import trace

logger = logging.getLogger(__name__)


def fence(*values) -> None:
    """Block until every ``value``'s device computation is done.

    Accepts jax arrays, numpy arrays, scalars, or (nested) tuples/lists —
    anything a runner returns. Non-device values are already 'ready'.
    """
    for value in values:
        if isinstance(value, (tuple, list)):
            fence(*value)
        elif hasattr(value, "block_until_ready"):
            value.block_until_ready()


@contextlib.contextmanager
def device_phase(name: str, **attrs):
    """``with device_phase("execution") as ph: ...; ph.fence(out)`` — a
    traced span closed behind an explicit device fence. The yielded handle's
    ``fence(*values)`` may be called any number of times (including zero,
    for host-only phases); the LAST device sync before the span ends is what
    the duration reflects."""

    class _Phase:
        fence = staticmethod(fence)

    with trace.span(name, **attrs):
        yield _Phase()


@contextlib.contextmanager
def capture(profile_dir: str | None):
    """Guarded ``jax.profiler`` capture into ``profile_dir``.

    No-op when ``profile_dir`` is falsy (callers pass their ``--profile``
    flag through unconditionally). Yields True when capture actually
    started. Guarantees:

    - a failing ``start_trace`` (profiler backend unavailable, zero device
      work, double-start) degrades to an unprofiled run with a loud log —
      never a crashed one;
    - stop runs exactly once, even when the profiled body raises;
    - a body that raises mid-capture does not leave a torn trace directory
      behind: the partial capture is stopped and swept, because a
      half-written xplane that loads as an empty/garbage profile is worse
      evidence than no directory at all.
    """
    if not profile_dir:
        yield False
        return
    import jax

    # Entries already present (an operator pointing several runs at one
    # parent dir) are not ours to sweep on failure.
    preexisting = set()
    if os.path.isdir(profile_dir):
        preexisting = set(os.listdir(profile_dir))
    started = False
    try:
        jax.profiler.start_trace(profile_dir)
        started = True
    except Exception as err:  # noqa: BLE001 - profiling is best-effort
        logger.warning(
            "profiler capture into %s failed to start (%s: %s); "
            "running unprofiled", profile_dir, type(err).__name__, err,
        )
    try:
        yield started
    except BaseException:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 - already on the error path
                pass
            _sweep_partial(profile_dir, preexisting)
        raise
    if started:
        try:
            jax.profiler.stop_trace()
        except Exception as err:  # noqa: BLE001 - capture is best-effort
            logger.warning(
                "profiler capture into %s failed to stop cleanly "
                "(%s: %s); the trace may be incomplete",
                profile_dir, type(err).__name__, err,
            )
            _sweep_partial(profile_dir, preexisting)


def _sweep_partial(profile_dir: str, preexisting: set) -> None:
    """Remove capture entries created by a failed capture (and the directory
    itself when the failed capture was its only content)."""
    try:
        for name in os.listdir(profile_dir):
            if name in preexisting:
                continue
            path = os.path.join(profile_dir, name)
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        if not preexisting and not os.listdir(profile_dir):
            os.rmdir(profile_dir)
        logger.warning("profiler: swept torn capture from %s", profile_dir)
    except OSError:
        pass
