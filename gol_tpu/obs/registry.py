"""General counter/gauge/histogram registry — the tree's ONE metrics core.

PR 2 grew a metrics registry inside ``gol_tpu/serve/metrics.py``; by PR 3 the
engine, the checkpoint protocol, the retry policy, and the tuner each had
numbers worth counting and nowhere to put them. This module hoists the
registry out of the serving package so every layer feeds the same machinery:

- ``Registry`` — named counters, gauges, and bounded-reservoir histograms,
  thread-safe, exportable as a JSON snapshot or Prometheus text.
  ``gol_tpu/serve/metrics.Metrics`` is now a thin façade over it (same
  classes, same output bytes — the serving contracts are pinned by
  tests/test_serve.py).
- ``default()`` — the process-global registry. Library layers record here:
  engine run/board/generation counts, checkpoint save/restore outcomes,
  retry attempts, tuner trials, and trace-time halo-exchange volume. The
  flight recorder (obs/recorder.py) and ``GET /debug/trace`` snapshot it,
  so a post-mortem dump carries the process's counters alongside its spans.
- ``quantile`` / ``median`` — the single copy of the nearest-rank percentile
  math. The serving histograms' p50/p95/p99 and tools/measure.py's
  median-across-sessions both route through here (byte-stable: the code
  moved, the rules did not — ``quantile`` keeps the serving round-based
  nearest rank, ``median`` keeps the measurement protocol's upper median).

Latency sources are ``time.perf_counter()`` exclusively; the wall clock is
banned from this package by tests/test_lint.py (as it is from serve/ and
tune/) — a clock that steps under NTP turns a p99 into fiction.

Stdlib-only on purpose: ``resilience/retry.py`` (imported before the
jax-heavy modules, including in subprocesses that must start fast) records
retry attempts here at module load.
"""

from __future__ import annotations

import collections
import re
import threading

# Quantiles exported for every histogram (the serving contract).
QUANTILES = (0.5, 0.95, 0.99)

_RESERVOIR = 2048  # samples kept per histogram (most recent)


def quantile(samples, q: float) -> float | None:
    """Nearest-rank quantile over ``samples`` (round-based, the serving
    histograms' rule since PR 2 — moved here verbatim so /metrics output is
    byte-stable). Returns None on an empty sample set."""
    return _quantile_sorted(sorted(samples), q)


def _quantile_sorted(ordered, q: float) -> float | None:
    """``quantile`` over an ALREADY-sorted list — the shared rank rule,
    split out so ``Histogram.summary`` pays one sort for all three
    quantiles (it runs on every registry snapshot, which the SLO sampler
    takes once per tick)."""
    if not ordered:
        return None
    idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
    return ordered[idx]


def median(samples) -> float:
    """The measurement protocol's median: ``sorted(v)[len(v) // 2]`` — the
    upper median on even counts, exactly what tools/measure.py has published
    since r4 (artifact byte-stability pins the rule; ``quantile(v, 0.5)``
    differs on counts ≡ 2 mod 4 because ``round`` banker's-rounds)."""
    ordered = sorted(samples)
    if not ordered:
        raise ValueError("no samples")
    return ordered[len(ordered) // 2]


class Histogram:
    __slots__ = ("samples", "count", "total")

    def __init__(self):
        self.samples = collections.deque(maxlen=_RESERVOIR)
        self.count = 0
        self.total = 0.0

    def observe(self, value: float) -> None:
        self.samples.append(float(value))
        self.count += 1
        self.total += float(value)

    def quantile(self, q: float) -> float | None:
        # Nearest-rank on the recent reservoir (the shared rule above).
        return quantile(self.samples, q)

    def summary(self) -> dict:
        ordered = sorted(self.samples)  # one sort serves all three ranks
        out = {"count": self.count, "sum": self.total}
        for q in QUANTILES:
            out[f"p{int(q * 100)}"] = _quantile_sorted(ordered, q)
        return out


class Registry:
    """Named counters, gauges, and histograms; thread-safe."""

    def __init__(self, prefix: str = "gol"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, Histogram] = {}

    def inc(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def remove_gauge(self, name: str) -> None:
        """Drop a gauge whose subject is gone (a retired worker's
        breaker-state series): a stale last value on a per-entity gauge
        reads as a live report, unlike a counter, which merges."""
        with self._lock:
            self._gauges.pop(name, None)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._hists.setdefault(name, Histogram()).observe(value)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        """Point-in-time JSON-able view of everything."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary() for k, h in self._hists.items()},
            }

    def prometheus(self) -> str:
        """Prometheus text exposition format (quantiles as summary series)."""
        snap = self.snapshot()
        p = self.prefix
        lines: list[str] = []
        for name, value in sorted(snap["counters"].items()):
            lines.append(f"# TYPE {p}_{name} counter")
            lines.append(f"{p}_{name} {_fmt(value)}")
        for name, value in sorted(snap["gauges"].items()):
            lines.append(f"# TYPE {p}_{name} gauge")
            lines.append(f"{p}_{name} {_fmt(value)}")
        for name, summary in sorted(snap["histograms"].items()):
            lines.append(f"# TYPE {p}_{name} summary")
            for q in QUANTILES:
                v = summary.get(f"p{int(q * 100)}")
                if v is not None:
                    lines.append(f'{p}_{name}{{quantile="{q}"}} {_fmt(v)}')
            lines.append(f"{p}_{name}_sum {_fmt(summary['sum'])}")
            lines.append(f"{p}_{name}_count {_fmt(summary['count'])}")
        return "\n".join(lines) + "\n"


def metric_label(label: str) -> str:
    """Sanitize a free-form label (a bucket name like ``256x256/c/packed``)
    into a Prometheus-legal metric-name suffix. The registry has no label
    dimension on purpose (a counter is one dict slot); per-bucket series
    mangle the bucket into the name instead, through this ONE rule so the
    writer (scheduler) and the readers (sampler, tune marginal records)
    can never disagree on the spelling."""
    return re.sub(r"[^A-Za-z0-9]+", "_", label).strip("_")


def _fmt(v: float) -> str:
    # Prometheus wants plain decimal/scientific; repr of a float is both.
    return repr(float(v)) if isinstance(v, float) and not v.is_integer() else str(int(v))


# The process-global registry. A plain module singleton (no lazy factory):
# recording a counter must never be more than a dict update behind a lock,
# and every layer — engine, resilience, tune, parallel — shares this one.
_DEFAULT = Registry(prefix="gol")


def default() -> Registry:
    """The process-global registry library layers record into."""
    return _DEFAULT


def reset_default() -> None:
    """Fresh global registry (tests; never called by library code)."""
    global _DEFAULT
    _DEFAULT = Registry(prefix="gol")
