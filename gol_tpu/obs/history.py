"""Durable metrics history: an append-only, size-capped snapshot log.

Every signal the tree grew through PRs 4-9 — registry snapshots, SLO
windows, the dispatch-gap gauges, fleet-merged counters — is a LIVE view:
it answers "how is the service doing now" and evaporates with the process
(or scrolls off ``gol top``). This module is the retrospective record: a
per-process, windowed snapshot log that survives restarts, so an incident
is replayable evidence instead of a half-remembered gauge.

Disk format — the journal's discipline (serve/jobs.py), applied to
telemetry instead of jobs:

- a history directory holds numbered JSONL **segments**
  (``seg-00000042.jsonl``); every line is one JSON record, appended whole,
  so a crash tears at most the final line and the reader drops it;
- each segment opens with a ``{"record": "header"}`` line carrying the
  writer's pid, a free-form ``source`` label, and the process's clock
  anchors; every sample line after it is
  ``{"record": "sample", "seq": N, "t": <perf_counter>, ...snapshot}``;
- segments rotate at ``segment_bytes`` and the directory is a **ring**:
  once the total exceeds ``total_bytes``, the oldest whole segments are
  deleted (compaction) — a history can run for months and hold the most
  recent window, never grow without bound;
- a RESPAWNED process reopening the same directory continues the segment
  numbering (max existing + 1) and writes a fresh header: readers see the
  pid change and know perf_counter values from different headers are not
  comparable.

Clock discipline: samples are stamped with ``time.perf_counter()`` only —
rates and windows are differences of a monotonic clock, never of a wall
clock NTP can step (the package-wide tests/test_lint.py ban). Each segment
header carries ONE wall-clock anchor pair (``time.time_ns`` at open, the
same sanctioned alignment read as ``trace.enable()``): it never enters any
rate or window arithmetic; it only lets ``gol history-report`` place
samples from different processes/boots on one human-readable axis.

Monotonicity across respawns is the FEEDER's job, by design: the router's
history tick records the ``_merged_snapshot`` view, which already rides
PR 8's ``MonotonicCounters`` floors — so the durable fleet record of
``jobs_completed_total`` never dips through a worker SIGKILL/respawn
(test-pinned). A worker's own history honestly records its restart at
zero, with the header break marking the boundary.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time

logger = logging.getLogger(__name__)

_SEGMENT_RE = re.compile(r"seg-(\d{8})\.jsonl$")

DEFAULT_SEGMENT_BYTES = 1 << 20  # rotate at 1 MiB
DEFAULT_TOTAL_BYTES = 16 << 20  # ring-cap the directory at 16 MiB


def _segment_name(index: int) -> str:
    return f"seg-{index:08d}.jsonl"


def _segments(directory: str) -> list[tuple[int, str]]:
    """(index, path) for every segment file, oldest first."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _SEGMENT_RE.fullmatch(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


class HistoryWriter:
    """Appends windowed snapshots to a size-capped segment ring.

    ``append`` never raises on I/O trouble: history is telemetry, and a
    full disk must degrade it (loudly, counted) — never take the serving
    path down with it. Thread-safe; one writer per directory by contract
    (the fleet gives each process its own partition/subdir, exactly like
    the journal).
    """

    def __init__(
        self,
        directory: str,
        source: str = "",
        segment_bytes: int = DEFAULT_SEGMENT_BYTES,
        total_bytes: int = DEFAULT_TOTAL_BYTES,
        clock=time.perf_counter,
    ):
        if segment_bytes < 1:
            raise ValueError(f"segment_bytes must be >= 1, got {segment_bytes}")
        if total_bytes < segment_bytes:
            raise ValueError(
                f"total_bytes ({total_bytes}) must be >= segment_bytes "
                f"({segment_bytes})"
            )
        self.directory = directory
        self.source = source
        self.segment_bytes = segment_bytes
        self.total_bytes = total_bytes
        self._clock = clock
        self._lock = threading.Lock()
        self._fh = None
        self._seq = 0
        self._errors = 0
        os.makedirs(directory, exist_ok=True)
        existing = _segments(directory)
        # Continue the ring a previous incarnation left: numbering never
        # reuses an index, so "oldest" stays well-defined across respawns.
        self._index = (existing[-1][0] + 1) if existing else 0

    @property
    def errors(self) -> int:
        return self._errors

    def _open_segment(self) -> None:
        path = os.path.join(self.directory, _segment_name(self._index))
        self._fh = open(path, "a", encoding="utf-8")
        header = {
            "record": "header",
            "schema": 1,
            "pid": os.getpid(),
            "source": self.source,
            # The one wall-clock read (alignment metadata ONLY — see the
            # module docstring; time.time_ns like the tracer's anchor).
            "anchor_perf_s": self._clock(),
            "anchor_unix_ns": time.time_ns(),
        }
        self._fh.write(json.dumps(header, separators=(",", ":")) + "\n")
        self._fh.flush()

    def append(self, snapshot: dict) -> None:
        """Append one sample (a registry-style snapshot dict). Rotates and
        compacts as needed; I/O failure logs + counts, never raises."""
        with self._lock:
            if self._fh is None and self._errors == 0:
                try:
                    self._open_segment()
                except OSError as err:
                    self._errors += 1
                    logger.error("metrics history: cannot open segment in "
                                 "%s: %s", self.directory, err)
                    return
            if self._fh is None:
                # A previous failure closed us; retry a fresh segment so a
                # transient ENOSPC does not end the history forever.
                try:
                    self._index += 1
                    self._open_segment()
                except OSError:
                    self._errors += 1
                    return
            self._seq += 1
            record = {
                "record": "sample",
                "seq": self._seq,
                "t": self._clock(),
                **snapshot,
            }
            try:
                self._fh.write(
                    json.dumps(record, separators=(",", ":")) + "\n"
                )
                self._fh.flush()
                if self._fh.tell() >= self.segment_bytes:
                    self._fh.close()
                    self._index += 1
                    self._open_segment()
                    self._compact()
            except (OSError, ValueError) as err:
                self._errors += 1
                logger.error("metrics history append failed (%s); samples "
                             "will be dropped until it recovers", err)
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def _compact(self) -> None:
        """Delete the oldest whole segments past the ring cap (the current
        segment is never a deletion candidate)."""
        segments = _segments(self.directory)
        sizes = {}
        for index, path in segments:
            try:
                sizes[index] = os.path.getsize(path)
            except OSError:
                sizes[index] = 0
        total = sum(sizes.values())
        for index, path in segments:
            if total <= self.total_bytes or index == self._index:
                break
            try:
                os.unlink(path)
                total -= sizes[index]
            except OSError as err:
                logger.warning("metrics history: could not compact %s: %s",
                               path, err)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def read_records(directory: str) -> list[dict]:
    """Every parseable record across the ring, segment order (oldest
    first), torn/garbage lines dropped — the journal's replay leniency."""
    records: list[dict] = []
    for _index, path in _segments(directory):
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        for line in raw.split(b"\n"):
            if not line:
                continue
            try:
                records.append(json.loads(line.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                continue
    return records


def runs(directory: str) -> list[dict]:
    """Group the ring's samples into contiguous writer RUNS.

    A run is one (header, samples) stretch — one process incarnation's
    window. perf_counter values are only comparable within a run; the
    reader is where that rule is enforced, so every consumer (the report,
    the bench gate) inherits it. Each run:
    ``{"header": {...}, "samples": [sample, ...]}``.
    """
    out: list[dict] = []
    current: dict | None = None
    for rec in read_records(directory):
        kind = rec.get("record")
        if kind == "header":
            # Consecutive headers from ONE incarnation (segment rotation)
            # continue the same run: perf_counter stays comparable within
            # a pid, and seq numbering is writer-global.
            if current is not None and current["header"].get("pid") == rec.get("pid"):
                continue
            current = {"header": rec, "samples": []}
            out.append(current)
        elif kind == "sample":
            if current is None:  # compaction ate the header: synthesize
                current = {"header": {"record": "header"}, "samples": []}
                out.append(current)
            current["samples"].append(rec)
    return out


def counter_series(directory: str, name: str) -> list[list[tuple[float, float]]]:
    """Per-run [(t, value), ...] series for one cumulative counter —
    the shape both the rate math below and tests consume."""
    series = []
    for run in runs(directory):
        points = [
            (float(s["t"]), float(s["counters"][name]))
            for s in run["samples"]
            if isinstance(s.get("counters"), dict) and name in s["counters"]
        ]
        if points:
            series.append(points)
    return series


def window_rate(directory: str, name: str) -> tuple[float, float] | None:
    """(rate_per_sec, window_seconds) for a cumulative counter over the
    WHOLE retained history: per-run deltas over per-run durations, summed —
    a respawn boundary (new run, counter back at zero) contributes its own
    delta instead of a bogus negative one. None when the counter never
    moved across a measurable window (the bench gate treats that as a
    shape error, not a zero rate)."""
    delta = 0.0
    seconds = 0.0
    for points in counter_series(directory, name):
        if len(points) < 2:
            continue
        t0, v0 = points[0]
        t1, v1 = points[-1]
        if t1 > t0:
            delta += v1 - v0
            seconds += t1 - t0
    if seconds <= 0:
        return None
    return delta / seconds, seconds


# -- gol history-report ------------------------------------------------------

_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values: list[float]) -> str:
    if not values:
        return ""
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK[0] * len(values)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / (hi - lo) * (len(_SPARK) - 1)))]
        for v in values
    )


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.4g}"


def render_report(directory: str, width: int = 48) -> str:
    """The ``gol history-report`` text: per-series rate/value/percentile
    timelines over the retained window, respawn boundaries called out.

    Counters render as per-interval RATES (the derivative an operator
    thinks in); gauges as raw values; histograms as their p99 track. Long
    series are downsampled to ``width`` buckets (max-preserving: a spike
    an incident review is looking for must not average away).
    """
    all_runs = runs(directory)
    lines = [f"# metrics history: {directory}", ""]
    if not all_runs:
        lines.append("(no history records)")
        return "\n".join(lines) + "\n"
    nsamples = sum(len(r["samples"]) for r in all_runs)
    boots = []
    for run in all_runs:
        h = run["header"]
        boots.append(f"pid {h.get('pid', '?')}"
                     + (f" [{h['source']}]" if h.get("source") else "")
                     + f" x{len(run['samples'])}")
    lines.append(f"{nsamples} sample(s) across {len(all_runs)} writer "
                 f"run(s): " + ", ".join(boots))
    if len(all_runs) > 1:
        lines.append("respawn boundaries between runs are marked '|' in "
                     "the timelines; cumulative counters restart per run "
                     "unless the feeder floors them (the router's merged "
                     "history does)")
    lines.append("")

    counters: set[str] = set()
    gauges: set[str] = set()
    hists: set[str] = set()
    for run in all_runs:
        for s in run["samples"]:
            counters.update((s.get("counters") or {}))
            gauges.update((s.get("gauges") or {}))
            hists.update((s.get("histograms") or {}))

    def downsample(values: list[float]) -> list[float]:
        if len(values) <= width:
            return values
        out = []
        for i in range(width):
            lo = i * len(values) // width
            hi = max(lo + 1, (i + 1) * len(values) // width)
            out.append(max(values[lo:hi]))
        return out

    def emit(title: str, names: set[str], per_run_values) -> None:
        if not names:
            return
        lines.append(f"## {title}")
        for name in sorted(names):
            chunks: list[str] = []
            lasts: list[float] = []
            flat: list[float] = []
            for run in all_runs:
                vals = per_run_values(run, name)
                if vals:
                    chunks.append(_sparkline(downsample(vals)))
                    lasts.append(vals[-1])
                    flat.extend(vals)
                else:
                    chunks.append("")
            track = "|".join(chunks)
            if not flat:
                continue
            lines.append(
                f"  {name:<44} {track}  "
                f"last={_fmt(lasts[-1])} max={_fmt(max(flat))}"
            )
        lines.append("")

    def counter_rates(run: dict, name: str) -> list[float]:
        rates = []
        prev = None
        for s in run["samples"]:
            c = s.get("counters") or {}
            if name not in c:
                continue
            point = (float(s["t"]), float(c[name]))
            if prev is not None and point[0] > prev[0]:
                rates.append((point[1] - prev[1]) / (point[0] - prev[0]))
            prev = point
        return rates

    def gauge_values(run: dict, name: str) -> list[float]:
        return [float((s.get("gauges") or {})[name])
                for s in run["samples"]
                if name in (s.get("gauges") or {})
                and (s["gauges"][name]) is not None]

    def hist_p99(run: dict, name: str) -> list[float]:
        out = []
        for s in run["samples"]:
            summary = (s.get("histograms") or {}).get(name) or {}
            v = summary.get("p99")
            if v is not None:
                out.append(float(v))
        return out

    emit("counter rates (per second, per sampling interval)", counters,
         counter_rates)
    emit("gauges", gauges, gauge_values)
    emit("histogram p99", hists, hist_p99)

    totals = []
    for name in sorted(counters):
        wr = window_rate(directory, name)
        if wr is not None:
            rate, seconds = wr
            totals.append(f"  {name:<44} {rate:10.3f}/s over {seconds:.1f}s")
    if totals:
        lines.append("## whole-window rates (per-run deltas summed)")
        lines.extend(totals)
        lines.append("")
    return "\n".join(lines) + "\n"


__all__ = [
    "DEFAULT_SEGMENT_BYTES", "DEFAULT_TOTAL_BYTES", "HistoryWriter",
    "counter_series", "read_records", "render_report", "runs",
    "window_rate",
]
