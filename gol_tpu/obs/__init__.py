"""gol_tpu.obs — the cross-cutting observability subsystem.

The reference's entire story is three phase printfs and four numbers from
rank 0 (src/game.c, include/timestamp.h). This package is the
production-scale replacement, consumed by the engine, resilience, serve,
tune, and the CLI:

- ``obs.trace``    — span-based structured tracing (ring buffer, Chrome
                     trace export; off by default, zero-allocation when
                     disabled);
- ``obs.registry`` — the one counter/gauge/histogram registry
                     (serve/metrics.py is a façade over it; engine /
                     checkpoint / retry / tuner / halo feed the process
                     default);
- ``obs.recorder`` — flight recorder: last-N-spans JSONL dumps on crash,
                     fault-injection trigger, and SIGUSR1;
- ``obs.profiler`` — device-fenced phase timing + guarded jax.profiler
                     capture (the one implementation behind ``--profile``);
- ``obs.report``   — ``gol trace-report`` rendering;
- ``obs.timeline`` — the per-job milestone/segment vocabulary behind
                     ``GET /jobs/<id>/timeline``;
- ``obs.slo``      — declarative service-level objectives evaluated over
                     rolling registry windows (``GET /slo``, burn-rate
                     alerts, optional admission shedding);
- ``obs.sampler``  — the serve-side background sampler: SLO evaluation
                     ticks plus the continuous dispatch-gap monitor;
- ``obs.top``      — ``gol top`` terminal dashboard rendering;
- ``obs.propagate``— trace-context propagation: the ``X-Gol-Trace``
                     header joining router and worker spans into one
                     fleet-wide trace;
- ``obs.fleettrace`` — ``gol fleet-trace``: collect every live process's
                     span ring and stitch ONE clock-normalized
                     Chrome/Perfetto timeline;
- ``obs.history``  — durable metrics history: append-only, size-capped
                     snapshot ring + ``gol history-report``.

Stdlib-only at import time (jax loads lazily inside ``profiler.capture``),
so arming observability never reorders backend initialization.
"""

from gol_tpu.obs import (  # noqa: F401
    fleettrace, history, profiler, propagate, recorder, registry, report,
    sampler, slo, timeline, top, trace,
)

__all__ = [
    "fleettrace", "history", "profiler", "propagate", "recorder",
    "registry", "report", "sampler", "slo", "timeline", "top", "trace",
]
