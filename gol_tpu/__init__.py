"""gol_tpu — TPU-native Game of Life benchmark framework.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities of
v-pap/Game-of-Life-in-parallel-MPI-OpenMP-CUDA (six standalone C/MPI/OpenMP/CUDA
programs, reference mounted at /root/reference): same CLI contract, same text
grid format, same B3/S23 toroidal semantics and early-exit behavior, rebuilt as
one engine with pluggable policies:

- compute kernels: ``lax`` slicing stencil or fused Pallas VMEM-tiled stencil
  (the reference's CUDA kernels, src/game_cuda.cu:52-148, reimagined for TPU)
- distribution: 2D ``jax.sharding.Mesh`` + ``shard_map`` with two-phase
  ``ppermute`` halo exchange (the reference's 16 persistent MPI requests,
  src/game_mpi.c:340-383, reimagined for ICI)
- termination: on-device ``lax.while_loop`` with ``psum`` consensus votes (the
  reference's MPI_Allreduce-per-generation, src/game_mpi_collective.c:331)
- I/O: serial, gathered (master-scatter, src/game_mpi.c:201-239) and sharded
  offset-pread/pwrite (collective MPI-IO, src/game_mpi_collective.c:174-196)
"""

from gol_tpu.config import GameConfig, DEFAULT_CONFIG, GEN_LIMIT, SIMILARITY_FREQUENCY
from gol_tpu.oracle import evolve as oracle_evolve, run as oracle_run, Result

__version__ = "0.1.0"

__all__ = [
    "GameConfig",
    "DEFAULT_CONFIG",
    "GEN_LIMIT",
    "SIMILARITY_FREQUENCY",
    "oracle_evolve",
    "oracle_run",
    "Result",
]
