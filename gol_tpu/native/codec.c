/* Native text-grid <-> bitpacked-words codec.
 *
 * The reference's I/O layer is native C in all six programs (fgetc parse
 * loops, src/game.c:149-166; MPI-IO byte windows, src/game_mpi_collective.c:
 * 174-196). This is the TPU build's native counterpart, shaped for the
 * bitpacked engine: text bytes ('0'/'1' cells, '\n' row terminators) convert
 * straight to/from uint32 cell words (bit j of word w = column w*32+j),
 * skipping the 8x larger uint8 cell intermediate entirely.
 *
 * Only the byte '1' is a live cell (the text_grid contract: anything else is
 * dead); unpacking emits '0' + bit. Single-threaded per call: ctypes
 * releases the GIL, and the Python sharded-I/O layer already fans shards out
 * over a thread pool.
 *
 * Row addressing uses a byte stride so callers can map the
 * height x (width+1) file layout directly (the '+1' newline column of
 * src/game_mpi_collective.c:180-186).
 */

#include <stdint.h>

/* text (rows x >=width chars at `stride` bytes apart) -> words (rows x
 * width/32). width must be a multiple of 32. */
void gol_pack_text(const uint8_t *text, int64_t stride, uint32_t *words,
                   int64_t rows, int64_t width) {
  const int64_t row_words = width / 32;
  for (int64_t r = 0; r < rows; ++r) {
    const uint8_t *src = text + r * stride;
    uint32_t *dst = words + r * row_words;
    for (int64_t w = 0; w < row_words; ++w) {
      uint32_t acc = 0;
      const uint8_t *chunk = src + w * 32;
      for (int b = 0; b < 32; ++b) {
        acc |= (uint32_t)(chunk[b] == '1') << b;
      }
      dst[w] = acc;
    }
  }
}

/* words (rows x width/32) -> text rows at `stride` bytes apart; writes the
 * '\n' terminator after each row iff newline != 0 (east-edge shards own the
 * newline column, src/game_mpi_collective.c:382-393). */
void gol_unpack_text(const uint32_t *words, int64_t stride, uint8_t *text,
                     int64_t rows, int64_t width, int newline) {
  const int64_t row_words = width / 32;
  for (int64_t r = 0; r < rows; ++r) {
    const uint32_t *src = words + r * row_words;
    uint8_t *dst = text + r * stride;
    for (int64_t w = 0; w < row_words; ++w) {
      uint32_t acc = src[w];
      uint8_t *chunk = dst + w * 32;
      for (int b = 0; b < 32; ++b) {
        chunk[b] = (uint8_t)('0' + ((acc >> b) & 1u));
      }
    }
    if (newline) {
      dst[width] = '\n';
    }
  }
}

