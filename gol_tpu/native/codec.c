/* Native text-grid <-> bitpacked-words codec.
 *
 * The reference's I/O layer is native C in all six programs (fgetc parse
 * loops, src/game.c:149-166; MPI-IO byte windows, src/game_mpi_collective.c:
 * 174-196). This is the TPU build's native counterpart, shaped for the
 * bitpacked engine: text bytes ('0'/'1' cells, '\n' row terminators) convert
 * straight to/from uint32 cell words (bit j of word w = column w*32+j),
 * skipping the 8x larger uint8 cell intermediate entirely.
 *
 * Only the byte '1' is a live cell (the text_grid contract: anything else is
 * dead); unpacking emits '0' + bit. Single-threaded per call: ctypes
 * releases the GIL, and the Python sharded-I/O layer already fans shards out
 * over a thread pool.
 *
 * Row addressing uses a byte stride so callers can map the
 * height x (width+1) file layout directly (the '+1' newline column of
 * src/game_mpi_collective.c:180-186).
 *
 * Hot loops use the 64-bit SWAR lane tricks (little-endian only; the scalar
 * fallback keeps big-endian correct):
 *  - pack: lanes are compared against '1' exactly (SWAR equality via xor +
 *    borrow — non-'0'/'1' bytes must read as dead), then a movemask multiply
 *    gathers the 8 lane bits into the top byte.
 *  - unpack: a bit-spread multiply fans 8 bits into 8 byte lanes, normalized
 *    to 0/1 and OR'd with 0x3030..30.
 */

#include <stdint.h>
#include <string.h>

#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
#define GOL_LE 1
#else
#define GOL_LE 0
#endif

static const uint64_t SPREAD = 0x8040201008040201ULL; /* lane i keeps bit i */
static const uint64_t GATHER = 0x0102040810204080ULL; /* lane i -> out bit i */
static const uint64_t ONES = 0x0101010101010101ULL;

/* 8 text bytes -> 8 cell bits (bit i = byte i == '1'). */
static inline uint32_t pack8(const uint8_t *p) {
  uint64_t v;
  memcpy(&v, p, 8);
  /* SWAR equality with '1': lanes equal to '1' zero out under xor, then the
   * borrow trick turns zero-lanes into 1 and everything else into 0. */
  uint64_t x = v ^ (ONES * '1');
  uint64_t eq = (~((x | ((x | (ONES << 7)) - ONES)) >> 7)) & ONES;
  return (uint32_t)((eq * GATHER) >> 56);
}

/* byte value -> its 8 ASCII cells, precomputed (2 KB, L1-resident). */
static uint64_t UNPACK_LUT[256];

__attribute__((constructor)) static void gol_init_lut(void) {
  for (int b = 0; b < 256; ++b) {
    uint64_t spread = ((uint64_t)b * ONES) & SPREAD;
    /* lanes hold 0 or 1<<i; +0x7f pushes any nonzero lane's high bit up. */
    uint64_t norm = ((spread + 0x7f7f7f7f7f7f7f7fULL) >> 7) & ONES;
    UNPACK_LUT[b] = norm | (ONES * '0');
  }
}

/* 8 cell bits -> 8 ASCII bytes at p. */
static inline void unpack8(uint32_t bits, uint8_t *p) {
  memcpy(p, &UNPACK_LUT[bits & 0xffu], 8);
}

/* text (rows x >=width chars at `stride` bytes apart) -> words (rows x
 * width/32). width must be a multiple of 32. */
void gol_pack_text(const uint8_t *text, int64_t stride, uint32_t *words,
                   int64_t rows, int64_t width) {
  const int64_t row_words = width / 32;
  for (int64_t r = 0; r < rows; ++r) {
    const uint8_t *src = text + r * stride;
    uint32_t *dst = words + r * row_words;
    for (int64_t w = 0; w < row_words; ++w) {
      const uint8_t *chunk = src + w * 32;
#if GOL_LE
      dst[w] = pack8(chunk) | (pack8(chunk + 8) << 8) |
               (pack8(chunk + 16) << 16) | (pack8(chunk + 24) << 24);
#else
      uint32_t acc = 0;
      for (int b = 0; b < 32; ++b) {
        acc |= (uint32_t)(chunk[b] == '1') << b;
      }
      dst[w] = acc;
#endif
    }
  }
}

/* words (rows x width/32) -> text rows at `stride` bytes apart; writes the
 * '\n' terminator after each row iff newline != 0 (east-edge shards own the
 * newline column, src/game_mpi_collective.c:382-393). */
void gol_unpack_text(const uint32_t *words, int64_t stride, uint8_t *text,
                     int64_t rows, int64_t width, int newline) {
  const int64_t row_words = width / 32;
  for (int64_t r = 0; r < rows; ++r) {
    const uint32_t *src = words + r * row_words;
    uint8_t *dst = text + r * stride;
    for (int64_t w = 0; w < row_words; ++w) {
      uint32_t acc = src[w];
      uint8_t *chunk = dst + w * 32;
#if GOL_LE
      unpack8(acc, chunk);
      unpack8(acc >> 8, chunk + 8);
      unpack8(acc >> 16, chunk + 16);
      unpack8(acc >> 24, chunk + 24);
#else
      for (int b = 0; b < 32; ++b) {
        chunk[b] = (uint8_t)('0' + ((acc >> b) & 1u));
      }
#endif
    }
    if (newline) {
      dst[width] = '\n';
    }
  }
}
