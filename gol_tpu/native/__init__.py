"""Native codec loader: compile-on-first-use C, ctypes-bound, numpy fallback.

The shared library is built from ``codec.c`` with the system compiler into
this package directory the first time it is needed (no pybind11 in the image;
ctypes needs nothing but a C toolchain — and when even that is missing,
``pack_text``/``unpack_text`` fall back to vectorized numpy so every feature
keeps working, just without the native fast path).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "codec.c")
_LIB = os.path.join(_DIR, "_codec.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
                for cc in ("cc", "gcc", "clang"):
                    try:
                        subprocess.run(
                            [cc, "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC],
                            check=True,
                            capture_output=True,
                        )
                        break
                    except (OSError, subprocess.CalledProcessError):
                        continue
                else:
                    return None
            lib = ctypes.CDLL(_LIB)
            i64, u8p, u32p = (
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_uint32),
            )
            lib.gol_pack_text.argtypes = [u8p, i64, u32p, i64, i64]
            lib.gol_unpack_text.argtypes = [u32p, i64, u8p, i64, i64, ctypes.c_int]
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def _u8p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _u32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


def pack_text(text: np.ndarray, width: int) -> np.ndarray:
    """(rows, stride>=width) ASCII bytes -> (rows, width/32) uint32 words.

    Only the byte '1' is a live cell (the text_grid contract — any other
    byte, including other odd ones, is dead).
    """
    if width % 32:
        raise ValueError(f"width {width} not a multiple of 32")
    rows, stride = text.shape
    if stride < width:
        # Guard the raw-pointer C call: a too-narrow array would be an
        # out-of-bounds read in C rather than a Python error.
        raise ValueError(f"text has {stride} columns, needs >= width {width}")
    out = np.empty((rows, width // 32), dtype=np.uint32)
    lib = _load()
    if lib is not None and text.strides[1] == 1:
        # Arbitrary row stride is fine (the memmap view over the newline
        # column layout); only the row interior must be byte-contiguous.
        lib.gol_pack_text(_u8p(text), text.strides[0], _u32p(out), rows, width)
        return out
    bits = (text[:, :width] == ord("1")).astype(np.uint32).reshape(rows, width // 32, 32)
    weights = (np.uint32(1) << np.arange(32, dtype=np.uint32))[None, None, :]
    np.sum(bits * weights, axis=-1, dtype=np.uint32, out=out)
    return out


def unpack_text(words: np.ndarray, out: np.ndarray, width: int, newline: bool) -> None:
    """(rows, width/32) uint32 -> ASCII '0'/'1' into out (rows, stride) bytes,
    plus the '\\n' column when ``newline``."""
    if width % 32:
        raise ValueError(f"width {width} not a multiple of 32")
    rows = words.shape[0]
    # Guard the raw-pointer C call against out-of-bounds writes.
    if words.shape[1] != width // 32:
        raise ValueError(f"words has {words.shape[1]} columns, needs {width // 32}")
    if out.shape[0] < rows or out.shape[1] < width + (1 if newline else 0):
        raise ValueError(
            f"out shape {out.shape} too small for {rows} rows x width {width}"
            f"{' + newline' if newline else ''}"
        )
    lib = _load()
    if lib is not None and out.strides[1] == 1 and words.flags.c_contiguous:
        lib.gol_unpack_text(
            _u32p(words), out.strides[0], _u8p(out), rows, width, int(newline)
        )
        return
    shifts = np.arange(32, dtype=np.uint32)[None, None, :]
    bits = (words[:, :, None] >> shifts) & np.uint32(1)
    out[:, :width] = bits.astype(np.uint8).reshape(rows, width) + ord("0")
    if newline:
        out[:, width] = ord("\n")
