"""Re-apply JAX_PLATFORMS before any jax-importing module loads.

Kept deliberately free of jax-importing dependencies: some environments
preload jax at interpreter start (sitecustomize), consuming JAX_PLATFORMS
before the user's value is seen. Backends initialize lazily, so re-applying
via jax.config works — but only if it happens before anything touches a
device. Entry points (``gol`` console script, ``python -m gol_tpu``,
bench.py) call this FIRST, above their gol_tpu imports, so no future
module-level device touch in a transitively imported module can order
itself ahead of the re-application (the hazard the round-3 advisor flagged
in the def-sandwiched-in-imports layout this module replaces).
"""

from __future__ import annotations

import logging
import os


class _DynamicStderrHandler(logging.StreamHandler):
    """StreamHandler that resolves ``sys.stderr`` at emit time, not at
    construction: test harnesses (and some launchers) swap the stream per
    run, and a handler pinned to a dead buffer would swallow every warning.
    """

    def __init__(self, level=logging.NOTSET):
        logging.Handler.__init__(self, level)

    @property
    def stream(self):
        import sys

        return sys.stderr

    @stream.setter
    def stream(self, value):
        # Keep the StreamHandler contract: ``setStream()`` / direct
        # ``handler.stream = ...`` assignment must not raise. The assignment
        # is accepted but has no effect — this handler is dynamic by design,
        # so redirecting ``sys.stderr`` itself is how output gets rerouted.
        del value


def configure_cli_logging(level: int = logging.INFO) -> None:
    """Route the ``gol_tpu`` logger tree to stderr for application entry
    points (the CLI, bench.py).

    Library modules log through ``logging.getLogger(__name__)`` and never
    attach handlers (the embedder owns routing); the entry points call this
    so kernel-demotion warnings and checkpoint/retry notices keep reaching
    stderr exactly as the pre-logging ``sys.stderr`` writes did. Idempotent;
    a host application that already configured the logger wins.
    """
    lg = logging.getLogger("gol_tpu")
    if any(isinstance(h, _DynamicStderrHandler) for h in lg.handlers):
        return
    handler = _DynamicStderrHandler()
    handler.setFormatter(logging.Formatter("gol_tpu: %(message)s"))
    lg.addHandler(handler)
    if lg.level == logging.NOTSET or lg.level > level:
        lg.setLevel(level)
    # Propagation stays on: a root handler an embedder (or test harness)
    # configured should keep seeing these records too.


def enable_compile_cache(cache_dir: str | None) -> None:
    """Wire JAX's persistent compilation cache to ``cache_dir``.

    The tuned server's startup cost is dominated by XLA/Mosaic compiles of
    programs it has compiled before (one per bucket/shape, identical across
    restarts); with the cache enabled, a restart replays them from disk. The
    two threshold knobs are dropped to zero so the serving-sized programs
    (small, fast-compiling — exactly the ones a warm fleet has thousands of)
    are cacheable too; on jax builds without those knobs the cache still
    works with its defaults. No-op when ``cache_dir`` is falsy, so entry
    points can pass their ``--compile-cache`` flag through unconditionally.
    """
    if not cache_dir:
        return
    os.makedirs(cache_dir, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(knob, value)
        except (AttributeError, ValueError):  # knob absent on this jax
            pass


def honor_platform_env() -> None:
    """Idempotent: safe to call from every entry point, any number of times.

    Without this, ``JAX_PLATFORMS=cpu gol ... --mesh 4x1`` on an
    8-virtual-CPU host still lands on the accelerator backend and fails
    device-count validation.
    """
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)
