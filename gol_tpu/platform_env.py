"""Re-apply JAX_PLATFORMS before any jax-importing module loads.

Kept deliberately free of jax-importing dependencies: some environments
preload jax at interpreter start (sitecustomize), consuming JAX_PLATFORMS
before the user's value is seen. Backends initialize lazily, so re-applying
via jax.config works — but only if it happens before anything touches a
device. Entry points (``gol`` console script, ``python -m gol_tpu``,
bench.py) call this FIRST, above their gol_tpu imports, so no future
module-level device touch in a transitively imported module can order
itself ahead of the re-application (the hazard the round-3 advisor flagged
in the def-sandwiched-in-imports layout this module replaces).
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Idempotent: safe to call from every entry point, any number of times.

    Without this, ``JAX_PLATFORMS=cpu gol ... --mesh 4x1`` on an
    8-virtual-CPU host still lands on the accelerator backend and fails
    device-count validation.
    """
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)
