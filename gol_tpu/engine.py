"""The simulation engine: one compiled program per run, zero host round-trips.

Plays the role of every reference driver loop at once (src/game.c:177-196,
src/game_mpi_collective.c:331-365, src/game_cuda.cu:222-276), collapsed into a
single ``lax.while_loop`` that runs entirely on device:

  cond:  alive & not-similar & generation bound     (the reference's
         `while (!empty_all(...) && generation <= GEN_LIMIT)`)
  body:  halo exchange -> stencil -> consensus votes -> carry swap

The double-buffer pointer swap of the reference (src/game.c:191-194, and the
odd/even duplicated MPI request sets it forces, src/game_mpi.c:340-383) is
simply the while_loop carry: XLA double-buffers and races are impossible by
construction. The CUDA program's per-generation device->host flag copy
(src/game_cuda.cu:259-268) becomes an on-device psum feeding the loop cond, so
the host blocks exactly once, at the end of the run.

Both loop-accounting conventions in the reference are implemented; see
``gol_tpu.config.Convention``.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from gol_tpu.config import Convention, DEFAULT_CONFIG, GameConfig
from gol_tpu.ops import Kernel, resolve_kernel
from gol_tpu.parallel import collectives
from gol_tpu.parallel.mesh import (
    Topology,
    grid_sharding,
    topology_for,
    validate_grid,
)


@dataclasses.dataclass
class EngineResult:
    """Host-side view of a finished run."""

    grid: np.ndarray  # uint8 {0,1}, global (height, width)
    generations: int  # the count the matching reference variant would print


def _generation(cur, kernel: Kernel, topology: Topology):
    """One generation plus its local termination flags.

    With a fused kernel the flags come out of the same memory pass as the
    stencil; otherwise they are separate (XLA-fused where possible) scans —
    the similarity compare stays lazy behind the engine's lax.cond.
    """
    if kernel.fused is not None:
        return kernel.fused(cur, topology)
    new = kernel.step(cur, topology)
    return new, jnp.any(new), None


def _similarity_vote(fire, cur, new, similar_local, topology: Topology):
    """Every-Kth-generation consensus that the generations are identical
    (similarity_all, src/game_mpi_collective.c:98-109).

    With a fused kernel the local flag already exists, so the vote is plain
    arithmetic — a lax.cond here measurably stalls the TPU pipeline (~80us per
    generation at 4096^2). Without one, the full-grid compare is guarded by
    lax.cond so it is only paid on firing generations.
    """
    if similar_local is not None:
        return fire & collectives.all_agree(similar_local, topology)
    return jax.lax.cond(
        fire,
        lambda: collectives.all_agree(jnp.all(cur == new), topology),
        lambda: jnp.asarray(False),
    )


def _simulate_c(grid, config: GameConfig, topology: Topology, kernel: Kernel):
    """C-variant loop (src/game.c:177-196, src/game_mpi_collective.c:331-365).

    Emptiness is checked at the top of every generation on the current grid;
    the similarity break does not increment the counter; the reported count is
    ``generation - 1``.
    """
    limit = jnp.int32(config.gen_limit)
    freq = jnp.int32(config.similarity_frequency)

    def cond(state):
        _, gen, _, alive, similar = state
        return alive & jnp.logical_not(similar) & (gen <= limit)

    def body(state):
        cur, gen, counter, _, _ = state
        new, alive_local, similar_local = _generation(cur, kernel, topology)
        if config.check_similarity:
            fire = (counter + 1) == freq
            similar = _similarity_vote(fire, cur, new, similar_local, topology)
            counter = jnp.where(fire, 0, counter + 1)
        else:
            similar = jnp.asarray(False)
        alive = collectives.any_flag(alive_local, topology)
        gen = jnp.where(similar, gen, gen + 1)
        return (new, gen, counter, alive, similar)

    alive0 = collectives.any_flag(jnp.any(grid), topology)
    state0 = (grid, jnp.int32(1), jnp.int32(0), alive0, jnp.asarray(False))
    final, gen, _, _, _ = jax.lax.while_loop(cond, body, state0)
    return final, gen - 1


def _simulate_cuda(grid, config: GameConfig, topology: Topology, kernel: Kernel):
    """CUDA-variant loop (src/game_cuda.cu:222-276).

    0-based exclusive bound; no emptiness test before the first evolve; the
    emptiness test runs on the new grid and breaks *before* the swap, so an
    empty exit keeps the last non-empty generation; reported count is the raw
    counter. Checks scan the interior only — deliberately not the binary's
    stale-halo padded scan; see gol_tpu.oracle._run_cuda.
    """
    limit = jnp.int32(config.gen_limit)
    freq = jnp.int32(config.similarity_frequency)

    def cond(state):
        _, gen, _, stop = state
        return jnp.logical_not(stop) & (gen < limit)

    def body(state):
        cur, gen, counter, _ = state
        new, alive_local, similar_local = _generation(cur, kernel, topology)
        if config.check_similarity:
            fire = (counter + 1) == freq
            similar = _similarity_vote(fire, cur, new, similar_local, topology)
            counter = jnp.where(fire, 0, counter + 1)
        else:
            similar = jnp.asarray(False)
        empty = jnp.logical_not(collectives.any_flag(alive_local, topology))
        stop = similar | empty
        cur = jnp.where(stop, cur, new)  # break precedes the swap (:250,:266)
        gen = jnp.where(stop, gen, gen + 1)
        return (cur, gen, counter, stop)

    state0 = (grid, jnp.int32(0), jnp.int32(0), jnp.asarray(False))
    final, gen, _, _ = jax.lax.while_loop(cond, body, state0)
    return final, gen


_SIMULATORS = {Convention.C: _simulate_c, Convention.CUDA: _simulate_cuda}


@functools.lru_cache(maxsize=64)
def make_runner(
    shape: tuple[int, int],
    config: GameConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
    kernel: str = "auto",
):
    """Compile a ``global_grid -> (global_grid, generations)`` runner.

    With a mesh, the runner is a ``shard_map`` over ('row', 'col') — the
    topology/bootstrap step the reference does with MPI_Init + MPI_Cart_create
    (src/game_mpi_collective.c:116-133) happens here, at trace time.
    """
    topology = topology_for(mesh)
    local_h, local_w = validate_grid(shape[0], shape[1], topology)
    kernel_obj = resolve_kernel(kernel, local_h, local_w, topology)
    if not kernel_obj.supports(local_h, local_w, topology):
        raise ValueError(
            f"kernel {kernel_obj.name!r} does not support a {local_h}x{local_w} "
            f"local shard on a {topology.shape[0]}x{topology.shape[1]} topology; "
            f"use kernel='auto' to fall back automatically"
        )
    simulate = _SIMULATORS[config.convention]

    def local_fn(g):
        # Kernels with their own carried representation (the bitpacked path)
        # convert once at the loop boundary; the generation loop never touches
        # the canonical uint8 grid.
        if kernel_obj.encode is not None:
            g = kernel_obj.encode(g)
        final, gen = simulate(g, config, topology, kernel_obj)
        if kernel_obj.decode is not None:
            final = kernel_obj.decode(final)
        return final, gen

    if topology.distributed:
        fn = jax.shard_map(
            local_fn,
            mesh=mesh,
            in_specs=P(*topology.axes),
            out_specs=(P(*topology.axes), P()),
        )
    else:
        fn = local_fn
    return jax.jit(fn)


def put_grid(grid, mesh: Mesh | None = None) -> jax.Array:
    """Place a host grid onto the device(s) with the engine's sharding."""
    arr = np.ascontiguousarray(np.asarray(grid, dtype=np.uint8))
    if mesh is None:
        return jnp.asarray(arr)
    return jax.device_put(arr, grid_sharding(mesh))


def simulate(
    grid,
    config: GameConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
    kernel: str = "auto",
) -> EngineResult:
    """Run a full simulation and fetch the result to the host."""
    shape = tuple(np.shape(grid))
    validate_grid(shape[0], shape[1], topology_for(mesh))
    device_grid = grid if isinstance(grid, jax.Array) else put_grid(grid, mesh)
    runner = make_runner(shape, config, mesh, kernel)
    final, gen = runner(device_grid)
    return EngineResult(np.asarray(jax.device_get(final), dtype=np.uint8), int(gen))
