"""The simulation engine: one compiled program per run, zero host round-trips.

Plays the role of every reference driver loop at once (src/game.c:177-196,
src/game_mpi_collective.c:331-365, src/game_cuda.cu:222-276), collapsed into a
single ``lax.while_loop`` that runs entirely on device:

  cond:  alive & not-similar & generation bound     (the reference's
         `while (!empty_all(...) && generation <= GEN_LIMIT)`)
  body:  halo exchange -> stencil -> consensus votes -> carry swap

The double-buffer pointer swap of the reference (src/game.c:191-194, and the
odd/even duplicated MPI request sets it forces, src/game_mpi.c:340-383) is
simply the while_loop carry: XLA double-buffers and races are impossible by
construction. The CUDA program's per-generation device->host flag copy
(src/game_cuda.cu:259-268) becomes an on-device psum feeding the loop cond, so
the host blocks exactly once, at the end of the run.

Both loop-accounting conventions in the reference are implemented; see
``gol_tpu.config.Convention``.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from gol_tpu.config import Convention, DEFAULT_CONFIG, GameConfig
from gol_tpu.obs import registry as obs_registry, trace as obs_trace
from gol_tpu.resilience.retry import RetryPolicy
from gol_tpu.ops import (
    Kernel,
    fallback_chain,
    get_kernel,
    resolve_kernel,
    with_temporal_depth,
)
from gol_tpu.ops.jit_compat import jit_donating
from gol_tpu.parallel import collectives
from gol_tpu.parallel.mesh import (
    Topology,
    grid_sharding,
    shard_map,
    topology_for,
    validate_grid,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class EngineResult:
    """Host-side view of a finished run."""

    grid: np.ndarray  # uint8 {0,1}, global (height, width)
    generations: int  # the count the matching reference variant would print


# Per-board exit classification of the batched engine (index = wire code).
# Solo runs never needed one — the caller IS the run — but a serving batch
# returns many fates per dispatch, so the reason travels with each board.
EXIT_GEN_LIMIT, EXIT_EMPTY, EXIT_SIMILAR = 0, 1, 2
EXIT_REASONS = ("gen_limit", "empty", "similar")


@dataclasses.dataclass
class BatchBoardResult:
    """One board's slice of a finished batch — an ``EngineResult`` plus the
    exit reason (bit-identical grid/count to a solo run of the same board)."""

    grid: np.ndarray  # uint8 {0,1}, (height, width) — cropped, not padded
    generations: int
    exit_reason: str  # one of EXIT_REASONS
    # Packed-kernel readbacks keep the board's device word layout here
    # (io/bitpack.py convention; packed mode is exact-fit by construction,
    # so the words ARE the cropped board): the serving stack can answer a
    # packed wire response or store a packed CAS payload without
    # re-packing. None on the byte/masked lanes.
    words: np.ndarray | None = None


def _generation(cur, kernel: Kernel, topology: Topology):
    """One generation plus its local termination flags.

    With a fused kernel the flags come out of the same memory pass as the
    stencil; otherwise they are separate (XLA-fused where possible) scans —
    the similarity compare stays lazy behind the engine's lax.cond.
    """
    if kernel.fused is not None:
        return kernel.fused(cur, topology)
    new = kernel.step(cur, topology)
    return new, jnp.any(new), None


def _similarity_vote(fire, cur, new, similar_local, topology: Topology):
    """Every-Kth-generation consensus that the generations are identical
    (similarity_all, src/game_mpi_collective.c:98-109).

    With a fused kernel the local flag already exists, so the vote is plain
    arithmetic — a lax.cond here measurably stalls the TPU pipeline (~80us per
    generation at 4096^2). Without one, the O(grid) compare is guarded by
    lax.cond so it is only paid on firing generations — but the *collective*
    runs unconditionally on the masked flag: a psum under a data-dependent
    lax.cond deadlocks any backend that cannot prove the predicate
    SPMD-uniform (ours is — the counter is identical on every shard — but
    XLA cannot know that). Off-generations vote False everywhere, so the
    unconditional all_agree is correct and matches the reference's
    unconditional every-3rd-gen similarity_all
    (src/game_mpi_collective.c:353-361).
    """
    if similar_local is not None:
        return fire & collectives.all_agree(similar_local, topology)
    # The compare's output is device-varying under shard_map; the False arm
    # must be cast to match (vma tracking rejects mixed-variance branches).
    false_arm = jnp.asarray(False)
    if topology.distributed and hasattr(jax.lax, "pcast"):
        # Older jax has no vma tracking (and no pcast) — there the plain
        # False arm is already accepted, so skipping the cast is exact.
        false_arm = jax.lax.pcast(false_arm, topology.axes, to="varying")
    sim_local = jax.lax.cond(
        fire,
        lambda: jnp.all(cur == new),
        lambda: false_arm,
    )
    return fire & collectives.all_agree(sim_local, topology)


# Generations per outer while iteration in the C-convention block loop. The
# while cond consumes flags produced by the generation kernel, so every
# iteration ends in a scalar sync that drains the TPU pipeline (~40us at
# 16384^2, ~35% over the raw kernel); running K generations per iteration
# amortizes that sync — and, on a mesh, turns K per-generation Allreduce votes
# (the reference's loop-condition cost, src/game_mpi_collective.c:331,76) into
# one K-vector psum per block. This is the *default*: the blocked loops take
# the block size as a parameter, so a measured plan (gol_tpu/tune) or an A/B
# harness (tools/measure.py block) can vary it per runner without mutating
# this module.
_TERMINATION_BLOCK = 16


def _block_generations(start, t, config, topology, kernel, block):
    """Run ``t`` generations from ``start``, voting flags once for the block.

    The shared machinery of both conventions' blocked loops: temporally-
    blocked fused_multi passes (T generations per kernel call; the runner
    factory strips fused_multi when the shape/topology can't) with a
    single-generation tail for the ``t % T`` remainder — flags land at
    vector slots T*j..T*j+T-1 / t-rem..t-1, so the callers' scalar replays
    are oblivious to the grouping. Returns ``(cur, a_all, s_all)``: the
    block-end state and the K-slot voted flag vectors (one vector vote per
    block instead of one scalar vote per generation; on a single device the
    collectives pass the int32 vectors through — normalize to bool so loop
    carries keep one dtype). ``s_all`` is None when the similarity check is
    disabled (the vote is dropped entirely).
    """
    zeros = jnp.zeros((block,), jnp.int32)

    def single_gen(slot_base):
        # One generation, flags recorded at slot_base + i.
        def sub(i, carry):
            cur, a_vec, s_vec = carry
            new, alive_local, similar_local = _generation(cur, kernel, topology)
            a_vec = a_vec.at[slot_base + i].set(alive_local.astype(jnp.int32))
            if config.check_similarity:
                s_vec = s_vec.at[slot_base + i].set(similar_local.astype(jnp.int32))
            return new, a_vec, s_vec

        return sub

    if kernel.fused_multi is not None:
        T = kernel.multi_gens

        def sub_multi(j, carry):
            cur, a_vec, s_vec = carry
            new, a_flags, s_flags = kernel.fused_multi(cur, topology)
            a_vec = jax.lax.dynamic_update_slice(a_vec, a_flags, (T * j,))
            if config.check_similarity:
                s_vec = jax.lax.dynamic_update_slice(s_vec, s_flags, (T * j,))
            return new, a_vec, s_vec

        cur, a_vec, s_vec = jax.lax.fori_loop(
            0, t // T, sub_multi, (start, zeros, zeros)
        )
        cur, a_vec, s_vec = jax.lax.fori_loop(
            0, t % T, single_gen(t - (t % T)), (cur, a_vec, s_vec)
        )
    else:
        cur, a_vec, s_vec = jax.lax.fori_loop(
            0, t, single_gen(0), (start, zeros, zeros)
        )
    a_all = collectives.any_flag(a_vec, topology).astype(jnp.bool_)
    s_all = (
        collectives.all_agree(s_vec, topology).astype(jnp.bool_)
        if config.check_similarity
        else None
    )
    return cur, a_all, s_all


def _replay_similarity(counter, freq, s_all, i, check: bool):
    """One replayed generation's similarity outcome: ``(similar_i, counter')``.

    The counter fires every ``freq``-th generation and resets on fire —
    shared by both conventions' scalar replays (their surrounding exit
    semantics differ; this firing rule does not)."""
    if not check:
        return jnp.asarray(False), counter
    fire = (counter + 1) == freq
    return fire & s_all[i], jnp.where(fire, 0, counter + 1)


def _simulate_c_block(grid, config, topology, kernel, gen0, counter0, bound, block):
    """Blocked C-convention loop: K generations per flag sync, bit-exact.

    Exactness argument: the C loop's two early exits are *fixed points* of the
    evolve — an empty grid stays empty (no cell has 3 neighbors), and a
    similarity exit means ``cur == new``, a still life that evolves to itself
    forever. So sub-steps that overrun an exit inside a block leave the grid
    byte-identical to stopping on time; only the generation/similarity
    counters need the exit point, and those are replayed exactly from the
    per-sub-step flag vectors (on scalars, after one vector-vote collective
    per block). The generation-limit exit is NOT a fixed point, so the block
    never crosses ``bound``: the inner trip count is clamped to the
    generations remaining.
    """
    K = block
    freq = jnp.int32(config.similarity_frequency)

    def cond(state):
        _, gen, _, alive, similar = state
        return alive & jnp.logical_not(similar) & (gen <= bound)

    def body(state):
        cur, gen, counter, alive, similar = state
        t = jnp.minimum(jnp.int32(K), bound - gen + 1)
        cur, a_all, s_all = _block_generations(cur, t, config, topology, kernel, K)

        def replay(i, c):
            gen, counter, alive, similar, stopped = c
            ran = jnp.logical_not(stopped) & (i < t)
            sim_i, counter_n = _replay_similarity(
                counter, freq, s_all, i, config.check_similarity
            )
            alive_n = a_all[i]
            gen_n = jnp.where(sim_i, gen, gen + 1)
            gen = jnp.where(ran, gen_n, gen)
            counter = jnp.where(ran, counter_n, counter)
            alive = jnp.where(ran, alive_n, alive)
            similar = jnp.where(ran, sim_i, similar)
            stopped = stopped | (
                ran & jnp.logical_not(alive_n & jnp.logical_not(sim_i) & (gen_n <= bound))
            )
            return gen, counter, alive, similar, stopped

        gen, counter, alive, similar, _ = jax.lax.fori_loop(
            0, K, replay, (gen, counter, alive, similar, jnp.asarray(False))
        )
        return (cur, gen, counter, alive, similar)

    alive0 = collectives.any_flag(jnp.any(grid), topology)
    state0 = (grid, jnp.int32(gen0), jnp.int32(counter0), alive0, jnp.asarray(False))
    return jax.lax.while_loop(cond, body, state0)


def _simulate_c(grid, config: GameConfig, topology: Topology, kernel: Kernel,
                resume=None, block: int | None = None):
    """C-variant loop (src/game.c:177-196, src/game_mpi_collective.c:331-365).

    Emptiness is checked at the top of every generation on the current grid;
    the similarity break does not increment the counter; the reported count is
    ``generation - 1``.

    ``resume`` is ``None`` for a whole run, or ``(gen0, counter0, seg_end)``
    scalars to execute one segment of a longer run exactly (the loop state a
    snapshotting driver carries between compiled calls).

    Fused kernels take the blocked loop (``_simulate_c_block``): K generations
    per flag sync, bit-exact with this per-generation form (pinned by tests).
    Non-fused kernels keep the per-generation loop — their similarity compare
    must stay behind a lax.cond to be paid only on firing generations.
    """
    limit = jnp.int32(config.gen_limit)
    freq = jnp.int32(config.similarity_frequency)
    gen0, counter0, seg_end = resume if resume is not None else (1, 0, limit)
    bound = jnp.minimum(limit, jnp.int32(seg_end))

    if kernel.fused is not None:
        final, gen, counter, alive, similar = _simulate_c_block(
            grid, config, topology, kernel, gen0, counter0, bound,
            block or _TERMINATION_BLOCK,
        )
        stopped = jnp.logical_not(alive) | similar | (gen > limit)
        return final, gen, counter, stopped

    def cond(state):
        _, gen, _, alive, similar = state
        return alive & jnp.logical_not(similar) & (gen <= bound)

    def body(state):
        cur, gen, counter, _, _ = state
        new, alive_local, similar_local = _generation(cur, kernel, topology)
        if config.check_similarity:
            fire = (counter + 1) == freq
            similar = _similarity_vote(fire, cur, new, similar_local, topology)
            counter = jnp.where(fire, 0, counter + 1)
        else:
            similar = jnp.asarray(False)
        alive = collectives.any_flag(alive_local, topology)
        gen = jnp.where(similar, gen, gen + 1)
        return (new, gen, counter, alive, similar)

    alive0 = collectives.any_flag(jnp.any(grid), topology)
    state0 = (grid, jnp.int32(gen0), jnp.int32(counter0), alive0, jnp.asarray(False))
    final, gen, counter, alive, similar = jax.lax.while_loop(cond, body, state0)
    stopped = jnp.logical_not(alive) | similar | (gen > limit)
    # Reported count is gen-1 (src/game.c:202); mid-run segments report the
    # raw resume state instead.
    return final, gen, counter, stopped


def _simulate_cuda_block(grid, config, topology, kernel, gen0, counter0, bound,
                         block):
    """Blocked CUDA-convention loop: K generations per flag sync, bit-exact.

    The CUDA loop's break-before-swap (src/game_cuda.cu:250,266) keeps the
    *pre-step* state on exit, which a fused multi-generation pass has
    overwritten — but the two exits differ in kind. A similarity exit means
    ``state_i == state_{i+1}``: a still life, so every overrun generation is
    identical and the block-end state IS the exit state. Only the empty exit
    keeps a non-fixed-point state (the last non-empty generation), so that
    rare case — at most once per run, in the loop's final block — replays
    ``i`` single generations from that block's start state, which the carry
    passes through so the recovery cond runs once AFTER the while_loop (a
    per-block lax.cond measured ~28% on the whole loop; hoisted it is free).
    Counts replay exactly like the C block.
    """
    K = block
    freq = jnp.int32(config.similarity_frequency)

    def cond(state):
        _, _, _, gen, _, stop, _ = state
        return jnp.logical_not(stop) & (gen < bound)

    def body(state):
        start, _, _, gen, counter, _, _ = state
        t = jnp.minimum(jnp.int32(K), bound - gen)
        cur, a_all, s_all = _block_generations(start, t, config, topology, kernel, K)

        # Scalar replay: flag entry i is (alive, similar) of the *new* grid
        # of CUDA iteration i — exactly what its per-generation checks read
        # (src/game_cuda.cu:238-268). On the stop iteration gen does not
        # advance (break precedes gen++ via the swap skip).
        def replay(i, c):
            gen, counter, stopped, exit_i, exit_empty = c
            ran = jnp.logical_not(stopped) & (i < t)
            sim_i, counter_n = _replay_similarity(
                counter, freq, s_all, i, config.check_similarity
            )
            empty_i = jnp.logical_not(a_all[i])
            stop_i = sim_i | empty_i
            gen = jnp.where(ran & jnp.logical_not(stop_i), gen + 1, gen)
            counter = jnp.where(ran, counter_n, counter)
            newly = ran & stop_i
            exit_i = jnp.where(newly, i, exit_i)
            exit_empty = jnp.where(newly, empty_i & jnp.logical_not(sim_i), exit_empty)
            stopped = stopped | newly
            return gen, counter, stopped, exit_i, exit_empty

        gen, counter, stopped, exit_i, exit_empty = jax.lax.fori_loop(
            0, K, replay,
            (gen, counter, jnp.asarray(False), jnp.int32(0), jnp.asarray(False)),
        )
        # Pass the block-start state through; an empty exit ends the loop,
        # so on exit it is the start of the block holding the exit.
        return (cur, start, exit_i, gen, counter, stopped, exit_empty)

    state0 = (
        grid, grid, jnp.int32(0), jnp.int32(gen0), jnp.int32(counter0),
        jnp.asarray(False), jnp.asarray(False),
    )
    cur, start, exit_i, gen, counter, stopped, exit_empty = jax.lax.while_loop(
        cond, body, state0
    )
    # Empty exit at in-block iteration i keeps state_i (the last non-empty
    # generation): replay i plain generations from the final block's start.
    final = jax.lax.cond(
        stopped & exit_empty,
        lambda: jax.lax.fori_loop(
            0, exit_i, lambda j, g: _generation(g, kernel, topology)[0], start
        ),
        lambda: cur,
    )
    return final, gen, counter, stopped


def _simulate_cuda(grid, config: GameConfig, topology: Topology, kernel: Kernel,
                   resume=None, block: int | None = None):
    """CUDA-variant loop (src/game_cuda.cu:222-276).

    0-based exclusive bound; no emptiness test before the first evolve; the
    emptiness test runs on the new grid and breaks *before* the swap, so an
    empty exit keeps the last non-empty generation; reported count is the raw
    counter. Checks scan the interior only — deliberately not the binary's
    stale-halo padded scan; see gol_tpu.oracle._run_cuda.

    Fused kernels take the blocked loop (``_simulate_cuda_block``), K
    generations per flag sync, bit-exact with this per-generation form.
    """
    limit = jnp.int32(config.gen_limit)
    freq = jnp.int32(config.similarity_frequency)
    gen0, counter0, seg_end = resume if resume is not None else (0, 0, limit)
    bound = jnp.minimum(limit, jnp.int32(seg_end))

    if kernel.fused is not None:
        final, gen, counter, stop = _simulate_cuda_block(
            grid, config, topology, kernel, gen0, counter0, bound,
            block or _TERMINATION_BLOCK,
        )
        return final, gen, counter, stop | (gen >= limit)

    def cond(state):
        _, gen, _, stop = state
        return jnp.logical_not(stop) & (gen < bound)

    def body(state):
        cur, gen, counter, _ = state
        new, alive_local, similar_local = _generation(cur, kernel, topology)
        if config.check_similarity:
            fire = (counter + 1) == freq
            similar = _similarity_vote(fire, cur, new, similar_local, topology)
            counter = jnp.where(fire, 0, counter + 1)
        else:
            similar = jnp.asarray(False)
        empty = jnp.logical_not(collectives.any_flag(alive_local, topology))
        stop = similar | empty
        cur = jnp.where(stop, cur, new)  # break precedes the swap (:250,:266)
        gen = jnp.where(stop, gen, gen + 1)
        return (cur, gen, counter, stop)

    state0 = (grid, jnp.int32(gen0), jnp.int32(counter0), jnp.asarray(False))
    final, gen, counter, stop = jax.lax.while_loop(cond, body, state0)
    stopped = stop | (gen >= limit)
    return final, gen, counter, stopped


_SIMULATORS = {Convention.C: _simulate_c, Convention.CUDA: _simulate_cuda}

# Per-convention: (first generation value, reported count from the final gen).
_GEN_START = {Convention.C: 1, Convention.CUDA: 0}
_REPORT = {Convention.C: lambda gen: gen - 1, Convention.CUDA: lambda gen: gen}


# Canonical absl/XLA status-code prefixes that mark a compile-resource
# failure when they lead a JaxRuntimeError's message (the typed path:
# JAX 0.9 surfaces XLA status codes as the message prefix of
# jax.errors.JaxRuntimeError — pinned against verbatim captured errors in
# tests/test_engine.py::test_compile_failure_real_error_text).
_COMPILE_FAILURE_STATUS = ("RESOURCE_EXHAUSTED:",)

# Substrings that mark a kernel *compile* failure directly: Mosaic
# lowering/VMEM exhaustion, XLA resource errors — as opposed to a user
# error like a wrong-shaped operand. Only compile failures may demote the
# kernel ladder.
_HARD_COMPILE_MARKS = (
    "mosaic",
    "resource_exhausted",
    "resource exhausted",
    "vmem",
    "ran out of memory",
    "out of memory",
    "scoped memory",
)

# The axon attach tunnel routes TPU compilation through a remote helper
# process that wraps Mosaic compile failures in "INTERNAL: ...: HTTP 500:
# tpu_compile_helper subprocess exit code 1" whose body is the helper's
# log, not the Mosaic message (captured verbatim from a real near-cap VMEM
# blowup in benchmarks/vmem_probe_r4.json error_samples). Without these
# marks a demotable compile failure on the tunnel would crash the run.
# When ONLY these marks match (no embedded OOM/Mosaic/status evidence) the
# ladder retries the same entry once before demoting — a transient helper
# outage should not pin the whole run on the ~2x slower kernel (advisor
# r4); a second failure demotes, since a warned slow run still beats an
# abort. See _is_tunnel_wrapper_only.
_TUNNEL_ONLY_MARKS = ("remote_compile", "tpu_compile_helper")

# One list feeds both classifiers: a mark is either hard or tunnel-only,
# never maintained in two places.
_COMPILE_FAILURE_MARKS = (*_HARD_COMPILE_MARKS, *_TUNNEL_ONLY_MARKS)


def _is_compile_failure(err: Exception) -> bool:
    # Typed path first: status-coded runtime errors. Substring matching over
    # the rendered text remains the fallback for exception families that
    # carry no status (Mosaic lowering errors raise plain RuntimeError
    # subclasses with prose messages).
    if isinstance(err, jax.errors.JaxRuntimeError):
        msg = str(err).lstrip()
        if any(msg.startswith(code) for code in _COMPILE_FAILURE_STATUS):
            return True
    text = f"{type(err).__name__}: {err}".lower()
    return any(mark in text for mark in _COMPILE_FAILURE_MARKS)


def _is_tunnel_wrapper_only(err: Exception) -> bool:
    """True when an error classifies as a compile failure ONLY via the
    attach-tunnel helper marks — no status code and no embedded Mosaic/OOM
    text. Such an error may be a transient helper outage rather than a real
    compile failure, so the ladder retries the same entry once before
    demoting (advisor r4; pinned against _REAL_TUNNEL_WRAPPER_ONLY)."""
    if isinstance(err, jax.errors.JaxRuntimeError):
        msg = str(err).lstrip()
        if any(msg.startswith(code) for code in _COMPILE_FAILURE_STATUS):
            return False
    text = f"{type(err).__name__}: {err}".lower()
    if any(mark in text for mark in _HARD_COMPILE_MARKS):
        return False
    return any(mark in text for mark in _TUNNEL_ONLY_MARKS)


class _KernelFallback:
    """A runner that demotes down a kernel ladder if its first compile fails.

    Pallas compiles lazily — at the first call, not at build time — and the
    packed/pallas VMEM caps are v5e-empirical, so another TPU generation can
    Mosaic-OOM a shape inside them. The reference never dies on a supported
    shape (src/game.c:224-245 runs anything malloc can hold); this wrapper
    matches that bar: on a first-call *compile* failure (``
    _is_compile_failure`` — user errors like wrong-shaped operands still
    raise) it logs a warning (the ``gol_tpu.engine`` logger; the CLI routes
    it to stderr) and retries with the next kernel
    (packed -> packed-jnp -> lax). Once any call has succeeded the ladder is
    frozen — later failures are real errors and propagate (a mid-run
    demotion would silently change the measured kernel).

    Multi-process runs never demote: the decision is process-local, and two
    processes settling on different kernels would run different collective
    programs — a distributed deadlock, not a fallback.
    """

    def __init__(self, builders, names, context: str):
        self._builders = list(builders)  # () -> jitted fn, lazy
        self._names = list(names)
        self._context = context
        self._fns = [None] * len(self._builders)
        self._idx = 0
        self._settled = False

    def _fn(self):
        if self._fns[self._idx] is None:
            self._fns[self._idx] = self._builders[self._idx]()
        return self._fns[self._idx]

    @property
    def kernel_name(self) -> str:
        """The currently-selected ladder entry (telemetry/tests)."""
        return self._names[self._idx]

    # Per-ladder-entry retry for tunnel-wrapper-only failures: 2 attempts,
    # no backoff (the remote helper either restarted or it didn't — see
    # _TUNNEL_ONLY_MARKS). The same RetryPolicy machinery wraps tensorstore
    # IO and the multihost create barrier (gol_tpu/resilience/retry.py), so
    # there is exactly one retry implementation in the tree.
    _TUNNEL_RETRY = RetryPolicy(attempts=2, base_delay=0.0)

    def _attempt(self, thunk):
        """Run ``thunk`` against the current ladder entry, demoting on
        compile-shaped failures — the single copy of the ladder policy,
        shared by ``__call__`` and ``compile_aot``."""

        def log_tunnel_retry(attempt, err, _delay):
            # Full error text in the log record (advisor r4): enough to
            # distinguish a real VMEM blowup from an infra outage after the
            # fact — logging handlers, not this site, decide any truncation.
            logger.warning(
                "kernel %r compile failed for %s with only attach-tunnel "
                "helper marks (transient helper outage?); retrying once "
                "before demoting (%s: %s)",
                self._names[self._idx], self._context,
                type(err).__name__, err,
            )

        while True:
            try:
                out = self._TUNNEL_RETRY.call(
                    thunk,
                    retryable=lambda e: (
                        not self._settled and _is_tunnel_wrapper_only(e)
                    ),
                    on_retry=log_tunnel_retry,
                )
            except Exception as err:
                demotable = (
                    not self._settled
                    and self._idx + 1 < len(self._names)
                    and _is_compile_failure(err)
                )
                if demotable and jax.process_count() > 1:
                    logger.error(
                        "kernel %r failed to compile for %s, but this is a "
                        "%d-process run — refusing the process-local "
                        "demotion (peers may have compiled; mixed kernels "
                        "deadlock at the next collective). Pick the "
                        "fallback explicitly on every process.",
                        self._names[self._idx], self._context,
                        jax.process_count(),
                    )
                    raise
                if not demotable:
                    raise
                logger.warning(
                    "kernel %r failed to compile for %s; falling back to "
                    "%r (%s: %s)",
                    self._names[self._idx], self._context,
                    self._names[self._idx + 1], type(err).__name__, err,
                )
                self._idx += 1
                continue
            self._settled = True
            return out

    def __call__(self, *args):
        return self._attempt(lambda: self._fn()(*args))

    def compile_aot(self, *args):
        """AOT-compile down the ladder: ``lower(*args).compile()`` with the
        same demotion rules as ``__call__`` (the CLI compiles before its
        timer, so compile failures must demote HERE, not at first call)."""
        return self._attempt(lambda: self._fn().lower(*args).compile())

    def __getattr__(self, name):
        # .lower()/.trace() etc. delegate to the current jitted fn.
        return getattr(self._fn(), name)


def compile_runner(runner, *args):
    """AOT-compile any runner the factories produce, fallback-aware.

    Plain jitted runners compile strictly; ladder runners demote on compile
    failure exactly as their first call would."""
    with obs_trace.span("engine.compile"):
        if isinstance(runner, _KernelFallback):
            return runner.compile_aot(*args)
        return runner.lower(*args).compile()


def _apply_plan(tuned, kernel_obj, local_h, local_w, topology, packed_state):
    """Resolve a measured plan (gol_tpu/tune) against this build's shape.

    Returns ``(tuned, kernel_obj)`` — the plan dropped (with a loud warning)
    when its kernel cannot serve the shape/lane, the kernel swapped to the
    planned one otherwise. Depth/block application happens at the call
    sites; this only settles *which* kernel the ladder starts from.
    """
    if tuned is None or not tuned.kernel or tuned.kernel == kernel_obj.name:
        return tuned, kernel_obj
    if packed_state and tuned.kernel not in ("packed", "packed-jnp"):
        logger.warning(
            "tuned plan names kernel %r, which cannot carry packed word "
            "state; ignoring the plan", tuned.kernel,
        )
        return None, kernel_obj
    try:
        planned = get_kernel(tuned.kernel)
    except ValueError:
        planned = None
    if planned is None or not planned.supports(local_h, local_w, topology):
        logger.warning(
            "tuned plan names kernel %r, which does not support a %dx%d "
            "shard on a %dx%d topology; ignoring the plan",
            tuned.kernel, local_h, local_w, *topology.shape,
        )
        return None, kernel_obj
    return tuned, planned


def _build_runner(
    shape: tuple[int, int],
    config: GameConfig,
    mesh: Mesh | None,
    kernel: str,
    *,
    segmented: bool,
    packed_state: bool,
    plan=None,
):
    """Shared scaffold of the four runner factories: topology/kernel
    validation, the simulate wrapper, and the shard_map lowering.

    ``packed_state`` runners take/return the (height, width/32) uint32 word
    array and never touch the uint8 grid; otherwise kernels with their own
    carried representation convert once at the loop boundary. ``segmented``
    runners take/return the resume scalars for snapshotting drivers.

    The auto lane and the packed-state lane return a ``_KernelFallback``
    ladder (compile failures demote instead of crashing); an explicitly
    named unpacked kernel stays strict — the caller asked for that kernel
    and a silent demotion would mislabel benchmark numbers.

    ``plan`` is a measured execution plan (``gol_tpu.tune.space.EnginePlan``)
    naming the kernel flavor / temporal depth / termination block / Pallas
    band target to build. The auto-selected lanes (kernel='auto' and the
    packed-state lane) consult the persistent plan cache when no plan is
    passed; an explicitly named unpacked kernel never consults — the caller
    asked for that kernel by name. With no cached plan the consult returns
    None and this builds exactly the pre-tune ladder (test-pinned).
    """
    topology = topology_for(mesh)
    local_h, local_w = validate_grid(shape[0], shape[1], topology)
    tuned = plan
    if tuned is None and (kernel == "auto" or packed_state):
        from gol_tpu.tune import select

        tuned = select.engine_plan(shape, config, mesh,
                                   packed_state=packed_state)
    kernel_obj = resolve_kernel("packed" if packed_state else kernel,
                                local_h, local_w, topology)
    tuned, kernel_obj = _apply_plan(tuned, kernel_obj, local_h, local_w,
                                    topology, packed_state)
    if not kernel_obj.supports(local_h, local_w, topology):
        hint = (
            "packed state has no fallback — use the unpacked lane"
            if packed_state
            else "use kernel='auto' to fall back automatically"
        )
        raise ValueError(
            f"kernel {kernel_obj.name!r} does not support a {local_h}x{local_w} "
            f"local shard on a {topology.shape[0]}x{topology.shape[1]} "
            f"topology; {hint}"
        )
    block = None
    if tuned is not None:
        if tuned.termination_block:
            block = tuned.termination_block
        if tuned.temporal_depth:
            try:
                kernel_obj = with_temporal_depth(kernel_obj, tuned.temporal_depth)
            except ValueError as err:
                logger.warning("tuned plan temporal depth dropped: %s", err)
    if kernel_obj.name in ("packed", "packed-jnp", "pallas"):
        # Unconditional (None clears): the override is process-global and
        # read at trace time, so a plan-less build after a planned one must
        # restore the width-aware default — a stale 2MB target on a shape
        # the default deliberately caps at 1MB reproduces the documented
        # Mosaic compile failure.
        from gol_tpu.ops import stencil_packed

        stencil_packed.set_band_target_override(
            tuned.band_bytes if tuned is not None else None
        )
    simulate = _SIMULATORS[config.convention]
    report = _REPORT[config.convention]

    def jit_for(kobj: Kernel):
        encode = None if packed_state else kobj.encode
        decode = None if packed_state else kobj.decode
        if kobj.fused_multi is not None and not kobj.supports_multi(
            local_h, local_w, topology
        ):
            # The temporally-blocked pass only where the kernel supports it.
            # Both conventions consume it: the C block replays exits from flag
            # vectors (fixed points), the CUDA block additionally recovers the
            # pre-step state on empty exits (_simulate_cuda_block).
            kobj = dataclasses.replace(kobj, fused_multi=None)

        if segmented:

            def local_fn(g, gen0, counter0, seg_end):
                if encode is not None:
                    g = encode(g)
                final, gen, counter, stopped = simulate(
                    g, config, topology, kobj,
                    resume=(gen0, counter0, seg_end), block=block,
                )
                if decode is not None:
                    final = decode(final)
                return final, gen, counter, stopped

            in_specs = (P(*topology.axes), P(), P(), P())
            out_specs = (P(*topology.axes), P(), P(), P())
        else:

            def local_fn(g):
                if encode is not None:
                    g = encode(g)
                final, gen, _, _ = simulate(g, config, topology, kobj, block=block)
                if decode is not None:
                    final = decode(final)
                return final, report(gen)

            in_specs = P(*topology.axes)
            out_specs = (P(*topology.axes), P())

        if topology.distributed:
            fn = shard_map(
                local_fn,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                # vma tracking does not yet thread through pallas_call kernel
                # constants, so the check is off for the Pallas-bearing kernels
                # (the JAX-documented workaround) but kept for the lax path.
                check_vma=kobj.name == "lax",
            )
        else:
            fn = local_fn
        if segmented:
            # Donate the carried state: segment N's output buffer is written
            # in place over segment N's input (the reference's double-buffer
            # pointer swap, realized as an input/output alias), eliminating
            # the per-segment copy. jit_compat gates this on backends that
            # implement donation (CPU would warn per call and ignore it).
            # Callers treat the state argument as CONSUMED — the zero-step
            # warm calls rebind (`state, *_ = runner(state, ...)`), and the
            # checkpoint lane snapshots to host BEFORE the next dispatch
            # (pipeline/snapshot.py), so no donated buffer is ever re-read.
            return jit_donating(fn, donate_argnums=(0,))
        return jax.jit(fn)

    if kernel != "auto" and not packed_state:
        return jit_for(kernel_obj)
    chain = fallback_chain(kernel_obj, local_h, local_w, topology,
                           packed_state=packed_state)
    if len(chain) == 1:
        return jit_for(chain[0])
    return _KernelFallback(
        [functools.partial(jit_for, k) for k in chain],
        [k.name for k in chain],
        context=(
            f"a {local_h}x{local_w} shard on a "
            f"{topology.shape[0]}x{topology.shape[1]} topology"
        ),
    )


@functools.lru_cache(maxsize=64)
def make_runner(
    shape: tuple[int, int],
    config: GameConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
    kernel: str = "auto",
):
    """Compile a ``global_grid -> (global_grid, generations)`` runner.

    With a mesh, the runner is a ``shard_map`` over ('row', 'col') — the
    topology/bootstrap step the reference does with MPI_Init + MPI_Cart_create
    (src/game_mpi_collective.c:116-133) happens here, at trace time.

    The lru_cache key includes the Mesh, which is safe by value: Mesh defines
    __eq__/__hash__ over the device grid + axis names, so two
    separately-constructed equal meshes hit the same cache entry — pinned by
    tests/test_engine.py::test_runner_cache_equal_meshes.
    """
    return _build_runner(shape, config, mesh, kernel,
                         segmented=False, packed_state=False)


@functools.lru_cache(maxsize=64)
def make_segment_runner(
    shape: tuple[int, int],
    config: GameConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
    kernel: str = "auto",
):
    """Compile a resumable segment: ``(grid, gen0, counter0, seg_end) ->
    (grid, gen, counter, stopped)``.

    Running segments back-to-back with the carried (gen, counter) state is
    bit-exact with one whole-run while_loop — the basis for periodic
    snapshots, which the reference lacks entirely (SURVEY.md §5
    checkpoint/resume: its only resume path is that the output file is a
    valid input file).

    DONATION CONTRACT (TPU/GPU): the runner donates its state argument
    (ops/jit_compat.py) — every call CONSUMES the passed array and the
    caller must rebind to the returned one (``state, *_ = runner(state,
    ...)``; a zero-step call returns the carry unchanged, the warm idiom).
    On CPU the runner is a plain jit and old references stay valid, so
    misuse only surfaces on accelerators.
    """
    return _build_runner(shape, config, mesh, kernel,
                         segmented=True, packed_state=False)


@functools.lru_cache(maxsize=64)
def make_packed_runner(
    shape: tuple[int, int],
    config: GameConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
):
    """Compile a runner over bitpacked state: ``words -> (words, generations)``.

    ``shape`` is the logical (height, width) grid shape; the operand is its
    (height, width/32) uint32 word array (io/packed_io.py reads/writes those
    directly, so the uint8 grid never exists anywhere).
    """
    return _build_runner(shape, config, mesh, "packed",
                         segmented=False, packed_state=True)


@functools.lru_cache(maxsize=64)
def make_packed_segment_runner(
    shape: tuple[int, int],
    config: GameConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
):
    """Compile a resumable segment over bitpacked word state.

    The packed analog of ``make_segment_runner``: ``(words, gen0, counter0,
    seg_end) -> (words, gen, counter, stopped)``; composing the packed-I/O
    lane with snapshots keeps the output-is-valid-input resume property
    (src/game.c:25-40 vs :154-165) at scales where only the packed lane is
    practical. ``make_segment_runner``'s donation contract applies: on
    TPU/GPU every call consumes its word-state argument.
    """
    return _build_runner(shape, config, mesh, "packed",
                         segmented=True, packed_state=True)


def resume_scalars(config: GameConfig, completed: int) -> tuple[int, int]:
    """Loop scalars ``(gen0, counter0)`` for resuming after ``completed``
    generations of a run that had not early-exited.

    Both conventions increment the similarity counter once per executed
    generation and reset it on every ``similarity_frequency``-th, so mid-run
    (non-exited) state needs no sidecar metadata: ``counter = completed mod
    frequency`` — a snapshot file plus its generation count is a complete
    checkpoint. (Early-exited runs are finished; there is nothing to resume.)
    """
    if completed < 0:
        raise ValueError(f"completed generations must be >= 0, got {completed}")
    counter = completed % config.similarity_frequency if config.check_similarity else 0
    return _GEN_START[config.convention] + completed, counter


def _iter_segments(runner, state, config: GameConfig, segment: int, completed: int = 0):
    """Drive a segment runner to completion, yielding after every segment."""
    if segment <= 0:
        raise ValueError(f"segment must be positive, got {segment}")
    report = _REPORT[config.convention]
    gen, counter = resume_scalars(config, completed)
    while True:
        seg_end = gen + segment - (1 if config.convention == Convention.C else 0)
        with obs_trace.span("engine.segment", gen0=gen, seg_end=seg_end):
            prev = gen
            state, gen_a, counter_a, stopped_a = runner(
                state, jnp.int32(gen), jnp.int32(counter), jnp.int32(seg_end)
            )
            # int() blocks until the segment finishes, so the span's duration
            # is device time, not enqueue time.
            gen, counter, stopped = int(gen_a), int(counter_a), bool(stopped_a)
        reg = obs_registry.default()
        reg.inc("engine_segments_total")
        reg.inc("engine_generations_total", max(0, gen - prev))
        yield report(gen), state, stopped
        if stopped:
            return


def simulate_segments(
    grid,
    config: GameConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
    kernel: str = "auto",
    segment: int = 100,
    completed: int = 0,
):
    """Generator of ``(generations_so_far, device_grid, stopped)`` per segment.

    Semantically identical to one ``simulate`` call (same final grid and
    reported count) but yields control to the host every ``segment``
    generations so callers can snapshot, log, or abort. The similarity
    counter is carried across segments, so exits fire on exactly the same
    generations as the unsegmented loop.

    ``completed`` resumes: the grid is taken to be the state after that many
    generations of a longer run (a snapshot), and the loop continues to
    ``config.gen_limit`` with the similarity phase realigned
    (``resume_scalars``) — yielded counts and exits match the uninterrupted
    run exactly.

    DONATION CONTRACT (TPU/GPU): the segment runner donates its carried
    state, so the passed-in device array and each yielded state are
    CONSUMED when the generator advances past that yield. Read/copy a
    yielded state (or write a snapshot from it) BEFORE resuming iteration,
    and do not reuse ``grid`` afterwards — the checkpoint lane's host
    snapshot (gol_tpu/pipeline/snapshot.py) exists for exactly this. On
    CPU (no donation) stale references happen to stay valid; do not rely
    on that.
    """
    shape = tuple(np.shape(grid))
    runner = make_segment_runner(shape, config, mesh, kernel)
    device_grid = grid if isinstance(grid, jax.Array) else put_grid(grid, mesh)
    yield from _iter_segments(runner, device_grid, config, segment, completed)


def simulate_packed_segments(
    words,
    shape: tuple[int, int],
    config: GameConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
    segment: int = 100,
    completed: int = 0,
):
    """Packed-state counterpart of ``simulate_segments``.

    ``shape`` is the logical (height, width); ``words`` its (height,
    width/32) uint32 array (from io/packed_io.read_packed). Yields the word
    state, which every consumer writes back through packed_io — the uint8
    grid never exists. The ``simulate_segments`` donation contract applies
    verbatim: on TPU/GPU, ``words`` and each yielded state are consumed
    when the generator advances.
    """
    runner = make_packed_segment_runner(shape, config, mesh)
    yield from _iter_segments(runner, words, config, segment, completed)


def put_grid(grid, mesh: Mesh | None = None) -> jax.Array:
    """Place a host grid onto the device(s) with the engine's sharding."""
    arr = np.ascontiguousarray(np.asarray(grid, dtype=np.uint8))
    if mesh is None:
        return jnp.asarray(arr)
    return jax.device_put(arr, grid_sharding(mesh))


def simulate(
    grid,
    config: GameConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
    kernel: str = "auto",
) -> EngineResult:
    """Run a full simulation and fetch the result to the host."""
    shape = tuple(np.shape(grid))
    validate_grid(shape[0], shape[1], topology_for(mesh))
    device_grid = grid if isinstance(grid, jax.Array) else put_grid(grid, mesh)
    runner = make_runner(shape, config, mesh, kernel)
    with obs_trace.span("engine.simulate", shape=f"{shape[0]}x{shape[1]}",
                        convention=config.convention):
        final, gen = runner(device_grid)
        generations = int(gen)  # blocks: the span measures the run, not enqueue
    reg = obs_registry.default()
    reg.inc("engine_runs_total")
    reg.inc("engine_generations_total", generations)
    return EngineResult(np.asarray(jax.device_get(final), dtype=np.uint8),
                        generations)


# ---------------------------------------------------------------------------
# Batched multi-board engine (the gol_tpu/serve/ subsystem's compute entry).
#
# Every lane above runs ONE board per compiled call — the reference's
# main()-per-run shape. A serving workload is many independent small boards,
# where per-call dispatch and per-op thunk overhead dominate the arithmetic;
# stacking B boards into one program amortizes both (the persistent-setup
# argument of the stencil-communication papers, applied to dispatch). The
# loop carries per-board scalar vectors (gen, counter, alive, similar) and a
# per-board active mask, so boards that exit early freeze — grid, counters,
# exit reason all land exactly where the solo loop would leave them — while
# the batch keeps stepping until the last live board stops. Early-exit
# freezing is cheap for the same reason the blocked solo loops are exact:
# both early exits are fixed points of the evolve.
#
# Three compiled step flavors, chosen statically per bucket:
#   "packed" — boards exactly fill the canvas and the width packs: vmapped
#              bit-sliced word evolve (32 cells/word, the fast path);
#   "byte"   — boards exactly fill the canvas: vmapped byte roll stencil;
#   "masked" — boards smaller than the canvas: a gather-based torus step
#              that wraps at each board's own (h, w) inside the shared
#              padded canvas, so one program serves mixed shapes.
# ---------------------------------------------------------------------------

BATCH_MODES = ("packed", "byte", "masked")


def _evolve_batch_masked(cur, heights, widths):
    """One generation of B independent tori living in one (B, PH, PW) canvas.

    Board b occupies ``cur[b, :h, :w]``; padding cells are zero and must stay
    zero (the masked rule re-zeroes them every step). The wrap is realized by
    per-board index gathers ``(i +/- 1) mod h`` — rows/cols at or past the
    board edge gather garbage, but every consumed index is taken mod the true
    extent, so interior counts are exactly the h x w torus counts.
    """
    ph, pw = cur.shape[1], cur.shape[2]
    r = jnp.arange(ph)
    c = jnp.arange(pw)

    def one(g, h, w):
        up = jnp.take(g, jnp.mod(r - 1, h), axis=0)
        down = jnp.take(g, jnp.mod(r + 1, h), axis=0)
        rows3 = up + g + down  # vertical triple sum, <= 3 fits uint8
        west = jnp.take(rows3, jnp.mod(c - 1, w), axis=1)
        east = jnp.take(rows3, jnp.mod(c + 1, w), axis=1)
        n = west + rows3 + east - g  # 3x3 block sum minus center
        new = (n == 3) | ((n == 2) & (g == 1))
        mask = (r[:, None] < h) & (c[None, :] < w)
        return (new & mask).astype(jnp.uint8)

    return jax.vmap(one)(cur, heights, widths)


def _temporal_body(substep, depth: int):
    """The while-loop body of a batched simulator at temporal depth T.

    ``substep`` is one generation of the per-generation form, with per-board
    freeze masking already applied (stopped boards are fixed under it — the
    masking holds their grid and scalars). Depth 1 returns ``substep``
    itself, so the traced program is byte-for-byte the pre-temporal one
    (test-pinned). Depth T > 1 runs T masked sub-generations per while
    iteration via a fori_loop — the batched analog of the solo engine's
    ``ops.with_temporal_depth`` — which is bit-exact at ANY depth because
    every sub-generation applies the same masking the per-generation loop
    does; only the while cond (the batch's one cross-board reduction per
    iteration) fires T times less often.
    """
    if depth == 1:
        return substep
    return lambda state: jax.lax.fori_loop(
        0, depth, lambda _i, s: substep(s), state
    )


def _batch_simulate_c(state0, limits, freq, check_sim, evolve, alive_of, equal,
                      depth: int = 1):
    """Batched C-convention loop: per-board replica of ``_simulate_c``'s
    per-generation form, masked so stopped boards freeze (oracle._run_c is
    the semantics contract; exactness vs solo runs is test-pinned).
    ``depth`` generations run per while iteration (``_temporal_body``)."""
    b = limits.shape[0]
    expand = (b,) + (1,) * (state0.ndim - 1)

    def run_mask(state):
        _, gen, _, alive, similar = state
        return alive & jnp.logical_not(similar) & (gen <= limits)

    def cond(state):
        return jnp.any(run_mask(state))

    def substep(state):
        cur, gen, counter, alive, similar = state
        run = run_mask(state)
        new = evolve(cur)
        alive_n = alive_of(new)
        if check_sim:
            # The O(canvas) compare only runs on generations where some
            # active board's counter fires (every freq-th; single-device, so
            # a data-dependent cond is safe — no collectives to desync).
            fire = (counter + 1) == freq
            eq = jax.lax.cond(
                jnp.any(run & fire),
                lambda: equal(cur, new),
                lambda: jnp.zeros_like(similar),
            )
            sim_n = fire & eq
            counter_n = jnp.where(fire, 0, counter + 1)
        else:
            sim_n = jnp.zeros_like(similar)
            counter_n = counter
        gen_n = jnp.where(sim_n, gen, gen + 1)
        # Full-canvas freeze masking only once some board has stopped; while
        # every board is live (the common phase) the swap is free.
        cur = jax.lax.cond(
            jnp.all(run),
            lambda: new,
            lambda: jnp.where(run.reshape(expand), new, cur),
        )
        gen = jnp.where(run, gen_n, gen)
        counter = jnp.where(run, counter_n, counter)
        alive = jnp.where(run, alive_n, alive)
        similar = jnp.where(run, sim_n, similar)
        return (cur, gen, counter, alive, similar)

    body = _temporal_body(substep, depth)
    zeros = jnp.zeros((b,), jnp.int32)
    state = (state0, zeros + 1, zeros, alive_of(state0), jnp.zeros((b,), bool))
    final, gen, _counter, alive, similar = jax.lax.while_loop(cond, body, state)
    reason = jnp.where(
        similar,
        EXIT_SIMILAR,
        jnp.where(jnp.logical_not(alive), EXIT_EMPTY, EXIT_GEN_LIMIT),
    ).astype(jnp.int32)
    return final, gen - 1, reason  # reported count is gen-1 (src/game.c:202)


def _batch_simulate_cuda(state0, limits, freq, check_sim, evolve, alive_of,
                         equal, depth: int = 1):
    """Batched CUDA-convention loop (per-board ``_simulate_cuda`` semantics:
    0-based exclusive bound, emptiness tested on the NEW grid, break before
    the swap so an empty exit keeps the last non-empty generation).
    ``depth`` generations run per while iteration (``_temporal_body``)."""
    b = limits.shape[0]
    expand = (b,) + (1,) * (state0.ndim - 1)

    def run_mask(state):
        _, gen, _, stop, _ = state
        return jnp.logical_not(stop) & (gen < limits)

    def cond(state):
        return jnp.any(run_mask(state))

    def substep(state):
        cur, gen, counter, stop, reason = state
        run = run_mask(state)
        new = evolve(cur)
        if check_sim:
            fire = (counter + 1) == freq
            eq = jax.lax.cond(
                jnp.any(run & fire),
                lambda: equal(cur, new),
                lambda: jnp.zeros((b,), bool),
            )
            sim_n = fire & eq
            counter_n = jnp.where(fire, 0, counter + 1)
        else:
            sim_n = jnp.zeros((b,), bool)
            counter_n = counter
        empty_n = jnp.logical_not(alive_of(new))
        stop_i = sim_n | empty_n
        # break precedes the swap (src/game_cuda.cu:250,:266)
        advance = run & jnp.logical_not(stop_i)
        cur = jax.lax.cond(
            jnp.all(advance),
            lambda: new,
            lambda: jnp.where(advance.reshape(expand), new, cur),
        )
        gen = jnp.where(advance, gen + 1, gen)
        counter = jnp.where(run, counter_n, counter)
        newly = run & stop_i
        # Similarity is checked before emptiness (src/game_cuda.cu:238-259).
        reason = jnp.where(
            newly, jnp.where(sim_n, EXIT_SIMILAR, EXIT_EMPTY), reason
        )
        stop = stop | newly
        return (cur, gen, counter, stop, reason)

    body = _temporal_body(substep, depth)
    zeros = jnp.zeros((b,), jnp.int32)
    state = (
        state0, zeros, zeros, jnp.zeros((b,), bool),
        jnp.full((b,), EXIT_GEN_LIMIT, jnp.int32),
    )
    final, gen, _counter, _stop, reason = jax.lax.while_loop(cond, body, state)
    return final, gen, reason  # reported count is the raw counter


_BATCH_SIMULATORS = {
    Convention.C: _batch_simulate_c,
    Convention.CUDA: _batch_simulate_cuda,
}


def _validate_batch_params(padded_shape, batch: int, mode: str,
                           convention: str, temporal_depth: int) -> None:
    """The ONE validation surface of the batched/ring runner factories —
    a program the batch lane rejects must be impossible to build as a
    ring, and vice versa."""
    if batch < 1:
        raise ValueError(f"batch must be >= 1, got {batch}")
    if mode not in BATCH_MODES:
        raise ValueError(f"unknown batch mode {mode!r}; one of {BATCH_MODES}")
    if mode == "packed" and padded_shape[1] % 32 != 0:
        raise ValueError(
            f"packed batch mode needs width % 32 == 0, got {padded_shape[1]}"
        )
    if convention not in _BATCH_SIMULATORS:
        raise ValueError(f"unknown convention: {convention!r}")
    if not 1 <= temporal_depth <= 64:
        raise ValueError(
            f"temporal_depth must be in [1, 64], got {temporal_depth}"
        )


def _batch_evolve(mode: str, heights, widths):
    """The per-mode one-generation step over a (B, ...) board stack —
    shared by the per-batch runner and the resident ring runner so both
    compile the identical evolve (byte-identity across lanes follows from
    every op being integer/bitwise)."""
    from gol_tpu.ops import packed_math, stencil_lax

    if mode == "packed":
        return jax.vmap(packed_math.evolve_torus_words)
    if mode == "byte":
        return jax.vmap(stencil_lax.evolve_torus)
    return lambda cur: _evolve_batch_masked(cur, heights, widths)


def _batch_alive_of(s):
    return jnp.any(s != 0, axis=tuple(range(1, s.ndim)))


def _batch_equal(a, b):
    return jnp.all(a == b, axis=tuple(range(1, a.ndim)))


def resolve_batch_mode(
    heights, widths, padded_shape: tuple[int, int]
) -> str:
    """Pick the step flavor for a set of boards sharing one padded canvas."""
    import sys

    ph, pw = padded_shape
    if any(h > ph or w > pw for h, w in zip(heights, widths)):
        raise ValueError(
            f"board exceeds the {ph}x{pw} padded canvas: "
            f"{list(zip(heights, widths))}"
        )
    if all(h == ph and w == pw for h, w in zip(heights, widths)):
        # The packed lane's host-side bit packing assumes a little-endian
        # host (bit j of a word = column 32w+j via np.packbits + uint32
        # view); big-endian hosts take the byte lane instead of silently
        # scrambling columns.
        return (
            "packed" if pw % 32 == 0 and sys.byteorder == "little" else "byte"
        )
    return "masked"


def _pack_board_words(stacked: np.ndarray) -> np.ndarray:
    """(B, H, W) uint8 cells -> (B, H, W/32) uint32 words on the host.

    The bit convention (bit j of word w = column 32w+j, matching
    ops/packed_math.encode) lives ONCE in ``io/bitpack.py`` — shared with
    the result cache's packed payload lane so the two can never drift.
    """
    from gol_tpu.io import bitpack

    return bitpack.pack_words(stacked)


def _unpack_board_words(words: np.ndarray) -> np.ndarray:
    """Inverse of ``_pack_board_words``: words -> (B, H, W) uint8 cells."""
    from gol_tpu.io import bitpack

    return bitpack.unpack_words(words)


@functools.lru_cache(maxsize=256)
def make_batch_runner(
    padded_shape: tuple[int, int],
    batch: int,
    convention: str = Convention.C,
    check_similarity: bool = True,
    similarity_frequency: int = DEFAULT_CONFIG.similarity_frequency,
    mode: str = "masked",
    temporal_depth: int = 1,
):
    """Compile a B-board runner: ``(boards, heights, widths, limits) ->
    (finals, generations, exit_reasons)``.

    ``boards`` is (B, PH, PW) uint8 with dead padding — except in "packed"
    mode, where the operand (and the returned state) is the host-packed
    (B, PH, PW/32) uint32 word array (``_pack_board_words``), so the
    transfer is 32x smaller and no encode/decode rides in the program.
    ``heights``/``widths`` give each board's true extent ((B,) int32 —
    consumed only by the masked mode, but always part of the signature so
    every mode shares one calling convention); ``limits`` is each board's
    OWN generation bound, a dynamic operand — jobs with different
    --gen-limit share the compiled program (unlike the solo runners, where
    the limit is baked into the trace).

    ``temporal_depth`` is the batched analog of the solo engine's
    deep-halo grouping (``ops.with_temporal_depth``): T masked generations
    run per while iteration, bit-exact at any T (``_temporal_body``) — a
    pure performance knob, searched by ``gol tune --serve-board``.

    Single-device by design: serving batches many small boards per chip;
    sharding one small board over a mesh is the opposite trade.
    """
    ph, pw = padded_shape
    _validate_batch_params(padded_shape, batch, mode, convention,
                           temporal_depth)
    simulate_fn = _BATCH_SIMULATORS[convention]
    freq = jnp.int32(similarity_frequency)

    def fn(boards, heights, widths, limits):
        return simulate_fn(
            boards, limits, freq, check_similarity,
            _batch_evolve(mode, heights, widths),
            _batch_alive_of, _batch_equal, depth=temporal_depth,
        )

    # Donate the board canvas: the final grids are written over the input
    # slots (same shape/dtype), halving the program's peak canvas footprint.
    # Every caller stages operands fresh per dispatch (stage_batch keeps the
    # HOST copy for retries), so no donated buffer is ever reused.
    return jit_donating(fn, donate_argnums=(0,))


@dataclasses.dataclass
class StagedBatch:
    """Host-side operands of one batch, ready to dispatch.

    The staging product of the pipelined serve path (gol_tpu/pipeline): all
    CPU work — stacking, zero-padding, ``packbits`` — is done, nothing
    has touched the device. The HOST operand arrays are retained here so an
    idempotent retry can re-dispatch without re-staging (and because the
    compiled program donates its device operand buffer)."""

    runner: Any
    operand: np.ndarray  # (total, PH, PW) uint8, or packed (total, PH, PW/32)
    h_arr: np.ndarray
    w_arr: np.ndarray
    limits: np.ndarray
    heights: list
    widths: list
    mode: str
    padded_shape: tuple[int, int]
    boards: int  # real board count (<= total)
    total: int  # padded batch slots the program runs
    # Loop parameters the compiled program baked in — carried so a resident
    # ring (stage_ring) can build the matching R-slot program without
    # re-deriving them from the configs.
    convention: str = Convention.C
    check_similarity: bool = True
    similarity_frequency: int = DEFAULT_CONFIG.similarity_frequency
    temporal_depth: int = 1


@dataclasses.dataclass
class InflightBatch:
    """One dispatched batch: device result futures + the staging it came
    from. JAX's async dispatch returns immediately — the device computes
    while the host goes on to stage the next batch; ``complete_batch``
    blocks on readback."""

    staged: StagedBatch
    finals: Any  # device arrays (unresolved futures until fetched)
    gens: Any
    reasons: Any


def stage_batch(
    boards,
    configs,
    padded_shape: tuple[int, int] | None = None,
    pad_batch_to: int | None = None,
    temporal_depth: int = 1,
    packed_boards=None,
) -> StagedBatch | None:
    """Host staging for ``simulate_batch``: validate, stack, pad, pack.

    Returns None for an empty board list. Pure host work — safe to run on a
    pipeline thread while the device computes a previous batch. Packing
    happens exactly once per staging (``engine_stage_packs_total`` counts
    the ``packbits`` passes; the retry paths re-dispatch from the
    retained staging, so the counter proves zero re-packs on retry).

    ``packed_boards`` (aligned with ``boards``; entries are each board's
    pre-packed (H, W/32) word array — a packed wire submit's retained
    payload — or None) is the zero-re-pack lane: when the batch resolves
    to the packed kernel and EVERY board carries words, the operand is
    assembled from them directly — no cell canvas is materialized and no
    ``packbits`` pass runs, byte-identically to packing the stacked
    cells (packed mode is exact-fit, and the wire payload IS the staging
    layout). Any board without words falls the whole batch back to the
    classic stack-and-pack path."""
    boards = [np.ascontiguousarray(np.asarray(b, dtype=np.uint8)) for b in boards]
    if not boards:
        return None
    if isinstance(configs, GameConfig):
        configs = [configs] * len(boards)
    configs = list(configs)
    if len(configs) != len(boards):
        raise ValueError(
            f"{len(boards)} boards but {len(configs)} configs"
        )
    head = configs[0]
    for c in configs[1:]:
        if (
            c.convention != head.convention
            or c.check_similarity != head.check_similarity
            or c.similarity_frequency != head.similarity_frequency
        ):
            raise ValueError(
                "boards in one batch must share convention and similarity "
                "settings (only gen_limit may vary); split into buckets"
            )
    heights = [b.shape[0] for b in boards]
    widths = [b.shape[1] for b in boards]
    if padded_shape is None:
        padded_shape = (max(heights), max(widths))
    mode = resolve_batch_mode(heights, widths, padded_shape)
    b = len(boards)
    total = max(b, pad_batch_to or b)
    ph, pw = padded_shape
    h_arr = np.ones((total,), np.int32)
    w_arr = np.ones((total,), np.int32)
    h_arr[:b] = heights
    w_arr[:b] = widths
    # Padding slots: zero boards with limit 0 never run in either convention.
    limits = np.zeros((total,), np.int32)
    limits[:b] = [c.gen_limit for c in configs]
    runner = make_batch_runner(
        padded_shape, total, head.convention,
        head.check_similarity, head.similarity_frequency, mode,
        temporal_depth,
    )
    words = None
    if (
        mode == "packed"
        and packed_boards is not None
        and len(packed_boards) == b
        and all(w is not None for w in packed_boards)
    ):
        words = np.zeros((total, ph, pw // 32), np.uint32)
        for i, w in enumerate(packed_boards):
            w = np.ascontiguousarray(np.asarray(w, dtype=np.uint32))
            if w.shape != (ph, pw // 32):
                raise ValueError(
                    f"packed board {i} has word shape {w.shape}; the "
                    f"{ph}x{pw} packed canvas needs ({ph}, {pw // 32})"
                )
            words[i] = w
    if mode == "packed" and words is not None:
        # The zero-re-pack lane: no cell canvas, no np.packbits pass —
        # engine_stage_packs_total deliberately NOT incremented, so the
        # counter's drop is the visible signal packed submits bypass the
        # staging tax.
        operand = words
    else:
        stacked = np.zeros((total, ph, pw), np.uint8)
        for i, board in enumerate(boards):
            stacked[i, : heights[i], : widths[i]] = board
        if mode == "packed":
            operand = _pack_board_words(stacked)
            obs_registry.default().inc("engine_stage_packs_total")
        else:
            operand = stacked
    return StagedBatch(
        runner=runner, operand=operand, h_arr=h_arr, w_arr=w_arr,
        limits=limits, heights=heights, widths=widths, mode=mode,
        padded_shape=padded_shape, boards=b, total=total,
        convention=head.convention,
        check_similarity=head.check_similarity,
        similarity_frequency=head.similarity_frequency,
        temporal_depth=temporal_depth,
    )


def dispatch_batch(staged: StagedBatch) -> InflightBatch:
    """Dispatch a staged batch; returns WITHOUT blocking on the result.

    The device operand is built fresh from the retained host arrays (the
    compiled program donates it), so dispatching the same staging twice —
    the retry path — is safe and idempotent."""
    finals, gens, reasons = staged.runner(
        jnp.asarray(staged.operand), jnp.asarray(staged.h_arr),
        jnp.asarray(staged.w_arr), jnp.asarray(staged.limits),
    )
    return InflightBatch(staged=staged, finals=finals, gens=gens,
                         reasons=reasons)


def _collect_board_results(staged: StagedBatch, finals, gens, reasons
                           ) -> list[BatchBoardResult]:
    """Crop one batch's fetched device results back into per-board slices
    (shared by ``complete_batch`` and ``complete_ring``)."""
    finals = np.asarray(finals)
    final_words = None
    if staged.mode == "packed":
        # Keep the device word layout: packed mode is exact-fit, so each
        # board's slice of the word canvas IS its packed result — retained
        # on the BatchBoardResult so a packed wire response or CAS payload
        # never re-packs what the device already computed in this layout.
        final_words = finals
        finals = _unpack_board_words(finals)
    finals = np.asarray(finals, dtype=np.uint8)
    gens = np.asarray(gens)
    reasons = np.asarray(reasons)
    b = staged.boards
    reg = obs_registry.default()
    reg.inc("engine_batches_total")
    reg.inc("engine_boards_total", b)
    reg.inc("engine_generations_total", int(gens[:b].sum()))
    return [
        BatchBoardResult(
            grid=finals[i, : staged.heights[i], : staged.widths[i]].copy(),
            generations=int(gens[i]),
            exit_reason=EXIT_REASONS[int(reasons[i])],
            words=(
                np.asarray(final_words[i], dtype=np.uint32).copy()
                if final_words is not None else None
            ),
        )
        for i in range(b)
    ]


def complete_batch(inflight: InflightBatch) -> list[BatchBoardResult]:
    """Block on an in-flight batch's results and crop per-board slices."""
    return _collect_board_results(
        inflight.staged,
        jax.device_get(inflight.finals),
        jax.device_get(inflight.gens),
        jax.device_get(inflight.reasons),
    )


def simulate_batch(
    boards,
    configs,
    padded_shape: tuple[int, int] | None = None,
    pad_batch_to: int | None = None,
    temporal_depth: int = 1,
) -> list[BatchBoardResult]:
    """Run many independent boards in ONE compiled program.

    ``boards`` is a sequence of (h, w) uint8 arrays; ``configs`` one
    ``GameConfig`` shared by all boards or a sequence of per-board configs.
    All configs must agree on convention/similarity settings (those are baked
    into the compiled program); ``gen_limit`` may differ per board (it is a
    dynamic operand). Boards are zero-padded into a shared ``padded_shape``
    canvas (default: the max extent over the batch) and, when
    ``pad_batch_to`` exceeds the board count, inert zero boards fill the
    remaining batch slots so a handful of request sizes reuse one compiled
    program.

    Internally this is ``stage_batch`` -> ``dispatch_batch`` ->
    ``complete_batch`` back to back; the pipelined serve scheduler
    (gol_tpu/serve/scheduler.py at ``pipeline_depth`` >= 2) calls the three
    stages from different threads so the device computes batch N while the
    host stages N+1 and journals N-1.

    Each returned (grid, generations, exit_reason) is bit-identical to a solo
    ``simulate`` run of the same board (test-pinned for both conventions,
    including boards that exit early inside a still-running batch).
    """
    staged = stage_batch(boards, configs, padded_shape, pad_batch_to,
                         temporal_depth)
    if staged is None:
        return []
    ph, pw = staged.padded_shape
    with obs_trace.span("engine.simulate_batch", boards=staged.boards,
                        slots=staged.total, canvas=f"{ph}x{pw}",
                        mode=staged.mode):
        return complete_batch(dispatch_batch(staged))


# ---------------------------------------------------------------------------
# Resident ring engine (the gol_tpu/serve/resident.py compute entry).
#
# The batch runner above still pays one Python jit dispatch — claim, operand
# transfer, program launch, scalar sync — per batch; at serving batch sizes
# that host tax is the gap between the marginal kernel rate and the
# end-to-end rate. The ring runner folds R staged batches into ONE compiled
# program: R slots, each running the full batched while_loop, every slot's
# output aliased over its input buffer (donation across the ring — the
# reference's double-buffer swap, R times over). The host refills slots with
# async device_put while an earlier drain computes and dispatches the next
# drain behind it on the device stream, so the device never waits on
# per-batch Python — the persistent, pre-planned dispatch the stencil
# communication literature argues for, realized as XLA programs. Unfilled
# slots carry zero boards with generation limit 0: their while loops exit
# before the first iteration, so a partially filled drain costs its filled
# slots only.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=128)
def make_ring_runner(
    padded_shape: tuple[int, int],
    batch: int,
    ring: int,
    convention: str = Convention.C,
    check_similarity: bool = True,
    similarity_frequency: int = DEFAULT_CONFIG.similarity_frequency,
    mode: str = "masked",
    temporal_depth: int = 1,
):
    """Compile an R-slot resident drain: ``(slot_0..slot_{R-1}, heights,
    widths, limits) -> ((final_0..final_{R-1}), generations, exit_reasons)``.

    Each slot is one batch-runner operand ((B, PH, PW) uint8, or the packed
    (B, PH, PW/32) uint32 words); ``heights``/``widths``/``limits`` are
    (R, B) int32. Every slot argument is DONATED — slot i's final boards
    are written in place over its input buffer, eliminating the per-batch
    output allocation ring-wide. Per-slot results are bit-identical to the
    per-batch runner's (same evolve, same loop, integer ops only) — pinned
    by tests/test_megabatch.py.
    """
    if ring < 1:
        raise ValueError(f"ring must be >= 1, got {ring}")
    _validate_batch_params(padded_shape, batch, mode, convention,
                           temporal_depth)
    simulate_fn = _BATCH_SIMULATORS[convention]
    freq = jnp.int32(similarity_frequency)

    def fn(*operands):
        slots = operands[:ring]
        heights, widths, limits = operands[ring:]
        finals, gens, reasons = [], [], []
        for r in range(ring):
            f, g, why = simulate_fn(
                slots[r], limits[r], freq, check_similarity,
                _batch_evolve(mode, heights[r], widths[r]),
                _batch_alive_of, _batch_equal, depth=temporal_depth,
            )
            finals.append(f)
            gens.append(g)
            reasons.append(why)
        return tuple(finals), jnp.stack(gens), jnp.stack(reasons)

    return jit_donating(fn, donate_argnums=tuple(range(ring)))


@dataclasses.dataclass
class StagedRing:
    """Up to ``ring`` staged batches bound to one resident drain program."""

    runner: Any
    staged: list  # StagedBatch per FILLED slot, in slot order
    ring: int


@dataclasses.dataclass
class InflightRing:
    """One dispatched ring drain: device futures for every slot."""

    staged_ring: StagedRing
    finals: Any  # tuple of R device arrays (futures)
    gens: Any  # (R, B)
    reasons: Any  # (R, B)


def stage_ring(staged_batches: list, ring: int) -> StagedRing:
    """Bind staged batches (same bucket geometry) to the R-slot program."""
    if not staged_batches:
        raise ValueError("cannot stage an empty ring")
    if len(staged_batches) > ring:
        raise ValueError(
            f"{len(staged_batches)} staged batches exceed the ring of {ring}"
        )
    head = staged_batches[0]
    for s in staged_batches[1:]:
        if (
            s.padded_shape != head.padded_shape
            or s.total != head.total
            or s.mode != head.mode
            or s.convention != head.convention
            or s.check_similarity != head.check_similarity
            or s.similarity_frequency != head.similarity_frequency
            or s.temporal_depth != head.temporal_depth
        ):
            raise ValueError(
                "staged batches in one ring must share the bucket geometry "
                "(canvas, batch rung, mode, convention, similarity, depth)"
            )
    runner = make_ring_runner(
        head.padded_shape, head.total, ring, head.convention,
        head.check_similarity, head.similarity_frequency, head.mode,
        head.temporal_depth,
    )
    return StagedRing(runner=runner, staged=list(staged_batches), ring=ring)


def _zero_slot(head: StagedBatch):
    """An inert slot operand: zero boards (with limit 0 they never run)."""
    return jnp.zeros(head.operand.shape, head.operand.dtype)


def dispatch_ring(sr: StagedRing, device_slots: list | None = None
                  ) -> InflightRing:
    """Dispatch a staged ring; returns WITHOUT blocking on any result.

    ``device_slots`` are per-slot device arrays a caller already uploaded
    (the resident lane's refill-while-the-loop-runs path: ``device_put`` at
    submit time overlaps the transfer with the previous drain's compute);
    absent, the retained host operands transfer here — which is also the
    idempotent retry path, since the donated device buffers of a failed
    drain are consumed but the host staging is retained."""
    head = sr.staged[0]
    filled = len(sr.staged)
    slots = []
    for i in range(sr.ring):
        if i < filled:
            dev = device_slots[i] if device_slots is not None else None
            slots.append(dev if dev is not None
                         else jnp.asarray(sr.staged[i].operand))
        else:
            slots.append(_zero_slot(head))
    total = head.total
    h = np.ones((sr.ring, total), np.int32)
    w = np.ones((sr.ring, total), np.int32)
    limits = np.zeros((sr.ring, total), np.int32)
    for i, s in enumerate(sr.staged):
        h[i] = s.h_arr
        w[i] = s.w_arr
        limits[i] = s.limits
    finals, gens, reasons = sr.runner(
        *slots, jnp.asarray(h), jnp.asarray(w), jnp.asarray(limits)
    )
    return InflightRing(staged_ring=sr, finals=finals, gens=gens,
                        reasons=reasons)


def complete_ring(inflight: InflightRing) -> list[list[BatchBoardResult]]:
    """Block on a drain's results; one ``BatchBoardResult`` list per filled
    slot, in slot order (each list bit-identical to ``complete_batch`` of
    the same staged batch)."""
    sr = inflight.staged_ring
    gens = np.asarray(jax.device_get(inflight.gens))
    reasons = np.asarray(jax.device_get(inflight.reasons))
    out = []
    for i, staged in enumerate(sr.staged):
        out.append(_collect_board_results(
            staged, jax.device_get(inflight.finals[i]), gens[i], reasons[i],
        ))
    return out


# ---------------------------------------------------------------------------
# Sparse tile-step runner (the gol_tpu/sparse/ compute entry).
#
# The engines above are dense: every lane pays O(width x height) per
# generation even when the universe is 99.9% dead. The sparse engine
# decomposes the board into fixed tiles with a live-occupancy index
# (gol_tpu/sparse/board.py) and simulates only live tiles plus their
# halo-activated neighbors; what the device runs per generation is this
# runner — one generation of B halo-extended tiles, batched up the same
# padding-bucket ladder the serve batcher uses (tiles ARE a bucket: the
# tile shape is fixed, the batch dimension rounds up the ladder, so a tile
# size compiles at most one program per rung — the <=7-compiled-programs
# invariant — and the operand buffer is donated like every batch lane).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=64)
def make_tile_step_runner(tile: int, batch: int):
    """Compile a B-tile halo step: ``(B, tile+2, tile+2) uint8 blocks ->
    (interiors (B, tile, tile), alive (B,), changed (B,))``.

    One generation per call by design — the halo ring must be re-exchanged
    (host-side, from the occupancy index) between generations, exactly the
    per-step halo exchange of the distributed lanes, at tile granularity.
    Convention-independent: the loop accounting (C vs CUDA, similarity
    phase, exits) lives entirely in the sparse host loop; a tile step is
    the same pure function under every convention, which is also what
    makes it memoizable (gol_tpu/sparse/memo.py).
    """
    if tile < 4:
        raise ValueError(f"tile must be >= 4, got {tile}")
    if batch < 1:
        raise ValueError(f"batch must be >= 1, got {batch}")
    from gol_tpu.ops import stencil_lax

    def fn(blocks):
        return stencil_lax.evolve_padded_batch(blocks)

    # Donate the halo blocks: the interiors are written over the operand's
    # pages and every caller stages blocks fresh per dispatch.
    return jit_donating(fn, donate_argnums=(0,))
