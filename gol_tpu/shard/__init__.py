"""Sharded single-job engine: one giant universe spanning N workers.

The sparse engine's tile grid is partitioned by rendezvous hashing
(``partition``), each worker advances its owned tiles through the exact
solo kernel path (``worker`` -> sparse.engine.step_tiles), boundary rings
cross the fleet as packed GOLP frames per super-step (``halo``), and a
leader-only coordinator lane in the router drives the barriers,
checkpoints, recovery, and elastic rebalance (``coordinator``) — the
distributed-memory half of the reference's ``game_mpi.c``, rebuilt on the
fleet's own wire, placement, and durability contracts.
"""
