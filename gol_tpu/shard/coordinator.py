"""Super-step coordination for the sharded single-job engine.

The coordinator is the control plane of one sharded job: it lives in the
ROUTER process (leader-only — fleet/router.py runs it behind the PR-16
flock lease, so a failed-over router never drives two copies of one job)
and replays the sparse engine's convention loops (_run_c/_run_cuda)
verbatim, with one twist: the per-generation step is a fleet-wide
super-step barrier instead of a local ``_step`` call.

Per super-step k, every worker — concurrently, one RPC each — sends its
boundary rings to its halo peers, blocks until every peer's frame for k
arrived, and advances its owned tiles through the exact solo kernel path.
The coordinator aggregates ``changed_any`` (OR) and the live-tile count
(sum) and feeds them into the SAME accounting the solo engine pins:
generation numbering, similarity counters, and all three exit reasons are
derived from the super-step count alone, so a sharded run's
(cells, generations, exit_reason) triple is byte-identical to one
worker's — the property tests/test_shard.py gates at N in {2, 3}.

Fault model: every ``checkpoint_every`` super-steps, all workers journal
their slice to their OWN partition's shard log and the coordinator
advances its durable floor only after EVERY ack. A SIGKILLed worker is
respawned by the fleet on the same partition; recovery restores it from
its own log at the floor (ONLY its shard replays), rewinds the survivors
in memory, and re-runs from the floor — super-steps are deterministic, so
the replayed timeline is the abandoned one, byte for byte.

Membership is elastic at checkpoint barriers: the HRW partition means a
grown worker set moves only the tiles the new worker wins (shard/
partition.moved_tiles), shipped as packed tile frames by their previous
owners.

Jax-free: this module runs in the router front-end.
"""

from __future__ import annotations

import threading
import time
import urllib.error
from concurrent.futures import ThreadPoolExecutor

from gol_tpu.config import Convention, GameConfig
from gol_tpu.fleet import client
from gol_tpu.shard.partition import Partition
from gol_tpu.shard.worker import PeerUnreachable, ShardHost
from gol_tpu.sparse.board import SparseBoard
from gol_tpu.sparse.engine import EXIT_EMPTY, EXIT_GEN_LIMIT, EXIT_SIMILAR

DEFAULT_CHECKPOINT_EVERY = 8
RECOVER_TIMEOUT = 120.0
PROBE_INTERVAL = 0.25


class ShardPeerDown(RuntimeError):
    """A worker did not answer (connection-level, 503, or barrier abort):
    the coordinator's cue to run recovery from the durable floor."""

    def __init__(self, worker_id: str, detail: str):
        super().__init__(f"shard worker {worker_id} down: {detail}")
        self.worker_id = worker_id


class ShardProtocolError(RuntimeError):
    """A worker answered with a non-retryable rejection (HTTP 4xx that is
    not a recovery abort): the job fails rather than retries."""


class LocalParticipant:
    """A worker reached by direct method call — the in-process test and
    LocalCluster substrate. Same surface as HttpParticipant.

    ``host_getter`` is consulted on EVERY call (mirroring the URL lookup
    of the HTTP path): a killed host resolves to None — ShardPeerDown —
    until its respawn installs a fresh one on the same journal dir."""

    def __init__(self, worker_id: str, host_getter):
        self.id = worker_id
        if isinstance(host_getter, ShardHost):
            host = host_getter
            host_getter = lambda: host  # noqa: E731 — fixed-host shorthand
        self._host_getter = host_getter
        self.job = None  # set by the coordinator at init

    def url(self) -> str:
        return f"local://{self.id}"

    def _host(self) -> ShardHost:
        host = self._host_getter()
        if host is None:
            raise ShardPeerDown(self.id, "process is down")
        return host

    def _guard(self, fn, *args):
        try:
            return fn(*args)
        except PeerUnreachable as e:
            raise ShardPeerDown(e.peer, str(e)) from e
        except ValueError as e:
            if "aborted for recovery" in str(e):
                raise ShardPeerDown(self.id, str(e)) from e
            raise ShardProtocolError(f"worker {self.id}: {e}") from e

    def init(self, payload: dict) -> dict:
        return self._guard(self._host().init_job, payload)

    def step(self, k: int) -> dict:
        return self._guard(self._host().step_job, self.job, k)

    def checkpoint(self, k: int) -> dict:
        return self._guard(self._host().checkpoint, self.job, k)

    def rewind(self, k: int, peers: dict) -> dict:
        return self._guard(self._host().rewind, self.job, k, peers)

    def restore(self, payload: dict) -> dict:
        return self._guard(self._host().restore_job, payload)

    def status(self) -> dict:
        return self._guard(self._host().status, self.job)

    def rebalance(self, payload: dict) -> dict:
        return self._guard(self._host().rebalance, payload)

    def collect(self, which: str) -> dict:
        return self._guard(self._host().collect, self.job, which)

    def finish(self) -> dict:
        return self._guard(self._host().finish, self.job)


class HttpParticipant:
    """A worker reached over HTTP through fleet/client.py — breakers,
    deadline budgets, and the chaos proxy apply exactly as they do to the
    serve tier's forward hop.

    ``url_getter`` is consulted on EVERY call: a respawned worker answers
    on a new port, and the fleet's membership record is the source of
    truth for where a partition currently lives."""

    def __init__(self, worker_id: str, url_getter, http=client.http_json):
        self.id = worker_id
        self._url_getter = url_getter
        self._http = http
        self.job = None  # set by the coordinator at init

    def url(self) -> str:
        url = self._url_getter()
        if not url:
            raise ShardPeerDown(self.id, "no URL on record (respawning?)")
        return url

    def _post(self, path: str, payload: dict, timeout: float = 120.0):
        url = self.url().rstrip("/") + "/shard/" + path
        try:
            status, body = self._http("POST", url, payload, timeout=timeout)
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise ShardPeerDown(self.id, f"{type(e).__name__}: {e}") from e
        if status == 503:
            raise ShardPeerDown(self.id, str(body)[:200])
        if status >= 400:
            detail = body.get("error", body) if isinstance(body, dict) \
                else body
            if "aborted for recovery" in str(detail):
                # Our own rewind interrupted this step's barrier: the
                # straggler RPC resolving during recovery, not a failure.
                raise ShardPeerDown(self.id, "step aborted for recovery")
            raise ShardProtocolError(
                f"worker {self.id} rejected /shard/{path}: "
                f"HTTP {status} {str(detail)[:300]}"
            )
        return body

    def init(self, payload: dict) -> dict:
        return self._post("init", payload)

    def step(self, k: int) -> dict:
        return self._post("step", {"job": self.job, "step": k})

    def checkpoint(self, k: int) -> dict:
        return self._post("checkpoint", {"job": self.job, "step": k})

    def rewind(self, k: int, peers: dict) -> dict:
        return self._post("rewind",
                          {"job": self.job, "step": k, "peers": peers})

    def restore(self, payload: dict) -> dict:
        return self._post("restore", payload)

    def status(self) -> dict:
        return self._post("status", {"job": self.job}, timeout=10.0)

    def rebalance(self, payload: dict) -> dict:
        return self._post("rebalance", payload, timeout=300.0)

    def collect(self, which: str) -> dict:
        return self._post("collect", {"job": self.job, "which": which},
                          timeout=300.0)

    def finish(self) -> dict:
        return self._post("done", {"job": self.job})


class ShardCoordinator:
    """Drives one sharded job over a set of participants.

    ``spec`` is the job document: rle, x, y, width, height, tile, plus the
    GameConfig fields (convention, gen_limit, check_similarity,
    similarity_frequency). ``membership`` is an optional zero-arg callable
    returning the CURRENT eligible participant list; consulted at
    checkpoint barriers only — the autoscaler's grow-mid-job hook."""

    def __init__(self, job_id: str, spec: dict, participants,
                 *, checkpoint_every: int = DEFAULT_CHECKPOINT_EVERY,
                 registry=None, membership=None,
                 recover_timeout: float = RECOVER_TIMEOUT,
                 probe_interval: float = PROBE_INTERVAL):
        if not participants:
            raise ValueError("a sharded job needs at least one worker")
        self.job_id = job_id
        self.spec = dict(spec)
        self.config = GameConfig(
            gen_limit=int(spec.get("gen_limit", GameConfig.gen_limit)),
            check_similarity=bool(spec.get(
                "check_similarity", GameConfig.check_similarity)),
            similarity_frequency=int(spec.get(
                "similarity_frequency", GameConfig.similarity_frequency)),
            convention=spec.get("convention", Convention.C),
        )
        self.participants = list(participants)
        for p in self.participants:
            p.job = job_id
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.registry = registry
        self.membership = membership
        self.recover_timeout = recover_timeout
        self.probe_interval = probe_interval
        self.k = 0  # completed super-steps
        self.durable = 0  # last super-step checkpointed by EVERY worker
        self.live = 0  # fleet-wide live-tile count
        self.supersteps = 0  # super-steps executed, replays included
        self.recoveries = 0
        self.rebalances = 0
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, len(self.participants) + 2),
            thread_name_prefix=f"gol-shard-{job_id[:8]}")

    # -- plumbing ----------------------------------------------------------

    def _obs(self, fn, *args):
        if self.registry is not None:
            getattr(self.registry, fn)(*args)

    def _ids(self):
        return [p.id for p in self.participants]

    def _peer_urls(self) -> dict:
        return {p.id: p.url() for p in self.participants}

    def _payload(self, p, *, blank: bool = False, step: int = 0) -> dict:
        body = {
            "job": self.job_id, "spec": self.spec, "self": p.id,
            "workers": self._ids(), "peers": self._peer_urls(),
        }
        if blank:
            body["blank"] = True
        if step:
            body["step"] = step
        return body

    def _all(self, fn_name, *args):
        """One RPC per participant, concurrently; replies in participant
        order. The first ShardPeerDown wins; stragglers are drained (a
        recovery rewind aborts any step still blocked on its barrier)."""
        futures = [
            self._pool.submit(getattr(p, fn_name), *args)
            for p in self.participants
        ]
        replies, down = [], None
        for fut in futures:
            try:
                replies.append(fut.result())
            except ShardPeerDown as e:
                down = down or e
                replies.append(None)
        if down is not None:
            raise down
        return replies

    def _gauge_ownership(self, counts: dict) -> None:
        for wid, n in counts.items():
            self._obs("set_gauge", f"shard_tiles_owned_{wid}", n)

    # -- lifecycle ---------------------------------------------------------

    def _init(self) -> None:
        futures = [
            self._pool.submit(p.init, self._payload(p))
            for p in self.participants
        ]
        replies = [f.result() for f in futures]
        self.live = sum(r["live"] for r in replies)
        self._obs("inc", "shard_jobs_total")
        self._obs("set_gauge", "shard_workers", len(self.participants))

    def _step_all(self, k: int) -> tuple[bool, int]:
        t0 = time.perf_counter()
        replies = self._all("step", k)
        self._obs("observe", "shard_superstep_seconds",
                  time.perf_counter() - t0)
        self.supersteps += 1
        changed = any(r["changed"] for r in replies)
        live = sum(r["live"] for r in replies)
        return changed, live

    def _checkpoint_all(self, k: int) -> None:
        self._all("checkpoint", k)
        self.durable = k
        self._obs("set_gauge", "shard_durable_step", k)

    def _recover(self) -> None:
        """All workers back to the durable floor. A worker that lost its
        process restores from its own shard log (only ITS shard replays);
        survivors rewind in memory. Loops until the whole set answers —
        the fleet's health tick is respawning the dead partition
        meanwhile — then the run loop re-executes from the floor."""
        self.recoveries += 1
        self._obs("inc", "shard_recoveries_total")
        deadline = time.perf_counter() + self.recover_timeout
        while True:
            try:
                peers = self._peer_urls()
                replies = []
                for p in self.participants:
                    if p.status().get("known"):
                        replies.append(p.rewind(self.durable, peers))
                    else:
                        replies.append(p.restore(
                            self._payload(p, step=self.durable)))
                self.k = self.durable
                self.live = sum(r["live"] for r in replies)
                return
            except ShardPeerDown:
                if time.perf_counter() > deadline:
                    raise
                time.sleep(self.probe_interval)

    def _maybe_rebalance(self) -> None:
        """At a checkpoint barrier (k == durable): adopt a changed worker
        set. Joiners init BLANK at the current step under the new
        partition; every old participant then pushes exactly its moved-out
        tiles to the new owners (HRW-minimal) and departing workers drop
        the job; finally the NEW set checkpoints so the floor covers the
        new ownership map."""
        if self.membership is None:
            return
        new = self.membership()
        if new is None:
            return
        new = list(new)
        if [p.id for p in new] == self._ids() or not new:
            return
        for p in new:
            p.job = self.job_id
        old = self.participants
        old_by_id = {p.id: p for p in old}
        new_ids = [p.id for p in new]
        peers = {p.id: p.url() for p in new}
        joiners = [p for p in new if p.id not in old_by_id]
        for p in joiners:
            body = self._payload(p, blank=True, step=self.k)
            body["workers"] = new_ids
            body["peers"] = peers
            p.init(body)
        moved = 0
        for p in old:
            reply = p.rebalance({
                "job": self.job_id, "workers": new_ids, "peers": peers,
                "step": self.k,
            })
            moved += int(reply.get("moved", 0))
        self.participants = [old_by_id.get(p.id, p) for p in new]
        self._checkpoint_all(self.k)
        self.rebalances += 1
        self._obs("inc", "shard_rebalances_total")
        self._obs("inc", "shard_rebalanced_tiles_total", moved)
        self._obs("set_gauge", "shard_workers", len(self.participants))

    def _barrier(self) -> None:
        """The periodic durability + elasticity point."""
        if self.k % self.checkpoint_every == 0:
            self._checkpoint_all(self.k)
            self._maybe_rebalance()

    # -- the run loop ------------------------------------------------------

    def run(self) -> dict:
        """Execute the job to completion; returns the result document.
        Convention accounting tracks engine._run_c/_run_cuda line for
        line, with ``generation`` derived from the super-step count so a
        recovery rewind re-derives the loop state exactly."""
        try:
            self._init()
            if self.config.convention == Convention.CUDA:
                return self._drive(self._loop_cuda)
            return self._drive(self._loop_c)
        finally:
            self._pool.shutdown(wait=False)

    def _drive(self, loop) -> dict:
        while True:
            try:
                return loop()
            except ShardPeerDown:
                self._recover()

    def _sim_counter(self) -> int:
        # The similarity counter is k mod frequency on any timeline that
        # reached k without a similar exit — what makes it re-derivable
        # after a rewind.
        return self.k % self.config.similarity_frequency

    def _loop_c(self) -> dict:
        cfg = self.config
        counter = self._sim_counter()
        # Loop-top invariant: generation == k + 1 (engine._run_c).
        while self.live > 0 and self.k < cfg.gen_limit:
            changed, live = self._step_all(self.k)
            self.k += 1
            if cfg.check_similarity:
                counter += 1
                if counter == cfg.similarity_frequency:
                    if not changed:
                        return self._finalize("current", self.k - 1,
                                              EXIT_SIMILAR)
                    counter = 0
            self.live = live
            self._barrier()
        reason = EXIT_GEN_LIMIT if self.live else EXIT_EMPTY
        return self._finalize("current", self.k, reason)

    def _loop_cuda(self) -> dict:
        cfg = self.config
        counter = self._sim_counter()
        # Loop-top invariant: generation == k (engine._run_cuda); the
        # breaks precede the swap, so both exits collect the PRE-step
        # board every worker kept as ``prev``.
        while self.k < cfg.gen_limit:
            changed, live = self._step_all(self.k)
            if cfg.check_similarity:
                counter += 1
                if counter == cfg.similarity_frequency:
                    if not changed:
                        return self._finalize("prev", self.k, EXIT_SIMILAR)
                    counter = 0
            if live == 0:
                return self._finalize("prev", self.k, EXIT_EMPTY)
            self.live = live
            self.k += 1
            self._barrier()
        return self._finalize("current", self.k, EXIT_GEN_LIMIT)

    # -- results -----------------------------------------------------------

    def _finish_one(self, p) -> None:
        """Once the merged board is in hand, a dropped finish ack must NOT
        escalate to recovery: the worker may have already appended its done
        record ("frame landed, ack lost"), and replaying the tail from the
        durable floor would finalize a second time — a duplicate done in
        the exactly-once audit. Retry this participant alone; the worker
        side is idempotent, so a resent finish lands as a no-op ack."""
        deadline = time.perf_counter() + self.recover_timeout
        while True:
            try:
                p.finish()
                return
            except ShardPeerDown:
                if time.perf_counter() > deadline:
                    raise
                time.sleep(self.probe_interval)

    def _finalize(self, which: str, generations: int, reason: str) -> dict:
        replies = self._all("collect", which)
        height = int(self.spec["height"])
        width = int(self.spec["width"])
        tile = int(self.spec.get("tile") or 0)
        merged = SparseBoard(height, width, tile) if tile else \
            SparseBoard(height, width)
        stats = {"tiles_active": 0, "tiles_computed": 0, "memo_hits": 0}
        for reply in replies:
            part = SparseBoard.from_rle(
                reply["rle"], height=height, width=width,
                tile=merged.tile)
            for coord, arr in part.tiles.items():
                merged.set_tile(coord, arr)
            _gens, active, computed, hits = reply["stats"]
            stats["tiles_active"] += int(active)
            stats["tiles_computed"] += int(computed)
            stats["memo_hits"] += int(hits)
        partition = Partition(self._ids(), merged.tiles_y, merged.tiles_x)
        ownership = partition.counts(merged.tiles)
        self._gauge_ownership(ownership)
        for p in self.participants:
            self._finish_one(p)
        t = merged.tile
        return {
            "rle": merged.to_rle(),
            "generations": int(generations),
            "exit_reason": reason,
            "population": merged.population(),
            "live_tiles": len(merged.tiles),
            "tiles_active": stats["tiles_active"],
            "tiles_computed": stats["tiles_computed"],
            "memo_hits": stats["memo_hits"],
            "cell_updates": stats["tiles_active"] * t * t,
            "supersteps": self.supersteps,
            "recoveries": self.recoveries,
            "rebalances": self.rebalances,
            "workers": self._ids(),
            "ownership": ownership,
        }


class LocalCluster:
    """N in-process ShardHosts wired into one halo fabric over ``local://``
    URLs — the unit-test and doc-example substrate: every protocol leg
    (init, halo frames as real GOLP bytes, barriers, checkpoints, kill/
    restore) runs exactly as over HTTP, minus the sockets."""

    def __init__(self, worker_ids, journal_root: str | None = None,
                 fault=None):
        self.ids = [str(w) for w in worker_ids]
        self.fault = fault  # optional hook(url, raw) -> raises to inject
        self.hosts: dict[str, ShardHost | None] = {}
        self.journal_dirs: dict[str, str | None] = {}
        self._lock = threading.Lock()
        for wid in self.ids:
            jdir = f"{journal_root}/{wid}" if journal_root else None
            self.journal_dirs[wid] = jdir
            self.hosts[wid] = ShardHost(
                journal_dir=jdir, http_exchange=self._exchange)

    def _exchange(self, method, url, body=None, *, raw=None, timeout=30.0,
                  headers=None, content_type=None):
        """Loopback transport for worker->worker frames: routes
        ``local://<wid>/shard/<leg>`` to the target host in process."""
        assert url.startswith("local://"), url
        rest = url[len("local://"):]
        wid, _, path = rest.partition("/")
        if self.fault is not None:
            self.fault(url, raw)
        with self._lock:
            host = self.hosts.get(wid)
        if host is None:
            raise ConnectionError(f"worker {wid} is down")
        import json as _json
        try:
            if path == "shard/halo":
                reply = host.halo_in(raw)
            elif path == "shard/adopt":
                reply = host.adopt(raw)
            else:
                raise AssertionError(f"unexpected loopback leg {path}")
        except ValueError as e:
            return 400, "application/json", _json.dumps(
                {"error": str(e)}).encode()
        return 200, "application/json", _json.dumps(reply).encode()

    def participants(self, ids=None):
        return [
            LocalParticipant(wid, (lambda w=wid: self.hosts.get(w)))
            for wid in (ids or self.ids)
        ]

    def add(self, wid: str, journal_root: str | None = None) -> None:
        """Grow the cluster (the autoscaler analog): a fresh host the
        membership hook can hand to the coordinator as a joiner."""
        wid = str(wid)
        with self._lock:
            if wid in self.ids:
                raise ValueError(f"worker {wid} already exists")
            jdir = f"{journal_root}/{wid}" if journal_root else None
            self.ids.append(wid)
            self.journal_dirs[wid] = jdir
            self.hosts[wid] = ShardHost(
                journal_dir=jdir, http_exchange=self._exchange)

    def kill(self, wid: str) -> None:
        """SIGKILL analog: the host object (all in-memory state) is
        dropped; the shard log on disk survives."""
        with self._lock:
            self.hosts[wid] = None

    def respawn(self, wid: str) -> ShardHost:
        """A fresh process on the same journal partition."""
        with self._lock:
            host = ShardHost(journal_dir=self.journal_dirs[wid],
                             http_exchange=self._exchange)
            self.hosts[wid] = host
        return host
