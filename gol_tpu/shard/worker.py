"""Worker-side engine of the sharded single-job lane.

A ``ShardHost`` lives inside each ``gol serve`` process (serve/server.py
mounts it under ``POST /shard/*``) and holds the per-job shard state: the
owned slice of the universe (a SparseBoard carrying ONLY the tiles this
worker wins under the HRW partition), the tile memo, and the super-step
counter. The protocol, driven by the router's coordinator lane:

- ``init``     — build the owned slice straight from the job's RLE via the
                 tile-filtered streaming path (SparseBoard.from_rle with
                 ``owned`` — a worker owning one slice of a 2^20-square
                 document never materializes the rest), and journal the
                 step-0 checkpoint.
- ``halo``     — a peer's ring frame for step k lands in the inbox
                 (idempotent on (step, sender): a retried frame carries
                 identical bytes, so re-delivery overwrites harmlessly —
                 the exactly-once-EFFECT rule of the halo hop).
- ``step``     — send this worker's boundary rings to every peer, block
                 until every peer's frame for this step arrived (safe on
                 the threading server: each worker steps on its own
                 handler thread), then advance the owned tiles one
                 generation through the exact solo kernel path
                 (engine.step_tiles: same memo, same batch ladder, same
                 compiled tile programs). The pre-step board is retained
                 one step — the CUDA convention's empty/similar exits
                 return it.
- ``checkpoint/rewind/restore`` — super-step checkpoints land in a
                 dedicated fsync'd append-log in this worker's journal
                 partition (``shard-<job>.jsonl``; deliberately NOT the
                 job journal — jobs.JobJournal treats unknown record kinds
                 as torn lines on replay). A SIGKILLed worker replays ONLY
                 its own shard from its own log; survivors rewind in
                 memory.
- ``rebalance/adopt`` — elastic membership change at a checkpoint
                 barrier: each worker ships exactly its moved-out tiles
                 (HRW-minimal) to their new owners as packed tile frames
                 and adopts the new partition.
- ``collect/finish`` — the owned slice out as RLE; the terminal ``done``
                 audit record.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.error

from gol_tpu.fleet import client
from gol_tpu.io import wire
from gol_tpu.obs import registry as obs_registry
from gol_tpu.shard import halo
from gol_tpu.shard.partition import Partition
from gol_tpu.sparse.board import DEFAULT_TILE, SparseBoard
from gol_tpu.sparse.engine import SparseStats, step_tiles
from gol_tpu.sparse.memo import TileMemo

# How long a super-step blocks for peer halo frames before giving up.
# Generous on purpose: the coordinator's barrier means a slow peer is
# usually a dead peer mid-respawn, and the coordinator aborts the step
# fleet-wide long before this fires — the timeout only prevents a handler
# thread from blocking forever when the coordinator itself died.
BARRIER_TIMEOUT = 300.0


class ShardError(ValueError):
    """A protocol-level client error (unknown job, wrong step, malformed
    frame): maps to HTTP 400."""


class PeerUnreachable(RuntimeError):
    """A halo send exhausted its retry budget: maps to HTTP 503 naming the
    peer, the coordinator's cue to run recovery."""

    def __init__(self, peer: str, detail: str):
        super().__init__(
            f"halo peer {peer} unreachable after retries: {detail}"
        )
        self.peer = peer


class _ShardJob:
    """One job's shard state on one worker."""

    def __init__(self, job, spec, self_id, part, peers, log_path):
        self.job = job
        self.spec = spec
        self.self_id = self_id
        self.partition = part
        self.owned = part.owns(self_id)
        self.peers = dict(peers)
        self.log_path = log_path
        self.board: SparseBoard | None = None
        self.prev: SparseBoard | None = None
        self.memo = TileMemo()
        self.stats = SparseStats()
        self.step = 0  # completed super-steps
        self.cond = threading.Condition()
        self.gate = threading.Lock()  # serializes step vs rewind
        self.abort = False  # set by rewind to unblock a stuck barrier
        self.inbox: dict[tuple[int, str], dict] = {}
        self.last_reply: dict | None = None
        self.ckpt_step = 0
        self.ckpt_board: SparseBoard | None = None
        self.ckpt_stats: tuple | None = None

    def stats_tuple(self):
        s = self.stats
        return (s.generations, s.tiles_active, s.tiles_computed, s.memo_hits)

    def load_stats(self, tup):
        (self.stats.generations, self.stats.tiles_active,
         self.stats.tiles_computed, self.stats.memo_hits) = (
            int(v) for v in tup)


def _board_from_spec(spec: dict, owned) -> SparseBoard:
    return SparseBoard.from_rle(
        spec["rle"],
        height=int(spec["height"]),
        width=int(spec["width"]),
        tile=int(spec.get("tile") or DEFAULT_TILE),
        x=int(spec.get("x", 0)),
        y=int(spec.get("y", 0)),
        owned=owned,
    )


class ShardHost:
    """All shard jobs resident on one worker process."""

    def __init__(self, journal_dir: str | None = None,
                 http_exchange=client.http_exchange,
                 send_retries: int = 4,
                 barrier_timeout: float = BARRIER_TIMEOUT):
        self.journal_dir = journal_dir
        self.http_exchange = http_exchange
        self.send_retries = send_retries
        self.barrier_timeout = barrier_timeout
        self.jobs: dict[str, _ShardJob] = {}
        self.finished: set[str] = set()
        self.lock = threading.Lock()

    # -- plumbing ----------------------------------------------------------

    def _get(self, job) -> _ShardJob:
        st = self.jobs.get(str(job))
        if st is None:
            raise ShardError(f"unknown shard job {job}")
        return st

    def _log_path(self, job: str) -> str | None:
        if self.journal_dir is None:
            return None
        return os.path.join(self.journal_dir, f"shard-{job}.jsonl")

    def _append(self, st: _ShardJob, record: dict) -> None:
        """Durable append to the shard log: the record is on disk (fsync)
        before the caller acks — a barrier the coordinator advances its
        durable floor on must survive a SIGKILL one instruction later."""
        if st.log_path is None:
            return
        os.makedirs(os.path.dirname(st.log_path), exist_ok=True)
        with open(st.log_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(record, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def _scan_ckpt(self, log_path: str, step: int) -> dict | None:
        """The LAST checkpoint record for ``step`` in a shard log (replay
        tolerates torn tails exactly like the job journal: a partial final
        line is skipped, never fatal)."""
        found = None
        try:
            with open(log_path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail (SIGKILL mid-append)
                    if rec.get("kind") == "ckpt" \
                            and int(rec.get("step", -1)) == step:
                        found = rec
        except OSError:
            return None
        return found

    def _build(self, body: dict) -> _ShardJob:
        job = str(body["job"])
        spec = dict(body["spec"])
        required = ("width", "height") if body.get("blank") \
            else ("rle", "width", "height")
        for field in required:
            if field not in spec:
                raise ShardError(f"shard spec missing {field!r}")
        self_id = str(body["self"])
        workers = [str(w) for w in body["workers"]]
        if self_id not in workers:
            raise ShardError(f"self {self_id!r} not in workers {workers}")
        tile = int(spec.get("tile") or DEFAULT_TILE)
        spec["tile"] = tile
        part = Partition.for_universe(
            workers, int(spec["height"]), int(spec["width"]), tile)
        peers = {str(k): str(v) for k, v in (body.get("peers") or {}).items()
                 if str(k) != self_id}
        return _ShardJob(job, spec, self_id, part, peers,
                         self._log_path(job))

    # -- protocol ----------------------------------------------------------

    def init_job(self, body: dict) -> dict:
        """POST /shard/init: build the owned slice, journal checkpoint 0.

        With ``"blank": true`` the slice starts EMPTY at super-step
        ``body["step"]`` — the elastic-join path: a worker added mid-job
        owns tiles under the new partition but receives their contents
        from the previous owners' rebalance pushes, never from the
        step-0 document."""
        st = self._build(body)
        if st.job in self.jobs:
            # Idempotent re-init (a coordinator retry after a lost ack):
            # same spec, same answer.
            return self._init_reply(self.jobs[st.job])
        if body.get("blank"):
            st.board = SparseBoard(int(st.spec["height"]),
                                   int(st.spec["width"]),
                                   int(st.spec["tile"]))
            st.step = int(body.get("step", 0))
        else:
            st.board = _board_from_spec(st.spec, st.owned)
        st.ckpt_step = st.step
        st.ckpt_board = st.board
        st.ckpt_stats = st.stats_tuple()
        self._append(st, {
            "kind": "ckpt", "job": st.job, "step": st.step,
            "rle": st.board.to_rle(), "stats": st.stats_tuple(),
        })
        with self.lock:
            self.jobs[st.job] = st
        obs_registry.default().inc("shard_jobs_hosted_total")
        return self._init_reply(st)

    def _init_reply(self, st: _ShardJob) -> dict:
        return {"job": st.job, "step": st.step,
                "live": len(st.board.tiles),
                "population": st.board.population()}

    def halo_in(self, raw: bytes) -> dict:
        """POST /shard/halo (packed): a peer's rings for one step."""
        meta, rings = halo.decode(raw)
        st = self._get(meta["job"])
        key = (int(meta["step"]), str(meta["from"]))
        with st.cond:
            st.inbox[key] = rings
            st.cond.notify_all()
        reg = obs_registry.default()
        reg.inc("shard_halo_frames_total")
        reg.inc("shard_halo_bytes_total", len(raw))
        return {"job": st.job, "step": key[0], "tiles": len(rings)}

    def _send_halo(self, st: _ShardJob, peer: str, raw: bytes) -> None:
        """One peer's frame out, with a bounded resend budget. A CRC 400
        from the receiver (the chaos proxy corrupting mid-frame) resends
        the same bytes; receiver-side idempotency on (step, sender) makes
        the retry exactly-once in effect."""
        url = st.peers[peer].rstrip("/") + "/shard/halo"
        detail = "no attempt"
        for attempt in range(self.send_retries):
            if attempt:
                time.sleep(0.05 * attempt)
            try:
                status, _ctype, body = self.http_exchange(
                    "POST", url, raw=raw, timeout=30,
                    content_type=wire.CONTENT_TYPE,
                )
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                detail = f"{type(e).__name__}: {e}"
                continue
            if status in (200, 202):
                return
            payload = body.decode("utf-8", "replace")[:200]
            if status == 400 and wire.is_crc_error(payload):
                # Torn/corrupted in flight: same bytes again.
                detail = f"crc reject: {payload}"
                continue
            if status in (400, 404) and "unknown shard job" in payload:
                # A freshly-respawned peer that has not been restored
                # yet: not-ready, not a protocol error — the coordinator
                # recovers the whole step from the durable floor.
                raise PeerUnreachable(peer, f"job not restored: {payload}")
            raise ShardError(
                f"halo peer {peer} rejected frame: HTTP {status} {payload}"
            )
        raise PeerUnreachable(peer, detail)

    def step_job(self, job, step, timeout: float | None = None) -> dict:
        """POST /shard/step: one super-step of the owned tiles.

        Holds the job's gate end to end (rewind serializes behind it) but
        the halo barrier wait is ABORTABLE: a concurrent rewind sets
        ``abort`` and wakes the condition, so a step stuck waiting on a
        SIGKILLed peer's frame fails fast instead of pinning recovery to
        the barrier timeout."""
        st = self._get(job)
        step = int(step)
        with st.gate:
            if step == st.step - 1 and st.last_reply is not None:
                return st.last_reply  # coordinator retry after a lost ack
            if step != st.step:
                raise ShardError(
                    f"shard job {st.job} is at super-step {st.step}, "
                    f"asked to run {step}"
                )
            peers = sorted(st.peers)
            out = halo.outgoing(st.board, st.partition, st.self_id)
            reg = obs_registry.default()
            for peer in peers:
                raw = halo.encode(st.job, step, st.self_id,
                                  out.get(peer) or {}, st.board.tile)
                self._send_halo(st, peer, raw)
                reg.inc("shard_halo_bytes_total", len(raw))
            ghost: dict = {}
            deadline = time.perf_counter() + (timeout or
                                              self.barrier_timeout)
            with st.cond:
                while True:
                    if st.abort:
                        raise ShardError(
                            f"super-step {step} aborted for recovery"
                        )
                    waiting = [p for p in peers
                               if (step, p) not in st.inbox]
                    if not waiting:
                        break
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        raise PeerUnreachable(
                            waiting[0],
                            f"no halo frame for step {step} within "
                            "barrier timeout",
                        )
                    st.cond.wait(min(remaining, 1.0))
                for peer in peers:
                    ghost.update(st.inbox.pop((step, peer)))
                # Anything older is unreachable now (barrier passed).
                for key in [k for k in st.inbox if k[0] < step]:
                    st.inbox.pop(key)
            new_board, changed = step_tiles(
                st.board, st.memo, st.stats, ghost=ghost, owned=st.owned)
            st.stats.generations += 1
            st.prev = st.board
            st.board = new_board
            st.step = step + 1
            st.last_reply = {
                "job": st.job, "step": st.step, "changed": bool(changed),
                "live": len(new_board.tiles),
                "stats": st.stats_tuple(),
            }
            return st.last_reply

    def checkpoint(self, job, step) -> dict:
        """POST /shard/checkpoint: the owned slice to the shard log."""
        st = self._get(job)
        step = int(step)
        if step != st.step:
            raise ShardError(
                f"checkpoint asked at step {step}; shard is at {st.step}"
            )
        st.ckpt_step = step
        st.ckpt_board = st.board
        st.ckpt_stats = st.stats_tuple()
        self._append(st, {
            "kind": "ckpt", "job": st.job, "step": step,
            "rle": st.board.to_rle(), "stats": st.stats_tuple(),
        })
        return {"job": st.job, "step": step, "durable": True}

    def rewind(self, job, step, peers=None) -> dict:
        """POST /shard/rewind: back to a checkpointed super-step (the
        survivors' half of recovery — in-memory when the barrier is the
        latest one taken, from the shard log otherwise). ``peers`` is the
        refreshed peer URL map: a respawned peer answers on a NEW port,
        and a survivor sending halos to the dead one would re-fail.

        Aborts a step blocked on the halo barrier first, then mutates
        under the gate — never two threads on one board."""
        st = self._get(job)
        step = int(step)
        with st.cond:
            st.abort = True
            st.cond.notify_all()
        st.gate.acquire()
        try:
            with st.cond:
                st.abort = False
            return self._rewind_locked(st, step, peers)
        finally:
            st.gate.release()

    def _rewind_locked(self, st: _ShardJob, step: int, peers) -> dict:
        if step == st.ckpt_step and st.ckpt_board is not None:
            st.board = st.ckpt_board
            st.load_stats(st.ckpt_stats)
        else:
            rec = self._scan_ckpt(st.log_path, step) if st.log_path else None
            if rec is None:
                raise ShardError(
                    f"no checkpoint at step {step} for shard job {st.job}"
                )
            st.board = SparseBoard.from_rle(
                rec["rle"], height=int(st.spec["height"]),
                width=int(st.spec["width"]), tile=int(st.spec["tile"]),
                owned=st.owned)
            st.load_stats(rec["stats"])
            st.ckpt_step = step
            st.ckpt_board = st.board
            st.ckpt_stats = st.stats_tuple()
        st.prev = None
        st.step = step
        st.last_reply = None
        if peers is not None:
            st.peers = {str(k): str(v) for k, v in peers.items()
                        if str(k) != st.self_id}
        with st.cond:
            # Frames for replayed steps arrive again with identical
            # bytes; anything buffered is from the abandoned timeline.
            st.inbox.clear()
        return {"job": st.job, "step": step, "live": len(st.board.tiles)}

    def restore_job(self, body: dict) -> dict:
        """POST /shard/restore: a respawned worker rebuilds its shard —
        and ONLY its shard — from its own log at the durable step."""
        st = self._build(body)
        step = int(body["step"])
        if st.job in self.jobs:
            return self.rewind(st.job, step, body.get("peers"))
        if st.log_path is None:
            raise ShardError("this worker has no shard log to restore from")
        rec = self._scan_ckpt(st.log_path, step)
        if rec is None:
            raise ShardError(
                f"no checkpoint at step {step} in {st.log_path}"
            )
        st.board = SparseBoard.from_rle(
            rec["rle"], height=int(st.spec["height"]),
            width=int(st.spec["width"]), tile=int(st.spec["tile"]),
            owned=st.owned)
        st.load_stats(rec["stats"])
        st.step = step
        st.ckpt_step = step
        st.ckpt_board = st.board
        st.ckpt_stats = st.stats_tuple()
        self._append(st, {"kind": "restore", "job": st.job, "step": step})
        with self.lock:
            self.jobs[st.job] = st
        obs_registry.default().inc("shard_restores_total")
        return {"job": st.job, "step": step, "live": len(st.board.tiles)}

    def status(self, job) -> dict:
        """POST /shard/status: liveness probe for recovery — does this
        process still hold the job, and at which step?"""
        st = self.jobs.get(str(job))
        if st is None:
            return {"job": str(job), "known": False}
        return {"job": st.job, "known": True, "step": st.step,
                "ckpt_step": st.ckpt_step, "live": len(st.board.tiles)}

    # -- elastic membership ------------------------------------------------

    def rebalance(self, body: dict) -> dict:
        """POST /shard/rebalance (at a checkpoint barrier): adopt the new
        membership, ship exactly the moved-out tiles to their new owners
        (HRW guarantees that is the minimal set), keep the rest."""
        st = self._get(body["job"])
        workers = [str(w) for w in body["workers"]]
        if st.self_id not in workers:
            # This worker is departing: everything it owns moves out.
            pass
        new_part = Partition(workers, st.partition.tiles_y,
                             st.partition.tiles_x)
        peers = {str(k): str(v) for k, v in (body.get("peers") or {}).items()
                 if str(k) != st.self_id}
        moving: dict[str, dict] = {}
        for coord, arr in list(st.board.tiles.items()):
            own = new_part.owner(coord)
            if own != st.self_id:
                moving.setdefault(own, {})[coord] = arr
        reg = obs_registry.default()
        for target, tiles in sorted(moving.items()):
            raw = halo.encode_tiles(st.job, st.step, st.self_id, tiles,
                                    st.board.tile)
            url = peers[target].rstrip("/") + "/shard/adopt"
            status, _ctype, resp = self.http_exchange(
                "POST", url, raw=raw, timeout=60,
                content_type=wire.CONTENT_TYPE,
            )
            if status not in (200, 202):
                raise ShardError(
                    f"tile transfer to {target} failed: HTTP {status} "
                    f"{resp.decode('utf-8', 'replace')[:200]}"
                )
            reg.inc("shard_rebalanced_tiles_total", len(tiles))
            for coord in tiles:
                st.board.tiles.pop(coord, None)
        departing = st.self_id not in workers
        if departing:
            with self.lock:
                self.jobs.pop(st.job, None)
        else:
            st.partition = new_part
            st.owned = new_part.owns(st.self_id)
            st.peers = peers
            st.prev = None
            st.last_reply = None
        moved = sum(len(t) for t in moving.values())
        return {"job": st.job, "step": st.step, "moved": moved,
                "departed": departing, "live": len(st.board.tiles)}

    def adopt(self, raw: bytes) -> dict:
        """POST /shard/adopt (packed): install migrated tiles."""
        meta, tiles = halo.decode_tiles(raw)
        st = self._get(meta["job"])
        for coord, arr in tiles.items():
            st.board.set_tile(coord, arr)
        return {"job": st.job, "adopted": len(tiles),
                "live": len(st.board.tiles)}

    # -- results -----------------------------------------------------------

    def collect(self, job, which: str = "current") -> dict:
        """POST /shard/collect: the owned slice as a full-geometry RLE
        document (only this worker's tiles are live in it — the
        coordinator merges the disjoint slices)."""
        st = self._get(job)
        if which == "prev":
            board = st.prev
            if board is None:
                raise ShardError(
                    f"shard job {st.job} holds no previous super-step"
                )
        elif which == "current":
            board = st.board
        else:
            raise ShardError(f"collect wants current|prev, got {which!r}")
        return {
            "job": st.job, "step": st.step, "rle": board.to_rle(),
            "live": len(board.tiles), "population": board.population(),
            "stats": st.stats_tuple(),
        }

    def finish(self, job) -> dict:
        """POST /shard/done: terminal audit record, state dropped."""
        job = str(job)
        with self.lock:
            st = self.jobs.pop(job, None)
            if job in self.finished:
                # A retried ack — or a recovery that restored state for a
                # job whose done record already landed ("frame landed, ack
                # lost" on the finish leg). Either way the audit record
                # exists; dropping state again is all that is left to do.
                return {"job": job, "done": True}
            if st is None:
                raise ShardError(f"unknown shard job {job}")
            self.finished.add(job)
        digest = hashlib.sha1(
            st.board.to_rle().encode("utf-8")).hexdigest()
        self._append(st, {
            "kind": "done", "job": job, "step": st.step,
            "live": len(st.board.tiles), "digest": digest,
        })
        return {"job": job, "done": True, "step": st.step}
