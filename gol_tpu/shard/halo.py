"""Halo extraction + GOLP frame codec for the sharded single-job engine.

The byte-exactness argument that makes ring-only exchange sufficient: the
sparse engine's tile step reads ONLY its neighbors' outermost ring
(engine._assemble_block), and its activation walk triggers ONLY on
ring-live tiles (engine._active_set) — so a remote live tile whose ring is
all-dead is indistinguishable from an absent tile. Per super-step each
worker therefore ships, to each peer, exactly the ring strips of its own
ring-live tiles that are torus-adjacent to a tile the peer owns — the
minimal traffic that is still provably byte-exact, and the direct analog
of ``game_mpi.c``'s halo ``MPI_Sendrecv`` rows/columns (one GOLP frame per
(sender, peer, step) instead of eight point-to-point messages).

A frame with no boundary tiles is STILL sent (zero payload rows): the
receiver's super-step barrier completes on frame ARRIVAL from every peer,
never on guessing whether a peer had anything to say — the deterministic
completion rule a data-dependent sender set needs.

Frames ride io/wire.py verbatim — same header, CRC, and body caps as every
other packed hop, so breakers, deadline budgets, retry budgets, and the
chaos proxy apply to the halo hop without a line of new transport code.

Numpy + wire only (no jax): both worker and coordinator sides import this.
"""

from __future__ import annotations

import typing

import numpy as np

from gol_tpu.io import wire


class Ring(typing.NamedTuple):
    """One tile's outermost ring, the only cells a neighbor's step reads.

    ``left``/``right`` are the full edge COLUMNS stored as length-``tile``
    arrays; corners appear in both the row and the column views (top[0] ==
    left[0], etc.) — engine._assemble_block reads corners from whichever
    view is natural."""

    top: np.ndarray
    bottom: np.ndarray
    left: np.ndarray
    right: np.ndarray


def ring_of(arr: np.ndarray) -> Ring:
    """Extract one tile's ring (copies: the frames outlive the board)."""
    return Ring(arr[0].copy(), arr[-1].copy(),
                arr[:, 0].copy(), arr[:, -1].copy())


def outgoing(board, partition, self_id: str) -> dict[str, dict]:
    """``peer_id -> {coord: Ring}``: what this worker owes each peer for
    the CURRENT board state.

    A tile crosses the wire to peer P iff it is live, its ring is live,
    and at least one of its 8 torus neighbors is owned by P — the exact
    set P's activation walk and block assembly can observe. Both sides
    compute adjacency from the same partition, so the expected-sender map
    is consistent across every ownership boundary by construction."""
    from gol_tpu.sparse.engine import ring_live

    out: dict[str, dict] = {
        wid: {} for wid in partition.worker_ids if wid != self_id
    }
    for coord, arr in board.tiles.items():
        ring = None
        for nb in partition.neighbors(coord):
            own = partition.owner(nb)
            if own == self_id or coord in out[own]:
                continue
            if ring is None:
                if not ring_live(arr):
                    break  # a ring-dead tile crosses no boundary
                ring = ring_of(arr)
            out[own][coord] = ring
    return out


def encode(job: str, step: int, sender: str, entries: dict,
           tile: int) -> bytes:
    """One halo frame: ``entries`` is ``{(ty, tx): Ring}`` (may be empty —
    the barrier-completion frame). Payload stacks 4 rows per tile in
    sorted-coord order: top, bottom, left-as-row, right-as-row."""
    coords = sorted(entries)
    grid = np.zeros((4 * len(coords), tile), np.uint8)
    for i, coord in enumerate(coords):
        ring = entries[coord]
        grid[4 * i] = ring.top
        grid[4 * i + 1] = ring.bottom
        grid[4 * i + 2] = ring.left
        grid[4 * i + 3] = ring.right
    meta = {
        wire.META_KIND: wire.SHARD_HALO_KIND,
        "job": job,
        "step": int(step),
        "from": sender,
        "tile": int(tile),
        "tiles": [[int(ty), int(tx)] for ty, tx in coords],
    }
    return wire.encode_frame(meta, grid=grid)


def decode(raw: bytes) -> tuple[dict, dict]:
    """Inverse of ``encode``: ``(meta, {(ty, tx): Ring})``. Raises
    wire.WireError on anything torn (the CRC pass runs inside
    decode_frame — a corrupted halo hop answers 400 and the sender
    resends, exactly like a corrupted submit)."""
    frame = wire.decode_frame(raw)
    meta = frame.meta
    if meta.get(wire.META_KIND) != wire.SHARD_HALO_KIND:
        raise wire.WireError(
            f"not a shard halo frame (kind={meta.get(wire.META_KIND)!r})"
        )
    for field in ("job", "step", "from", "tile", "tiles"):
        if field not in meta:
            raise wire.WireError(f"halo frame meta missing {field!r}")
    tiles = meta["tiles"]
    tile = int(meta["tile"])
    if frame.width != tile or frame.height != 4 * len(tiles):
        raise wire.WireError(
            f"halo frame geometry {frame.height}x{frame.width} does not "
            f"match {len(tiles)} tiles of edge {tile}"
        )
    grid = frame.grid()
    rings = {}
    for i, (ty, tx) in enumerate(tiles):
        rings[(int(ty), int(tx))] = Ring(
            grid[4 * i], grid[4 * i + 1], grid[4 * i + 2], grid[4 * i + 3]
        )
    return meta, rings


def encode_tiles(job: str, step: int, sender: str, tiles: dict,
                 tile: int) -> bytes:
    """One tile-transfer frame (elastic rebalance): ``tiles`` is
    ``{(ty, tx): (tile, tile) uint8}`` full migrating tiles, stacked as
    ``tile`` rows each in sorted-coord order."""
    coords = sorted(tiles)
    grid = np.zeros((tile * len(coords), tile), np.uint8)
    for i, coord in enumerate(coords):
        grid[i * tile:(i + 1) * tile] = tiles[coord]
    meta = {
        wire.META_KIND: wire.SHARD_TILES_KIND,
        "job": job,
        "step": int(step),
        "from": sender,
        "tile": int(tile),
        "tiles": [[int(ty), int(tx)] for ty, tx in coords],
    }
    return wire.encode_frame(meta, grid=grid)


def decode_tiles(raw: bytes) -> tuple[dict, dict]:
    """Inverse of ``encode_tiles``: ``(meta, {(ty, tx): array})``."""
    frame = wire.decode_frame(raw)
    meta = frame.meta
    if meta.get(wire.META_KIND) != wire.SHARD_TILES_KIND:
        raise wire.WireError(
            f"not a shard tile-transfer frame "
            f"(kind={meta.get(wire.META_KIND)!r})"
        )
    tiles = meta.get("tiles", [])
    tile = int(meta.get("tile", 0))
    if frame.width != tile or frame.height != tile * len(tiles):
        raise wire.WireError(
            f"tile-transfer geometry {frame.height}x{frame.width} does "
            f"not match {len(tiles)} tiles of edge {tile}"
        )
    grid = frame.grid()
    out = {}
    for i, (ty, tx) in enumerate(tiles):
        out[(int(ty), int(tx))] = grid[i * tile:(i + 1) * tile].copy()
    return meta, out
