"""Tile→worker ownership for the sharded single-job engine.

One giant universe spans N workers: the sparse engine's fixed tile grid
(gol_tpu/sparse/board.py) is partitioned by rendezvous hashing over tile
coordinates, reusing the fleet's HRW ranking verbatim
(gol_tpu/fleet/placement.py — the same score function that places serve
buckets places tiles). Rendezvous hashing is the membership-change
contract the reference's ``MPI_Cart_create`` cannot express: adding a
worker moves ONLY the tiles the new worker now wins, removing one moves
ONLY the departed worker's tiles — every other tile keeps its owner, so
an elastic rebalance ships exactly the moved shards and nothing else
(``moved_tiles`` is the test-pinned statement of that property).

Ownership is a pure function of ``(worker ids, tile coord)`` — never an
enumeration of the grid. A 2^20-square universe has 2^24 tiles; the
partition answers ``owner`` per-coordinate on demand (memoized for the
coords actually asked about: the active set and its neighbors), so the
cost tracks live area exactly like the engine itself does.

Jax-free and numpy-free on purpose: the router's shard coordinator lane
imports this, and the router is a front-end process.
"""

from __future__ import annotations

from gol_tpu.fleet import placement


def tile_label(ty: int, tx: int) -> str:
    """The HRW label of one tile coordinate (the shard analog of the
    serve tier's bucket label)."""
    return f"tile:{ty}:{tx}"


class Partition:
    """Ownership of a ``tiles_y x tiles_x`` tile grid over a worker set.

    Immutable once built; membership change is a NEW Partition over the
    new id set (compare with ``moved_tiles``). With ``weights`` the
    ranking is capacity-weighted (placement.rank_weighted — equal weights
    delegate to plain rank, so weighted-with-no-signal is byte-identical
    to unweighted)."""

    def __init__(self, worker_ids, tiles_y: int, tiles_x: int,
                 weights: dict[str, float] | None = None):
        ids = [str(w) for w in worker_ids]
        if not ids:
            raise ValueError("a partition needs at least one worker")
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate worker ids: {ids}")
        if tiles_y <= 0 or tiles_x <= 0:
            raise ValueError(
                f"tile grid must be positive, got {tiles_y}x{tiles_x}"
            )
        self.worker_ids = tuple(ids)
        self.tiles_y = tiles_y
        self.tiles_x = tiles_x
        self.weights = dict(weights) if weights else None
        self._owners: dict[tuple[int, int], str] = {}

    @classmethod
    def for_universe(cls, worker_ids, height: int, width: int, tile: int,
                     weights: dict[str, float] | None = None) -> "Partition":
        if height % tile or width % tile:
            raise ValueError(
                f"universe {height}x{width} does not divide into {tile}^2 "
                "tiles"
            )
        return cls(worker_ids, height // tile, width // tile, weights)

    def owner(self, coord: tuple[int, int]) -> str:
        """The worker id owning one tile (deterministic across every
        process that holds the same membership — both sides of every halo
        boundary compute the same map from the same ids)."""
        own = self._owners.get(coord)
        if own is None:
            ty, tx = coord
            if not (0 <= ty < self.tiles_y and 0 <= tx < self.tiles_x):
                raise ValueError(
                    f"tile {coord} outside the "
                    f"{self.tiles_y}x{self.tiles_x} grid"
                )
            label = tile_label(ty, tx)
            if self.weights:
                own = placement.rank_weighted(label, self.weights)[0]
            else:
                own = placement.rank(label, list(self.worker_ids))[0]
            self._owners[coord] = own
        return own

    def owns(self, worker_id: str):
        """``(ty, tx) -> bool`` membership predicate for one worker — the
        ``owned`` filter SparseBoard.from_rle and engine.step_tiles take."""
        return lambda coord: self.owner(coord) == worker_id

    def neighbors(self, coord: tuple[int, int]) -> list[tuple[int, int]]:
        """The 8 torus neighbors of one tile (self-wrap included on
        1-tile-wide grids, exactly like the engine's activation walk)."""
        ty, tx = coord
        return [
            ((ty + dy) % self.tiles_y, (tx + dx) % self.tiles_x)
            for dy in (-1, 0, 1) for dx in (-1, 0, 1) if dy or dx
        ]

    def counts(self, coords) -> dict[str, int]:
        """Ownership histogram over a concrete coord set (the per-worker
        tile-ownership gauges in ``gol top`` ride this)."""
        out: dict[str, int] = {wid: 0 for wid in self.worker_ids}
        for coord in coords:
            out[self.owner(coord)] += 1
        return out


def moved_tiles(old: Partition, new: Partition, coords) -> set:
    """The coords (of a concrete set — live tiles, usually) whose owner
    changes between two memberships. HRW's minimal-disruption property,
    stated operationally: growing the set moves only tiles the NEW worker
    wins; shrinking moves only tiles the DEPARTED worker held. The elastic
    rebalance ships exactly these."""
    if (old.tiles_y, old.tiles_x) != (new.tiles_y, new.tiles_x):
        raise ValueError("partitions cover different tile grids")
    return {c for c in coords if old.owner(c) != new.owner(c)}
