"""Bit-sliced Game-of-Life arithmetic on packed uint32 words.

The shared core of every packed path: the Pallas kernel (stencil_pallas's
sibling stencil_packed), the jnp torus evolve, and the distributed shard step
all feed the same carry-save adder network. Bit j of word w is the cell at
column ``w*32 + j``.

The network computes all eight Moore neighbor counts bit-parallel: per-row 3:2
compressors, then a 4-bit carry-save sum N = s0 + 2*b1 + 4*u0 + 8*u1, under
which rule B3/S23 (src/game.c:91-98) collapses to
``new = b1 & ~(u0|u1) & (s0|mid)`` — ~30 bitwise ops for 32 cells.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

BITS = 32


def west(x: jnp.ndarray, left_words: jnp.ndarray) -> jnp.ndarray:
    """Packed array of west (column-1) neighbors.

    ``left_words[w]`` must be word ``w-1`` of the same row — however the
    caller realizes that (lane roll for a torus, ghost word column for a
    shard boundary). Shift constants are built at trace time — module-level
    jnp scalars would be captured constants, which Pallas kernels reject."""
    return jax.lax.shift_left(x, jnp.uint32(1)) | jax.lax.shift_right_logical(
        left_words, jnp.uint32(BITS - 1)
    )


def east(x: jnp.ndarray, right_words: jnp.ndarray) -> jnp.ndarray:
    """Packed array of east (column+1) neighbors (``right_words[w]`` = word w+1)."""
    return jax.lax.shift_right_logical(x, jnp.uint32(1)) | jax.lax.shift_left(
        right_words, jnp.uint32(BITS - 1)
    )


def csa3(a, b, c):
    """3:2 compressor: (sum, carry) bitplanes of a+b+c."""
    axb = a ^ b
    return axb ^ c, (a & b) | (c & axb)


def rule(uw, uc, ue, mw, me, dw, dc, de, mid):
    """B3/S23 from the eight packed neighbor arrays and the center cells."""
    a0, a1 = csa3(uw, uc, ue)
    c0, c1 = csa3(dw, dc, de)
    m0, m1 = mw ^ me, mw & me
    s0, k0 = csa3(a0, m0, c0)
    # count4 = a1 + m1 + c1 + k0 = 4*u1 + 2*u0 + b1
    p, q = a1 ^ m1, a1 & m1
    r, s = c1 ^ k0, c1 & k0
    b1, t = p ^ r, p & r
    u0 = q ^ s ^ t
    u1 = (q & s) | (t & (q ^ s))
    # N = s0 + 2*b1 + 4*u0 + 8*u1; alive iff N==3 or (N==2 and alive).
    return b1 & ~(u0 | u1) & (s0 | mid)


def evolve_rows(up, mid, down, roll_words):
    """One generation given the three row-shifted packed arrays.

    ``roll_words(x, shift)`` must return the word array rolled along the word
    axis (torus wrap across the row ends) — jnp.roll outside kernels,
    pltpu.roll inside."""
    def we(x):
        return west(x, roll_words(x, 1)), east(x, roll_words(x, -1))

    uw, ue = we(up)
    mw, me = we(mid)
    dw, de = we(down)
    return rule(uw, up, ue, mw, me, dw, down, de, mid=mid)


def evolve_torus_words(x: jnp.ndarray) -> jnp.ndarray:
    """Whole-torus packed evolve (jnp level, any backend)."""
    up = jnp.roll(x, 1, axis=0)
    down = jnp.roll(x, -1, axis=0)
    return evolve_rows(up, x, down, lambda a, s: jnp.roll(a, s, axis=1))


def evolve_extended(xce: jnp.ndarray) -> jnp.ndarray:
    """One generation for the interior of a ghost-extended word block.

    ``xce`` is (h+2, nwords+2): one ghost word row above/below and one ghost
    word column either side (of which only the adjacent bit is consumed by
    the shift carries). This is the packed analog of the byte-level
    ``evolve_padded`` (the src/game_mpi.c:73-84 shape)."""
    h = xce.shape[0] - 2

    def band(r):
        b = xce[r : r + h, :]
        x = b[:, 1:-1]
        return west(x, b[:, :-2]), x, east(x, b[:, 2:])

    uw, uc, ue = band(0)
    mw, mc, me = band(1)
    dw, dc, de = band(2)
    return rule(uw, uc, ue, mw, me, dw, dc, de, mid=mc)


def evolve_ghost(words, top, bot, gwest, geast):
    """One generation of an (h, nwords) shard from separate ghost operands.

    ``top``/``bot`` are the ghost word rows (1, nwords); ``gwest``/``geast``
    are the per-extended-row ghost carry words (h+2,), covering rows -1..h so
    the corner bits ride along (the two-phase trick, src/game_cuda.cu:64-74).
    Only bit 31 of ``gwest`` and bit 0 of ``geast`` are consumed — they carry
    exactly the boundary *bit* column the reference moves with its derived
    column datatype (src/game_mpi.c:335-338), not whole ghost words.
    """
    h = words.shape[0]
    xr = jnp.concatenate([top, words, bot], axis=0)  # (h+2, nwords)

    def band(r):
        x = xr[r : r + h, :]
        left = jnp.roll(x, 1, axis=1).at[:, 0].set(gwest[r : r + h])
        right = jnp.roll(x, -1, axis=1).at[:, -1].set(geast[r : r + h])
        return west(x, left), x, east(x, right)

    uw, uc, ue = band(0)
    mw, mc, me = band(1)
    dw, dc, de = band(2)
    return rule(uw, uc, ue, mw, me, dw, dc, de, mid=mc)


def pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(n,) uint32 {0,1} -> (ceil(n/32),) packed words (bit k%32 of word k/32)."""
    n = bits.shape[0]
    pad = (-n) % BITS
    b = jnp.pad(bits, (0, pad)).reshape(-1, BITS)
    weights = (jnp.uint32(1) << jnp.arange(BITS, dtype=jnp.uint32))[None, :]
    return jnp.sum(b * weights, axis=1, dtype=jnp.uint32)


def unpack_bits(words: jnp.ndarray, n: int) -> jnp.ndarray:
    """Inverse of ``pack_bits``: (nw,) words -> (n,) uint32 {0,1} bits."""
    shifts = jnp.arange(BITS, dtype=jnp.uint32)[None, :]
    bits = (words[:, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(-1)[:n]


def encode(grid: jnp.ndarray) -> jnp.ndarray:
    """uint8 (H, W) cells -> uint32 (H, W/32) words (bit j = column w*32+j)."""
    height, width = grid.shape
    bits = grid.reshape(height, width // BITS, BITS).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(BITS, dtype=jnp.uint32))[None, None, :]
    return jnp.sum(bits * weights, axis=-1, dtype=jnp.uint32)


def decode(words: jnp.ndarray) -> jnp.ndarray:
    """uint32 (H, W/32) words -> uint8 (H, W) cells."""
    height, nwords = words.shape
    shifts = jnp.arange(BITS, dtype=jnp.uint32)[None, None, :]
    bits = (words[:, :, None] >> shifts) & jnp.uint32(1)
    return bits.astype(jnp.uint8).reshape(height, nwords * BITS)
