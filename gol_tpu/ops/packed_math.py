"""Bit-sliced Game-of-Life arithmetic on packed uint32 words.

The shared core of every packed path: the Pallas kernel (stencil_pallas's
sibling stencil_packed), the jnp torus evolve, and the distributed shard step
all feed the same carry-save adder network. Bit j of word w is the cell at
column ``w*32 + j``.

The network computes all eight Moore neighbor counts bit-parallel, and shares
work across rows: each row's horizontal triple sum west+center+east (two
bitplanes, ``row_sums``) is computed once and serves as the "up" contribution
of the row below and the "down" contribution of the row above — the vertical
combine (``combine``) only re-ranks the same planes by a row shift. N =
t + d + m with t/d the up/down triple sums and m the mid west+east pair;
rule B3/S23 (src/game.c:91-98) collapses to
``b1 & ~over & (t0|mid)`` — ~28 bitwise ops for 32 cells (down from ~51 in
the per-row-neighbor formulation this replaced).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

BITS = 32


def west(x: jnp.ndarray, left_words: jnp.ndarray) -> jnp.ndarray:
    """Packed array of west (column-1) neighbors.

    ``left_words[w]`` must be word ``w-1`` of the same row — however the
    caller realizes that (lane roll for a torus, ghost word column for a
    shard boundary). Shift constants are built at trace time — module-level
    jnp scalars would be captured constants, which Pallas kernels reject."""
    return jax.lax.shift_left(x, jnp.uint32(1)) | jax.lax.shift_right_logical(
        left_words, jnp.uint32(BITS - 1)
    )


def east(x: jnp.ndarray, right_words: jnp.ndarray) -> jnp.ndarray:
    """Packed array of east (column+1) neighbors (``right_words[w]`` = word w+1)."""
    return jax.lax.shift_right_logical(x, jnp.uint32(1)) | jax.lax.shift_left(
        right_words, jnp.uint32(BITS - 1)
    )


def csa3(a, b, c):
    """3:2 compressor: (sum, carry) bitplanes of a+b+c."""
    axb = a ^ b
    return axb ^ c, (a & b) | (c & axb)


def row_sums(x, left, right):
    """Per-row horizontal sums of a packed array: ``(m0, m1, s0, s1)``.

    ``m = west + east`` (the mid-row pair, excluding center) and
    ``s = west + center + east`` (the triple a row contributes to its vertical
    neighbors), each as two bitplanes. ``left``/``right`` deliver the cross-word
    carry words, however the caller realizes them (lane roll, seam patch).
    Computed ONCE per row and reused by all three output rows it feeds.
    """
    w = west(x, left)
    e = east(x, right)
    m0 = w ^ e
    m1 = w & e
    s0 = m0 ^ x
    s1 = m1 | (x & m0)
    return m0, m1, s0, s1


def combine(u0, u1, d0, d1, m0, m1, mid):
    """B3/S23 from the up/down triple-sum planes and the mid pair planes.

    N = (u0 + 2*u1) + (d0 + 2*d1) + (m0 + 2*m1); alive iff N == 3 or
    (N == 2 and center alive) — i.e. bit1 set, nothing at weight 4+, and
    (bit0 | center).
    """
    t0, tc = csa3(u0, d0, m0)  # ones column: bit 0 of N + carry into twos
    v0, v1 = csa3(u1, d1, m1)  # twos column (sans tc) + carry into fours
    b1 = v0 ^ tc  # bit 1 of N
    over = v1 | (v0 & tc)  # any weight-4 contribution => N >= 4
    return b1 & ~over & (t0 | mid)


def evolve_torus_words(x: jnp.ndarray) -> jnp.ndarray:
    """Whole-torus packed evolve (jnp level, any backend)."""
    m0, m1, s0, s1 = row_sums(x, jnp.roll(x, 1, axis=1), jnp.roll(x, -1, axis=1))
    u0, u1 = jnp.roll(s0, 1, axis=0), jnp.roll(s1, 1, axis=0)
    d0, d1 = jnp.roll(s0, -1, axis=0), jnp.roll(s1, -1, axis=0)
    return combine(u0, u1, d0, d1, m0, m1, x)


def evolve_extended(xce: jnp.ndarray) -> jnp.ndarray:
    """One generation for the interior of a ghost-extended word block.

    ``xce`` is (h+2, nwords+2): one ghost word row above/below and one ghost
    word column either side (of which only the adjacent bit is consumed by
    the shift carries). This is the packed analog of the byte-level
    ``evolve_padded`` (the src/game_mpi.c:73-84 shape)."""
    h = xce.shape[0] - 2
    x = xce[:, 1:-1]
    m0, m1, s0, s1 = row_sums(x, xce[:, :-2], xce[:, 2:])
    return combine(
        s0[0:h], s1[0:h], s0[2 : h + 2], s1[2 : h + 2],
        m0[1 : h + 1], m1[1 : h + 1], x[1 : h + 1],
    )


def evolve_ghost(words, top, bot, gwest, geast):
    """One generation of an (h, nwords) shard from separate ghost operands.

    ``top``/``bot`` are the ghost word rows (1, nwords); ``gwest``/``geast``
    are the per-extended-row ghost carry words (h+2,), covering rows -1..h so
    the corner bits ride along (the two-phase trick, src/game_cuda.cu:64-74).
    Only bit 31 of ``gwest`` and bit 0 of ``geast`` are consumed — they carry
    exactly the boundary *bit* column the reference moves with its derived
    column datatype (src/game_mpi.c:335-338), not whole ghost words.
    """
    h = words.shape[0]
    xr = jnp.concatenate([top, words, bot], axis=0)  # (h+2, nwords)
    left = jnp.roll(xr, 1, axis=1).at[:, 0].set(gwest)
    right = jnp.roll(xr, -1, axis=1).at[:, -1].set(geast)
    m0, m1, s0, s1 = row_sums(xr, left, right)
    return combine(
        s0[0:h], s1[0:h], s0[2 : h + 2], s1[2 : h + 2],
        m0[1 : h + 1], m1[1 : h + 1], words,
    )


def pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(n,) uint32 {0,1} -> (ceil(n/32),) packed words (bit k%32 of word k/32)."""
    n = bits.shape[0]
    pad = (-n) % BITS
    b = jnp.pad(bits, (0, pad)).reshape(-1, BITS)
    weights = (jnp.uint32(1) << jnp.arange(BITS, dtype=jnp.uint32))[None, :]
    return jnp.sum(b * weights, axis=1, dtype=jnp.uint32)


def unpack_bits(words: jnp.ndarray, n: int) -> jnp.ndarray:
    """Inverse of ``pack_bits``: (nw,) words -> (n,) uint32 {0,1} bits."""
    shifts = jnp.arange(BITS, dtype=jnp.uint32)[None, :]
    bits = (words[:, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(-1)[:n]


def encode(grid: jnp.ndarray) -> jnp.ndarray:
    """uint8 (H, W) cells -> uint32 (H, W/32) words (bit j = column w*32+j)."""
    height, width = grid.shape
    bits = grid.reshape(height, width // BITS, BITS).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(BITS, dtype=jnp.uint32))[None, None, :]
    return jnp.sum(bits * weights, axis=-1, dtype=jnp.uint32)


def decode(words: jnp.ndarray) -> jnp.ndarray:
    """uint32 (H, W/32) words -> uint8 (H, W) cells."""
    height, nwords = words.shape
    shifts = jnp.arange(BITS, dtype=jnp.uint32)[None, None, :]
    bits = (words[:, :, None] >> shifts) & jnp.uint32(1)
    return bits.astype(jnp.uint8).reshape(height, nwords * BITS)
