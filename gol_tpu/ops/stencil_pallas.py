"""Pallas VMEM-tiled stencil — the fast path (the CUDA kernel equivalent).

Plays the role of the reference's five device kernels (src/game_cuda.cu:52-148)
but restructured for TPU rather than translated:

- The CUDA program materializes the toroidal wrap into a ghost border with two
  halo kernels each generation (src/game_cuda.cu:52-74) and then runs a
  one-thread-per-cell evolve. Here the grid is processed in row bands: the
  band plus the two aligned 8-row blocks holding its wrap rows stream into
  VMEM through Pallas's pipelined BlockSpecs (the same array passed three
  times with row-shifted index maps — the torus wrap is modular block-index
  arithmetic), and the column wrap is two lane-rolls of the VMEM-resident
  band. No ghost cells ever exist in memory.
- The CUDA program's compare/empty reduction kernels (src/game_cuda.cu:76-126)
  plus the per-generation 4-byte device->host flag copy (src/game_cuda.cu:
  259-268) become two scalar flags accumulated in SMEM across the band grid
  and consumed on-device by the engine's while_loop cond — the host never sees
  them.

Traffic per generation is ~2 bytes/cell (one read + one write) plus two 8-row
blocks per band, all double-buffered by the Pallas pipeline so DMA overlaps
compute. The sequential band grid makes the SMEM flag accumulation race-free.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.ops.pallas_compat import CompilerParams as _CompilerParams
from gol_tpu.parallel import halo
from gol_tpu.parallel.mesh import ROW_AXIS, Topology

# Lane width of the VPU; widths must align for the lane-roll column wrap.
_LANES = 128
# Sublane granule for uint8 tiles: every row offset/extent a BlockSpec or DMA
# touches must be a multiple of this.
_SUBLANES = 8
# Target VMEM bytes for one band of uint8 cells — small enough that the int32
# compute copies and the double-buffered in/out blocks fit beside it, large
# enough to amortize per-band pipeline overhead.
_BAND_BYTES = 512 << 10
# Width cap: the kernel widens to int32 with ~10 live temporaries, so even the
# minimum 8-row band costs ~320*width bytes of VMEM. Empirical limit on v5e:
# 65536 compiles and matches the oracle, 98304 VMEM-OOMs at compile.
_MAX_WIDTH = 64 << 10


def supports(height: int, width: int, topology: Topology) -> bool:
    """Shapes the compiled kernel handles; anything else falls back to lax.

    ``height``/``width`` are the LOCAL shard shape under a mesh — the
    distributed path runs the same band kernel fed ppermute'd ghosts.
    """
    return (
        width % _LANES == 0
        and width <= _MAX_WIDTH
        and height % _SUBLANES == 0
        and height >= _SUBLANES
    )


def _pick_band(height: int, width: int) -> int:
    """Largest divisor of ``height`` that fits the VMEM window and the uint8
    sublane granule."""
    target = max(_SUBLANES, min(height, _BAND_BYTES // max(width, 1)))
    for band in range(target, _SUBLANES - 1, -1):
        if height % band == 0 and band % _SUBLANES == 0:
            return band
    raise ValueError(f"no {_SUBLANES}-aligned band divides height {height}")


def _roll(x: jnp.ndarray, shift: int) -> jnp.ndarray:
    """Lane-roll along the width axis: the toroidal column wrap.

    ``pltpu.roll`` only takes non-negative shifts; a roll of -1 is width-1.
    """
    return pltpu.roll(x, shift % x.shape[1], 1)


def _band_kernel(main_ref, top_ref, bot_ref, out_ref, alive_ref, similar_ref, *, band: int):
    i = pl.program_id(0)

    # Mosaic vectorizes, rotates, and reduces i32 (not i8/i16): cells stay
    # uint8 in HBM/VMEM storage and widen to int32 only as compute values.
    mid = main_ref[:].astype(jnp.int32)
    # The wrap rows ride in as aligned 8-row blocks (sublane slices of size 1
    # would be misaligned): the row above the band is the LAST row of the
    # block 8 rows up, the row below is the FIRST row of the next block.
    # Extract by masked max-reduce over the block.
    r8 = jax.lax.broadcasted_iota(jnp.int32, (8, mid.shape[1]), 0)
    top_row = jnp.max(
        jnp.where(r8 == 7, top_ref[:].astype(jnp.int32), 0), axis=0, keepdims=True
    )
    bot_row = jnp.max(
        jnp.where(r8 == 0, bot_ref[:].astype(jnp.int32), 0), axis=0, keepdims=True
    )
    # Each row's horizontal sums once (pair m = w+e, triple s = w+x+e); the
    # vertical combine re-ranks s by row shifts, wrap rows patched in at the
    # band edges (same row-sum-sharing shape as the packed kernel).
    def hs(x):
        m = _roll(x, 1) + _roll(x, -1)
        return m, m + x

    m, s = hs(mid)
    _, ts = hs(top_row)
    _, bs = hs(bot_row)
    rows = jax.lax.broadcasted_iota(jnp.int32, mid.shape, 0)
    up = jnp.where(rows == 0, jnp.broadcast_to(ts, mid.shape), pltpu.roll(s, 1, 0))
    down = jnp.where(
        rows == band - 1, jnp.broadcast_to(bs, mid.shape), pltpu.roll(s, band - 1, 0)
    )
    counts = up + down + m
    # B3/S23, branchless (src/game_cuda.cu:146).
    new = jnp.where((counts == 3) | ((counts == 2) & (mid == 1)), 1, 0)
    out_ref[:] = new.astype(jnp.uint8)

    # max-based reductions sidestep any sum-overflow concern.
    alive = (jnp.max(new) > 0).astype(jnp.int32)
    similar = (jnp.max(jnp.abs(new - mid)) == 0).astype(jnp.int32)

    @pl.when(i == 0)
    def _init():
        alive_ref[0, 0] = alive
        similar_ref[0, 0] = similar

    @pl.when(i > 0)
    def _accumulate():
        alive_ref[0, 0] = alive_ref[0, 0] | alive
        similar_ref[0, 0] = similar_ref[0, 0] & similar


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step(grid: jnp.ndarray, interpret: bool = False):
    height, width = grid.shape
    band = _pick_band(height, width)
    bb = band // _SUBLANES  # band size in 8-row block units
    nb = height // _SUBLANES  # grid height in 8-row block units
    new, alive, similar = pl.pallas_call(
        functools.partial(_band_kernel, band=band),
        grid=(height // band,),
        in_specs=[
            # The band itself...
            pl.BlockSpec((band, width), lambda i: (i, 0), memory_space=pltpu.VMEM),
            # ...the 8-row block whose last row wraps in above it...
            pl.BlockSpec(
                (_SUBLANES, width),
                lambda i: ((i * bb - 1) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
            # ...and the 8-row block whose first row wraps in below it.
            pl.BlockSpec(
                (_SUBLANES, width),
                lambda i: ((i * bb + bb) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec((band, width), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((height, width), jnp.uint8),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),  # flags accumulate sequentially
        ),
        interpret=interpret,
    )(grid, grid, grid)
    return new, alive[0, 0] > 0, similar[0, 0] > 0


def _dist_band_kernel(
    main_ref,
    top_ref,
    bot_ref,
    gtop_ref,
    gbot_ref,
    gmid_ref,
    gwrap_ref,
    out_ref,
    alive_ref,
    similar_ref,
    *,
    band: int,
    nbands: int,
):
    """Band kernel for one mesh shard: ghost rows/columns arrive as operands.

    The same VMEM band stencil as ``_band_kernel``, with the torus wrap at
    shard edges taken from the ppermute'd ghosts — the reference runs its
    hand-written evolve in every MPI variant the same way
    (src/game_mpi.c:73-84 over ghost cells). Seam bytes for the two wrap rows
    ride in as this band's gwrap row (west/east for the row above and below).
    """
    i = pl.program_id(0)
    mid = main_ref[:].astype(jnp.int32)
    width = mid.shape[1]
    r8 = jax.lax.broadcasted_iota(jnp.int32, (8, width), 0)

    def _extract(block_ref, row_index):
        return jnp.max(
            jnp.where(r8 == row_index, block_ref[:].astype(jnp.int32), 0),
            axis=0,
            keepdims=True,
        )

    top_row = jnp.where(i == 0, _extract(gtop_ref, 7), _extract(top_ref, 7))
    bot_row = jnp.where(i == nbands - 1, _extract(gbot_ref, 0), _extract(bot_ref, 0))

    def _hs(x, gw_col, ge_col):
        # Horizontal sums with the seam patch: the lane rolled in across the
        # shard seam is replaced by the neighbor's boundary byte.
        lanes = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        gw = jnp.broadcast_to(gw_col.astype(jnp.int32), x.shape)
        ge = jnp.broadcast_to(ge_col.astype(jnp.int32), x.shape)
        w = jnp.where(lanes == 0, gw, _roll(x, 1))
        e = jnp.where(lanes == width - 1, ge, _roll(x, -1))
        m = w + e
        return m, m + x

    m, s = _hs(mid, gmid_ref[:, 0:1], gmid_ref[:, 1:2])
    _, ts = _hs(top_row, gwrap_ref[i, 0], gwrap_ref[i, 1])
    _, bs = _hs(bot_row, gwrap_ref[i, 2], gwrap_ref[i, 3])
    rows = jax.lax.broadcasted_iota(jnp.int32, mid.shape, 0)
    up = jnp.where(rows == 0, jnp.broadcast_to(ts, mid.shape), pltpu.roll(s, 1, 0))
    down = jnp.where(
        rows == band - 1, jnp.broadcast_to(bs, mid.shape), pltpu.roll(s, band - 1, 0)
    )
    counts = up + down + m
    new = jnp.where((counts == 3) | ((counts == 2) & (mid == 1)), 1, 0)
    out_ref[:] = new.astype(jnp.uint8)

    alive = (jnp.max(new) > 0).astype(jnp.int32)
    similar = (jnp.max(jnp.abs(new - mid)) == 0).astype(jnp.int32)

    @pl.when(i == 0)
    def _init():
        alive_ref[0, 0] = alive
        similar_ref[0, 0] = similar

    @pl.when(i > 0)
    def _accumulate():
        alive_ref[0, 0] = alive_ref[0, 0] | alive
        similar_ref[0, 0] = similar_ref[0, 0] & similar


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dist_step(grid, gtop8, gbot8, gmid, gwrap, interpret=False):
    height, width = grid.shape
    band = _pick_band(height, width)
    bb = band // _SUBLANES
    nb = height // _SUBLANES
    nbands = height // band
    new, alive, similar = pl.pallas_call(
        functools.partial(_dist_band_kernel, band=band, nbands=nbands),
        grid=(nbands,),
        in_specs=[
            pl.BlockSpec((band, width), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (_SUBLANES, width),
                lambda i: ((i * bb - 1) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_SUBLANES, width),
                lambda i: ((i * bb + bb) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((_SUBLANES, width), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_SUBLANES, width), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((band, 2), lambda i: (i, 0), memory_space=pltpu.VMEM),
            # The whole per-band wrap-carry table sits in SMEM (nbands x 4
            # scalars); each band reads its row by program id.
            pl.BlockSpec((nbands, 4), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((band, width), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((height, width), jnp.uint8),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(grid, grid, grid, gtop8, gbot8, gmid, gwrap)
    return new, alive[0, 0] > 0, similar[0, 0] > 0


def _distributed_step(cur: jnp.ndarray, topology: Topology):
    """Shard-local byte step: ppermute ghost rows + exact boundary columns.

    N/S ghosts are whole rows; E/W ghosts are the boundary *byte columns*
    over the row-extended range (corners ride along, src/game_cuda.cu:64-74)
    — exactly the bytes the reference's derived column datatype moves
    (src/game_mpi.c:335-338).
    """
    rows, _cols = topology.shape
    row_axis = ROW_AXIS if topology.distributed else None
    top, bot = halo.ghost_slices(cur, 0, row_axis, rows)
    west_col, east_col = halo.boundary_columns(cur, top, bot)
    gwest, geast = halo.exchange_columns(west_col, east_col, topology)
    gtop8, gbot8, gmid, gwrap = halo.assemble_band_ghosts(
        top, bot, gwest, geast, _pick_band(*cur.shape)
    )
    interpret = jax.default_backend() != "tpu"
    # The four seam bytes per band ride in SMEM, which holds 32-bit scalars.
    return _dist_step(
        cur, gtop8, gbot8, gmid, gwrap.astype(jnp.int32), interpret=interpret
    )


def pallas_step(cur: jnp.ndarray, topology: Topology):
    """Fused generation step: ``cur -> (new, any_alive, similar)``.

    The flags are this kernel's fusion of the reference's evolve + empty +
    compare kernels (src/game_cuda.cu:76-148) into a single memory pass.
    Under a mesh the same band kernel runs per shard, fed ppermute'd ghosts.
    """
    height, width = cur.shape
    if not supports(height, width, topology):
        raise ValueError(
            f"the pallas kernel requires a (local shard) height a multiple of "
            f"{_SUBLANES} and width a multiple of {_LANES}; got "
            f"{height}x{width} on {topology.shape[0]}x{topology.shape[1]} "
            f"devices — use kernel='lax' (or 'auto') instead"
        )
    if topology.distributed:
        return _distributed_step(cur, topology)
    interpret = jax.default_backend() != "tpu"
    return _step(cur, interpret=interpret)
