"""Buffer-donation shim: ``jax.jit(donate_argnums=...)`` across backends.

The segmented engine carries its state through repeated compiled calls —
segment N's output array IS segment N+1's input. Donating the input buffer
lets XLA write the new state over the old one in place, eliminating the
per-segment output allocation + copy the aliasing would otherwise cost
(the double-buffer pointer swap of the reference driver loops,
src/game.c:191-194, realized as an input/output alias instead of a second
buffer). Same story for the serve batch runner's board canvas.

Donation is a *backend* capability: TPU and GPU implement input/output
aliasing; the CPU runtime ignores the annotation and warns on **every
call** ("Some donated buffers were not usable") — noise with no win. This
shim sits alongside the tree's other jax-compat shims
(``parallel/mesh.shard_map``, ``ops/pallas_compat``) and makes the decision
once, at runner-build time:

- donating backend -> ``jax.jit(fn, donate_argnums=...)``;
- anything else (CPU, unknown, or a jax too old to accept the kwarg) ->
  plain ``jax.jit(fn)``.

Callers must treat every donated argument as CONSUMED: rebind the variable
to the call's output (zero-step warm calls return the carry unchanged, so
``state, *_ = runner(state, ...)`` is the donation-safe warm idiom — see
``cli._prepare_checkpointed``).
"""

from __future__ import annotations

import jax

# Backends whose runtimes implement input/output buffer aliasing. The CPU
# runtime accepts the annotation but ignores it with a per-call warning.
_DONATING_BACKENDS = ("tpu", "gpu", "cuda", "rocm")


def supports_donation() -> bool:
    """True when the default backend honors ``donate_argnums``."""
    try:
        return jax.default_backend() in _DONATING_BACKENDS
    except Exception:  # noqa: BLE001 - no backend at all: donation moot
        return False


def jit_donating(fn, donate_argnums=(0,)):
    """``jax.jit`` with buffer donation where the backend implements it.

    On non-donating backends (or a jax rejecting the kwarg) this is exactly
    ``jax.jit(fn)`` — bit-identical results either way; donation only
    changes buffer reuse, never values (pinned by the segment-equivalence
    tests).
    """
    if not supports_donation():
        return jax.jit(fn)
    try:
        return jax.jit(fn, donate_argnums=donate_argnums)
    except TypeError:
        # Ancient jax without the kwarg on this entry point: degrade to the
        # copying form rather than failing the build.
        return jax.jit(fn)
