"""Bitpacked Pallas stencil — 32 cells per word, bit-sliced adder network.

The fastest path, and the one that earns the TPU its keep. Where the
reference's CUDA kernel spends one thread per cell (src/game_cuda.cu:128-148),
this kernel packs 32 cells into each uint32 lane element and evolves all of
them with ~60 bitwise VPU ops per word — a carry-save adder network computing
all eight neighbor counts bit-parallel:

- Cells live packed as uint32 words along the width axis: bit j of word w is
  the cell at column ``w*32 + j``. HBM traffic per generation drops to ~2
  *bits* per cell.
- West/east neighbors are one-bit shifts within words, with the cross-word
  (and toroidal cross-row) carry bit delivered by a lane-roll of the word
  array.
- Neighbor counts come from a boolean adder tree: per-row 3:2 compressors,
  then a 4-bit carry-save sum. With count bits N = s0 + 2*b1 + 4*u0 + 8*u1,
  rule B3/S23 (src/game.c:91-98) collapses to
  ``new = b1 & ~(u0|u1) & (s0|mid)``.
- The alive/similar termination flags accumulate in SMEM exactly as in the
  unpacked Pallas kernel, so the engine's while_loop stays host-free.

Packing/unpacking happens once per run at the engine boundary (the grid state
carried through the generation loop stays packed); the text-I/O contract is
untouched.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.parallel.mesh import Topology

_BITS = 32
_SUBLANES = 8  # 32-bit tile granule: every row offset/extent must divide by 8
# Target VMEM bytes for one band of packed words; the ~10 live temporaries of
# the adder network and the double-buffered in/out blocks sit beside it.
_BAND_BYTES = 256 << 10


def supports(height: int, width: int, topology: Topology) -> bool:
    # Narrow word arrays (nwords < 128 lanes) are fine: Mosaic's dynamic
    # rotate operates on the logical shape, verified compiled on v5e down to
    # a single-word row (64x32 and 512x1152 grids match the oracle).
    return (
        not topology.distributed
        and width % _BITS == 0
        and height % _SUBLANES == 0
        and height >= _SUBLANES
    )


def _pick_band(height: int, words: int) -> int:
    row_bytes = max(words * 4, 1)
    target = max(_SUBLANES, min(height, _BAND_BYTES // row_bytes))
    for band in range(target, _SUBLANES - 1, -1):
        if height % band == 0 and band % _SUBLANES == 0:
            return band
    raise ValueError(f"no {_SUBLANES}-aligned band divides height {height}")


def encode(grid: jnp.ndarray) -> jnp.ndarray:
    """uint8 (H, W) cells -> uint32 (H, W/32) words (bit j = column w*32+j)."""
    height, width = grid.shape
    bits = grid.reshape(height, width // _BITS, _BITS).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(_BITS, dtype=jnp.uint32))[None, None, :]
    return jnp.sum(bits * weights, axis=-1, dtype=jnp.uint32)


def decode(words: jnp.ndarray) -> jnp.ndarray:
    """uint32 (H, W/32) words -> uint8 (H, W) cells."""
    height, nwords = words.shape
    shifts = jnp.arange(_BITS, dtype=jnp.uint32)[None, None, :]
    bits = (words[:, :, None] >> shifts) & jnp.uint32(1)
    return bits.astype(jnp.uint8).reshape(height, nwords * _BITS)


def _west(x: jnp.ndarray) -> jnp.ndarray:
    """Packed array of each cell's west (column-1) neighbor."""
    carry = jax.lax.shift_right_logical(
        pltpu.roll(x, 1, 1), jnp.uint32(_BITS - 1)
    )
    return jax.lax.shift_left(x, jnp.uint32(1)) | carry


def _east(x: jnp.ndarray) -> jnp.ndarray:
    """Packed array of each cell's east (column+1) neighbor."""
    carry = jax.lax.shift_left(
        pltpu.roll(x, x.shape[1] - 1, 1), jnp.uint32(_BITS - 1)
    )
    return jax.lax.shift_right_logical(x, jnp.uint32(1)) | carry


def _csa3(a, b, c):
    """3:2 compressor: sum and carry bitplanes of a+b+c."""
    axb = a ^ b
    return axb ^ c, (a & b) | (c & axb)


def _evolve_words(up, mid, down):
    """One generation for packed rows (up/mid/down already row-shifted)."""
    a0, a1 = _csa3(_west(up), up, _east(up))
    c0, c1 = _csa3(_west(down), down, _east(down))
    mw, me = _west(mid), _east(mid)
    m0, m1 = mw ^ me, mw & me
    s0, k0 = _csa3(a0, m0, c0)
    # count4 = a1 + m1 + c1 + k0 = 4*u1 + 2*u0 + b1
    p, q = a1 ^ m1, a1 & m1
    r, s = c1 ^ k0, c1 & k0
    b1, t = p ^ r, p & r
    u0, u1 = _csa3(q, s, t)[0], (q & s) | (t & (q ^ s))
    # N = s0 + 2*b1 + 4*u0 + 8*u1; B3/S23: alive iff N==3 or (N==2 and alive).
    return b1 & ~(u0 | u1) & (s0 | mid)


def _band_kernel(main_ref, top_ref, bot_ref, out_ref, alive_ref, similar_ref, *, band: int):
    i = pl.program_id(0)

    mid = main_ref[:]
    # Wrap rows arrive as aligned 8-row blocks; extract last/first row by a
    # masked sum-reduce (single-row sublane slices would be misaligned, and
    # Mosaic doesn't reduce unsigned vectors — bitcast to i32; the sum is
    # exact because exactly one row survives the mask).
    r8 = jax.lax.broadcasted_iota(jnp.int32, (8, mid.shape[1]), 0)

    def _extract(block_ref, row_index):
        block = jax.lax.bitcast_convert_type(block_ref[:], jnp.int32)
        row = jnp.sum(jnp.where(r8 == row_index, block, 0), axis=0, keepdims=True)
        return jax.lax.bitcast_convert_type(row, jnp.uint32)

    top_row = _extract(top_ref, 7)
    bot_row = _extract(bot_ref, 0)
    rows = jax.lax.broadcasted_iota(jnp.int32, mid.shape, 0)
    up = jnp.where(rows == 0, jnp.broadcast_to(top_row, mid.shape), pltpu.roll(mid, 1, 0))
    down = jnp.where(
        rows == band - 1, jnp.broadcast_to(bot_row, mid.shape), pltpu.roll(mid, band - 1, 0)
    )

    new = _evolve_words(up, mid, down)
    out_ref[:] = new

    alive = jnp.max(jnp.where(new != 0, 1, 0))
    similar = 1 - jnp.max(jnp.where((new ^ mid) != 0, 1, 0))

    @pl.when(i == 0)
    def _init():
        alive_ref[0, 0] = alive
        similar_ref[0, 0] = similar

    @pl.when(i > 0)
    def _accumulate():
        alive_ref[0, 0] = alive_ref[0, 0] | alive
        similar_ref[0, 0] = similar_ref[0, 0] & similar


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step(words: jnp.ndarray, interpret: bool = False):
    height, nwords = words.shape
    band = _pick_band(height, nwords)
    bb = band // _SUBLANES
    nb = height // _SUBLANES
    new, alive, similar = pl.pallas_call(
        functools.partial(_band_kernel, band=band),
        grid=(height // band,),
        in_specs=[
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (_SUBLANES, nwords),
                lambda i: ((i * bb - 1) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_SUBLANES, nwords),
                lambda i: ((i * bb + bb) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((height, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words)
    return new, alive[0, 0] > 0, similar[0, 0] > 0


def packed_step(cur: jnp.ndarray, topology: Topology):
    """Fused generation step on packed state: ``words -> (words, alive, similar)``."""
    height, nwords = cur.shape
    if not supports(height, nwords * _BITS, topology):
        raise ValueError(
            f"the packed kernel requires a single-device grid with height a "
            f"multiple of {_SUBLANES} and width a multiple of {_BITS}; got "
            f"{height}x{nwords * _BITS} on {topology.shape[0]}x"
            f"{topology.shape[1]} devices — use kernel='lax' (or 'auto')"
        )
    interpret = jax.default_backend() != "tpu"
    return _step(cur, interpret=interpret)
