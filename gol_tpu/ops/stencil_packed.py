"""Bitpacked Pallas stencil — 32 cells per word, bit-sliced adder network.

The fastest path, and the one that earns the TPU its keep. Where the
reference's CUDA kernel spends one thread per cell (src/game_cuda.cu:128-148),
this kernel packs 32 cells into each uint32 lane element and evolves all of
them with ~34 bitwise VPU ops per word — a carry-save adder network computing
all eight neighbor counts bit-parallel, sharing each row's horizontal sums
with all three output rows it feeds:

- Cells live packed as uint32 words along the width axis: bit j of word w is
  the cell at column ``w*32 + j``. HBM traffic per generation drops to ~2
  *bits* per cell.
- West/east neighbors are one-bit shifts within words, with the cross-word
  (and toroidal cross-row) carry bit delivered by a lane-roll of the word
  array.
- Neighbor counts come from a boolean adder tree that shares work across
  rows: each row's horizontal triple sum is computed once
  (``packed_math.row_sums``) and re-ranked by row shifts for the vertical
  combine (``packed_math.combine``) — ~28 bitwise ops + 6 rolls per word.
- The alive/similar termination flags accumulate in SMEM exactly as in the
  unpacked Pallas kernel, so the engine's while_loop stays host-free.

Packing/unpacking happens once per run at the engine boundary (the grid state
carried through the generation loop stays packed); the text-I/O contract is
untouched.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.ops import packed_math
from gol_tpu.ops.pallas_compat import CompilerParams as _CompilerParams
from gol_tpu.parallel import collectives, halo
from gol_tpu.parallel.mesh import ROW_AXIS, SINGLE_DEVICE as SINGLE_DEVICE_TOPOLOGY, Topology

_BITS = packed_math.BITS
_SUBLANES = 8  # 32-bit tile granule: every row offset/extent must divide by 8
# Word-count cap: the kernel's live temporaries scale with nwords, so very
# wide rows exhaust scoped VMEM regardless of band height. Empirical limit
# on v5e: 32768 words (width 2^20) compiles and matches the oracle with the
# width-aware 1MB band target (_pick_band), 65536 VMEM-OOMs at compile.
_MAX_WORDS = 32 << 10
# Target VMEM bytes for one band of packed words; the adder network's live
# temporaries and the double-buffered in/out blocks sit beside it. Measured
# at 16384^2 on v5e (interleaved A/B, net of dispatch): 1MB beat 256KB by
# +11%, and 2MB beats 1MB by another ~7% (2.73 Tcells/s marginal) — the
# "2MB OOMs" note from the pre-row-sum-sharing network no longer holds
# after its live set shrank.
_BAND_BYTES = 2 << 20

# Measured-plan band target (gol_tpu/tune): when set, replaces the
# width-aware default target in _pick_band/_bandt_target. Read at TRACE
# time, so it must be set before the runner's first call compiles
# (engine._build_runner applies it when a plan names one; per-process — two
# live plans wanting different targets would need per-kernel plumbing this
# deliberately avoids). The temporal kernels still clamp the override
# through their scoped-VMEM budget, so a stale plan can shrink a band but
# never Mosaic-OOM one.
_BAND_TARGET_OVERRIDE: int | None = None


def set_band_target_override(target_bytes: int | None) -> None:
    global _BAND_TARGET_OVERRIDE
    _BAND_TARGET_OVERRIDE = target_bytes


# Re-exported for the kernel registry: the engine packs/unpacks at the loop
# boundary through these.
encode = packed_math.encode
decode = packed_math.decode


def supports(height: int, width: int, topology) -> bool:
    """Packed paths: compiled Pallas single-device, jnp+ppermute distributed.

    Narrow word arrays (nwords < 128 lanes) are fine: Mosaic's dynamic rotate
    operates on the logical shape, verified compiled on v5e down to a
    single-word row (64x32 and 512x1152 grids match the oracle). ``width``
    and ``height`` are the LOCAL shard shape under a mesh.
    """
    if width % _BITS != 0 or width // _BITS > _MAX_WORDS:
        return False
    if topology.distributed:
        return True  # odd heights fall to the jnp path, no tiling constraints
    return height % _SUBLANES == 0 and height >= _SUBLANES


def supports_jnp(height: int, width: int, topology) -> bool:
    """Shape gate for the pure-jnp adder network (kernel='packed-jnp'):
    packing is the ONLY constraint — no Pallas tiling, no VMEM caps — so
    any height and any width multiple of 32 runs. This is what lets `auto`
    give odd-height single-device grids the 32-cells/word network instead
    of falling all the way to the byte lax kernel (r4 verdict weak #5);
    distributed odd-height shards already took this path."""
    return width % _BITS == 0


def supports_multi_jnp(height: int, width: int, topology) -> bool:
    """Temporal blocking on the jnp network: a single device needs nothing
    beyond packing (the torus evolve is height-agnostic); distributed
    shards need the deep-halo ghost-row depth."""
    if not supports_jnp(height, width, topology):
        return False
    if not topology.distributed:
        return True
    return height >= 2 * TEMPORAL_GENS


def _pick_band(height: int, words: int, target_bytes: int | None = None) -> int:
    # VMEM rows are padded to full 128-lane tiles: a 3-word strip still
    # occupies 512 bytes per row on chip, so narrow arrays must budget by
    # the padded width or a whole-height band blows scoped VMEM.
    row_bytes = max(words, 128) * 4
    if target_bytes is None:
        # Width-aware default: the kernel's live set scales with the band, so
        # 64KB+ rows (16K+ words) keep the 1MB target whose band sizes were
        # compile-validated up to the _MAX_WORDS cap; 2MB 16-row bands at
        # 32768 words fail to compile. A measured plan's override wins.
        target_bytes = _BAND_TARGET_OVERRIDE or (
            _BAND_BYTES if row_bytes < (64 << 10) else (1 << 20)
        )
    target = max(_SUBLANES, min(height, target_bytes // row_bytes))
    for band in range(target, _SUBLANES - 1, -1):
        if height % band == 0 and band % _SUBLANES == 0:
            return band
    raise ValueError(f"no {_SUBLANES}-aligned band divides height {height}")


def _vertical_combine(s0, s1, m0, m1, mid, t0, t1, b0, b1, band):
    """Finish a band: re-rank the per-row horizontal sums by row shifts.

    ``t*``/``b*`` are the wrap rows' (1, nwords) triple-sum planes; interior
    rows take the adjacent row's planes via a sublane roll. Shared by the
    single-device and mesh band kernels, which differ only in how wrap rows
    and seam carries are sourced.
    """
    rows = jax.lax.broadcasted_iota(jnp.int32, mid.shape, 0)

    def shift_down(plane, wrap_row):
        return jnp.where(
            rows == 0, jnp.broadcast_to(wrap_row, mid.shape), pltpu.roll(plane, 1, 0)
        )

    def shift_up(plane, wrap_row):
        return jnp.where(
            rows == band - 1,
            jnp.broadcast_to(wrap_row, mid.shape),
            pltpu.roll(plane, band - 1, 0),
        )

    return packed_math.combine(
        shift_down(s0, t0), shift_down(s1, t1),
        shift_up(s0, b0), shift_up(s1, b1),
        m0, m1, mid,
    )


def _band_kernel(main_ref, top_ref, bot_ref, out_ref, alive_ref, similar_ref, *, band: int):
    i = pl.program_id(0)

    mid = main_ref[:]
    # Wrap rows arrive as aligned 8-row blocks; extract last/first row by a
    # masked sum-reduce (single-row sublane slices would be misaligned, and
    # Mosaic doesn't reduce unsigned vectors — bitcast to i32; the sum is
    # exact because exactly one row survives the mask).
    r8 = jax.lax.broadcasted_iota(jnp.int32, (8, mid.shape[1]), 0)

    def _extract(block_ref, row_index):
        block = jax.lax.bitcast_convert_type(block_ref[:], jnp.int32)
        row = jnp.sum(jnp.where(r8 == row_index, block, 0), axis=0, keepdims=True)
        return jax.lax.bitcast_convert_type(row, jnp.uint32)

    top_row = _extract(top_ref, 7)
    bot_row = _extract(bot_ref, 0)
    nwords = mid.shape[1]

    def hs(x):
        left = pltpu.roll(x, 1 % nwords, 1)
        right = pltpu.roll(x, (nwords - 1) % nwords, 1)
        return packed_math.row_sums(x, left, right)

    # Horizontal triple sums once per row; the wrap rows' sums are 1-row work.
    m0, m1, s0, s1 = hs(mid)
    _, _, t0, t1 = hs(top_row)
    _, _, b0, b1 = hs(bot_row)
    new = _vertical_combine(s0, s1, m0, m1, mid, t0, t1, b0, b1, band)
    out_ref[:] = new

    alive = jnp.any(new != 0).astype(jnp.int32)
    similar = 1 - jnp.any((new ^ mid) != 0).astype(jnp.int32)

    @pl.when(i == 0)
    def _init():
        alive_ref[0, 0] = alive
        similar_ref[0, 0] = similar

    @pl.when(i > 0)
    def _accumulate():
        alive_ref[0, 0] = alive_ref[0, 0] | alive
        similar_ref[0, 0] = similar_ref[0, 0] & similar


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step(words: jnp.ndarray, interpret: bool = False):
    height, nwords = words.shape
    band = _pick_band(height, nwords)
    bb = band // _SUBLANES
    nb = height // _SUBLANES
    new, alive, similar = pl.pallas_call(
        functools.partial(_band_kernel, band=band),
        grid=(height // band,),
        in_specs=[
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (_SUBLANES, nwords),
                lambda i: ((i * bb - 1) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_SUBLANES, nwords),
                lambda i: ((i * bb + bb) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((height, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words)
    return new, alive[0, 0] > 0, similar[0, 0] > 0


# Temporal blocking: generations fused per VMEM pass, and the band target for
# that kernel's larger live set. The 8-row aligned wrap blocks over-fetch far
# more halo than one generation needs (16 ghost rows support up to 8 fused
# generations), so T=8 uses the whole validity budget: vs the single-gen
# kernel the T=4 pass measured parity-to-1.3x and T=8 adds another ~2% at
# 16384^2 (compute-bound) and ~11% at 65536^2 (HBM-weighted) — net-of-
# dispatch interleaved A/B on v5e, chain-length differencing to cancel the
# attach tunnel's ~90ms fixed round trip. Each doubling of the band target
# shrinks the 16-ghost-row over-fetch fraction: 512KB -> 1MB gained
# +12%/+14% (16384^2/65536^2), 1MB -> 2MB another +6%/+8% (2.99/2.63
# Tcells/s); 4MB fails to compile at 65536^2 (512-row bands), so 2MB is
# the ceiling. At the width cap below the 2MB target means 128-row bands —
# verified to compile and match the oracle at (1024, 2^17).
TEMPORAL_GENS = 8
_BANDT_BYTES = 2 << 20


# Scoped-VMEM budget for a temporal kernel's (band + 2T)-row extended block,
# with rows PADDED to whole 128-lane tiles (what Mosaic allocates). The
# r3 rule dropped the band target only at exactly nwords >= _MAX_WORDS_T,
# but the blowup it guards is continuous in width (advisor r3, medium).
# Mapped on v5e by compile probes over ALL THREE temporal forms
# (benchmarks/vmem_probe_r4.json + cap_raise_r4.json): the largest extended
# block that compiles in every form is 7168 words x (64+16) rows =
# 2,293,760 bytes (scoped usage runs ~6.6x the extended block, right under
# the 16MB limit there); 2,359,296 bytes already fails for the MESH forms
# at wide rows (their two full-width 8-row ghost operands add ~0.8MB:
# 12288 words x (32+16) rows overflowed scoped VMEM by 348KB) and
# 2,457,600+ fails every form (7680 x 80). The budget is the
# all-forms-measured-OK maximum, inclusive — which also keeps the headline
# 65536^2 config (2048 words x 272 rows = 2,228,224) on its measured-fast
# 2MB/256-row bands. Re-probed at the boundary by
# test_tpu_hw.py::test_temporal_near_cap_widths.
_BANDT_EXT_BUDGET = (2 << 20) + (192 << 10)


def _bandt_target(height: int, nwords: int) -> int:
    """Band byte target for the temporal kernels: the largest target whose
    ACTUAL band (``_pick_band`` under this height's divisors) keeps the
    padded extended block within ``_BANDT_EXT_BUDGET``. Width-continuous —
    near-cap rows shrink the target before the cap, instead of jumping from
    the 2MB target straight to a Mosaic OOM at the _MAX_WORDS_T edge."""
    padded_row = max(-(-nwords // 128) * 128, 128) * 4
    targets = (_BANDT_BYTES, 3 << 19, 1 << 20)
    if _BAND_TARGET_OVERRIDE:
        # Plan override first, but still budget-gated below — falls through
        # to the built-in ladder when it would blow scoped VMEM.
        targets = (_BAND_TARGET_OVERRIDE, *targets)
    for target in targets:
        band = _pick_band(height, nwords, target)
        if (band + 2 * TEMPORAL_GENS) * padded_row <= _BANDT_EXT_BUDGET:
            return target
    return 1 << 20


def _vroll_combine(s0, s1, m0, m1, x):
    """Vertical combine over a whole extended block: re-rank the triple-sum
    planes by ±1 sublane torus rolls (the roll-seam rows are the callers'
    garbage frontier) and finish B3/S23."""
    rows = x.shape[0]
    return packed_math.combine(
        pltpu.roll(s0, 1, 0), pltpu.roll(s1, 1, 0),
        pltpu.roll(s0, rows - 1, 0), pltpu.roll(s1, rows - 1, 0),
        m0, m1, x,
    )


def _evolve_with_ghost_plane(x, G, lanes, glanes):
    """One generation of an extended block plus its (·, 2) ghost plane.

    ``G`` carries BOTH ghost word columns (west in lane 0, east in lane 1),
    row-aligned with ``x``. Each generation patches the two edge words'
    cross-seam neighbor words from those lanes and evolves both ghost
    columns in ONE extra adder-network pass over the combined plane — their
    outer-side inputs are garbage, which advances one bit per generation
    from the far edge of the 32-bit word, so the carry bits stay exact for
    TEMPORAL_GENS <= 8. This keeps the main block at its natural lane
    width: concatenating ghost columns instead costs an extra 128-lane tile
    per band wherever nwords is a tile multiple (measured 35% at 16384^2).
    """
    rows, nwords = x.shape
    left = pltpu.roll(x, 1 % nwords, 1)
    right = pltpu.roll(x, (nwords - 1) % nwords, 1)
    gw = G[:, 0:1]
    ge = G[:, 1:2]
    left = jnp.where(lanes == 0, jnp.broadcast_to(gw, (rows, nwords)), left)
    right = jnp.where(lanes == nwords - 1, jnp.broadcast_to(ge, (rows, nwords)), right)
    m0, m1, s0, s1 = packed_math.row_sums(x, left, right)
    new_x = _vroll_combine(s0, s1, m0, m1, x)
    # Evolve the ghost plane from current-generation values: the west
    # ghost's east neighbor is shard word 0, the east ghost's west neighbor
    # is shard word nwords-1; their outer-side inputs are garbage (zeros)
    # that never crosses the 32-bit word within 8 generations.
    x0 = x[:, 0:1]
    xl = x[:, nwords - 1 : nwords]
    zero = jnp.zeros_like(G)
    g_left = jnp.where(glanes == 1, jnp.broadcast_to(xl, G.shape), zero)
    g_right = jnp.where(glanes == 0, jnp.broadcast_to(x0, G.shape), zero)
    m0g, m1g, s0g, s1g = packed_math.row_sums(G, g_left, g_right)
    return new_x, _vroll_combine(s0g, s1g, m0g, m1g, G)


def _record_flags(i, flags, alive_ref, similar_ref):
    """Accumulate per-generation (alive, similar) pairs into the SMEM flag
    vectors across the sequential band grid."""

    @pl.when(i == 0)
    def _init():
        for t, (alive, similar) in enumerate(flags):
            alive_ref[0, t] = alive
            similar_ref[0, t] = similar

    @pl.when(i > 0)
    def _accumulate():
        for t, (alive, similar) in enumerate(flags):
            alive_ref[0, t] = alive_ref[0, t] | alive
            similar_ref[0, t] = similar_ref[0, t] & similar


def _record_summary(i, vals, summ_ref):
    """Accumulate the fast-flag pass summary ``(in_alive, out_alive, simT,
    sim1)`` across the sequential band grid: OR for the alive pair, AND for
    the similarity pair."""

    @pl.when(i == 0)
    def _init():
        for j, v in enumerate(vals):
            summ_ref[0, j] = v

    @pl.when(i > 0)
    def _accumulate():
        summ_ref[0, 0] = summ_ref[0, 0] | vals[0]
        summ_ref[0, 1] = summ_ref[0, 1] | vals[1]
        summ_ref[0, 2] = summ_ref[0, 2] & vals[2]
        summ_ref[0, 3] = summ_ref[0, 3] & vals[3]


def _derive_or_replay(summary, exact_thunk, topology=None):
    """Per-generation flag vectors from the pass summary, exact always.

    Both exit conditions are MONOTONE within a pass OVER THE WHOLE TORUS:
    an empty generation stays empty forever (no cell has three neighbors),
    and a generation equal to its predecessor is a still life, equal
    forever after. Hence, for the GLOBAL summary:

    - ``out_alive == 1``  => no generation died  => alive_vec all ones;
      ``in_alive == 0``   => all were empty      => alive_vec all zeros
      (and ``out_alive`` is 0 too, so ``full(out_alive)`` covers both);
    - ``simT == 0``       => no adjacent pair was equal => zeros;
      ``sim1 == 1``       => the input was already still => ones
      (``full(simT)`` covers both).

    Monotonicity does NOT hold per shard — a shard is an open system, and
    a cross-boundary transient can enter and die out between a shard's
    summary taps (g0/g1 and g7/g8), making its local summary lie (found
    by adversarial search: tests/test_packed.py::
    test_fast_flag_cross_shard_transient pins a 4-shard grid whose
    locally-derived, engine-voted similarity vector fires a generation
    early). So under a mesh the four scalars are VOTED globally first —
    alive pair by any_flag, similarity pair by all_agree — and the
    derivation happens on the closed-system summary; the replay predicate
    is then replicated across shards, so every shard replays together
    (the replay kernel is collective-free either way).

    Only a transition INSIDE the pass — global death (in=1, out=0) or
    global stillness onset (simT=1, sim1=0) — needs the per-generation
    flag kernel, and each happens at most once per run, right before the
    run exits; ``lax.cond`` pays that replay only when it fires. This
    removes 14 of the 16 per-pass flag reductions that measured 29-34% of
    the whole kernel (benchmarks/roofline_flags_r4.json).
    """
    in_alive, out_alive = summary[0, 0], summary[0, 1]
    simT, sim1 = summary[0, 2], summary[0, 3]
    if topology is not None and topology.distributed:
        in_alive = collectives.any_flag(in_alive, topology).astype(jnp.int32)
        out_alive = collectives.any_flag(out_alive, topology).astype(jnp.int32)
        simT = collectives.all_agree(simT, topology).astype(jnp.int32)
        sim1 = collectives.all_agree(sim1, topology).astype(jnp.int32)
    need = ((in_alive == 1) & (out_alive == 0)) | ((simT == 1) & (sim1 == 0))
    T = TEMPORAL_GENS

    def derived():
        return (jnp.full((T,), out_alive, jnp.int32),
                jnp.full((T,), simT, jnp.int32))

    return jax.lax.cond(need, exact_thunk, derived)


def _bandt_kernel(
    main_ref, top_ref, bot_ref, out_ref, alive_ref, similar_ref,
    *, band: int, interior=None,
):
    """TEMPORAL_GENS generations per VMEM pass (temporal blocking), torus form.

    Each generation is computed over the full (band+16)-row extended block
    with rolled row shifts; the rows adjacent to the roll seam are garbage,
    but garbage spreads one row per generation and the interior starts 8
    rows in, so the interior (an aligned [8, band+8) slice) stays exact for
    up to 8 fused generations. Per-generation flags accumulate in SMEM so
    the engine's blocked termination replay stays per-generation exact
    (mid-pass exits are fixed points — see engine._simulate_c_block).

    ``interior`` = (row_lo, row_hi, col_lo, col_hi), absolute over the whole
    array: when the array holds ghost rows/columns the flags must see only
    those cells (the assembled-extended-block form; the production mesh path
    is ``_bandtg_kernel``, whose operands carry ghosts separately).
    """
    i = pl.program_id(0)
    x = jnp.concatenate([top_ref[:], main_ref[:], bot_ref[:]], axis=0)
    nwords = x.shape[1]

    def evolve_full(x):
        # Torus column wrap via lane rolls; row wrap via sublane rolls whose
        # wrapped-in rows are garbage only at the extended block's two ends.
        left = pltpu.roll(x, 1 % nwords, 1)
        right = pltpu.roll(x, (nwords - 1) % nwords, 1)
        m0, m1, s0, s1 = packed_math.row_sums(x, left, right)
        return _vroll_combine(s0, s1, m0, m1, x)

    prev = main_ref[:]
    bitmask = None
    if interior is not None:
        row_lo, row_hi, col_lo, col_hi = interior
        r = jax.lax.broadcasted_iota(jnp.int32, (band, nwords), 0) + i * band
        c = jax.lax.broadcasted_iota(jnp.int32, (band, nwords), 1)
        mask = (r >= row_lo) & (r < row_hi) & (c >= col_lo) & (c < col_hi)
        bitmask = jnp.where(mask, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    flags = []
    for _ in range(TEMPORAL_GENS):
        x = evolve_full(x)
        g = x[8 : band + 8]
        live = g if bitmask is None else g & bitmask
        diff = (g ^ prev) if bitmask is None else (g ^ prev) & bitmask
        alive = jnp.any(live != 0).astype(jnp.int32)
        similar = 1 - jnp.any(diff != 0).astype(jnp.int32)
        flags.append((alive, similar))
        prev = g
    out_ref[:] = prev
    _record_flags(i, flags, alive_ref, similar_ref)


def _bandtg_kernel(
    main_ref, topn_ref, botn_ref, gtop_ref, gbot_ref,
    ga_ref, gb_ref, gc_ref,
    out_ref, alive_ref, similar_ref,
    *, band: int, nbands: int,
):
    """TEMPORAL_GENS generations per pass for one mesh shard, banded operands.

    Same temporal-blocking shape as ``_bandt_kernel``, but the (band+16)-row
    extended block is assembled in VMEM from banded operands: the shard band,
    its 8-row neighbor blocks (replaced by the ppermute'd TEMPORAL_GENS-row
    ghost blocks at the shard's first/last band), and the row-aligned
    (·, 2) ghost-column plane. No (h + 2T, nwords) extended array ever
    exists in HBM and the output is the shard rows directly — the
    materialized-extended-array form this replaces spent ~2.4 ms/pass on
    pure concat/slice HBM traffic at 32768², vs 3.4 ms for the whole kernel.
    Flags need no interior mask: the main band block holds exactly the
    shard's own rows.
    """
    i = pl.program_id(0)
    top_ctx = jnp.where(i == 0, gtop_ref[:], topn_ref[:])
    bot_ctx = jnp.where(i == nbands - 1, gbot_ref[:], botn_ref[:])
    x = jnp.concatenate([top_ctx, main_ref[:], bot_ctx], axis=0)
    G = jnp.concatenate([ga_ref[:], gb_ref[:], gc_ref[:]], axis=0)
    rows, nwords = x.shape  # (band + 16, nwords)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (rows, nwords), 1)
    glanes = jax.lax.broadcasted_iota(jnp.int32, G.shape, 1)

    prev = main_ref[:]
    flags = []
    for _ in range(TEMPORAL_GENS):
        x, G = _evolve_with_ghost_plane(x, G, lanes, glanes)
        g = x[8 : band + 8]
        alive = jnp.any(g != 0).astype(jnp.int32)
        similar = 1 - jnp.any((g ^ prev) != 0).astype(jnp.int32)
        flags.append((alive, similar))
        prev = g
    out_ref[:] = prev
    _record_flags(i, flags, alive_ref, similar_ref)


def _fast_target(height: int, nwords: int) -> int:
    """Band target for the fast-flag kernels: the temporal target capped at
    512-row bands. Their summary bookkeeping extends operand liveness in a
    way Mosaic's scoped-VMEM scheduler is sensitive to: 1024-row and
    2048-row fast bands Mosaic-OOMed at 17.4-17.5M scoped (shapes where
    the exact kernel fits), and the measured boundary moved between two
    equivalent formulations of the same summary math — so the cap keeps a
    2x margin below the failures instead of riding the boundary. The
    extra ghost-row overfetch at the capped shapes is <= 1.6% and the
    fast path still measures 1.2x the exact kernel end to end."""
    row_bytes = max(nwords, 128) * 4
    return min(_bandt_target(height, nwords), 512 * row_bytes)


def _fast_pass_body(i, x, main_ref, out_ref, summ_ref, band,
                    bitmask=None, stitch=None):
    """Shared body of the fast-flag kernels: evolve the extended block
    TEMPORAL_GENS generations and record the pass summary. Callers differ
    only in how ``x``'s top/bottom context rows are sourced, and (for the
    split-edge form) in ``bitmask`` — the flag-visibility mask ANDed into
    every summary read (the main pass excludes the two edge word columns;
    the strip pass sees only them) — and ``stitch``, a final-state
    transform applied at the output write (the edge-column stitch).

    Liveness note: the summary scalars are computed in place (the g_1
    plane is never retained) — keeping it live across the unrolled
    generations grew the scoped-VMEM stack past 16M at the 65536^2
    band configuration; see also the 512-row band cap in ``_fast_target``.
    """
    nwords = x.shape[1]

    def seen(plane):
        return plane if bitmask is None else plane & bitmask

    g0 = main_ref[:]
    in_alive = jnp.any(seen(g0) != 0).astype(jnp.int32)
    prev = g0
    for t in range(TEMPORAL_GENS):
        left = pltpu.roll(x, 1 % nwords, 1)
        right = pltpu.roll(x, (nwords - 1) % nwords, 1)
        m0, m1, s0, s1 = packed_math.row_sums(x, left, right)
        x = _vroll_combine(s0, s1, m0, m1, x)
        g = x[8 : band + 8]
        if t == 0:
            sim1 = 1 - jnp.any(seen(g ^ g0) != 0).astype(jnp.int32)
        if t == TEMPORAL_GENS - 1:
            simT = 1 - jnp.any(seen(g ^ prev) != 0).astype(jnp.int32)
            out_alive = jnp.any(seen(g) != 0).astype(jnp.int32)
        prev = g
    out_ref[:] = prev if stitch is None else stitch(prev)
    _record_summary(i, (in_alive, out_alive, simT, sim1), summ_ref)


def _bandt_fast_kernel(main_ref, top_ref, bot_ref, out_ref, summ_ref, *, band: int):
    """``_bandt_kernel`` with the per-generation flag math replaced by the
    four pass-level summary scalars (see ``_derive_or_replay``)."""
    i = pl.program_id(0)
    x = jnp.concatenate([top_ref[:], main_ref[:], bot_ref[:]], axis=0)
    _fast_pass_body(i, x, main_ref, out_ref, summ_ref, band)


def _bandtrow_fast_kernel(
    main_ref, topn_ref, botn_ref, gtop_ref, gbot_ref, out_ref, summ_ref,
    *, band: int, nbands: int,
):
    """``_bandtrow_kernel`` with pass-summary flags (see ``_derive_or_replay``)."""
    i = pl.program_id(0)
    top_ctx = jnp.where(i == 0, gtop_ref[:], topn_ref[:])
    bot_ctx = jnp.where(i == nbands - 1, gbot_ref[:], botn_ref[:])
    x = jnp.concatenate([top_ctx, main_ref[:], bot_ctx], axis=0)
    _fast_pass_body(i, x, main_ref, out_ref, summ_ref, band)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step_t_fast(words: jnp.ndarray, interpret: bool = False):
    """Fast-flag torus pass: summary scalars per pass, with the exact
    per-generation kernel replayed under lax.cond only on the (at most
    once-per-run) pass where an exit fires mid-pass."""
    height, nwords = words.shape
    band = _pick_band(height, nwords, _fast_target(height, nwords))
    nb = height // _SUBLANES
    new, summ = pl.pallas_call(
        functools.partial(_bandt_fast_kernel, band=band),
        grid=(height // band,),
        in_specs=_banded_specs(band, nwords, nb),
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 4), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((height, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, 4), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words)
    alive, similar = _derive_or_replay(
        summ, lambda: _step_t(words, interpret=interpret)[1:]
    )
    return new, alive, similar


@functools.partial(jax.jit, static_argnames=("interpret", "topology"))
def _step_trow_fast(words: jnp.ndarray, gtop: jnp.ndarray, gbot: jnp.ndarray,
                    topology: Topology = SINGLE_DEVICE_TOPOLOGY,
                    interpret: bool = False):
    """Fast-flag rows-only pass (see ``_step_t_fast``). ``topology`` is
    needed because the summary scalars must be voted ACROSS shards before
    the monotone derivation — see ``_derive_or_replay``."""
    h, nwords = words.shape
    band = _pick_band(h, nwords, _fast_target(h, nwords))
    nb = h // _SUBLANES
    new, summ = pl.pallas_call(
        functools.partial(_bandtrow_fast_kernel, band=band, nbands=h // band),
        grid=(h // band,),
        in_specs=[
            *_banded_specs(band, nwords, nb),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 4), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((h, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, 4), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words, gtop, gbot)
    alive, similar = _derive_or_replay(
        summ, lambda: _step_trow(words, gtop, gbot, interpret=interpret)[1:],
        topology,
    )
    return new, alive, similar


def _bandtrow_kernel(
    main_ref, topn_ref, botn_ref, gtop_ref, gbot_ref,
    out_ref, alive_ref, similar_ref,
    *, band: int, nbands: int,
):
    """TEMPORAL_GENS generations per pass for one FULL-WIDTH mesh shard.

    The rows-only specialization of ``_bandtg_kernel`` for R x 1 meshes
    (row-only domain decomposition): the shard spans the whole grid width,
    so the east/west torus wrap is the shard's own lane roll — exactly the
    single-device kernel's column handling — and the entire ghost-column
    plane (its per-generation adder pass, the per-row edge patches, and the
    column-phase exchange feeding it) vanishes. Only the vertical context
    differs from ``_bandt_kernel``: the first/last band take the
    ppermute'd TEMPORAL_GENS-row ghost blocks instead of the modular wrap.

    Row-only decomposition is also the recommended pod layout for this
    stencil: per-chip comm drops to the two N/S ghost-row blocks riding one
    ICI ring axis (the reference's E/W column messages and 4 corner
    requests, src/game_mpi.c:340-383, have no analog here at all).
    (``_bandtrow_stitch_kernel`` is this kernel adapted as the split-edge
    2D form's main pass: edge-masked flags + fused edge-column stitch.)
    """
    i = pl.program_id(0)
    top_ctx = jnp.where(i == 0, gtop_ref[:], topn_ref[:])
    bot_ctx = jnp.where(i == nbands - 1, gbot_ref[:], botn_ref[:])
    x = jnp.concatenate([top_ctx, main_ref[:], bot_ctx], axis=0)
    nwords = x.shape[1]

    def evolve_full(x):
        left = pltpu.roll(x, 1 % nwords, 1)
        right = pltpu.roll(x, (nwords - 1) % nwords, 1)
        m0, m1, s0, s1 = packed_math.row_sums(x, left, right)
        return _vroll_combine(s0, s1, m0, m1, x)

    prev = main_ref[:]
    flags = []
    for _ in range(TEMPORAL_GENS):
        x = evolve_full(x)
        g = x[8 : band + 8]
        alive = jnp.any(g != 0).astype(jnp.int32)
        similar = 1 - jnp.any((g ^ prev) != 0).astype(jnp.int32)
        flags.append((alive, similar))
        prev = g
    out_ref[:] = prev
    _record_flags(i, flags, alive_ref, similar_ref)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step_trow(words: jnp.ndarray, gtop: jnp.ndarray, gbot: jnp.ndarray,
               interpret: bool = False):
    """Temporal pass for one full-width (h, nwords) shard from N/S ghost
    blocks only (see ``_bandtrow_kernel``)."""
    h, nwords = words.shape
    band = _pick_band(h, nwords, _bandt_target(h, nwords))
    nb = h // _SUBLANES
    T = TEMPORAL_GENS
    new, alive, similar = pl.pallas_call(
        functools.partial(_bandtrow_kernel, band=band, nbands=h // band),
        grid=(h // band,),
        in_specs=[
            *_banded_specs(band, nwords, nb),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, T), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((h, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words, gtop, gbot)
    return new, alive[0], similar[0]


def _banded_specs(band: int, nwords: int, nb: int):
    """The (main, top-wrap, bot-wrap) BlockSpec triple every temporal
    operand uses: a band-aligned block plus the 8-row neighbor blocks
    wrapped modulo the whole array."""
    bb = band // _SUBLANES
    return [
        pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec(
            (_SUBLANES, nwords),
            lambda i: ((i * bb - 1) % nb, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (_SUBLANES, nwords),
            lambda i: ((i * bb + bb) % nb, 0),
            memory_space=pltpu.VMEM,
        ),
    ]


@functools.partial(jax.jit, static_argnames=("interpret", "interior"))
def _step_t(words: jnp.ndarray, interpret: bool = False, interior=None):
    height, nwords = words.shape
    band = _pick_band(height, nwords, _bandt_target(height, nwords))
    nb = height // _SUBLANES
    T = TEMPORAL_GENS
    new, alive, similar = pl.pallas_call(
        functools.partial(_bandt_kernel, band=band, interior=interior),
        grid=(height // band,),
        in_specs=_banded_specs(band, nwords, nb),
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, T), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((height, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words)
    return new, alive[0], similar[0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step_tgb(words: jnp.ndarray, gtop: jnp.ndarray, gbot: jnp.ndarray,
              G_ext: jnp.ndarray, interpret: bool = False):
    """Temporal pass for one (h, nwords) shard from banded ghost operands.

    ``gtop``/``gbot`` are the ppermute'd TEMPORAL_GENS-row ghost word blocks
    (neighbor's far rows); ``G_ext`` is the (h + 2T, 2) ghost-column plane
    covering extended rows -T..h+T-1 (west column in lane 0, east in lane
    1). Returns ``(new_words, alive_vec, similar_vec)`` — shard-shaped
    output, flags over exactly the shard's cells.

    Row alignment leans on T == 8 == the sublane granule: band i's extended
    block covers shard rows [i*band - 8, i*band + band + 8), which in
    ``G_ext``'s indexing (row j = shard row j - 8) is rows
    [i*band, i*band + band + 16) — one (band, 2) banded block plus two
    8-row blocks at block offsets (i+1)*band/8 and (i+1)*band/8 + 1, all
    exactly expressible as BlockSpecs with no overlap tricks.
    """
    h, nwords = words.shape
    band = _pick_band(h, nwords, _bandt_target(h, nwords))
    bb = band // _SUBLANES
    nb = h // _SUBLANES
    T = TEMPORAL_GENS
    new, alive, similar = pl.pallas_call(
        functools.partial(_bandtg_kernel, band=band, nbands=h // band),
        grid=(h // band,),
        in_specs=[
            *_banded_specs(band, nwords, nb),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((band, 2), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (_SUBLANES, 2),
                lambda i: (i * bb + bb, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_SUBLANES, 2),
                lambda i: (i * bb + bb + 1, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, T), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((h, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words, gtop, gbot, G_ext, G_ext, G_ext)
    return new, alive[0], similar[0]


def _bandtrow_stitch_kernel(
    main_ref, topn_ref, botn_ref, gtop_ref, gbot_ref, w0_ref, wn_ref,
    out_ref, alive_ref, similar_ref,
    *, band: int, nbands: int,
):
    """``_bandtrow_kernel`` with the split-edge stitch fused into the output
    write: the exact edge word columns (computed by the strip pass, which
    runs FIRST) arrive as (band, 1) operands and replace lanes 0/nwords-1
    in ``out_ref`` — two selects per band per T generations, instead of a
    whole-shard read+write XLA pass after the kernel (which measured ~15%
    of the main pass in HBM traffic at 16384^2). Flags stay edge-masked;
    the strip pass owns the edge columns' flags.
    """
    i = pl.program_id(0)
    top_ctx = jnp.where(i == 0, gtop_ref[:], topn_ref[:])
    bot_ctx = jnp.where(i == nbands - 1, gbot_ref[:], botn_ref[:])
    x = jnp.concatenate([top_ctx, main_ref[:], bot_ctx], axis=0)
    nwords = x.shape[1]

    def evolve_full(x):
        left = pltpu.roll(x, 1 % nwords, 1)
        right = pltpu.roll(x, (nwords - 1) % nwords, 1)
        m0, m1, s0, s1 = packed_math.row_sums(x, left, right)
        return _vroll_combine(s0, s1, m0, m1, x)

    lanes = jax.lax.broadcasted_iota(jnp.int32, (band, nwords), 1)
    bitmask = jnp.where(
        (lanes == 0) | (lanes == nwords - 1), jnp.uint32(0), jnp.uint32(0xFFFFFFFF)
    )
    prev = main_ref[:]
    flags = []
    for _ in range(TEMPORAL_GENS):
        x = evolve_full(x)
        g = x[8 : band + 8]
        live = g & bitmask
        diff = (g ^ prev) & bitmask
        alive = jnp.any(live != 0).astype(jnp.int32)
        similar = 1 - jnp.any(diff != 0).astype(jnp.int32)
        flags.append((alive, similar))
        prev = g
    stitched = jnp.where(lanes == 0, jnp.broadcast_to(w0_ref[:], prev.shape), prev)
    out_ref[:] = jnp.where(
        lanes == nwords - 1, jnp.broadcast_to(wn_ref[:], prev.shape), stitched
    )
    _record_flags(i, flags, alive_ref, similar_ref)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step_trow_stitch(words: jnp.ndarray, gtop: jnp.ndarray, gbot: jnp.ndarray,
                      w0_col: jnp.ndarray, wn_col: jnp.ndarray,
                      interpret: bool = False):
    """Main pass of the split-edge form: rows-only evolution with the
    strip's exact edge columns stitched in at the output write."""
    h, nwords = words.shape
    band = _pick_band(h, nwords, _bandt_target(h, nwords))
    nb = h // _SUBLANES
    T = TEMPORAL_GENS
    new, alive, similar = pl.pallas_call(
        functools.partial(_bandtrow_stitch_kernel, band=band, nbands=h // band),
        grid=(h // band,),
        in_specs=[
            *_banded_specs(band, nwords, nb),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((band, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((band, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, T), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((h, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words, gtop, gbot, w0_col, wn_col)
    return new, alive[0], similar[0]


def _stript_kernel(
    main_ref, topn_ref, botn_ref, out_ref, alive_ref, similar_ref,
    *, band: int, row_lo: int, row_hi: int,
):
    """TEMPORAL_GENS generations of the lane-FOLDED edge strip.

    The operand is the (Lo+16, 6F) folded strip: F independent vertical
    windows of the (h+2T, 6) edge strip ``[gwest, w0, w1, w_{n-2}, w_{n-1},
    geast]`` laid side by side in the lane dimension (see ``_fold_strip``).
    Evolution is the plain torus-roll network — every cross-seam roll
    (between the two 3-lane halves, between folds, and at the global lane
    wrap) delivers garbage ONLY to a lane side that tolerates it: seam
    garbage advances one bit per generation from the word's far edge, and
    each context lane (gwest, w1, w_{n-2}, geast) has >= 16 bits of slack
    for T=8 (the same invariant the ghost-column plane relied on,
    src/game_cuda.cu:64-74 being the corner-context trick upstream).

    Flags cover exactly the shard's two edge word columns: rows in
    [row_lo, row_hi) of the folded array (each fold's interior) and lanes
    congruent to 1 or 4 mod 6 (w0 / w_{n-1}); the caller ORs/ANDs them with
    the main pass's edge-masked flags.
    """
    i = pl.program_id(0)
    x = jnp.concatenate([topn_ref[:], main_ref[:], botn_ref[:]], axis=0)
    nlanes = x.shape[1]

    r = jax.lax.broadcasted_iota(jnp.int32, (band, nlanes), 0) + i * band
    c = jax.lax.broadcasted_iota(jnp.int32, (band, nlanes), 1)
    cm = c - (c // 6) * 6
    mask = (r >= row_lo) & (r < row_hi) & ((cm == 1) | (cm == 4))
    bitmask = jnp.where(mask, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))

    prev = main_ref[:]
    flags = []
    for _ in range(TEMPORAL_GENS):
        left = pltpu.roll(x, 1 % nlanes, 1)
        right = pltpu.roll(x, (nlanes - 1) % nlanes, 1)
        m0, m1, s0, s1 = packed_math.row_sums(x, left, right)
        x = _vroll_combine(s0, s1, m0, m1, x)
        g = x[8 : band + 8]
        alive = jnp.any((g & bitmask) != 0).astype(jnp.int32)
        similar = 1 - jnp.any(((g ^ prev) & bitmask) != 0).astype(jnp.int32)
        flags.append((alive, similar))
        prev = g
    out_ref[:] = prev
    _record_flags(i, flags, alive_ref, similar_ref)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step_strip(folded: jnp.ndarray, interpret: bool = False):
    """Run ``_stript_kernel`` over the folded strip, banded like every other
    temporal pass (the folded array is small, but banding keeps its live set
    bounded for tall shards). Returns ``(folded_T, alive_vec, similar_vec)``."""
    rows, nlanes = folded.shape
    # One 128-lane tile per row either way; cap at the 1MB target (tests
    # shrink _BANDT_BYTES to force banding in both passes at small shapes).
    band = _pick_band(rows, nlanes, min(_BANDT_BYTES, 1 << 20))
    nb = rows // _SUBLANES
    T = TEMPORAL_GENS
    new, alive, similar = pl.pallas_call(
        functools.partial(
            _stript_kernel, band=band, row_lo=_SUBLANES, row_hi=rows - _SUBLANES
        ),
        grid=(rows // band,),
        in_specs=_banded_specs(band, nlanes, nb),
        out_specs=(
            pl.BlockSpec((band, nlanes), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, T), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((rows, nlanes), jnp.uint32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(folded, folded, folded)
    return new, alive[0], similar[0]


def _stript_fast_kernel(
    main_ref, topn_ref, botn_ref, out_ref, summ_ref,
    *, band: int, row_lo: int, row_hi: int,
):
    """``_stript_kernel`` with pass-summary flags: the summary scalars see
    only the shard's two edge word columns (each fold's interior rows,
    lanes 1/4 mod 6); the caller joins them with the main pass's
    edge-masked summary before the monotone derivation."""
    i = pl.program_id(0)
    x = jnp.concatenate([topn_ref[:], main_ref[:], botn_ref[:]], axis=0)
    nlanes = x.shape[1]
    r = jax.lax.broadcasted_iota(jnp.int32, (band, nlanes), 0) + i * band
    c = jax.lax.broadcasted_iota(jnp.int32, (band, nlanes), 1)
    cm = c - (c // 6) * 6
    mask = (r >= row_lo) & (r < row_hi) & ((cm == 1) | (cm == 4))
    bitmask = jnp.where(mask, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    _fast_pass_body(i, x, main_ref, out_ref, summ_ref, band, bitmask=bitmask)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step_strip_fast(folded: jnp.ndarray, interpret: bool = False):
    """Fast-flag strip pass (see ``_step_strip``): ``(folded_T, summary)``."""
    rows, nlanes = folded.shape
    band = _pick_band(rows, nlanes, min(_BANDT_BYTES, 1 << 20))
    nb = rows // _SUBLANES
    new, summ = pl.pallas_call(
        functools.partial(
            _stript_fast_kernel, band=band,
            row_lo=_SUBLANES, row_hi=rows - _SUBLANES,
        ),
        grid=(rows // band,),
        in_specs=_banded_specs(band, nlanes, nb),
        out_specs=(
            pl.BlockSpec((band, nlanes), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 4), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((rows, nlanes), jnp.uint32),
            jax.ShapeDtypeStruct((1, 4), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(folded, folded, folded)
    return new, summ


def _bandtrow_stitch_fast_kernel(
    main_ref, topn_ref, botn_ref, gtop_ref, gbot_ref, w0_ref, wn_ref,
    out_ref, summ_ref,
    *, band: int, nbands: int,
):
    """``_bandtrow_stitch_kernel`` with pass-summary flags: edge-masked
    summary scalars (the strip pass owns the edge columns' flags) and the
    same fused edge-column stitch at the output write."""
    i = pl.program_id(0)
    top_ctx = jnp.where(i == 0, gtop_ref[:], topn_ref[:])
    bot_ctx = jnp.where(i == nbands - 1, gbot_ref[:], botn_ref[:])
    x = jnp.concatenate([top_ctx, main_ref[:], bot_ctx], axis=0)
    nwords = x.shape[1]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (band, nwords), 1)
    bitmask = jnp.where(
        (lanes == 0) | (lanes == nwords - 1), jnp.uint32(0), jnp.uint32(0xFFFFFFFF)
    )

    def stitch(prev):
        s = jnp.where(lanes == 0, jnp.broadcast_to(w0_ref[:], prev.shape), prev)
        return jnp.where(
            lanes == nwords - 1, jnp.broadcast_to(wn_ref[:], prev.shape), s
        )

    _fast_pass_body(i, x, main_ref, out_ref, summ_ref, band,
                    bitmask=bitmask, stitch=stitch)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step_trow_stitch_fast(words: jnp.ndarray, gtop: jnp.ndarray,
                           gbot: jnp.ndarray, w0_col: jnp.ndarray,
                           wn_col: jnp.ndarray, interpret: bool = False):
    """Fast-flag main pass of the split-edge form: ``(new, summary)``."""
    h, nwords = words.shape
    band = _pick_band(h, nwords, _fast_target(h, nwords))
    nb = h // _SUBLANES
    new, summ = pl.pallas_call(
        functools.partial(
            _bandtrow_stitch_fast_kernel, band=band, nbands=h // band
        ),
        grid=(h // band,),
        in_specs=[
            *_banded_specs(band, nwords, nb),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((band, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((band, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 4), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((h, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, 4), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words, gtop, gbot, w0_col, wn_col)
    return new, summ


def _tsplit_operands(words: jnp.ndarray, topology: Topology):
    """Ghost/edge operands for the split-edge form: ``(gtop, gbot, cols4,
    G_ext)``.

    Same wire traffic as ``deep_ghost_operands`` (T-row N/S ghost blocks,
    whole-word ghost columns riding the column exchange), but the shard's
    own edge columns are extracted ONCE into the compact ``cols4`` and
    every downstream strip/G_ext consumer reads that, not the big array —
    the r3-shaped operand build (row-extended concat + per-consumer lane
    extracts) measured ~45% of a whole pass at 16384^2 in device time.
    Measured dead ends, for the record: a Pallas extraction kernel cannot
    beat these fused XLA slices — BlockSpec lane dims must be
    128-multiples (whole-tile reads moved 2/(nwords/128) of the array and
    lost ~2%), and manual ``make_async_copy`` slices of a tiled HBM ref
    hit the same constraint ("Slice shape along dimension 1 must be
    aligned to tiling (128)", v5e probe).
    """
    h, nwords = words.shape
    rows, _cols = topology.shape
    row_axis = ROW_AXIS if topology.distributed else None
    gtop, gbot = halo.ghost_slices(words, 0, row_axis, rows, depth=TEMPORAL_GENS)
    cols4 = jnp.concatenate([words[:, :2], words[:, nwords - 2:]], axis=1)
    west = jnp.concatenate([gtop[:, 0], cols4[:, 0], gbot[:, 0]])
    east = jnp.concatenate([gtop[:, -1], cols4[:, 3], gbot[:, -1]])
    gwest, geast = halo.exchange_columns(west, east, topology)
    G_ext = jnp.stack([gwest, geast], axis=1)
    return gtop, gbot, cols4, G_ext


# Lane budget for the folded strip: 6 lanes per fold, at most one full
# 128-lane tile (more folds than 21 would spill into a second tile and
# double the strip pass's per-op cost for nothing).
_MAX_FOLDS = 21


def _fold_count(h: int) -> int:
    """Most folds the shard height admits: the largest divisor of h/8 that
    keeps 6*F lanes within one 128-lane tile."""
    base = h // _SUBLANES
    return max(f for f in range(1, min(_MAX_FOLDS, base) + 1) if base % f == 0)


def _step_tsplit(words: jnp.ndarray, gtop: jnp.ndarray, gbot: jnp.ndarray,
                 cols4: jnp.ndarray, G_ext: jnp.ndarray,
                 interpret: bool = False):
    """Split-edge temporal pass for one 2D-mesh shard: rows-only main pass
    plus a lane-folded exact edge strip.

    The r3 ghost-plane form (``_step_tgb``) paid two structural taxes every
    generation: per-row seam patches on the full-width operands (~2 selects
    + broadcasts over (rows, nwords)) and a whole adder-network pass over a
     2-lane ghost plane that still costs a full 128-lane vector op per row
    tile — together the measured 0.64-0.96x of single-chip
    (benchmarks/compare_{16384,32768}_r3.json). This form deletes both:

    - MAIN: the shard runs the unmodified rows-only kernel (pure torus
      rolls, zero patches). Seam corruption moves one bit per generation,
      so after T <= 8 generations only the outer 8 bits of word columns 0
      and nwords-1 are wrong; interior columns are exact.
    - STRIP: the six seam-relevant word columns [gwest, w0, w1, w_{n-2},
      w_{n-1}, geast] evolve exactly in a separate narrow pass whose row
      dimension is FOLDED into lanes (F vertical windows side by side, 6F
      <= 126 lanes = one tile), cutting the narrow-array tile tax by F
      (~16x for power-of-two heights).
    - STITCH: the strip runs FIRST, and its exact w0/w_{n-1} columns ride
      into the main-pass kernel as (band, 1) operands that replace the two
      edge lanes at the output write — fused, because a post-kernel
      whole-shard select measured ~15% of the main pass in pure HBM
      traffic at 16384^2 (device-time profile, compare_16384_r4.json's
      first series). Per-generation flags OR/AND across the two passes
      (main's flags exclude the edge columns).

    Needs nwords >= 2 (at nwords == 1 the strip's lane adjacency cannot
    express the torus; that single-word case keeps ``_step_tgb``). At
    nwords == 2 the strip duplicates both shard columns and the main pass
    contributes nothing — wasteful but exact (pinned by the dryrun's
    packed-interp lane).
    """
    folded, F, Lo = _fold_strip(words, gtop, gbot, cols4, G_ext)
    folded_T, alive_s, similar_s = _step_strip(folded, interpret=interpret)
    w0_col, wn_col = _unfold_edge_cols(folded_T, words.shape[0], F, Lo)

    new, alive_m, similar_m = _step_trow_stitch(
        words, gtop, gbot, w0_col, wn_col, interpret=interpret
    )
    alive = jnp.maximum(alive_m, alive_s)
    similar = jnp.minimum(similar_m, similar_s)
    return new, alive, similar


def _fold_strip(words, gtop, gbot, cols4, G_ext):
    """Assemble the lane-folded edge strip: ``(folded, F, Lo)``.

    The (h+2T, 6) edge strip over extended rows. The shard rows' edge
    columns arrive pre-extracted (``cols4`` — XLA-level lane extracts from
    the big array measured ~45% of a whole pass at 16384^2); only the tiny
    T-row ghost blocks are sliced here. Fold k covers extended rows
    [k*Lo, k*Lo + Lo + 16): its Lo-row body and both 8-row context flanks
    are plain reshape views of E shifted by 0 / 8 / 16 rows — no per-fold
    slicing.
    """
    h, nwords = words.shape
    T = TEMPORAL_GENS
    west2 = jnp.concatenate([gtop[:, :2], cols4[:, :2], gbot[:, :2]], axis=0)
    east2 = jnp.concatenate(
        [gtop[:, nwords - 2:], cols4[:, 2:], gbot[:, nwords - 2:]], axis=0
    )
    E = jnp.concatenate(
        [G_ext[:, 0:1], west2, east2, G_ext[:, 1:2]], axis=1
    )  # (h+16, 6)
    F = _fold_count(h)
    Lo = h // F
    body = E[8 : h + 8].reshape(F, Lo, 6)
    top = E[:h].reshape(F, Lo, 6)[:, :8]
    bot = E[16 : h + 16].reshape(F, Lo, 6)[:, Lo - 8:]
    folded = (
        jnp.concatenate([top, body, bot], axis=1)
        .transpose(1, 0, 2)
        .reshape(Lo + 2 * T, 6 * F)
    )
    return folded, F, Lo


def _unfold_edge_cols(folded_T, h, F, Lo):
    """Extract the exact edge columns from the evolved folded strip: rows
    [T, Lo+T) of fold k are shard rows [k*Lo, (k+1)*Lo); lanes 1/4 mod 6
    are w0/w_{n-1}. Returns ``(w0_col, wn_col)``, each (h, 1)."""
    T = TEMPORAL_GENS
    out_rows = folded_T[T : Lo + T].reshape(Lo, F, 6)
    return out_rows[:, :, 1].T.reshape(h, 1), out_rows[:, :, 4].T.reshape(h, 1)


def _step_tsplit_fast(words: jnp.ndarray, gtop: jnp.ndarray, gbot: jnp.ndarray,
                      cols4: jnp.ndarray, G_ext: jnp.ndarray,
                      topology: Topology = SINGLE_DEVICE_TOPOLOGY,
                      interpret: bool = False):
    """Fast-flag split-edge pass: ``_step_tsplit`` with the per-generation
    flag machinery replaced by pass-level summaries (the measured 29-34% of
    the kernel, benchmarks/roofline_flags_r4.json).

    The four summary scalars are produced JOINTLY by the two passes — the
    strip summary sees only the two edge word columns, the main summary is
    edge-masked, and they join by OR (alive pair) / AND (similarity pair)
    before the monotone derivation, so the composed summary covers exactly
    the shard's cells once. Under a mesh the joined scalars are voted
    globally inside ``_derive_or_replay`` (a shard is an open system —
    see the cross-shard-transient counterexample there); the replay thunk
    re-runs the FULL exact split composition (strip + stitch, per-
    generation flags), which is collective-free — operands were already
    exchanged — so every shard replays together on the replicated
    predicate.
    """
    folded, F, Lo = _fold_strip(words, gtop, gbot, cols4, G_ext)
    folded_T, summ_s = _step_strip_fast(folded, interpret=interpret)
    w0_col, wn_col = _unfold_edge_cols(folded_T, words.shape[0], F, Lo)
    new, summ_m = _step_trow_stitch_fast(
        words, gtop, gbot, w0_col, wn_col, interpret=interpret
    )
    joint = jnp.concatenate(
        [
            jnp.maximum(summ_m[:, :2], summ_s[:, :2]),  # in/out alive: OR
            jnp.minimum(summ_m[:, 2:], summ_s[:, 2:]),  # simT/sim1: AND
        ],
        axis=1,
    )
    alive, similar = _derive_or_replay(
        joint,
        lambda: _step_tsplit(words, gtop, gbot, cols4, G_ext,
                             interpret=interpret)[1:],
        topology,
    )
    return new, alive, similar


# Width cap for the temporal kernel: its live set spans (band+16)-row
# planes, so at very wide rows even the minimum band exceeds scoped VMEM.
# At the 8192-word cap (width 2^18) the width-continuous _bandt_target
# picks 32-row bands, which compile and match the jnp network on v5e at
# (1024, 8192); 16384 words fails at Mosaic compile under every target.
# Between 2048 and 8192 words the compile boundary was mapped by
# tools/probe_vmem_r4.py (benchmarks/vmem_probe_r4.json) and encoded as
# _BANDT_EXT_BUDGET; re-run the probe when raising _MAX_WORDS_T, the band
# targets, or the network's live set. Wider falls back to the single-gen
# kernel. The cap matters doubly since the row-only (n, 1) default mesh:
# it bounds the widest grid whose full-width shards keep the temporal
# kernel (choose_mesh_shape adds mesh columns past it).
_MAX_WORDS_T = 8 << 10


def supports_multi(height: int, width: int, topology) -> bool:
    """The temporally-blocked pass: same shape rules as ``supports`` plus a
    VMEM-driven width cap; distributed shards additionally need the
    8-aligned Pallas height (the deep-halo assembly has no jnp-network
    escape hatch for odd heights — those fall back to the per-generation
    fused path)."""
    if width // _BITS > _MAX_WORDS_T or not supports(height, width, topology):
        return False
    if not topology.distributed:
        return True
    return height % _SUBLANES == 0 and height >= 2 * TEMPORAL_GENS


def exchange_packed_deep_parts(words: jnp.ndarray, topology: Topology):
    """Deep two-phase halo feeding TEMPORAL_GENS generations at once.

    The wide-ghost-zone trade on the reference's per-generation 16-request
    exchange (src/game_mpi.c:340-401): TEMPORAL_GENS ghost word rows N/S,
    then whole ghost word *columns* E/W over the row-extended range (corners
    ride along, the src/game_cuda.cu:64-74 trick). One exchange per
    TEMPORAL_GENS generations — TEMPORAL_GENS-times fewer, larger messages, a win where
    halos are latency-bound. The 32-bit ghost word column carries enough
    cross-seam context because the invalid frontier advances one bit per
    generation from its far edge (32 >> TEMPORAL_GENS).

    Returns ``(xr, gwest, geast)``: the (h + 2T, nwords) row-extended block
    and the two (h + 2T,) ghost word columns. A thin assembled view over
    ``deep_ghost_operands`` (the banded-operand form the TPU kernel consumes
    directly), kept for the off-TPU jnp branch and halo benchmarking — one
    exchange protocol, two presentations.
    """
    gtop, gbot, G_ext = deep_ghost_operands(words, topology)
    xr = jnp.concatenate([gtop, words, gbot], axis=0)
    return xr, G_ext[:, 0], G_ext[:, 1]


def exchange_packed_deep(words: jnp.ndarray, topology: Topology) -> jnp.ndarray:
    """``exchange_packed_deep_parts`` assembled into one
    (h + 2*TEMPORAL_GENS, nwords + 2) extended block."""
    xr, gwest, geast = exchange_packed_deep_parts(words, topology)
    return jnp.concatenate([gwest[:, None], xr, geast[:, None]], axis=1)


def _jnp_multi(state, prev0, interior):
    """The T-generation jnp flag loop shared by both off-TPU branches:
    evolve ``state`` T times, reading flags from its ``interior`` slice
    against the previous interior generation."""
    alive, similar, prev = [], [], prev0
    for _ in range(TEMPORAL_GENS):
        state = packed_math.evolve_torus_words(state)
        g = state[interior]
        alive.append(jnp.any(g != 0))
        similar.append(jnp.all(g == prev))
        prev = g
    return (
        prev,
        jnp.stack(alive).astype(jnp.int32),
        jnp.stack(similar).astype(jnp.int32),
    )


def _distributed_step_multi(words: jnp.ndarray, topology: Topology,
                            force_jnp: bool = False,
                            force_interp: bool = False):
    """Shard-local temporal pass: deep halo, then TEMPORAL_GENS generations.

    The ghost word rows and columns ride as banded kernel operands
    (``_bandtg_kernel``) — nothing larger than the (h+2T, 2) ghost-column
    plane is ever materialized around the shard array."""
    T = TEMPORAL_GENS
    h, nwords = words.shape
    if force_jnp or (jax.default_backend() != "tpu" and not force_interp):
        # Identical math at jnp level: torus rolls over the extended block
        # wrap garbage only into the invalid frontier (never the interior).
        xe = exchange_packed_deep(words, topology)
        return _jnp_multi(
            xe, words, (slice(T, T + h), slice(1, nwords + 1))
        )
    # The sequential banded-operand form: exchange, then one kernel pass
    # consuming every ghost operand. An overlapped interior/frontier split
    # (frame-masked whole-shard kernel + T-row strip and 6-lane edge-column
    # frontier kernels + stitch) was built and measured on v5e and RETIRED:
    # its frontier machinery cost ~0.8x of the main kernel (tiny-kernel
    # launches, strided column extraction) to hide an exchange that costs
    # ~0.15x here and tens of microseconds over real ICI — a structural
    # loss at both scales (benchmarks/compare_32768_r3.json: overlap 0.40
    # vs seq 0.49-0.88 of the single-chip rate across sessions).
    interpret = jax.default_backend() != "tpu"
    if topology.shape[1] == 1:
        # Row-only decomposition (R x 1 mesh): full-width shards, so the
        # E/W wrap is the shard's own lane roll and the whole ghost-column
        # machinery — measured at ~2/3 of the mesh form's overhead at the
        # 16384^2 pod-shard size — drops out. The recommended pod layout.
        rows, _cols = topology.shape
        row_axis = ROW_AXIS if topology.distributed else None
        gtop, gbot = halo.ghost_slices(
            words, 0, row_axis, rows, depth=TEMPORAL_GENS
        )
        return _step_trow_fast(words, gtop, gbot, topology=topology,
                                interpret=interpret)
    if nwords >= 2:
        # The split-edge form: rows-only main pass + lane-folded exact edge
        # strip (see _step_tsplit) — replaces the r3 ghost-plane form whose
        # per-generation patches + 2-lane adder pass cost 0.64-0.96x of
        # single-chip on any R x C mesh with mesh columns. Fast-flag form
        # (r5): pass summaries joined across the two passes, voted, with
        # the exact composition replayed only on mid-pass exits.
        gtop, gbot, cols4, G_ext = _tsplit_operands(words, topology)
        return _step_tsplit_fast(words, gtop, gbot, cols4, G_ext,
                                 topology=topology, interpret=interpret)
    gtop, gbot, G_ext = deep_ghost_operands(words, topology)
    return _step_tgb(words, gtop, gbot, G_ext, interpret=interpret)


def deep_ghost_operands(words: jnp.ndarray, topology: Topology):
    """The deep-halo exchange in banded-operand form: ``(gtop, gbot, G_ext)``.

    ``gtop``/``gbot`` are the ppermute'd TEMPORAL_GENS-row ghost word blocks;
    ``G_ext`` is the (h + 2T, 2) ghost-column plane (west in lane 0, east
    in lane 1) over the extended row range — the ghost rows' edge words ride
    the column exchange so corner context arrives too (the two-phase trick,
    src/game_cuda.cu:64-74). Same wire traffic as ``exchange_packed_deep``;
    nothing shard-sized is ever concatenated. (The plane used to be padded
    to 128 lanes for the kernel BlockSpecs; Mosaic handles narrow lane
    blocks fine, and at 32768 rows the pad cost a 16MB HBM round trip per
    exchange.)
    """
    rows, _cols = topology.shape
    row_axis = ROW_AXIS if topology.distributed else None
    gtop, gbot = halo.ghost_slices(words, 0, row_axis, rows, depth=TEMPORAL_GENS)
    west, east = halo.boundary_columns(words, gtop, gbot)
    gwest, geast = halo.exchange_columns(west, east, topology)
    G_ext = jnp.stack([gwest, geast], axis=1)
    return gtop, gbot, G_ext


def packed_step_multi(cur: jnp.ndarray, topology: Topology, *,
                      force_jnp: bool = False, force_interp: bool = False):
    """TEMPORAL_GENS fused generations:
    ``words -> (words_T, alive_vec, similar_vec)``.

    Flag vectors are int32 ``(TEMPORAL_GENS,)``, one entry per generation in
    order — exactly what the engine's blocked replay consumes. Off-TPU the
    compute is the jnp adder network (identical math); on TPU it is the
    temporally-blocked band kernel. Distributed shards run the deep-halo
    form (one exchange per TEMPORAL_GENS generations).

    ``force_jnp`` routes every branch through the jnp adder network even on
    TPU — the engine's demotion target when Mosaic refuses to compile a
    shape the empirical VMEM caps admit (the reference bar: no supported
    shape ever aborts, src/game.c:224-245). ``force_interp`` is the inverse
    test knob: distributed shards take the Pallas kernel composition in
    interpret mode even off TPU (exposed as kernel='packed-interp', a
    first-class registry entry so runner caches key per routing).
    """
    height, nwords = cur.shape
    gate = supports_multi_jnp if force_jnp else supports_multi
    if not gate(height, nwords * _BITS, topology):
        raise ValueError("packed_step_multi requires a supported shape/topology")
    if topology.distributed:
        return _distributed_step_multi(cur, topology, force_jnp, force_interp)
    if force_jnp or jax.default_backend() != "tpu":
        return _jnp_multi(cur, cur, (slice(None), slice(None)))
    return _step_t_fast(cur)


def exchange_packed(words: jnp.ndarray, topology: Topology):
    """Two-phase packed halo: word rows N/S, bit-packed columns E/W.

    The reference exchanges byte rows plus exact boundary-byte columns via a
    derived MPI_Type_vector datatype (src/game_mpi.c:335-338). Packed, the
    N/S rows are already bit-minimal (one word row per side); the E/W
    exchange sends the boundary *bit column* packed into (h+2)/32 words —
    32x less traffic than shipping whole ghost word columns. The column
    phase covers the row-extended range so corner bits ride along (the
    src/game_cuda.cu:64-74 trick).

    Returns ``(top, bot, gwest, geast)``: ghost word rows (1, nwords) and
    per-extended-row carry words (h+2,) with the neighbor bit pre-positioned
    at bit 31 (west) / bit 0 (east) for direct use by the shift carries.
    """
    h, _ = words.shape
    rows, _cols = topology.shape
    row_axis = ROW_AXIS if topology.distributed else None
    top, bot = halo.ghost_slices(words, 0, row_axis, rows)
    # Boundary bit columns over the row-extended block (h+2 bits each).
    west_col, east_col = halo.boundary_columns(words, top, bot)
    gwest_bits, geast_bits = halo.exchange_columns(
        west_col & jnp.uint32(1),
        east_col >> jnp.uint32(_BITS - 1),
        topology,
        transform=(
            packed_math.pack_bits,
            lambda w: packed_math.unpack_bits(w, h + 2),
        ),
    )
    return top, bot, gwest_bits << jnp.uint32(_BITS - 1), geast_bits


def _dist_band_kernel(
    main_ref,
    top_ref,
    bot_ref,
    gtop_ref,
    gbot_ref,
    gmid_ref,
    gwrap_ref,
    out_ref,
    alive_ref,
    similar_ref,
    *,
    band: int,
    nbands: int,
):
    """Band kernel for one mesh shard: ghost rows/carries arrive as operands.

    Same VMEM-banded adder network as ``_band_kernel``, but the torus wrap at
    the shard edges comes from the ppermute'd ghosts instead of modular block
    indexing — the Pallas analog of the reference running its hand-written
    kernels in every MPI variant (src/game_mpi.c:73-84).
    """
    i = pl.program_id(0)
    mid = main_ref[:]
    nwords = mid.shape[1]
    r8 = jax.lax.broadcasted_iota(jnp.int32, (8, nwords), 0)

    def _extract(block_ref, row_index):
        block = jax.lax.bitcast_convert_type(block_ref[:], jnp.int32)
        row = jnp.sum(jnp.where(r8 == row_index, block, 0), axis=0, keepdims=True)
        return jax.lax.bitcast_convert_type(row, jnp.uint32)

    # Interior bands take their wrap rows from the adjacent 8-row blocks; the
    # first/last band take the shard's ppermute'd ghost rows instead. The wrap
    # rows' seam carries arrive as this band's gwrap row — four scalars
    # (west/east for the row above and the row below), right for interior and
    # edge bands alike, since assemble_band_ghosts builds them from the carry
    # column over the full extended row range.
    top_row = jnp.where(i == 0, _extract(gtop_ref, 7), _extract(top_ref, 7))
    bot_row = jnp.where(i == nbands - 1, _extract(gbot_ref, 0), _extract(bot_ref, 0))

    def _hs(x, gwest, geast):
        # Seam patch: the word rolled in across the shard seam is replaced by
        # the neighbor's carry word (lane 0 = ghost west, bit 31 pre-positioned;
        # last lane = ghost east, bit 0).
        lanes = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
        gw = jnp.broadcast_to(gwest, x.shape)
        ge = jnp.broadcast_to(geast, x.shape)
        left = jnp.where(lanes == 0, gw, pltpu.roll(x, 1 % nwords, 1))
        right = jnp.where(
            lanes == nwords - 1, ge, pltpu.roll(x, (nwords - 1) % nwords, 1)
        )
        return packed_math.row_sums(x, left, right)

    # Horizontal triple sums once per row (mid block + the two wrap rows; the
    # wrap rows' four seam carries are SMEM scalars).
    m0, m1, s0, s1 = _hs(mid, gmid_ref[:, 0:1], gmid_ref[:, 1:2])
    _, _, t0, t1 = _hs(top_row, gwrap_ref[i, 0], gwrap_ref[i, 1])
    _, _, b0, b1 = _hs(bot_row, gwrap_ref[i, 2], gwrap_ref[i, 3])
    new = _vertical_combine(s0, s1, m0, m1, mid, t0, t1, b0, b1, band)
    out_ref[:] = new

    alive = jnp.any(new != 0).astype(jnp.int32)
    similar = 1 - jnp.any((new ^ mid) != 0).astype(jnp.int32)

    @pl.when(i == 0)
    def _init():
        alive_ref[0, 0] = alive
        similar_ref[0, 0] = similar

    @pl.when(i > 0)
    def _accumulate():
        alive_ref[0, 0] = alive_ref[0, 0] | alive
        similar_ref[0, 0] = similar_ref[0, 0] & similar


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dist_step_pallas(words, gtop8, gbot8, gmid, gwrap, interpret=False):
    height, nwords = words.shape
    band = _pick_band(height, nwords)
    bb = band // _SUBLANES
    nb = height // _SUBLANES
    nbands = height // band
    new, alive, similar = pl.pallas_call(
        functools.partial(_dist_band_kernel, band=band, nbands=nbands),
        grid=(nbands,),
        in_specs=[
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (_SUBLANES, nwords),
                lambda i: ((i * bb - 1) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_SUBLANES, nwords),
                lambda i: ((i * bb + bb) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_SUBLANES, nwords), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((band, 2), lambda i: (i, 0), memory_space=pltpu.VMEM),
            # The whole per-band wrap-carry table sits in SMEM (nbands x 4
            # scalars); each band reads its row by program id.
            pl.BlockSpec((nbands, 4), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((height, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words, gtop8, gbot8, gmid, gwrap)
    return new, alive[0, 0] > 0, similar[0, 0] > 0


def _distributed_step(words: jnp.ndarray, topology: Topology,
                      force_jnp: bool = False, force_interp: bool = False):
    """Shard-local packed step under shard_map.

    The halo is the two-phase ppermute exchange (word rows N/S, bit columns
    E/W); the stencil is the compiled Pallas band kernel whenever the shard
    height tiles (h % 8 == 0), with the jnp adder network as the fallback for
    odd shard heights. Either way the hot loop under a mesh runs the same
    carry-save network as the single-device path.
    """
    h, nwords = words.shape
    top, bot, gwest, geast = exchange_packed(words, topology)
    on_tpu = jax.default_backend() == "tpu"
    if h % _SUBLANES == 0 and not force_jnp and (on_tpu or force_interp):
        # Off TPU the compiled kernel would be the Mosaic interpreter per
        # generation; the jnp network below is the identical math at full
        # XLA:CPU speed (kernel='packed-interp' still routes CI through
        # the interpret-mode kernel composition).
        gtop8, gbot8, gmid, gwrap = halo.assemble_band_ghosts(
            top, bot, gwest, geast, _pick_band(h, nwords)
        )
        return _dist_step_pallas(
            words, gtop8, gbot8, gmid, gwrap, interpret=not on_tpu
        )
    new = packed_math.evolve_ghost(words, top, bot, gwest, geast)
    return new, jnp.any(new != 0), jnp.all(new == words)


def packed_step(cur: jnp.ndarray, topology: Topology, *,
                force_jnp: bool = False, force_interp: bool = False):
    """Fused generation step on packed state: ``words -> (words, alive, similar)``.

    Single device: the compiled Pallas band kernel. Distributed: the same
    band kernel fed ppermute'd ghost rows and bit-column carries (jnp adder
    network only for odd shard heights). ``force_jnp`` routes everything
    through the jnp adder network even on TPU (the Mosaic-compile-failure
    demotion target; see ``packed_step_multi``).
    """
    height, nwords = cur.shape
    gate = supports_jnp if force_jnp else supports
    if not gate(height, nwords * _BITS, topology):
        raise ValueError(
            f"the packed kernel requires width a multiple of {_BITS} and, on "
            f"a single device, height a multiple of {_SUBLANES}; got "
            f"{height}x{nwords * _BITS} on {topology.shape[0]}x"
            f"{topology.shape[1]} devices — use kernel='lax' (or 'auto')"
        )
    if topology.distributed:
        return _distributed_step(cur, topology, force_jnp, force_interp)
    if force_jnp or jax.default_backend() != "tpu":
        # Off-TPU the jnp adder network beats running Mosaic's interpreter;
        # the kernel body itself is covered by interpret-mode tests.
        new = packed_math.evolve_torus_words(cur)
        return new, jnp.any(new != 0), jnp.all(new == cur)
    return _step(cur)
