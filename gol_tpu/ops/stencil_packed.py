"""Bitpacked Pallas stencil — 32 cells per word, bit-sliced adder network.

The fastest path, and the one that earns the TPU its keep. Where the
reference's CUDA kernel spends one thread per cell (src/game_cuda.cu:128-148),
this kernel packs 32 cells into each uint32 lane element and evolves all of
them with ~60 bitwise VPU ops per word — a carry-save adder network computing
all eight neighbor counts bit-parallel:

- Cells live packed as uint32 words along the width axis: bit j of word w is
  the cell at column ``w*32 + j``. HBM traffic per generation drops to ~2
  *bits* per cell.
- West/east neighbors are one-bit shifts within words, with the cross-word
  (and toroidal cross-row) carry bit delivered by a lane-roll of the word
  array.
- Neighbor counts come from a boolean adder tree: per-row 3:2 compressors,
  then a 4-bit carry-save sum. With count bits N = s0 + 2*b1 + 4*u0 + 8*u1,
  rule B3/S23 (src/game.c:91-98) collapses to
  ``new = b1 & ~(u0|u1) & (s0|mid)``.
- The alive/similar termination flags accumulate in SMEM exactly as in the
  unpacked Pallas kernel, so the engine's while_loop stays host-free.

Packing/unpacking happens once per run at the engine boundary (the grid state
carried through the generation loop stays packed); the text-I/O contract is
untouched.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.ops import packed_math
from gol_tpu.parallel import halo
from gol_tpu.parallel.mesh import Topology

_BITS = packed_math.BITS
_SUBLANES = 8  # 32-bit tile granule: every row offset/extent must divide by 8
# Target VMEM bytes for one band of packed words; the ~10 live temporaries of
# the adder network and the double-buffered in/out blocks sit beside it.
_BAND_BYTES = 256 << 10

# Re-exported for the kernel registry: the engine packs/unpacks at the loop
# boundary through these.
encode = packed_math.encode
decode = packed_math.decode


def supports(height: int, width: int, topology) -> bool:
    """Packed paths: compiled Pallas single-device, jnp+ppermute distributed.

    Narrow word arrays (nwords < 128 lanes) are fine: Mosaic's dynamic rotate
    operates on the logical shape, verified compiled on v5e down to a
    single-word row (64x32 and 512x1152 grids match the oracle). ``width``
    and ``height`` are the LOCAL shard shape under a mesh.
    """
    if width % _BITS != 0:
        return False
    if topology.distributed:
        return True  # jnp-level path, no tiling constraints
    return height % _SUBLANES == 0 and height >= _SUBLANES


def _pick_band(height: int, words: int) -> int:
    row_bytes = max(words * 4, 1)
    target = max(_SUBLANES, min(height, _BAND_BYTES // row_bytes))
    for band in range(target, _SUBLANES - 1, -1):
        if height % band == 0 and band % _SUBLANES == 0:
            return band
    raise ValueError(f"no {_SUBLANES}-aligned band divides height {height}")


def _band_kernel(main_ref, top_ref, bot_ref, out_ref, alive_ref, similar_ref, *, band: int):
    i = pl.program_id(0)

    mid = main_ref[:]
    # Wrap rows arrive as aligned 8-row blocks; extract last/first row by a
    # masked sum-reduce (single-row sublane slices would be misaligned, and
    # Mosaic doesn't reduce unsigned vectors — bitcast to i32; the sum is
    # exact because exactly one row survives the mask).
    r8 = jax.lax.broadcasted_iota(jnp.int32, (8, mid.shape[1]), 0)

    def _extract(block_ref, row_index):
        block = jax.lax.bitcast_convert_type(block_ref[:], jnp.int32)
        row = jnp.sum(jnp.where(r8 == row_index, block, 0), axis=0, keepdims=True)
        return jax.lax.bitcast_convert_type(row, jnp.uint32)

    top_row = _extract(top_ref, 7)
    bot_row = _extract(bot_ref, 0)
    rows = jax.lax.broadcasted_iota(jnp.int32, mid.shape, 0)
    up = jnp.where(rows == 0, jnp.broadcast_to(top_row, mid.shape), pltpu.roll(mid, 1, 0))
    down = jnp.where(
        rows == band - 1, jnp.broadcast_to(bot_row, mid.shape), pltpu.roll(mid, band - 1, 0)
    )

    new = packed_math.evolve_rows(
        up, mid, down, lambda a, s: pltpu.roll(a, s % a.shape[1], 1)
    )
    out_ref[:] = new

    alive = jnp.max(jnp.where(new != 0, 1, 0))
    similar = 1 - jnp.max(jnp.where((new ^ mid) != 0, 1, 0))

    @pl.when(i == 0)
    def _init():
        alive_ref[0, 0] = alive
        similar_ref[0, 0] = similar

    @pl.when(i > 0)
    def _accumulate():
        alive_ref[0, 0] = alive_ref[0, 0] | alive
        similar_ref[0, 0] = similar_ref[0, 0] & similar


@functools.partial(jax.jit, static_argnames=("interpret",))
def _step(words: jnp.ndarray, interpret: bool = False):
    height, nwords = words.shape
    band = _pick_band(height, nwords)
    bb = band // _SUBLANES
    nb = height // _SUBLANES
    new, alive, similar = pl.pallas_call(
        functools.partial(_band_kernel, band=band),
        grid=(height // band,),
        in_specs=[
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (_SUBLANES, nwords),
                lambda i: ((i * bb - 1) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (_SUBLANES, nwords),
                lambda i: ((i * bb + bb) % nb, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec((band, nwords), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((height, nwords), jnp.uint32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(words, words, words)
    return new, alive[0, 0] > 0, similar[0, 0] > 0


def _distributed_step(words: jnp.ndarray, topology: Topology):
    """Shard-local packed step under shard_map: word-level ppermute halo.

    The reference exchanges byte rows/columns with 16 persistent requests
    (src/game_mpi.c:340-383); packed, the same two-phase exchange moves word
    rows and one ghost word column per side (of which only the adjacent bit
    feeds the shift carries). The column phase runs over the row-extended
    block, so corner words ride along exactly as in the byte-level exchange
    (the src/game_cuda.cu:64-74 trick, one level up).
    """
    xce = halo.exchange(words, topology)  # (h+2, nwords+2) ghost-extended words
    new = packed_math.evolve_extended(xce)
    alive = jnp.any(new != 0)
    similar = jnp.all(new == words)
    return new, alive, similar


def packed_step(cur: jnp.ndarray, topology: Topology):
    """Fused generation step on packed state: ``words -> (words, alive, similar)``.

    Single device: the compiled Pallas band kernel. Distributed: the jnp
    adder network around a word-level ppermute halo exchange.
    """
    height, nwords = cur.shape
    if not supports(height, nwords * _BITS, topology):
        raise ValueError(
            f"the packed kernel requires width a multiple of {_BITS} and, on "
            f"a single device, height a multiple of {_SUBLANES}; got "
            f"{height}x{nwords * _BITS} on {topology.shape[0]}x"
            f"{topology.shape[1]} devices — use kernel='lax' (or 'auto')"
        )
    if topology.distributed:
        return _distributed_step(cur, topology)
    if jax.default_backend() != "tpu":
        # Off-TPU the jnp adder network beats running Mosaic's interpreter;
        # the kernel body itself is covered by interpret-mode tests.
        new = packed_math.evolve_torus_words(cur)
        return new, jnp.any(new != 0), jnp.all(new == cur)
    return _step(cur)
