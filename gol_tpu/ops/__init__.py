"""Compute kernels: the 3x3 Moore stencil in lax and Pallas flavors.

A kernel owns one generation of compute for a shard's (h, w) uint8 block,
including its halo strategy (local wrap, ppermute ghosts, or fused DMA).

Two call forms:

- ``step(cur, topology) -> new`` — just the next generation.
- ``fused(cur, topology) -> (new, any_alive, similar)`` — optionally, the next
  generation plus the termination flags computed in the same memory pass (the
  Pallas path; fusing the reference's separate empty/compare kernels,
  src/game_cuda.cu:76-126, into the evolve pass).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from gol_tpu.ops import stencil_lax
from gol_tpu.parallel import halo
from gol_tpu.parallel.mesh import Topology


@dataclasses.dataclass(frozen=True)
class Kernel:
    """A named evolve implementation with optional fused termination flags.

    ``encode``/``decode`` let a kernel carry the grid through the generation
    loop in its own representation (the bitpacked kernel: uint32 words); the
    engine applies them once at the loop boundary. Both operate on/return the
    canonical uint8 (H, W) grid.
    """

    name: str
    step: Callable  # (cur, Topology) -> new
    fused: Callable | None = None  # (cur, Topology) -> (new, alive, similar)
    supports: Callable = lambda height, width, topology: True
    encode: Callable | None = None  # uint8 grid -> carried state
    decode: Callable | None = None  # carried state -> uint8 grid
    # Optional temporally-blocked pass: (cur, Topology) -> (new_after_T_gens,
    # alive_vec, similar_vec) with int32 (multi_gens,) per-generation flags.
    fused_multi: Callable | None = None
    multi_gens: int = 1
    supports_multi: Callable = lambda height, width, topology: False


def lax_evolve(cur, topology: Topology):
    if topology.distributed:
        return stencil_lax.evolve_padded(halo.exchange(cur, topology))
    return stencil_lax.evolve_torus(cur)


def _registry() -> dict[str, Kernel]:
    kernels = {"lax": Kernel(name="lax", step=lax_evolve)}
    try:
        import functools

        from gol_tpu.ops import stencil_packed, stencil_pallas

        kernels["pallas"] = Kernel(
            name="pallas",
            step=lambda cur, topo: stencil_pallas.pallas_step(cur, topo)[0],
            fused=stencil_pallas.pallas_step,
            supports=stencil_pallas.supports,
        )

        def _packed(name: str, **routing) -> Kernel:
            fused = functools.partial(stencil_packed.packed_step, **routing)
            # The jnp-network route has no Pallas tiling/VMEM constraints, so
            # its shape gates are the relaxed packing-only ones — this is how
            # `auto` serves odd-height single-device grids a packed-family
            # kernel instead of byte lax (r4 verdict weak #5).
            jnp_only = routing.get("force_jnp", False)
            return Kernel(
                name=name,
                step=lambda cur, topo: stencil_packed.decode(
                    fused(stencil_packed.encode(cur), topo)[0]
                ),
                fused=fused,
                supports=(stencil_packed.supports_jnp if jnp_only
                          else stencil_packed.supports),
                encode=stencil_packed.encode,
                decode=stencil_packed.decode,
                fused_multi=functools.partial(stencil_packed.packed_step_multi,
                                              **routing),
                multi_gens=stencil_packed.TEMPORAL_GENS,
                supports_multi=(stencil_packed.supports_multi_jnp if jnp_only
                                else stencil_packed.supports_multi),
            )

        kernels["packed"] = _packed("packed")
        # The Mosaic-compile-failure demotion target: identical word-state
        # semantics through the jnp adder network, no Pallas anywhere. Not
        # offered by `auto` directly — engine._KernelFallback engages it when
        # the packed kernel's first compile fails (the VMEM caps are
        # v5e-empirical; another TPU generation may refuse a shape inside
        # them, and the reference never dies on a supported shape,
        # src/game.c:224-245).
        kernels["packed-jnp"] = _packed("packed-jnp", force_jnp=True)
        # Test lane: the distributed Pallas kernel composition in interpret
        # mode off TPU (CI/soak coverage of the real kernel wiring without
        # a chip) — a first-class kernel name so runner caches key
        # correctly per routing. Never chosen by `auto`.
        kernels["packed-interp"] = _packed("packed-interp", force_interp=True)
    except ImportError:  # pragma: no cover - pallas unavailable on some backends
        pass
    return kernels


def with_temporal_depth(kernel: Kernel, depth: int) -> Kernel:
    """A depth-``T`` temporally-grouped variant of ``kernel``.

    The engine's blocked loops consume ``fused_multi`` at whatever
    ``multi_gens`` the kernel declares, and the scalar replay is oblivious
    to the grouping (engine._block_generations), so *any* depth is bit-exact
    with the per-generation loop — depth is purely a performance knob, which
    makes it a tunable axis (gol_tpu/tune/space.py) rather than a constant:

    - ``depth == kernel.multi_gens`` with a native ``fused_multi`` returns
      the kernel unchanged (the deep-halo Pallas pass at its built-in T);
    - ``depth == 1`` strips ``fused_multi``: one fused pass per generation,
      flags recorded per-step (the pre-temporal-blocking form);
    - other depths compose ``depth`` fused passes into one ``fused_multi``
      call via a fori_loop, amortizing the per-call flag-vector plumbing
      without requiring a kernel rebuild — valid wherever the per-step
      kernel runs (``supports_multi`` becomes the per-step ``supports``).

    Kernels without a fused pass (byte lax) only admit depth 1.
    """
    if depth < 1:
        raise ValueError(f"temporal depth must be >= 1, got {depth}")
    if depth == kernel.multi_gens and kernel.fused_multi is not None:
        return kernel
    if depth == 1:
        if kernel.fused_multi is None:
            return kernel
        return dataclasses.replace(
            kernel, fused_multi=None, multi_gens=1,
            supports_multi=lambda height, width, topology: False,
        )
    if kernel.fused is None:
        raise ValueError(
            f"kernel {kernel.name!r} has no fused pass; temporal depth "
            f"{depth} needs one (only depth 1 is valid)"
        )
    fused = kernel.fused

    def fused_multi(cur, topology):
        def sub(i, carry):
            cur, a_vec, s_vec = carry
            new, alive, similar = fused(cur, topology)
            a_vec = a_vec.at[i].set(alive.astype(jnp.int32))
            s_vec = s_vec.at[i].set(similar.astype(jnp.int32))
            return new, a_vec, s_vec

        zeros = jnp.zeros((depth,), jnp.int32)
        return jax.lax.fori_loop(0, depth, sub, (cur, zeros, zeros))

    return dataclasses.replace(
        kernel, fused_multi=fused_multi, multi_gens=depth,
        supports_multi=kernel.supports,
    )


def get_kernel(name: str) -> Kernel:
    """Resolve an explicit kernel name (``auto`` is only accepted by
    ``resolve_kernel``, which needs the shape/topology to choose)."""
    kernels = _registry()
    if name not in kernels:
        raise ValueError(f"unknown kernel {name!r}; available: {sorted(kernels)}")
    return kernels[name]


def resolve_kernel(name: str, height: int, width: int, topology: Topology) -> Kernel:
    """Pick the best kernel for a concrete shape/topology.

    ``auto`` prefers the Pallas fast path when the compiled kernel supports
    the shape on this backend. Off TPU the packed kernel still wins where it
    fits: every off-TPU path routes to the jnp adder network (32 cells/word
    — measured 18x the lax roll stencil on CPU at 4096²), never the Mosaic
    interpreter (which only the kernel='packed-interp' test lane engages).
    Shapes the compiled packed kernel cannot tile (odd heights, widths past
    the VMEM cap) but that still pack take ``packed-jnp`` — the same word
    network without Pallas, ahead of the byte kernels (32x less HBM traffic;
    single-device odd heights measured 14x lax on CPU at 1000x4096). The byte
    ``pallas`` kernel is TPU-only for auto: off TPU it would run wholly in
    interpret mode. ``lax`` remains the any-shape fallback.
    """
    if name != "auto":
        return get_kernel(name)
    kernels = _registry()
    candidates = (
        ("packed", "packed-jnp", "pallas")
        if jax.default_backend() == "tpu"
        else ("packed", "packed-jnp")
    )
    for candidate in candidates:
        kernel = kernels.get(candidate)
        if kernel is not None and kernel.supports(height, width, topology):
            return kernel
    return kernels["lax"]


def fallback_chain(kernel: Kernel, height: int, width: int, topology: Topology,
                   *, packed_state: bool) -> list[Kernel]:
    """The compile-failure demotion ladder behind ``kernel``, best first.

    Pallas compiles lazily — at the engine runner's first call, not at
    resolution time — and the packed/pallas VMEM caps are v5e-empirical
    constants, so on another TPU generation a shape inside the caps can
    Mosaic-OOM at compile. The engine wraps the runner's first call and
    demotes down this ladder instead of crashing (the reference bar: no
    supported shape ever aborts, src/game.c:224-245):

      packed -> packed-jnp (-> lax)     pallas -> lax

    ``packed_state`` runners carry uint32 word state, which only the packed
    family speaks — their ladder stops at packed-jnp. Fallback entries that
    do not support the shape are dropped (today none: packed-jnp shares
    packed's `supports` and lax supports everything, but the filter keeps
    the invariant checked rather than assumed).
    """
    kernels = _registry()
    chain = [kernel]
    if kernel.name == "packed":
        chain.append(kernels["packed-jnp"])
    if not packed_state and kernel.name != "lax":
        chain.append(kernels["lax"])
    return [chain[0]] + [
        k for k in chain[1:] if k.supports(height, width, topology)
    ]
