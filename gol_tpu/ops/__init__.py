"""Compute kernels: the 3x3 Moore stencil in lax and Pallas flavors.

A kernel owns one generation of compute for a shard's (h, w) uint8 block,
including its halo strategy (local wrap, ppermute ghosts, or fused DMA).

Two call forms:

- ``step(cur, topology) -> new`` — just the next generation.
- ``fused(cur, topology) -> (new, any_alive, similar)`` — optionally, the next
  generation plus the termination flags computed in the same memory pass (the
  Pallas path; fusing the reference's separate empty/compare kernels,
  src/game_cuda.cu:76-126, into the evolve pass).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax

from gol_tpu.ops import stencil_lax
from gol_tpu.parallel import halo
from gol_tpu.parallel.mesh import Topology


@dataclasses.dataclass(frozen=True)
class Kernel:
    """A named evolve implementation with optional fused termination flags."""

    name: str
    step: Callable  # (cur, Topology) -> new
    fused: Callable | None = None  # (cur, Topology) -> (new, alive, similar)
    supports: Callable = lambda height, width, topology: True


def lax_evolve(cur, topology: Topology):
    if topology.distributed:
        return stencil_lax.evolve_padded(halo.exchange(cur, topology))
    return stencil_lax.evolve_torus(cur)


def _registry() -> dict[str, Kernel]:
    kernels = {"lax": Kernel(name="lax", step=lax_evolve)}
    try:
        from gol_tpu.ops import stencil_pallas

        kernels["pallas"] = Kernel(
            name="pallas",
            step=lambda cur, topo: stencil_pallas.pallas_step(cur, topo)[0],
            fused=stencil_pallas.pallas_step,
            supports=stencil_pallas.supports,
        )
    except ImportError:  # pragma: no cover - pallas unavailable on some backends
        pass
    return kernels


def get_kernel(name: str) -> Kernel:
    """Resolve an explicit kernel name (``auto`` is only accepted by
    ``resolve_kernel``, which needs the shape/topology to choose)."""
    kernels = _registry()
    if name not in kernels:
        raise ValueError(f"unknown kernel {name!r}; available: {sorted(kernels)}")
    return kernels[name]


def resolve_kernel(name: str, height: int, width: int, topology: Topology) -> Kernel:
    """Pick the best kernel for a concrete shape/topology.

    ``auto`` prefers the Pallas fast path when the compiled kernel supports the
    shape on this backend, falling back to the always-correct lax path.
    """
    if name != "auto":
        return get_kernel(name)
    kernels = _registry()
    pallas = kernels.get("pallas")
    if (
        pallas is not None
        and jax.default_backend() == "tpu"
        and pallas.supports(height, width, topology)
    ):
        return pallas
    return kernels["lax"]
