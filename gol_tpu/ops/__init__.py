"""Compute kernels: the 3x3 Moore stencil in lax and Pallas flavors.

A kernel is a callable ``evolve(cur, topology) -> new`` mapping a shard's
(h, w) uint8 block to the next generation, owning its own halo strategy:
the lax kernel wraps locally via rolls or exchanges ghosts via ppermute;
the Pallas kernel fuses halo handling into its VMEM tiling.
"""

from __future__ import annotations

from gol_tpu.ops import stencil_lax
from gol_tpu.parallel import halo
from gol_tpu.parallel.mesh import Topology


def lax_evolve(cur, topology: Topology):
    if topology.distributed:
        return stencil_lax.evolve_padded(halo.exchange(cur, topology))
    return stencil_lax.evolve_torus(cur)


def get_kernel(name: str):
    """Resolve a kernel name to an ``(cur, topology) -> new`` evolve function."""
    kernels = {"lax": lax_evolve}
    try:
        from gol_tpu.ops.stencil_pallas import pallas_evolve

        kernels["pallas"] = pallas_evolve
    except ImportError:  # pragma: no cover - pallas unavailable on some backends
        pass
    if name not in kernels:
        raise ValueError(f"unknown kernel {name!r}; available: {sorted(kernels)}")
    return kernels[name]
