"""lax-level 3x3 Moore stencil — the obviously-correct compute path.

Equivalent of the reference's evolve kernels. Two forms:

- ``evolve_torus``: whole-array form for an unsharded grid; the toroidal wrap
  is 8 ``jnp.roll`` shifts (the index-remapping wrap of src/game.c:69-86 done
  as whole-array ops). Rolls preserve the 128-lane tile alignment of the
  (H, W) array, which XLA fuses into a single VPU pass — measured ~15x faster
  on TPU than slicing a (H+2, W+2) padded copy, whose odd shape defeats
  tiling.

- ``evolve_padded``: halo form for a ghost-extended (h+2, w+2) shard block
  (the src/game_mpi.c:73-84 shape). The reference sums ASCII codes against
  387/386 (3*'1'+5*'0' / 2*'1'+6*'0', src/game_mpi.c:45-47); with numeric
  {0,1} cells the thresholds are just 3 and 2.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _apply_rule(neighbors: jnp.ndarray, center: jnp.ndarray) -> jnp.ndarray:
    # B3/S23 (src/game.c:91-98): born on 3, survive on 2.
    return ((neighbors == 3) | ((neighbors == 2) & (center == 1))).astype(jnp.uint8)


def neighbor_counts_torus(grid: jnp.ndarray) -> jnp.ndarray:
    """Sum of the 8 Moore neighbors with toroidal wrap (uint8 is enough)."""
    counts = jnp.zeros_like(grid)
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dy == 0 and dx == 0:
                continue
            counts = counts + jnp.roll(grid, (dy, dx), (0, 1))
    return counts


def evolve_torus(grid: jnp.ndarray) -> jnp.ndarray:
    """One generation of the full (unsharded) torus."""
    return _apply_rule(neighbor_counts_torus(grid), grid)


def evolve_padded(padded: jnp.ndarray) -> jnp.ndarray:
    """One generation for the interior of a halo-extended (h+2, w+2) block."""
    center = padded[1:-1, 1:-1]
    neighbors = (
        padded[:-2, :-2]
        + padded[:-2, 1:-1]
        + padded[:-2, 2:]
        + padded[1:-1, :-2]
        + padded[1:-1, 2:]
        + padded[2:, :-2]
        + padded[2:, 1:-1]
        + padded[2:, 2:]
    )
    return _apply_rule(neighbors, center)


def evolve_padded_batch(blocks: jnp.ndarray):
    """One generation over B independent halo-extended blocks, with the
    per-block flags the sparse tile engine consumes.

    ``blocks`` is (B, h+2, w+2): each block is a tile plus its 1-cell halo
    ring (assembled host-side from the tile's 8 torus neighbors —
    gol_tpu/sparse/engine.py). Interior cells read only in-block
    neighbors, so the step is exact for the interior regardless of what a
    torus/dead-wall rule would do to the discarded outer ring. Returns
    ``(interiors, alive, changed)``: the (B, h, w) next interiors plus
    per-block any-live and interior-changed flags — the two reductions the
    sparse host loop needs every generation, computed in the same memory
    pass as the stencil rather than as host-side scans.
    """
    def one(block):
        new = evolve_padded(block)
        old = block[1:-1, 1:-1]
        return new, jnp.any(new), jnp.any(new != old)

    return jax.vmap(one)(blocks)
