"""jax-version compat shims for the pallas TPU kernels — one home, like
``parallel/mesh.py``'s ``shard_map`` shim, so a future jax rename is fixed
once instead of per-kernel-file."""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

# Older jax names the params class TPUCompilerParams; same fields.
CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
