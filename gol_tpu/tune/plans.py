"""Persistent plan cache: fingerprinted JSON, atomic writes, loud fallbacks.

One file (default ``~/.cache/gol_tpu/plans.json``, overridable via the
``GOL_PLAN_CACHE`` env var or ``--plan-cache``) maps *fingerprints* to
measured plans. A fingerprint bakes in everything that invalidates a
measurement:

    schema version | jax version | kind | HxW | convention | state family |
    mesh RxC | device kind

so a jax upgrade, a schema change, a different chip, or a different mesh
simply *misses* — stale plans can never be served, only skipped (and they
are pruned from the file on the next ``put``).

Durability follows the resilience staging discipline (the same
``.inprogress`` suffix the checkpoint/ts_store writers use): the new cache
body is written to a temp path, fsynced, and committed with ``os.replace``
— a crash mid-write leaves either the old cache or the new one, never a
torn file. Reads are tolerant anyway: an unreadable/torn cache logs a loud
warning and falls back to the bundled defaults (``default_plans.json``),
which encode the hard-coded ladders — a cold or corrupted machine behaves
exactly like the pre-tune engine.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import tempfile

from gol_tpu.resilience import STAGING_SUFFIX

logger = logging.getLogger(__name__)

SCHEMA_VERSION = 1
ENV_CACHE_PATH = "GOL_PLAN_CACHE"
_BUNDLED_DEFAULTS = os.path.join(os.path.dirname(__file__),
                                 "default_plans.json")


def default_cache_path() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(base, "gol_tpu", "plans.json")


def cache_path() -> str:
    return os.environ.get(ENV_CACHE_PATH) or default_cache_path()


def _jax_version() -> str:
    # A function (not an import-time constant) so tests can patch it to
    # exercise version invalidation without faking an installed jax.
    import jax

    return jax.__version__


def device_kind() -> str:
    import jax

    return jax.devices()[0].device_kind


def fingerprint(
    kind: str,
    height: int,
    width: int,
    convention: str,
    family: str,
    mesh_shape: tuple[int, int],
    dev_kind: str,
) -> str:
    """The stable cache key. Every field is part of the string, so any
    mismatch — including the jax/schema versions — is a clean miss."""
    return "|".join(
        (
            f"schema={SCHEMA_VERSION}",
            f"jax={_jax_version()}",
            f"kind={kind}",
            f"grid={height}x{width}",
            f"conv={convention}",
            f"family={family}",
            f"mesh={mesh_shape[0]}x{mesh_shape[1]}",
            f"device={dev_kind}",
        )
    )


@dataclasses.dataclass
class PlanStore:
    """Load/commit interface over one plans.json file.

    Loading is lazy and cached per instance; ``put`` re-reads the file
    first, so concurrent tuners lose at most their own entry, never the
    whole file (last ``os.replace`` wins per entry set).
    """

    path: str | None = None
    _entries: dict | None = dataclasses.field(default=None, repr=False)
    _defaults: dict | None = dataclasses.field(default=None, repr=False)

    def __post_init__(self):
        if self.path is None:
            self.path = cache_path()

    # -- reads -------------------------------------------------------------

    def _read_file(self, path: str, *, bundled: bool) -> dict:
        try:
            with open(path, "r", encoding="utf-8") as f:
                body = json.load(f)
            entries = body["plans"]
            if not isinstance(entries, dict):
                raise ValueError(f"'plans' is {type(entries).__name__}, not a dict")
            return entries
        except FileNotFoundError:
            return {}
        except (OSError, ValueError, KeyError) as err:
            # A torn/partial cache (crash mid-write of a non-staging writer,
            # disk corruption, a hand edit) must degrade to defaults LOUDLY
            # — silently serving half a cache would look like a perf
            # regression with no trail.
            logger.warning(
                "%s plan file %s is unreadable (%s: %s); falling back to "
                "built-in ladder defaults",
                "bundled" if bundled else "cached", path,
                type(err).__name__, err,
            )
            return {}

    def entries(self) -> dict:
        if self._entries is None:
            self._entries = self._read_file(self.path, bundled=False)
        return self._entries

    def defaults(self) -> dict:
        if self._defaults is None:
            self._defaults = self._read_file(_BUNDLED_DEFAULTS, bundled=True)
        return self._defaults

    def get(self, fp: str) -> dict | None:
        """The plan dict stored under ``fp``, or None. The fingerprint
        carries the schema/jax versions, so no further staleness check is
        needed here — a stale entry cannot be addressed at all."""
        entry = self.entries().get(fp)
        if entry is None:
            return None
        plan = entry.get("plan")
        return plan if isinstance(plan, dict) else None

    def get_default(self, kind: str) -> dict | None:
        """Bundled fallback for ``kind`` ('engine' | 'serve'): version-less
        by design — defaults describe the built-in ladders, which travel
        with the code, not with a jax install."""
        entry = self.defaults().get(f"default:{kind}")
        if entry is None:
            return None
        plan = entry.get("plan")
        return plan if isinstance(plan, dict) else None

    # -- writes ------------------------------------------------------------

    def put(self, fp: str, plan: dict, measured: dict | None = None) -> None:
        """Insert/replace one entry and commit the file atomically.

        Entries whose recorded schema/jax no longer match the running
        versions are pruned on the way out — the cache never accretes
        unreachable keys across upgrades.
        """
        current = self._read_file(self.path, bundled=False)
        keep = {
            key: entry
            for key, entry in current.items()
            if isinstance(entry, dict)
            and entry.get("schema") == SCHEMA_VERSION
            and entry.get("jax") == _jax_version()
        }
        dropped = len(current) - len(keep)
        if dropped:
            logger.info("pruned %d stale plan cache entr%s from %s",
                        dropped, "y" if dropped == 1 else "ies", self.path)
        keep[fp] = {
            "schema": SCHEMA_VERSION,
            "jax": _jax_version(),
            "plan": dict(plan),
        }
        if measured is not None:
            keep[fp]["measured"] = measured
        self._commit(keep)
        self._entries = keep

    def _commit(self, entries: dict) -> None:
        body = {"schema": SCHEMA_VERSION, "plans": entries}
        directory = os.path.dirname(self.path) or "."
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=directory,
            prefix=os.path.basename(self.path) + ".",
            suffix=STAGING_SUFFIX,
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(body, f, indent=1, sort_keys=True)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
