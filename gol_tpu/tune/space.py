"""The declarative search space: every tunable the engine and batcher expose.

A *plan* is a point in this space; a *candidate* is a plan the validity
filter admits for a concrete (height, width, convention, mesh shape, device
kind) context. The axes mirror the reference's compile-time configuration
surface (BLOCK_SIZE/THREADS ``#define``s) plus the ladders this codebase
hard-coded as it grew:

- kernel flavor      — byte lax vs Pallas band vs bit-packed words (the
                       ``ops`` registry names);
- temporal depth     — generations fused per deep-halo/VMEM pass, in
                       {1, 2, 4, 8} (``ops.with_temporal_depth``);
- termination block  — generations per flag-sync of the blocked while loop
                       (``engine._TERMINATION_BLOCK``'s measured override);
- Pallas band target — VMEM bytes per band of the packed kernels (TPU only;
                       ``stencil_packed.set_band_target_override``);
- packed vs byte carried state — which runner *family* a plan describes
                       (searched side by side; selection stays per-family
                       because the CLI's I/O lane fixes the family);
- serve padding quantum + batch-size ladder — the batcher's bucket geometry.

Validity filtering happens HERE, once, instead of being scattered through
the measurement loop: a candidate that comes out of ``engine_candidates``
builds and runs on that context by construction (kernel ``supports`` gates,
packing divisibility, depth needing a fused pass, band targets needing a
TPU backend).
"""

from __future__ import annotations

import dataclasses

from gol_tpu import ops
from gol_tpu.parallel.mesh import MESH_TOPOLOGY_AXES, Topology

# Axis domains. Kept small and explicit — the space is searched exhaustively
# per shape, so every value here multiplies measurement time.
TEMPORAL_DEPTHS = (1, 2, 4, 8)
TERMINATION_BLOCKS = (8, 16, 32, 64)
# VMEM band-byte targets for the compiled packed kernels (the values the
# width-aware default in stencil_packed._pick_band chooses among).
BAND_TARGETS = (1 << 20, 3 << 19, 2 << 20)
# Serve batcher geometry: board extents round up to the quantum; request
# counts round up the ladder. Every quantum is a multiple of 32 so exact-fit
# buckets keep the bit-packed fast path; every ladder ends at the batcher's
# hard cap so scheduler/server admission bounds stay invariant.
PAD_QUANTA = (32, 64, 128)
BATCH_LADDERS = (
    (1, 2, 4, 8, 16, 32, 64),
    (1, 4, 16, 64),
    (1, 8, 64),
)
# Batched temporal depth: generations per while iteration of the batch/ring
# programs (engine.make_batch_runner temporal_depth — bit-exact at any
# depth, so purely a measured axis). Crossed with the quanta but not the
# ladders: depth amortizes the per-iteration cross-board sync, which
# interacts with the canvas (quantum) and not with how request counts
# round — the full 3-way cross would triple search time for candidates
# that cannot differ.
SERVE_TEMPORAL_DEPTHS = (1, 2, 4, 8)
# Sparse-engine tile edges (gol_tpu/sparse): bit-exact at any admissible
# value — the tile size trades per-tile dispatch amortization against
# elision granularity (smaller tiles skip more dead area; larger tiles
# batch better), so it is a measured axis like the serve geometry. The
# sparse lane's tile-batch counts already round up the serve plan's
# BATCH_LADDERS via batcher.pad_batch, so a tuned ladder applies to tile
# batching with no extra plumbing.
SPARSE_TILES = (128, 256, 512)


def valid_sparse_tile(tile: int, height: int, width: int) -> bool:
    """A tile edge is admissible for a universe iff the extents tile
    evenly (the sparse board's own constructor invariant)."""
    return tile >= 4 and height % tile == 0 and width % tile == 0


@dataclasses.dataclass(frozen=True)
class EnginePlan:
    """A point in the solo-engine space; ``None`` fields mean "built-in".

    Doubles as the runtime plan object ``engine._build_runner`` applies —
    the search measures exactly what selection later builds.
    """

    kernel: str | None = None  # ops registry name; None = the auto ladder
    temporal_depth: int | None = None  # generations per fused_multi pass
    termination_block: int | None = None  # generations per flag sync
    band_bytes: int | None = None  # Pallas band VMEM target (TPU only)

    def label(self) -> str:
        parts = [self.kernel or "auto"]
        if self.temporal_depth:
            parts.append(f"T{self.temporal_depth}")
        if self.termination_block:
            parts.append(f"K{self.termination_block}")
        if self.band_bytes:
            parts.append(f"band{self.band_bytes >> 10}K")
        return "/".join(parts)

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}

    @classmethod
    def from_dict(cls, data: dict) -> "EnginePlan":
        fields = {f.name for f in dataclasses.fields(cls)}
        kwargs = {}
        for key, value in data.items():
            if key not in fields or value is None:
                continue
            kwargs[key] = str(value) if key == "kernel" else int(value)
        return cls(**kwargs)


@dataclasses.dataclass(frozen=True)
class ServePlan:
    """Serve-batcher geometry: one plan covers the whole fleet's buckets.

    ``temporal_depth`` is the batched engine's generations-per-while-
    iteration (bit-exact at any value — engine._temporal_body), applied to
    every bucket program the batcher builds; depth 1 is the pre-tune
    behavior, byte-identically."""

    pad_quantum: int = 32
    batch_ladder: tuple[int, ...] = BATCH_LADDERS[0]
    temporal_depth: int = 1

    def label(self) -> str:
        label = f"q{self.pad_quantum}/ladder{'-'.join(map(str, self.batch_ladder))}"
        if self.temporal_depth != 1:
            label += f"/T{self.temporal_depth}"
        return label

    def to_dict(self) -> dict:
        out = {
            "pad_quantum": self.pad_quantum,
            "batch_ladder": list(self.batch_ladder),
        }
        # Only when tuned off the default: older caches (and their pinned
        # goldens) stay byte-stable.
        if self.temporal_depth != 1:
            out["temporal_depth"] = self.temporal_depth
        return out

    @classmethod
    def from_dict(cls, data: dict) -> "ServePlan":
        return cls(
            pad_quantum=int(data["pad_quantum"]),
            batch_ladder=tuple(int(x) for x in data["batch_ladder"]),
            temporal_depth=int(data.get("temporal_depth", 1)),
        )


# The behavior the hard-coded ladders implement today: these plans are what
# "no plan" means, and the bundled default_plans.json encodes them — so a
# cold machine (or a torn cache file) gets exactly the pre-tune ladders.
DEFAULT_SERVE_PLAN = ServePlan()


def valid_serve_plan(plan: ServePlan, max_batch: int) -> bool:
    """Admission gate for serve plans, shared by the candidate generator and
    the runtime consult (a stale/hand-edited cache entry must not be able to
    change the server's admission invariants)."""
    ladder = plan.batch_ladder
    return (
        plan.pad_quantum >= 32
        and plan.pad_quantum % 32 == 0
        and len(ladder) >= 1
        and ladder[0] == 1
        and ladder[-1] == max_batch
        and all(a < b for a, b in zip(ladder, ladder[1:]))
        # Any depth is bit-exact, but the engine caps the axis (and a
        # hand-edited 10^6 would hang every program in useless no-op
        # sub-steps after the batch converges).
        and 1 <= plan.temporal_depth <= 64
    )


@dataclasses.dataclass(frozen=True)
class TuneContext:
    """Everything the validity filter (and the plan fingerprint) keys on."""

    height: int
    width: int
    convention: str
    packed_state: bool  # carried-state family: words vs uint8 grid
    mesh_shape: tuple[int, int] = (1, 1)
    device_kind: str = "cpu"

    @property
    def family(self) -> str:
        return "packed" if self.packed_state else "byte"

    @property
    def topology(self) -> Topology:
        if self.mesh_shape == (1, 1):
            return Topology()
        return Topology(shape=self.mesh_shape, axes=MESH_TOPOLOGY_AXES)

    @property
    def local_shape(self) -> tuple[int, int]:
        return (self.height // self.mesh_shape[0],
                self.width // self.mesh_shape[1])

    @property
    def on_tpu(self) -> bool:
        return "tpu" in self.device_kind.lower()


def context_for(shape, config, mesh=None, packed_state=False) -> TuneContext:
    """Derive the tuning context of a concrete run (reads jax lazily)."""
    import jax

    mesh_shape = (1, 1)
    if mesh is not None:
        from gol_tpu.parallel.mesh import topology_for

        mesh_shape = topology_for(mesh).shape
    return TuneContext(
        height=int(shape[0]),
        width=int(shape[1]),
        convention=config.convention,
        packed_state=packed_state,
        mesh_shape=mesh_shape,
        device_kind=jax.devices()[0].device_kind,
    )


def default_engine_plan(ctx: TuneContext) -> EnginePlan:
    """The plan the hard-coded ladder picks for this context today: the
    search's baseline candidate, and the ratio denominator in reports."""
    local_h, local_w = ctx.local_shape
    kernel = (
        "packed" if ctx.packed_state
        else ops.resolve_kernel("auto", local_h, local_w, ctx.topology).name
    )
    kobj = ops.get_kernel(kernel)
    depth = (
        kobj.multi_gens
        if kobj.fused_multi is not None
        and kobj.supports_multi(local_h, local_w, ctx.topology)
        else 1
    )
    return EnginePlan(kernel=kernel, temporal_depth=depth,
                      termination_block=16)


def engine_candidates(ctx: TuneContext, quick: bool = False) -> list[EnginePlan]:
    """Every engine plan valid for ``ctx``, default candidate first.

    Kernel flavors come from the ops registry filtered by their own
    ``supports`` gates (the packed family only where the width packs, the
    byte Pallas kernel only on TPU — off TPU it would run wholly in
    interpret mode, a measurement of nothing). Depth needs a fused pass
    (byte lax has none); band targets need the compiled Pallas path.

    ``quick`` prunes the depth/block axes to their extremes — the smoke and
    CI searches, where each candidate costs a compile.
    """
    local_h, local_w = ctx.local_shape
    topo = ctx.topology
    if ctx.packed_state:
        kernel_names = ["packed", "packed-jnp"]
    else:
        kernel_names = ["packed", "packed-jnp", "lax"]
        if ctx.on_tpu:
            kernel_names.insert(2, "pallas")
    all_depths = (1, TEMPORAL_DEPTHS[-1]) if quick else TEMPORAL_DEPTHS
    all_blocks = (16, TERMINATION_BLOCKS[-1]) if quick else TERMINATION_BLOCKS
    candidates = [default_engine_plan(ctx)]
    for name in kernel_names:
        try:
            kobj = ops.get_kernel(name)
        except ValueError:  # registry pruned (pallas unavailable)
            continue
        if not kobj.supports(local_h, local_w, topo):
            continue
        depths = all_depths if kobj.fused is not None else (1,)
        bands = (
            BAND_TARGETS if ctx.on_tpu and name in ("packed", "pallas")
            else (None,)
        )
        for depth in depths:
            blocks = all_blocks if kobj.fused is not None else (16,)
            for block in blocks:
                for band in bands:
                    cand = EnginePlan(kernel=name, temporal_depth=depth,
                                      termination_block=block, band_bytes=band)
                    if cand not in candidates:
                        candidates.append(cand)
    return candidates


def serve_candidates(max_batch: int = 64) -> list[ServePlan]:
    """Every serve plan, default first: the geometry axes (quantum x
    ladder, at depth 1) plus the batched temporal-depth axis (depth x
    quantum, at the default ladder — see SERVE_TEMPORAL_DEPTHS for why the
    ladder is not crossed)."""
    candidates = [DEFAULT_SERVE_PLAN]
    for quantum in PAD_QUANTA:
        for ladder in BATCH_LADDERS:
            cand = ServePlan(pad_quantum=quantum, batch_ladder=ladder)
            if valid_serve_plan(cand, max_batch) and cand not in candidates:
                candidates.append(cand)
    for quantum in PAD_QUANTA:
        for depth in SERVE_TEMPORAL_DEPTHS:
            cand = ServePlan(pad_quantum=quantum, temporal_depth=depth)
            if valid_serve_plan(cand, max_batch) and cand not in candidates:
                candidates.append(cand)
    return candidates
