"""Autotuning + persistent plan cache: measured kernel/layout selection.

The reference tunes by recompilation: BLOCK_SIZE, THREADS, GEN_LIMIT are
compile-time ``#define``s (src/game_cuda.cu:4, src/game_openmp.c:11), so
"try a different configuration" means "edit, rebuild, rerun". This package
promotes those decisions — and the ones this codebase accreted as
hard-coded ladders (kernel flavor, deep-halo temporal depth, termination
block size, Pallas band target, the serve batcher's padding quantum and
batch-size ladder) — to *measured* choices, made once offline and reused:

- ``space``   — the declarative search space, validity-filtered per
  (shape, convention, mesh, device kind);
- ``measure`` — timed trials (``perf_counter`` only, warmup + outlier-
  trimmed medians) behind a byte-exact correctness gate;
- ``plans``   — the persistent JSON plan cache: stable fingerprints,
  atomic writes, stale-key invalidation, bundled defaults;
- ``select``  — runtime consult: the engine and the serve batcher ask here
  instead of their inlined ladders (bit-identical behavior when no plan
  exists).

Import layering: ``plans`` is stdlib-only (jax is touched lazily, for the
version/device fingerprint); ``select`` adds ``space``; ``measure`` pulls
the engine and is imported only by the offline drivers (``gol tune``,
``bench.py --suite tune``, tools/tune_smoke.py). Nothing here may read the
wall clock — ``time.perf_counter`` only (enforced by tests/test_lint.py).
"""
