"""Timed trials behind a byte-exact correctness gate.

Measurement discipline (the hard-won protocol of tools/measure.py, applied
in-process):

- ``time.perf_counter`` ONLY — the wall clock steps under NTP and is banned
  from this package (tests/test_lint.py);
- every candidate is warmed (compile + first dispatch) before any sample;
- per-candidate samples are reduced by an **outlier-trimmed median** (drop
  the extremes, median the rest) — robust to the one-off stalls shared
  machines inject;
- completion is forced by a scalar readback (``int(...)``), the only
  reliable barrier over remote-attach tunnels;
- and NO timing counts until the candidate passes the **correctness gate**:
  its final grid and generation count are byte-compared against the
  reference output (the default-ladder solo engine — itself oracle-checked
  on small grids). A mismatching candidate is excluded from selection and
  reported loudly; it can never win a race it cheated.

The searches are exhaustive over ``space`` candidates; winners are returned
as plans ready for ``plans.PlanStore.put``.
"""

from __future__ import annotations

import dataclasses
import logging
import time

import numpy as np

from gol_tpu.config import GameConfig
from gol_tpu.obs import registry as obs_registry, trace as obs_trace
from gol_tpu.tune import space

logger = logging.getLogger(__name__)


def _count_trial(trial: Trial) -> Trial:
    """Record a finished trial in the global obs registry (and as a trace
    event): a tuning session's progress is then visible over SIGUSR1 /
    ``GET /debug/trace`` like every other long-running phase."""
    reg = obs_registry.default()
    reg.inc("tuner_trials_total")
    if trial.gate != "ok":
        reg.inc("tuner_gate_failures_total")
    obs_trace.event("tune.trial", label=trial.label, gate=trial.gate,
                    median_s=trial.median_s)
    return trial

# A grid this small is cheap to oracle-check, so the reference output itself
# is verified against ground truth before any candidate is gated on it.
_ORACLE_GATE_CELLS = 1 << 16


def trimmed_median(samples) -> float:
    """Median after dropping the min and max (when there are enough samples
    to spare them): one cold-cache or preempted run cannot shift the stat."""
    ordered = sorted(samples)
    if not ordered:
        raise ValueError("no samples")
    if len(ordered) >= 4:
        ordered = ordered[1:-1]
    mid = len(ordered) // 2
    if len(ordered) % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2


def timed_samples(fn, *, warmup: int = 1, iters: int = 5) -> list[float]:
    """Run ``fn`` ``warmup`` untimed + ``iters`` timed times."""
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    for _ in range(warmup):
        fn()
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return samples


@dataclasses.dataclass
class Trial:
    label: str
    plan: object  # EnginePlan | ServePlan
    median_s: float | None  # None when the gate failed (never timed)
    samples: list[float]
    gate: str  # "ok" | "mismatch" | "error: <type>"

    def to_dict(self) -> dict:
        return {
            "label": self.label,
            "plan": self.plan.to_dict(),
            "median_s": self.median_s,
            "samples": [round(s, 6) for s in self.samples],
            "gate": self.gate,
        }


@dataclasses.dataclass
class SearchResult:
    kind: str  # "engine" | "serve"
    context: dict  # human-readable search context (shape, convention, ...)
    trials: list[Trial]
    default_label: str
    winner: object  # the winning plan (EnginePlan | ServePlan)
    # Serve searches only: the winner geometry's marginal kernel rate per
    # bucket (sanitized label -> cell-updates/s), the roofline the live
    # dispatch-gap monitor (obs/sampler.py) compares achieved rates against.
    marginal: dict | None = None

    @property
    def winner_trial(self) -> Trial:
        label = self.winner.label()
        return next(t for t in self.trials if t.label == label)

    @property
    def default_trial(self) -> Trial:
        return next(t for t in self.trials if t.label == self.default_label)

    @property
    def speedup(self) -> float:
        """default median / winner median: >= 1.0 by construction (the
        default is in the candidate set and the winner is the argmin)."""
        return self.default_trial.median_s / self.winner_trial.median_s

    def to_dict(self) -> dict:
        out = {
            "kind": self.kind,
            "context": self.context,
            "default": self.default_label,
            "winner": self.winner.label(),
            "winner_plan": self.winner.to_dict(),
            "tuned_vs_default": round(self.speedup, 4),
            "gates_all_ok": all(t.gate == "ok" for t in self.trials),
            "trials": [t.to_dict() for t in self.trials],
        }
        if self.marginal:
            out["marginal_kernel_cells_per_sec"] = self.marginal
        return out


def _pick_winner(trials: list[Trial], default_label: str):
    ok = [t for t in trials if t.gate == "ok"]
    if not ok:
        raise RuntimeError("no candidate passed the correctness gate")
    bad = [t.label for t in trials if t.gate != "ok"]
    if bad:
        logger.warning("correctness gate FAILED for candidate(s) %s — "
                       "excluded from selection", bad)
    winner = min(ok, key=lambda t: t.median_s)
    # Within measurement noise, keep the default: a plan should only exist
    # when it buys something real (2% here is well inside the trimmed-median
    # scatter of shared machines).
    default = next((t for t in ok if t.label == default_label), None)
    if default is not None and default is not winner:
        if default.median_s / winner.median_s < 1.02:
            winner = default
    return winner


def _pack_words(grid: np.ndarray) -> np.ndarray:
    # The ONE bit-order rule lives in io/bitpack.py (tests/test_lint.py
    # bans np.packbits elsewhere in the library).
    from gol_tpu.io import bitpack

    return bitpack.pack_words(grid)


def run_engine_search(
    height: int,
    width: int,
    config: GameConfig,
    mesh=None,
    *,
    packed_state: bool = False,
    seed: int = 42,
    warmup: int = 1,
    iters: int = 5,
    quick: bool = False,
) -> SearchResult:
    """Exhaustively measure the engine candidates for one shape/context.

    The reference output is the DEFAULT candidate's run (the hard-coded
    ladder's choice, built with an explicit empty-plan bypass so an existing
    plan cache cannot shift the baseline), itself byte-checked against the
    NumPy oracle when the grid is small enough to afford it.
    """
    import jax

    from gol_tpu import engine

    ctx = space.context_for((height, width), config, mesh, packed_state)
    candidates = space.engine_candidates(ctx, quick=quick)
    default_label = candidates[0].label()

    rng = np.random.default_rng(seed)
    grid = rng.integers(0, 2, size=(height, width), dtype=np.uint8)
    if packed_state:
        host_state = _pack_words(grid)
    else:
        host_state = grid
    if mesh is None:
        operand = jax.device_put(host_state)
    else:
        from gol_tpu.parallel.mesh import grid_sharding

        operand = jax.device_put(host_state, grid_sharding(mesh))

    reference: tuple[np.ndarray, int] | None = None
    trials: list[Trial] = []
    try:
        for cand in candidates:
            try:
                runner = engine._build_runner(
                    (height, width), config, mesh, cand.kernel or "auto",
                    segmented=False, packed_state=packed_state, plan=cand,
                )

                def run_once(runner=runner):
                    final, gen = runner(operand)
                    return np.asarray(jax.device_get(final)), int(gen)

                out_grid, out_gen = run_once()  # compile + warm + gate material
            except Exception as err:  # noqa: BLE001 - candidate isolation
                # Candidates are built with explicit kernel names (no
                # demotion ladder) and band targets deliberately probe
                # compile limits — one Mosaic refusal must cost one
                # candidate, not the whole search. The default candidate
                # stays fatal: with no reference there is nothing to tune.
                if reference is None:
                    raise
                logger.warning(
                    "candidate %s failed to build/run (%s: %s); excluded",
                    cand.label(), type(err).__name__, err,
                )
                trials.append(_count_trial(
                    Trial(cand.label(), cand, None, [],
                          f"error: {type(err).__name__}")
                ))
                continue
            if reference is None:
                # First candidate IS the default: it becomes the reference,
                # after an oracle check where affordable.
                if not packed_state and height * width <= _ORACLE_GATE_CELLS:
                    from gol_tpu import oracle

                    expect = oracle.run(grid, config)
                    if not (np.array_equal(out_grid, expect.grid)
                            and out_gen == expect.generations):
                        raise RuntimeError(
                            f"default candidate {cand.label()} disagrees "
                            f"with the oracle on {height}x{width}/"
                            f"{config.convention} — refusing to tune against "
                            "a wrong reference"
                        )
                reference = (out_grid, out_gen)
            ok = (
                np.array_equal(out_grid, reference[0])
                and out_gen == reference[1]
            )
            if not ok:
                trials.append(_count_trial(
                    Trial(cand.label(), cand, None, [], "mismatch")
                ))
                continue

            samples = timed_samples(
                lambda: int(runner(operand)[1]), warmup=max(0, warmup - 1),
                iters=iters,
            )
            trials.append(_count_trial(
                Trial(cand.label(), cand, trimmed_median(samples), samples, "ok")
            ))
            logger.info("  %-28s %8.3f ms", cand.label(),
                        trials[-1].median_s * 1e3)
    finally:
        # A band-target candidate leaves its override armed at trace time;
        # never leak it past the search.
        from gol_tpu.ops import stencil_packed

        stencil_packed.set_band_target_override(None)

    winner = _pick_winner(trials, default_label)
    return SearchResult(
        kind="engine",
        context={
            "height": height,
            "width": width,
            "convention": config.convention,
            "family": ctx.family,
            "mesh": f"{ctx.mesh_shape[0]}x{ctx.mesh_shape[1]}",
            "device_kind": ctx.device_kind,
            "gen_limit": config.gen_limit,
            "seed": seed,
            "iters": iters,
        },
        trials=trials,
        default_label=default_label,
        winner=winner.plan,
    )


# Serving-shaped request-count mix: the sizes a flush under light-to-bursty
# load actually dispatches (partial buckets, odd counts, one full batch).
_SERVE_COUNTS = (1, 3, 5, 8, 13, 21)


def measure_marginal_rate(
    board_height: int,
    board_width: int,
    convention: str,
    plan,
    *,
    gen_limit: int = 8,
    batch: int = 8,
    seed: int = 7,
    repeats: int = 3,
) -> dict[str, float]:
    """The winner geometry's **marginal kernel rate**: cell-updates/s of the
    compiled batch program with every fixed cost differenced out (timed at
    G and 3G generation limits, rate from the difference — the BENCH_r08
    protocol, run at tune time). Returned as {sanitized bucket label:
    rate} so the serve-side dispatch-gap monitor (obs/sampler.py) can match
    it against the live ``serve_cell_updates_total_<bucket>`` counters —
    both sides spell the bucket through ``obs.registry.metric_label``."""
    from gol_tpu import engine
    from gol_tpu.obs.registry import metric_label
    from gol_tpu.serve import batcher
    from gol_tpu.serve.batcher import BucketKey

    ph = batcher.pad_dim(board_height, plan=plan)
    pw = batcher.pad_dim(board_width, plan=plan)
    total = batcher.pad_batch(
        min(batch, plan.batch_ladder[-1]), plan=plan
    )
    rng = np.random.default_rng(seed)
    chunk = [
        rng.integers(0, 2, size=(board_height, board_width), dtype=np.uint8)
        for _ in range(min(batch, total))
    ]
    config_for = lambda g: GameConfig(gen_limit=g, convention=convention)
    g1, g2 = gen_limit, 3 * gen_limit

    def staged_for(g):
        return engine.stage_batch(
            chunk, config_for(g), padded_shape=(ph, pw), pad_batch_to=total,
            temporal_depth=plan.temporal_depth,
        )

    times = {}
    for g in (g1, g2):
        engine.complete_batch(engine.dispatch_batch(staged_for(g)))  # warm
        best = float("inf")
        for _ in range(repeats):
            # Fresh staging per run (the program donates its operand); the
            # transfer cost is identical at g1 and g2, so the difference
            # subtracts it out along with dispatch and readback.
            s = staged_for(g)
            t0 = time.perf_counter()
            engine.complete_batch(engine.dispatch_batch(s))
            best = min(best, time.perf_counter() - t0)
        times[g] = best
    per_gen = max(times[g2] - times[g1], 1e-9) / (g2 - g1)
    rate = board_height * board_width * len(chunk) / per_gen
    mode = engine.resolve_batch_mode(
        [board_height] * len(chunk), [board_width] * len(chunk), (ph, pw)
    )
    key = BucketKey(height=ph, width=pw, convention=convention, kernel=mode)
    return {metric_label(key.label()): round(rate, 1)}


def run_serve_search(
    board_height: int,
    board_width: int,
    convention: str = "c",
    *,
    gen_limit: int = 8,
    nboards: int = 21,
    seed: int = 42,
    warmup: int = 1,
    iters: int = 5,
    max_batch: int = 64,
) -> SearchResult:
    """Measure the serve-bucket geometry candidates on one request shape.

    Each candidate's bucket math is applied THROUGH the batcher's own
    ``pad_dim``/``pad_batch`` (with the candidate as the plan override), so
    the measured geometry is exactly what the server later runs, driving
    ``engine.simulate_batch`` over a serving-shaped mix of request counts;
    the gate byte-compares every board of every candidate against solo
    engine runs.
    """
    from gol_tpu import engine
    from gol_tpu.serve import batcher

    candidates = space.serve_candidates(max_batch)
    default_label = candidates[0].label()
    config = GameConfig(gen_limit=gen_limit, convention=convention)

    rng = np.random.default_rng(seed)
    boards = [
        rng.integers(0, 2, size=(board_height, board_width), dtype=np.uint8)
        for _ in range(nboards)
    ]
    solo = [engine.simulate(b, config) for b in boards]
    chunks = []
    i = 0
    for count in _SERVE_COUNTS:
        count = min(count, nboards)
        chunks.append([boards[(i + j) % nboards] for j in range(count)])
        i += count

    trials: list[Trial] = []
    for cand in candidates:
        ph = batcher.pad_dim(board_height, plan=cand)
        pw = batcher.pad_dim(board_width, plan=cand)

        def dispatch(cand=cand, ph=ph, pw=pw, gate=False):
            for chunk in chunks:
                results = engine.simulate_batch(
                    chunk, config, padded_shape=(ph, pw),
                    pad_batch_to=batcher.pad_batch(len(chunk), plan=cand),
                    temporal_depth=cand.temporal_depth,
                )
                if gate:
                    for board, result in zip(chunk, results):
                        idx = next(
                            k for k, b in enumerate(boards) if b is board
                        )
                        if not (
                            np.array_equal(result.grid, solo[idx].grid)
                            and result.generations == solo[idx].generations
                        ):
                            return False
            return True

        if not dispatch(gate=True):  # compile + warm + gate in one pass
            trials.append(_count_trial(
                Trial(cand.label(), cand, None, [], "mismatch")
            ))
            continue
        samples = timed_samples(dispatch, warmup=max(0, warmup - 1),
                                iters=iters)
        trials.append(_count_trial(
            Trial(cand.label(), cand, trimmed_median(samples), samples, "ok")
        ))
        logger.info("  %-28s %8.3f ms", cand.label(),
                    trials[-1].median_s * 1e3)

    winner = _pick_winner(trials, default_label)
    try:
        marginal = measure_marginal_rate(
            board_height, board_width, convention, winner.plan,
            gen_limit=gen_limit,
        )
    except Exception as err:  # noqa: BLE001 - the plan is still good
        logger.warning(
            "marginal-rate measurement failed (%s: %s); the plan persists "
            "without a dispatch-gap roofline", type(err).__name__, err,
        )
        marginal = None
    return SearchResult(
        kind="serve",
        context={
            "board": f"{board_height}x{board_width}",
            "convention": convention,
            "gen_limit": gen_limit,
            "counts": [len(c) for c in chunks],
            "device_kind": space.context_for(
                (board_height, board_width), config
            ).device_kind,
            "seed": seed,
            "iters": iters,
        },
        trials=trials,
        default_label=default_label,
        winner=winner.plan,
        marginal=marginal,
    )


@dataclasses.dataclass
class CrossoverResult:
    """One ``gol tune --sparse-crossover`` measurement: the per-host area
    where dense per-generation cost overtakes the sparse engine's."""

    auto_area: int
    dense_points: list  # [(area_cells, s_per_gen), ...]
    sparse_s_per_gen: float
    tile: int

    def to_dict(self) -> dict:
        return {
            "kind": "sparse_crossover",
            "auto_area": self.auto_area,
            "dense_points": [
                [int(a), round(s, 6)] for a, s in self.dense_points
            ],
            "sparse_s_per_gen": round(self.sparse_s_per_gen, 6),
            "tile": self.tile,
        }


def fit_crossover(dense_points, sparse_s_per_gen: float,
                  floor: int = 1 << 16, ceil: int = 1 << 36) -> int:
    """Solve the dense/sparse crossover area from measurements.

    Dense per-generation cost is linear in the canvas area (every cell is
    touched a fixed number of times: BENCH_r14's column grows ~4x per 4x
    area); the sparse engine's cost is flat in the UNIVERSE area (it
    tracks live tiles, which a fixed pattern load pins). Least-squares
    fit ``dense(area) = a * area + b`` through the measured points and
    solve ``dense(area) == sparse`` for area, clamped to the admissible
    band (a machine where dense wins everywhere measured still gets a
    finite threshold instead of infinity)."""
    if len(dense_points) < 2:
        raise ValueError("need >= 2 dense measurements to fit a slope")
    if sparse_s_per_gen <= 0:
        raise ValueError(f"sparse_s_per_gen must be > 0, "
                         f"got {sparse_s_per_gen}")
    xs = np.array([float(a) for a, _ in dense_points])
    ys = np.array([float(s) for _, s in dense_points])
    a, b = np.polyfit(xs, ys, 1)
    # Dense cost must GROW measurably across the probed band (>= 5% of
    # the mean sample over the span): a flat or negative fit — a fast
    # device, probe sizes all under its dispatch floor, or pure noise —
    # measures nothing, and extrapolating it would put the crossover at
    # an arbitrary clamp. Fail loudly instead.
    if a <= 0 or a * (xs.max() - xs.min()) < 0.05 * float(ys.mean()):
        raise ValueError(
            f"dense cost did not grow with area over the probe "
            f"(slope {a:.3e}); measure larger sizes"
        )
    crossover = (sparse_s_per_gen - b) / a
    return int(min(max(crossover, floor), ceil))


def run_sparse_crossover_search(
    tile: int = 256,
    gens: int = 12,
    iters: int = 3,
    quick: bool = False,
) -> CrossoverResult:
    """Measure THIS host's dense/sparse crossover (`--engine auto`'s
    threshold): dense per-generation wall time at a ladder of square
    universes (linear in area) vs the sparse engine on the same
    glider load (flat), fit and solved by ``fit_crossover``.

    The load mirrors BENCH_r14's: a handful of gliders — sparse cost
    pinned to a few tiles regardless of universe size. Dense probes stay
    small (the fit extrapolates the linear cost; probing 2^26 cells to
    learn the slope would burn minutes measuring what 2^22 already
    says). Sparse is measured at the LARGEST probe size: its flatness is
    the model, its value the only free parameter."""
    from gol_tpu import engine
    from gol_tpu.io import rle as rle_codec
    from gol_tpu.sparse.board import SparseBoard
    from gol_tpu.sparse.engine import simulate_sparse

    sides = (1024, 2048) if quick else (1024, 2048, 4096)
    config = GameConfig(gen_limit=gens, check_similarity=False)
    glider = rle_codec.parse("x = 3, y = 3\nbob$2bo$3o!")

    def place_gliders(side: int) -> np.ndarray:
        grid = np.zeros((side, side), np.uint8)
        gh, gw = glider.shape
        # 5 gliders spread across the universe (tile-boundary crossers
        # included), the BENCH_r14 load shape; positions wrap into the
        # in-bounds band so every glider lands whole.
        for k in range(5):
            y = (k * side // 5) % (side - gh)
            x = (k * 2 * side // 7) % (side - gw)
            grid[y:y + gh, x:x + gw] = glider
        return grid

    dense_points = []
    for side in sides:
        grid = place_gliders(side)
        device_grid = engine.put_grid(grid)
        runner = engine.make_runner((side, side), config, None, "auto")
        compiled = engine.compile_runner(runner, device_grid)

        def run_dense():
            _, gen = compiled(device_grid)
            int(gen)  # the completion barrier

        s = trimmed_median(timed_samples(run_dense, warmup=1, iters=iters))
        dense_points.append((side * side, s / gens))
        logger.info("sparse-crossover: dense %dx%d = %.3f ms/gen",
                    side, side, 1000 * s / gens)

    side = sides[-1]
    # Built ONCE outside the timer: from_dense scans the whole canvas —
    # exactly the O(area) work the sparse engine elides — and timing it
    # would inflate sparse_s_per_gen and bias the crossover toward
    # dense. Each timed run simulates a fresh O(live-tiles) deep copy
    # (simulate_sparse mutates the board in place).
    import copy as _copy

    sparse_board = SparseBoard.from_dense(place_gliders(side), tile)

    def run_sparse():
        simulate_sparse(_copy.deepcopy(sparse_board), config)

    s = trimmed_median(timed_samples(run_sparse, warmup=1, iters=iters))
    sparse_s_per_gen = s / gens
    logger.info("sparse-crossover: sparse %dx%d (tile %d) = %.3f ms/gen "
                "(%d live tiles)", side, side, tile,
                1000 * sparse_s_per_gen, sparse_board.live_tiles)
    area = fit_crossover(dense_points, sparse_s_per_gen)
    logger.info("sparse-crossover: dense overtakes sparse at ~%d cells "
                "(%.0f^2)", area, area ** 0.5)
    return CrossoverResult(
        auto_area=area,
        dense_points=dense_points,
        sparse_s_per_gen=sparse_s_per_gen,
        tile=tile,
    )


def render_report(results: list[SearchResult]) -> str:
    """Human-readable tuning report (``gol tune`` prints/writes this)."""
    lines = ["# gol tune report", ""]
    for res in results:
        ctx = ", ".join(f"{k}={v}" for k, v in res.context.items())
        lines.append(f"## {res.kind}: {ctx}")
        lines.append("")
        lines.append("| candidate | median | vs default | gate |")
        lines.append("|---|---|---|---|")
        default_s = res.default_trial.median_s
        for t in sorted(res.trials,
                        key=lambda t: (t.median_s is None, t.median_s)):
            if t.median_s is None:
                lines.append(f"| {t.label} | — | — | {t.gate} |")
                continue
            marks = []
            if t.label == res.winner.label():
                marks.append("**winner**")
            if t.label == res.default_label:
                marks.append("default")
            ratio = default_s / t.median_s
            lines.append(
                f"| {t.label} {' '.join(marks)} | {t.median_s * 1e3:.3f} ms "
                f"| {ratio:.3f}x | {t.gate} |"
            )
        lines.append("")
        lines.append(
            f"winner: `{res.winner.label()}` at {res.speedup:.3f}x the "
            "default ladder"
        )
        lines.append("")
    return "\n".join(lines)
