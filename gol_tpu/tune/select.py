"""Runtime plan selection: the engine and the serve batcher ask here.

The consult contract, pinned by tests/test_tune.py:

- **no plan cached → None/defaults**, and the callers' hard-coded ladders
  run byte-identically to the pre-tune codebase;
- a cached plan is served only when its fingerprint matches *exactly*
  (schema, jax version, shape, convention, family, mesh, device kind —
  ``plans.fingerprint``), and is validity-checked again at the consumer
  (``engine._apply_plan``, ``space.valid_serve_plan``) so a hand-edited or
  stale-but-addressable entry degrades loudly instead of crashing a server.

Plans load once per process (the store caches its file read, and the engine
runner factories are lru-cached anyway); a tuner writing plans while a
server runs takes effect on the server's next restart — or after
``reset()``, which drops the cached store (tests, and `gol serve`'s warmup
path after an in-process tune).
"""

from __future__ import annotations

import logging

from gol_tpu.tune import plans, space
from gol_tpu.tune.space import DEFAULT_SERVE_PLAN, EnginePlan, ServePlan

logger = logging.getLogger(__name__)

_STORE: plans.PlanStore | None = None


def _store() -> plans.PlanStore:
    global _STORE
    if _STORE is None:
        _STORE = plans.PlanStore()
    return _STORE


def reset() -> None:
    """Drop the cached store so the next consult re-reads the cache file."""
    global _STORE
    _STORE = None


def engine_fingerprint(shape, config, mesh=None, packed_state=False) -> str:
    """The cache key of a solo-engine run context — shared by the consult
    below and the writers (`gol tune`, tools/tune_smoke.py), so a written
    plan is addressable by construction."""
    ctx = space.context_for(shape, config, mesh, packed_state)
    return plans.fingerprint(
        "engine", ctx.height, ctx.width, ctx.convention, ctx.family,
        ctx.mesh_shape, ctx.device_kind,
    )


def engine_plan(shape, config, mesh=None, packed_state=False) -> EnginePlan | None:
    """The measured plan for this exact run context, or None (= built-in
    ladder). Called by ``engine._build_runner`` on the auto-selected lanes."""
    ctx = space.context_for(shape, config, mesh, packed_state)
    store = _store()
    fp = engine_fingerprint(shape, config, mesh, packed_state)
    entry = store.get(fp)
    if entry is None:
        entry = store.get_default("engine")
    if not entry:
        return None
    try:
        plan = EnginePlan.from_dict(entry)
    except (TypeError, ValueError) as err:
        logger.warning("unusable engine plan for %s (%s: %s); using the "
                       "built-in ladder", fp, type(err).__name__, err)
        return None
    if plan == EnginePlan():
        return None
    logger.info("tuned engine plan %s for %dx%d/%s/%s", plan.label(),
                ctx.height, ctx.width, ctx.convention, ctx.family)
    return plan


def serve_fingerprint() -> str:
    """Serve plans cover the whole bucket space, so the grid/convention/
    family fields are wildcarded — the geometry depends on the device and
    versions, not on any one request shape."""
    return plans.fingerprint("serve", 0, 0, "any", "any", (1, 1),
                             plans.device_kind())


def serve_plan(max_batch: int = 64) -> ServePlan:
    """The batcher's geometry plan; always returns something valid (the
    built-in quantum-32 / full-ladder plan when nothing measured exists)."""
    store = _store()
    entry = store.get(serve_fingerprint())
    if entry is None:
        entry = store.get_default("serve")
    if not entry:
        return DEFAULT_SERVE_PLAN
    try:
        plan = ServePlan.from_dict(entry)
    except (TypeError, ValueError, KeyError) as err:
        logger.warning("unusable serve plan (%s: %s); using the built-in "
                       "bucket geometry", type(err).__name__, err)
        return DEFAULT_SERVE_PLAN
    if not space.valid_serve_plan(plan, max_batch):
        logger.warning(
            "serve plan %s violates the bucket invariants (quantum %% 32, "
            "ladder 1..%d ascending); using the built-in geometry",
            plan.label(), max_batch,
        )
        return DEFAULT_SERVE_PLAN
    if plan != DEFAULT_SERVE_PLAN:
        logger.info("tuned serve plan %s", plan.label())
    return plan


def marginal_rates() -> dict[str, float]:
    """The tuned serve plan's recorded marginal kernel rates: sanitized
    bucket label (``obs.registry.metric_label`` spelling) -> cell-updates/s.
    The serving dispatch-gap monitor (obs/sampler.py) divides achieved
    bucket rates by these to export the live BENCH_r08 gap ratio. Empty
    when nothing measured exists — the monitor then reports rates only,
    the usual absent-cache degradation."""
    entry = _store().get(serve_fingerprint())
    if not entry:
        return {}
    recorded = entry.get("marginal")
    if not isinstance(recorded, dict):
        return {}
    out = {}
    for label, rate in recorded.items():
        try:
            rate = float(rate)
        except (TypeError, ValueError):
            continue
        if rate > 0:
            out[str(label)] = rate
    return out


def sparse_fingerprint() -> str:
    """The sparse-engine crossover covers the whole universe space on one
    device — grid/convention/family wildcarded like the serve geometry."""
    return plans.fingerprint("sparse", 0, 0, "any", "any", (1, 1),
                             plans.device_kind())


# The admissible crossover band: below 2^16 cells even a lone glider's
# dense canvas is trivial; above 2^36 the dense lane is ruled out by the
# cells guard long before the threshold matters. A cached value outside
# the band is a corrupt/hand-edited entry and degrades loudly.
SPARSE_AREA_FLOOR = 1 << 16
SPARSE_AREA_CEIL = 1 << 36


def sparse_auto_area(default: int) -> int:
    """The measured dense/sparse crossover area for `--engine auto`
    (``gol run --pattern``): the plan-cached value this host measured
    (``gol tune --sparse-crossover``), else the bundled default, else
    ``default`` (the engine's shipped constant). Invalid entries are
    rejected loudly — a corrupt cache must not flip giant universes onto
    the dense lane."""
    entry = _store().get(sparse_fingerprint())
    if entry is None:
        entry = _store().get_default("sparse")
    if not entry:
        return default
    try:
        area = int(entry["auto_area"])
        if not SPARSE_AREA_FLOOR <= area <= SPARSE_AREA_CEIL:
            raise ValueError(f"auto_area {area} outside "
                             f"[{SPARSE_AREA_FLOOR}, {SPARSE_AREA_CEIL}]")
    except (KeyError, TypeError, ValueError) as err:
        logger.warning("unusable sparse crossover plan (%s: %s); using the "
                       "built-in threshold", type(err).__name__, err)
        return default
    if area != default:
        logger.info("tuned sparse auto threshold: %d cells", area)
    return area


def macro_fingerprint() -> str:
    """The macro-engine crossover is one number per host, like the sparse
    one — grid/convention/family wildcarded."""
    return plans.fingerprint("macro", 0, 0, "any", "any", (1, 1),
                             plans.device_kind())


# The admissible sparse/macro crossover band: below 2^6 generations the
# tree build alone dwarfs any per-generation loop; above 2^40 the macro
# lane would effectively never engage, which defeats recording a plan at
# all. Outside the band = corrupt/hand-edited entry, degrade loudly.
MACRO_GENS_FLOOR = 1 << 6
MACRO_GENS_CEIL = 1 << 40


def macro_auto_gens(default: int) -> int:
    """The measured sparse/macro generation-count crossover for
    ``--engine auto``: the plan-cached value this host measured, else the
    bundled default, else ``default`` (the macro engine's shipped
    constant). Invalid entries are rejected loudly — a corrupt cache must
    not route shallow runs onto the tree engine."""
    entry = _store().get(macro_fingerprint())
    if entry is None:
        entry = _store().get_default("macro")
    if not entry:
        return default
    try:
        gens = int(entry["auto_gens"])
        if not MACRO_GENS_FLOOR <= gens <= MACRO_GENS_CEIL:
            raise ValueError(f"auto_gens {gens} outside "
                             f"[{MACRO_GENS_FLOOR}, {MACRO_GENS_CEIL}]")
    except (KeyError, TypeError, ValueError) as err:
        logger.warning("unusable macro crossover plan (%s: %s); using the "
                       "built-in threshold", type(err).__name__, err)
        return default
    if gens != default:
        logger.info("tuned macro auto threshold: %d generations", gens)
    return gens


def warm_entries() -> list[dict]:
    """Shapes recorded by the offline tuner for server warmup: each entry is
    ``{"height", "width", "convention", ...}`` — `gol serve --warm-plans`
    pre-compiles their bucket programs at boot so the first request of each
    tuned shape pays dispatch, not compile."""
    entry = _store().get(serve_fingerprint())
    if not entry:
        return []
    warm = entry.get("warm")
    if not isinstance(warm, list):
        return []
    return [w for w in warm if isinstance(w, dict)
            and {"height", "width"} <= set(w)]
