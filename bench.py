"""Benchmark harness: cell-updates/sec/chip on the flagship engine.

Reproduces the reference's measurement contract — the generation-loop
``Execution time`` the six programs self-report (src/game.c:199-203,
src/game_mpi_collective.c:367-370, src/game_cuda.cu:279,295) — as the
BASELINE.md primary metric: cell-updates/sec/chip at GEN_LIMIT=1000.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline is value / 1e11, the BASELINE.md per-chip target. Human-readable
detail goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np


def _honor_platform_env() -> None:
    """Re-apply JAX_PLATFORMS if a site hook consumed it (shared helper).

    Imported from the dependency-free platform_env module, NOT via
    gol_tpu.cli — pulling cli here would load every jax-importing module
    before the re-application, the ordering hazard the helper exists to
    prevent."""
    from gol_tpu.platform_env import configure_cli_logging, honor_platform_env

    honor_platform_env()
    # Kernel-demotion warnings and IO-retry notices must reach stderr here
    # exactly as in the CLI — stdout stays reserved for the one JSON line.
    configure_cli_logging()

TARGET_CELL_UPDATES_PER_SEC_PER_CHIP = 1e11  # BASELINE.md north star


def _env_stamp(mesh: str | None = None) -> dict:
    """Environment stamp for every emitted JSON artifact: jax version,
    device kind, mesh shape — the same fields the tuner's plan fingerprints
    bake in (gol_tpu/tune/plans.py), so a bench number can always be matched
    to the software/hardware context that produced it."""
    import jax

    return {
        "jax": jax.__version__,
        "device_kind": jax.devices()[0].device_kind,
        "mesh": mesh or "1x1",
    }


def resolve_kernel_name(requested: str | None, size: int, mesh) -> str:
    if requested:
        return requested
    from gol_tpu.ops import resolve_kernel
    from gol_tpu.parallel.mesh import topology_for

    topo = topology_for(mesh)
    local_h, local_w = size // topo.shape[0], size // topo.shape[1]
    return resolve_kernel("auto", local_h, local_w, topo).name


def _bench_halo(args) -> int:
    """p50 latency of one two-phase ppermute halo exchange on the mesh."""
    import statistics

    import jax
    import jax.numpy as jnp
    import numpy as np

    from gol_tpu.parallel import halo
    from gol_tpu.parallel.mesh import (
        MESH_TOPOLOGY_AXES,
        grid_sharding,
        make_mesh,
        shard_map,
        topology_for,
    )

    if args.mesh:
        r, c = (int(x) for x in args.mesh.split("x"))
        mesh = make_mesh(r, c)
    else:
        mesh = make_mesh()
    topo = topology_for(mesh)
    if not topo.distributed:
        print("bench --halo needs a >1-device mesh "
              "(try XLA_FLAGS=--xla_force_host_platform_device_count=8)", file=sys.stderr)
        return 1

    rng = np.random.default_rng(0)
    grid = rng.integers(0, 2, size=(args.size, args.size), dtype=np.uint8)
    device_grid = jax.device_put(grid, grid_sharding(mesh))

    def consume_edges(ext):
        # Consume ONLY the exchanged boundary (plus a psum of four scalars):
        # a full-grid reduction would dwarf the ppermute phases being
        # measured. Shared by the byte and deep-packed measurements so both
        # consume identical work and stay comparable.
        edge = (
            jnp.sum(ext[0].astype(jnp.int32))
            + jnp.sum(ext[-1].astype(jnp.int32))
            + jnp.sum(ext[:, 0].astype(jnp.int32))
            + jnp.sum(ext[:, -1].astype(jnp.int32))
        )
        return jax.lax.psum(edge, topo.axes)

    def body(x):
        return consume_edges(halo.exchange(x, topo))

    @jax.jit
    def exchange_once(g):
        return shard_map(
            body,
            mesh=mesh,
            in_specs=jax.sharding.PartitionSpec(*MESH_TOPOLOGY_AXES),
            out_specs=jax.sharding.PartitionSpec(),
        )(g)

    def timed_p50(fn, arg):
        fn(arg).block_until_ready()
        samples = []
        for _ in range(max(args.repeats * 10, 30)):
            t0 = time.perf_counter()
            int(fn(arg))
            samples.append((time.perf_counter() - t0) * 1e6)
        return statistics.median(samples), len(samples)

    p50, n = timed_p50(exchange_once, device_grid)

    # The flagship's actual halo: the deep (TEMPORAL_GENS-row) packed-word
    # exchange, one per TEMPORAL_GENS generations. Word state is 32x smaller,
    # the ghost zone TEMPORAL_GENS x taller; per-generation cost is p50/T.
    from gol_tpu.ops import packed_math, stencil_packed as sp

    local_h = args.size // topo.shape[0]
    local_w = args.size // topo.shape[1]
    deep_p50 = None
    # Same eligibility the engine uses to route shards onto the deep-halo
    # temporal pass — measuring it for shapes the flagship would route to
    # the per-generation path would be a number for a path never taken.
    if sp.supports_multi(local_h, local_w, topo):
        spec = jax.sharding.PartitionSpec(*MESH_TOPOLOGY_AXES)
        words = jax.jit(
            shard_map(packed_math.encode, mesh=mesh,
                          in_specs=spec, out_specs=spec)
        )(device_grid)

        def deep_body(w):
            return consume_edges(sp.exchange_packed_deep(w, topo))

        @jax.jit
        def deep_once(w):
            return shard_map(deep_body, mesh=mesh,
                                 in_specs=spec,
                                 out_specs=jax.sharding.PartitionSpec())(w)

        deep_p50, _ = timed_p50(deep_once, words)
        deep_msg = (f"; deep packed exchange {deep_p50:.1f} us per "
                    f"{sp.TEMPORAL_GENS} generations")
    else:
        deep_msg = " (shard shape not deep-halo eligible; byte exchange only)"

    print(f"halo p50 over {n} runs on {mesh.shape}{deep_msg}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "halo_exchange_p50_latency",
                "value": p50,
                "unit": "us",
                # No published halo baseline exists (BASELINE.md): null, not a
                # fake ratio.
                "vs_baseline": None,
                "deep_packed_exchange_p50_us": deep_p50,
                "deep_exchange_feeds_generations": sp.TEMPORAL_GENS,
                "env": _env_stamp(f"{topo.shape[0]}x{topo.shape[1]}"),
            }
        )
    )
    return 0


def _bench_batch(args) -> int:
    """Boards/sec through the serve batcher at B in {1, 8, 64} (--suite batch).

    The serving question: how much does stacking independent boards into one
    compiled program buy over dispatching them one at a time? 64 random 256^2
    boards run through gol_tpu/serve/batcher.run_batch — the exact path
    ``gol batch`` and the server dispatch — as 64/B dispatches of B boards.
    The headline value is the B=64 rate; vs_baseline is its speedup over the
    B=1 sequential rate (same kernel, same boards, batch-size scaling only —
    the amortized-dispatch win, not a kernel change).

    The suite's workload is deliberately serving-shaped: SHORT requests
    (gen_limit 4 unless --gen-limit is passed). Per-generation compute is
    identical per board at any batch size — batching amortizes the
    per-dispatch fixed cost (host staging, transfer, program dispatch), so
    the win concentrates where requests are dispatch-dominated, exactly the
    many-small-users regime the serve/ subsystem targets; at GEN_LIMIT=1000
    a 256^2 job is compute-bound and the ratio approaches 1 (measurable by
    passing --gen-limit 1000). The JSON records the gen_limit measured.
    """
    import jax

    from gol_tpu.serve import batcher
    from gol_tpu.serve.jobs import new_job

    if args.gen_limit is None:
        args.gen_limit = 4
    size = 256
    nboards = 64
    batch_sizes = (1, 8, 64)
    rng = np.random.default_rng(42)
    boards = [
        rng.integers(0, 2, size=(size, size), dtype=np.uint8)
        for _ in range(nboards)
    ]
    jobs = [
        new_job(size, size, b, gen_limit=args.gen_limit) for b in boards
    ]
    key = batcher.bucket_for(jobs[0])
    print(
        f"bench batch: {nboards} boards of {size}x{size}, "
        f"gen_limit={args.gen_limit}, bucket={key.label()}, "
        f"platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )

    rates = {}
    occupancy = {}
    for b in batch_sizes:
        # Warm: compile this batch shape outside the timer (the server pays
        # it once per bucket, on the first dispatch).
        batcher.run_batch(key, jobs[:b])
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            for i in range(0, nboards, b):
                chunk = jobs[i : i + b]
                results = batcher.run_batch(key, chunk)
                assert len(results) == len(chunk)
            best = min(best, time.perf_counter() - t0)
        rates[b] = nboards / best
        occupancy[b] = b / batcher.pad_batch(b)
        print(
            f"  B={b:3d}: {best * 1000:8.1f} ms for {nboards} boards "
            f"-> {rates[b]:8.1f} boards/s",
            file=sys.stderr,
        )

    headline = rates[batch_sizes[-1]]
    sequential = rates[1]
    print(
        json.dumps(
            {
                "metric": "batch_boards_per_sec",
                "value": headline,
                "unit": "boards/s",
                # Baseline here is the B=1 sequential rate of the same
                # batcher: the amortization factor the subsystem exists for.
                "vs_baseline": headline / sequential,
                "detail": {f"b{b}": rates[b] for b in batch_sizes},
                "occupancy": occupancy,
                "grid": f"{size}x{size}",
                "boards": nboards,
                "gen_limit": args.gen_limit,
                "bucket": key.label(),
                "env": _env_stamp(),
            }
        )
    )
    return 0


def _bench_compare(args) -> int:
    """Kernel-only throughput table: every single-chip evolve path.

    Quantifies the cost gap between the compiled Pallas band kernels, the
    distributed-style kernel (ghost operands, local wrap — the per-chip proxy
    for pod throughput), and the jnp fallbacks, at a fixed generation count
    with no termination machinery (the reference's pure-evolve cost,
    src/game_cuda.cu:234-236).
    """
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import packed_math, stencil_lax
    from gol_tpu.ops import stencil_packed as sp
    from gol_tpu.ops import stencil_pallas as spl
    from gol_tpu.parallel.mesh import SINGLE_DEVICE

    size = args.size
    # Marginal measurement: the tunnel adds ~80ms fixed dispatch per compiled
    # call, so each path is timed at g1 and 3*g1 generations and the rate is
    # taken from the difference.
    g1 = min(args.gen_limit, 500)
    g2 = 3 * g1
    rng = np.random.default_rng(42)
    grid = rng.integers(0, 2, size=(size, size), dtype=np.uint8)
    on_tpu = jax.default_backend() == "tpu"

    def loop(step, gens):
        def run(state):
            final = jax.lax.fori_loop(0, gens, lambda i, s: step(s), state)
            # Return a scalar tied to the final state: over the axon tunnel,
            # block_until_ready on a device array can return before the work
            # completes — fetching a scalar is the reliable sync.
            return final[0, 0]

        return jax.jit(run)

    # Each entry: (step_fn, state_kind, generations per call).
    paths = {
        "packed-jnp": (packed_math.evolve_torus_words, "words", 1),
        "packed-dist-kernel": (
            lambda w: sp._distributed_step(w, SINGLE_DEVICE)[0],
            "words",
            1,
        ),
        "lax": (stencil_lax.evolve_torus, "grid", 1),
    }
    if on_tpu:
        paths["packed-pallas"] = (lambda w: sp._step(w)[0], "words", 1)
        paths["pallas-byte"] = (lambda g: spl._step(g)[0], "grid", 1)
        if sp.supports_multi(size, size, SINGLE_DEVICE):
            # The flagship: TEMPORAL_GENS generations per VMEM pass.
            paths[f"packed-temporal-T{sp.TEMPORAL_GENS}"] = (
                lambda w: sp._step_t(w)[0],
                "words",
                sp.TEMPORAL_GENS,
            )
            # What a pod shard actually runs: deep-halo assembly (local
            # wrap standing in for ppermute'd neighbors) + the sequential
            # banded temporal pass — the honest per-chip proxy for flagship
            # mesh throughput. (An overlapped interior/frontier split was
            # measured here in r3 and retired: see _distributed_step_multi.)
            # SINGLE_DEVICE has cols == 1, so this lane measures the
            # rows-only kernel — the R x 1 recommended pod layout.
            paths["packed-dist-temporal"] = (
                lambda w: sp._distributed_step_multi(w, SINGLE_DEVICE)[0],
                "words",
                sp.TEMPORAL_GENS,
            )
            # The 2D-mesh form (ghost-column plane engaged): a cols > 1
            # topology with local wraps — what an R x C pod chip runs.
            from gol_tpu.parallel.mesh import PROXY_2D

            paths["packed-dist-temporal-2d"] = (
                lambda w: sp._distributed_step_multi(w, PROXY_2D)[0],
                "words",
                sp.TEMPORAL_GENS,
            )

    device_grid = jnp.asarray(grid)
    device_words = jax.jit(sp.encode)(device_grid)
    device_words.block_until_ready()

    results = {}
    for name, (step, rep, gens_per_call) in sorted(paths.items()):
        state0 = device_words if rep == "words" else device_grid
        best = {}
        for gens in (g1, g2):
            run = loop(step, max(1, gens // gens_per_call))
            int(run(state0))  # compile + warm
            best[gens] = float("inf")
            for _ in range(args.repeats):
                t0 = time.perf_counter()
                int(run(state0))
                best[gens] = min(best[gens], time.perf_counter() - t0)
        marginal_s = max(best[g2] - best[g1], 1e-9) / (g2 - g1)
        rate = size * size / marginal_s
        results[name] = rate
        print(
            f"  {name:20s} {marginal_s * 1e3:8.3f} ms/gen  {rate:.3e} cells/s",
            file=sys.stderr,
        )

    temporal = [v for k, v in results.items() if k.startswith("packed-temporal")]
    fast = (
        (temporal[0] if temporal else None)
        or results.get("packed-pallas")
        or results["packed-dist-kernel"]
    )
    speedup = fast / results["packed-jnp"]
    print(
        json.dumps(
            {
                "metric": "packed_pallas_vs_jnp_speedup",
                "value": speedup,
                "unit": "x",
                "vs_baseline": None,
                "detail": {k: v for k, v in sorted(results.items())},
                "size": size,
                "generations": [g1, g2],
                "env": _env_stamp(),
            }
        )
    )
    return 0


def _bench_tune(args) -> int:
    """Tuned-vs-default through the autotuner (--suite tune).

    Runs the gol_tpu/tune search on two engine shapes plus the serve-bucket
    geometry, each candidate byte-gated against the default engine (itself
    oracle-checked where affordable), and records the full per-candidate
    series in BENCH_r06.json. The winner is the measured argmin over a
    candidate set that CONTAINS the default ladder, so tuned >= default on
    every shape by construction; the headline value is the best
    tuned-over-default speedup, and ``strictly_faster`` says whether any
    shape's winner beat the ladder outright (a >2% win — inside that the
    search keeps the default).
    """
    import jax

    from gol_tpu.config import GameConfig
    from gol_tpu.tune import measure

    gen_limit = args.gen_limit if args.gen_limit is not None else 64
    shapes = ((256, 256), (512, 512))
    records = []
    print(
        f"bench tune: shapes {['x'.join(map(str, s)) for s in shapes]} + "
        f"serve geometry, gen_limit={gen_limit}, iters={args.repeats}, "
        f"platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )
    detail = {}
    for height, width in shapes:
        print(f"  engine search {height}x{width}/c", file=sys.stderr)
        result = measure.run_engine_search(
            height, width, GameConfig(gen_limit=gen_limit),
            iters=args.repeats,
        )
        records.append(result.to_dict())
        detail[f"engine:{height}x{width}"] = round(result.speedup, 4)
        print(
            f"  -> winner {result.winner.label()} at {result.speedup:.3f}x "
            f"default ({result.default_label})",
            file=sys.stderr,
        )
    print("  serve geometry search (48x48 boards)", file=sys.stderr)
    serve_result = measure.run_serve_search(
        48, 48, gen_limit=min(gen_limit, 8), iters=args.repeats,
    )
    records.append(serve_result.to_dict())
    detail["serve:48x48"] = round(serve_result.speedup, 4)
    print(
        f"  -> winner {serve_result.winner.label()} at "
        f"{serve_result.speedup:.3f}x default",
        file=sys.stderr,
    )

    speedups = [r["tuned_vs_default"] for r in records]
    gates_ok = all(r["gates_all_ok"] for r in records)
    payload = {
        "metric": "tuned_vs_default_speedup",
        "value": max(speedups),
        "unit": "x",
        # No external baseline: the default ladder IS the denominator.
        "vs_baseline": None,
        "detail": detail,
        "tuned_ge_default_everywhere": all(s >= 1.0 for s in speedups),
        "strictly_faster_somewhere": any(s > 1.0 for s in speedups),
        "all_candidates_passed_gate": gates_ok,
        "gen_limit": gen_limit,
        "env": _env_stamp(),
        "searches": records,
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r06.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    # The stdout contract: ONE JSON line (without the bulky per-candidate
    # series, which lives in the artifact).
    print(json.dumps({k: v for k, v in payload.items() if k != "searches"}))
    return 0 if gates_ok else 1


def _bench_pipeline(args) -> int:
    """Async-pipeline evidence suite (--suite pipeline) -> BENCH_r07.json.

    Two measurements, mirroring the two halves of gol_tpu/pipeline:

    1. **Checkpointed-run wall-clock, sync vs async writer** at
       ``--checkpoint-every 8`` on 2048^2 and 4096^2 (the byte lane's lax
       kernel — the shape-universal fallback, whose per-segment compute is
       big enough that there is something to hide I/O behind; the packed
       kernels finish 8 generations faster than one payload write, so for
       them a checkpoint boundary is irreducibly I/O-bound on CPU). Both
       modes run the identical engine/codec path (the CLI's text-grid
       checkpoint codec); the async run reports how many write-seconds were
       hidden under compute and how often the pipeline stalled. On a CPU
       backend the "device" compute competes with the writer thread for
       cores/bandwidth, so these ratios are the conservative floor — on an
       accelerator the host is idle during device compute.

    2. **Serve boards/sec at pipeline depth 1 vs 2** on a multi-bucket load
       (64 boards across an exact-fit 256^2 packed bucket and a masked
       250^2 bucket, serving-shaped short requests) through the real
       scheduler + journal: depth 2 overlaps host staging (np.packbits,
       operand build) and journaling (fsync per terminal record) with
       device compute.
    """
    import shutil
    import tempfile

    import jax

    from gol_tpu import engine
    from gol_tpu.config import GameConfig
    from gol_tpu.io import text_grid
    from gol_tpu.obs import registry as obs_registry
    from gol_tpu.pipeline.writer import AsyncCheckpointWriter
    from gol_tpu.resilience.checkpoint import CheckpointManager, PayloadCodec
    from gol_tpu.serve import batcher
    from gol_tpu.serve.jobs import DONE, JobJournal, new_job
    from gol_tpu.serve.scheduler import Scheduler

    repeats = args.repeats
    every = 8
    gen_limit = args.gen_limit if args.gen_limit is not None else 32
    shapes = (2048, 4096)
    kernel = "lax"
    workroot = tempfile.mkdtemp(prefix="gol-bench-pipeline-")
    print(
        f"bench pipeline: checkpoint-every {every}, gen_limit {gen_limit}, "
        f"shapes {list(shapes)}, kernel {kernel}, repeats {repeats}, "
        f"platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )

    def codec(n):
        return PayloadCodec(
            format="text-grid", suffix=".out",
            write=lambda p, s: text_grid.write_grid(
                p, np.asarray(s, dtype=np.uint8)),
            read=lambda p: text_grid.read_grid(p, n, n),
        )

    def ckpt_run(n, state0, async_writer):
        ck = tempfile.mkdtemp(dir=workroot)
        mgr = CheckpointManager(ck, height=n, width=n, codec=codec(n), keep=2)
        config = GameConfig(gen_limit=gen_limit)
        writer = AsyncCheckpointWriter(mgr) if async_writer else None
        t0 = time.perf_counter()
        try:
            for gens, final, stopped in engine.simulate_segments(
                state0, config, None, kernel, every
            ):
                if not stopped:
                    _, counter = engine.resume_scalars(config, gens)
                    (writer.save if writer else mgr.save)(final, gens, counter)
            if writer:
                writer.drain()
        finally:
            if writer:
                writer.close()
        elapsed = time.perf_counter() - t0
        shutil.rmtree(ck, ignore_errors=True)
        return elapsed

    checkpoint_detail = {}
    for n in shapes:
        rng = np.random.default_rng(42)
        # HOST array on purpose: the segment runners donate their state
        # operand on TPU/GPU, so a shared device array would be consumed by
        # the first run's first segment. simulate_segments re-stages a host
        # grid per run (put_grid), keeping every run's operand fresh.
        state0 = rng.integers(0, 2, size=(n, n), dtype=np.uint8)
        ckpt_run(n, state0, False)  # compile + page-cache warm
        obs_registry.reset_default()
        sync_s = min(ckpt_run(n, state0, False) for _ in range(repeats))
        obs_registry.reset_default()
        async_s = min(ckpt_run(n, state0, True) for _ in range(repeats))
        reg = obs_registry.default()
        entry = {
            "sync_seconds": round(sync_s, 4),
            "async_seconds": round(async_s, 4),
            "async_over_sync": round(sync_s / async_s, 4),
            # Accumulated over the measured repeats (registry reset before
            # the async series), so divide by `repeats` for a per-run view.
            "write_seconds_hidden_total": round(
                reg.counter("checkpoint_write_hidden_seconds"), 4),
            "pipeline_stalls_total": reg.counter("pipeline_stalls_total"),
        }
        checkpoint_detail[f"{n}x{n}"] = entry
        print(
            f"  ckpt {n}x{n}: sync {sync_s * 1e3:8.1f} ms  async "
            f"{async_s * 1e3:8.1f} ms  -> {entry['async_over_sync']:.2f}x "
            f"({entry['write_seconds_hidden_total'] * 1e3 / repeats:.0f} ms "
            f"of write hidden per run)",
            file=sys.stderr,
        )

    # -- serve: pipeline depth 1 vs 2 on a multi-bucket load ----------------
    nboards = 64
    serve_gen_limit = 16
    max_batch = 8

    def make_jobs():
        jobs = []
        for i in range(nboards):
            side = 256 if i % 2 == 0 else 250  # packed + masked buckets
            jobs.append(new_job(
                side, side, text_grid.generate(side, side, seed=3000 + i),
                gen_limit=serve_gen_limit,
            ))
        return jobs

    def serve_run(depth):
        tmp = tempfile.mkdtemp(dir=workroot)
        journal = JobJournal(os.path.join(tmp, "journal"))
        sched = Scheduler(journal=journal, flush_age=0.001,
                          max_batch=max_batch, pipeline_depth=depth,
                          max_queue_depth=4096)
        jobs = make_jobs()
        for job in jobs:
            sched.submit(job)
        sched.start()
        t0 = time.perf_counter()
        ok = sched.drain(timeout=600)
        elapsed = time.perf_counter() - t0
        sched.stop(drain=False)
        journal.close()
        if not ok or any(j.state != DONE for j in jobs):
            raise RuntimeError("serve lane failed to drain every job DONE")
        shutil.rmtree(tmp, ignore_errors=True)
        return nboards / elapsed

    for side in (256, 250):  # compile both buckets' programs off the clock
        j = new_job(side, side, text_grid.generate(side, side, seed=1),
                    gen_limit=serve_gen_limit)
        batcher.run_batch(batcher.bucket_for(j), [j] * max_batch)
    serve_run(1)
    serve_run(2)  # warm every partial-flush rung both paths hit
    depth1 = max(serve_run(1) for _ in range(repeats))
    depth2 = max(serve_run(2) for _ in range(repeats))
    serve_detail = {
        "boards": nboards,
        "gen_limit": serve_gen_limit,
        "max_batch": max_batch,
        "buckets": ["256x256/packed", "256x256/masked(250x250)"],
        "depth1_boards_per_sec": round(depth1, 2),
        "depth2_boards_per_sec": round(depth2, 2),
        "depth2_over_depth1": round(depth2 / depth1, 4),
    }
    print(
        f"  serve: depth1 {depth1:7.1f} boards/s  depth2 {depth2:7.1f} "
        f"boards/s  -> {depth2 / depth1:.2f}x",
        file=sys.stderr,
    )
    shutil.rmtree(workroot, ignore_errors=True)

    speedups = [e["async_over_sync"] for e in checkpoint_detail.values()]
    payload = {
        "metric": "pipeline_overlap_speedup",
        "value": max(max(speedups), serve_detail["depth2_over_depth1"]),
        "unit": "x",
        # No external baseline: the synchronous path IS the denominator.
        "vs_baseline": None,
        "checkpoint": {
            "checkpoint_every": every,
            "gen_limit": gen_limit,
            "kernel": kernel,
            "shapes": checkpoint_detail,
            "async_beats_sync_everywhere": all(s > 1.0 for s in speedups),
        },
        "serve": serve_detail,
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r07.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    ok = (
        all(s > 1.0 for s in speedups)
        and serve_detail["depth2_over_depth1"] >= 1.15
    )
    return 0 if ok else 1


def _bench_megabatch(args) -> int:
    """Resident mega-batch evidence suite (--suite megabatch) -> BENCH_r08.

    The dispatch-gap question: the compiled batch programs sustain some
    marginal kernel rate; how close does END-TO-END serving get? Three
    measurements on the BENCH_r07 serve load (64 boards across an exact-fit
    256^2 packed bucket and a masked 250^2 bucket, short serving-shaped
    requests through the real scheduler + journal):

    1. **Marginal kernel rate** per bucket: the batch program timed at G and
       3G generations, rate from the difference — compute with zero
       host/dispatch cost, the roofline of any serving lane. Also measured
       at the load-matched batched temporal depth (the deep-halo axis
       `gol tune --serve-board` now searches) — the faster is the roofline.
    2. **End-to-end serve rate** at pipeline depth 1 (the classic worker,
       the PR-5 baseline), depth 2 and 4 (pipelined, resident off), and the
       resident ring (on, ring 4) at pipeline depth 2x ring, at temporal
       depth 1 and the load-matched tuned depth.
    3. The **dispatch-gap ratio** end_to_end/marginal for every lane,
       recorded explicitly: 1.0 means the host tax is gone.

    rc 0 iff the best resident lane clears 1.5x the depth-1 rate and
    every job of every run lands DONE.
    """
    import shutil
    import tempfile

    import jax

    from gol_tpu import engine
    from gol_tpu.config import GameConfig
    from gol_tpu.io import text_grid
    from gol_tpu.serve import batcher
    from gol_tpu.serve.jobs import DONE, JobJournal, new_job
    from gol_tpu.serve.scheduler import Scheduler
    from gol_tpu.tune.space import ServePlan

    repeats = args.repeats
    nboards = 64
    # Serving-shaped short requests by default (the --suite batch
    # convention): the dispatch gap is a fixed per-batch cost, so it
    # concentrates exactly where requests are short; --gen-limit measures
    # any other point (at 1000 the load is compute-bound and every lane
    # converges on the marginal rate).
    gen_limit = args.gen_limit if args.gen_limit is not None else 4
    max_batch = 8
    ring = 4
    sides = (256, 250)  # exact-fit packed bucket + masked bucket
    workroot = tempfile.mkdtemp(prefix="gol-bench-megabatch-")
    print(
        f"bench megabatch: {nboards} boards, buckets {list(sides)}, "
        f"gen_limit {gen_limit}, max_batch {max_batch}, ring {ring}, "
        f"repeats {repeats}, platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )

    boards = {
        side: [text_grid.generate(side, side, seed=3000 + side + i)
               for i in range(nboards // 2)]
        for side in sides
    }
    # Total logical work of the load, assuming gen_limit exits (random soups
    # at these sizes never exit early inside 16 generations): the numerator
    # of every cell-updates/s figure below, identical across lanes.
    total_cells = sum(side * side * len(bs) for side, bs in boards.items())
    total_work = total_cells * gen_limit

    # -- 1. marginal kernel rate per bucket ---------------------------------
    def marginal_rate(side, temporal_depth):
        """Cell-updates/s of the bucket's compiled batch program, dispatch
        excluded: timed at G and 3G generation limits, rate from the diff."""
        chunk = boards[side][:max_batch]
        g1, g2 = gen_limit, 3 * gen_limit

        def staged_for(g):
            return engine.stage_batch(
                chunk, GameConfig(gen_limit=g),
                padded_shape=(batcher.pad_dim(side), batcher.pad_dim(side)),
                pad_batch_to=max_batch, temporal_depth=temporal_depth,
            )

        times = {}
        for g in (g1, g2):
            engine.complete_batch(engine.dispatch_batch(staged_for(g)))  # warm
            best = float("inf")
            for _ in range(repeats):
                # Dispatch from a fresh host staging each run (the program
                # donates its operand). The host->device transfer sits
                # inside the timed window, but it is identical at g1 and
                # g2, so the G/3G difference subtracts it out of the
                # marginal rate along with every other fixed cost.
                s = staged_for(g)
                t0 = time.perf_counter()
                engine.complete_batch(engine.dispatch_batch(s))
                best = min(best, time.perf_counter() - t0)
            times[g] = best
        per_gen = max(times[g2] - times[g1], 1e-9) / (g2 - g1)
        return side * side * max_batch / per_gen

    # The tuned batched temporal depth for this load: matching the request
    # length wastes no sub-steps (a T > gen_limit ring runs T masked
    # sub-generations per while iteration of jobs that only need
    # gen_limit). This is the axis `gol tune --serve-board` searches.
    tuned_T = min(gen_limit, 4)
    marginal = {}
    for side in sides:
        for depth in sorted({1, tuned_T}):
            rate = marginal_rate(side, depth)
            marginal[f"{side}xT{depth}"] = rate
            print(f"  marginal {side}^2 T{depth}: {rate:.3e} cells/s",
                  file=sys.stderr)
    # The roofline of the whole load: every batch at its bucket's best
    # marginal rate, zero host time between them.
    roofline_s = sum(
        (side * side * len(boards[side]) * gen_limit)
        / max(marginal[k] for k in marginal if k.startswith(f"{side}x"))
        for side in sides
    )
    marginal_rate_combined = total_work / roofline_s

    # -- 2. end-to-end serve rate -------------------------------------------
    def make_jobs():
        out = []
        for i in range(nboards):
            side = sides[i % 2]
            out.append(new_job(
                side, side, boards[side][i // 2], gen_limit=gen_limit,
            ))
        return out

    def serve_run(depth, resident=0, temporal_depth=1):
        plan_before = batcher._PLAN
        if temporal_depth != 1:
            batcher._PLAN = ServePlan(temporal_depth=temporal_depth)
        try:
            tmp = tempfile.mkdtemp(dir=workroot)
            journal = JobJournal(os.path.join(tmp, "journal"))
            sched = Scheduler(journal=journal, flush_age=0.001,
                              max_batch=max_batch, pipeline_depth=depth,
                              resident_ring=resident, max_queue_depth=4096)
            jobs = make_jobs()
            for job in jobs:
                sched.submit(job)
            sched.start()
            t0 = time.perf_counter()
            ok = sched.drain(timeout=600)
            elapsed = time.perf_counter() - t0
            sched.stop(drain=False)
            journal.close()
            if not ok or any(j.state != DONE for j in jobs):
                raise RuntimeError("serve lane failed to drain every job DONE")
            shutil.rmtree(tmp, ignore_errors=True)
            return total_work / elapsed
        finally:
            batcher._PLAN = plan_before

    lanes = [
        ("depth1", dict(depth=1)),
        ("depth2", dict(depth=2)),
        ("depth4", dict(depth=4)),
        ("resident_depth8", dict(depth=2 * ring, resident=ring)),
    ]
    if tuned_T != 1:
        lanes.append((
            f"resident_depth8_T{tuned_T}",
            dict(depth=2 * ring, resident=ring, temporal_depth=tuned_T),
        ))
    rates = {}
    for name, kwargs in lanes:
        serve_run(**kwargs)  # warm every program this lane compiles
        rates[name] = max(serve_run(**kwargs) for _ in range(repeats))
        print(
            f"  serve {name}: {rates[name]:.3e} cell-updates/s "
            f"(gap ratio {rates[name] / marginal_rate_combined:.3f})",
            file=sys.stderr,
        )

    best_resident = max(v for k, v in rates.items() if k.startswith("resident"))
    resident_over_depth1 = best_resident / rates["depth1"]
    gap_ratio = {k: round(v / marginal_rate_combined, 4)
                 for k, v in rates.items()}
    shutil.rmtree(workroot, ignore_errors=True)

    payload = {
        "metric": "resident_over_depth1_serve_rate",
        "value": round(resident_over_depth1, 4),
        "unit": "x",
        # No external baseline: the classic depth-1 lane IS the denominator.
        "vs_baseline": None,
        "load": {
            "boards": nboards,
            "gen_limit": gen_limit,
            "max_batch": max_batch,
            "ring": ring,
            "buckets": [f"{s}x{s}" for s in sides],
            "total_cell_updates": total_work,
        },
        "marginal_kernel_cells_per_sec": {
            k: round(v, 1) for k, v in marginal.items()
        },
        "marginal_rate_combined": round(marginal_rate_combined, 1),
        "serve_cells_per_sec": {k: round(v, 1) for k, v in rates.items()},
        # The dispatch gap, explicitly: end-to-end over marginal-kernel.
        "dispatch_gap_ratio": gap_ratio,
        "best_resident_gap_ratio": round(
            best_resident / marginal_rate_combined, 4),
        "resident_over_depth1": round(resident_over_depth1, 4),
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r08.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if resident_over_depth1 >= 1.5 else 1


def _bench_telemetry(args) -> int:
    """Telemetry overhead suite (--suite telemetry) -> BENCH_r09.

    ISSUE 7's cost acceptance: telemetry ON (span tracing + flow events +
    flight recorder armed + the SLO engine and dispatch-gap sampler ticking)
    must cost < 3% serve throughput on the BENCH_r08 megabatch load.
    Telemetry OFF is the no-op fast path (span() returns the module
    singleton; the per-job timeline stamps are part of the base serve path
    and present in BOTH columns — they are the always-on substrate the
    ops surface reads, not the toggle).

    Measures the pipelined lane (depth 2) and the resident ring (ring 4 at
    depth 8) off vs on; the headline is the WORST on/off ratio. rc 0 iff it
    clears 0.97 and every job of every run lands DONE.
    """
    import shutil
    import tempfile

    import jax

    from gol_tpu.io import text_grid
    from gol_tpu.obs import recorder as obs_recorder, slo as obs_slo
    from gol_tpu.obs import sampler as obs_sampler, trace as obs_trace
    from gol_tpu.serve.jobs import DONE, JobJournal, new_job
    from gol_tpu.serve.scheduler import Scheduler

    repeats = args.repeats
    nboards = 64
    gen_limit = args.gen_limit if args.gen_limit is not None else 4
    max_batch = 8
    ring = 4
    rounds = 8  # the megabatch load, submitted 8x per timed run: a ~60ms
    # run cannot resolve a 3% budget over scheduler-thread noise; ~0.5s can.
    sides = (256, 250)
    workroot = tempfile.mkdtemp(prefix="gol-bench-telemetry-")
    print(
        f"bench telemetry: {nboards} boards x {rounds} rounds, buckets "
        f"{list(sides)}, gen_limit {gen_limit}, repeats {repeats}, "
        f"platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )
    boards = {
        side: [text_grid.generate(side, side, seed=3000 + side + i)
               for i in range(nboards // 2)]
        for side in sides
    }
    total_work = sum(
        side * side * len(bs) for side, bs in boards.items()
    ) * gen_limit * rounds

    def make_jobs():
        out = []
        for _ in range(rounds):
            for i in range(nboards):
                side = sides[i % 2]
                out.append(new_job(
                    side, side, boards[side][i // 2], gen_limit=gen_limit,
                ))
        return out

    def serve_run(depth, resident=0, telemetry=False):
        tmp = tempfile.mkdtemp(dir=workroot)
        journal = JobJournal(os.path.join(tmp, "journal"))
        sched = Scheduler(journal=journal, flush_age=0.001,
                          max_batch=max_batch, pipeline_depth=depth,
                          resident_ring=resident, max_queue_depth=4096)
        sampler = None
        if telemetry:
            slo = obs_slo.SloEngine(
                obs_slo.default_objectives(4096), registry=sched.metrics,
            )
            sampler = obs_sampler.ServeSampler(
                sched.metrics, slo=slo, interval=0.25,
            )
            sampler.start()
        try:
            jobs = make_jobs()
            for job in jobs:
                sched.submit(job)
            sched.start()
            t0 = time.perf_counter()
            ok = sched.drain(timeout=600)
            elapsed = time.perf_counter() - t0
            sched.stop(drain=False)
            journal.close()
            if not ok or any(j.state != DONE for j in jobs):
                raise RuntimeError("serve lane failed to drain every job DONE")
            return total_work / elapsed
        finally:
            if sampler is not None:
                sampler.stop()
            shutil.rmtree(tmp, ignore_errors=True)

    lanes = [
        ("depth2", dict(depth=2)),
        ("resident_depth8", dict(depth=2 * ring, resident=ring)),
    ]
    results = {}
    trace_dir = os.path.join(workroot, "trace")
    try:
        for name, kwargs in lanes:
            serve_run(**kwargs)  # warm every compiled program
            # Interleave off/on runs: machine-level drift (thermal, noisy
            # neighbors) across the measurement window then biases both
            # columns equally instead of landing entirely on one.
            off_runs, on_runs = [], []
            for _ in range(repeats):
                off_runs.append(serve_run(**kwargs))
                obs_trace.enable()
                obs_recorder.install(trace_dir)
                try:
                    on_runs.append(serve_run(telemetry=True, **kwargs))
                finally:
                    obs_trace.disable()
                    obs_trace.clear()
                    obs_recorder.uninstall()
            off, on = max(off_runs), max(on_runs)
            results[name] = {
                "off_cells_per_sec": round(off, 1),
                "on_cells_per_sec": round(on, 1),
                "on_over_off": round(on / off, 4),
            }
            print(
                f"  {name}: off {off:.3e} on {on:.3e} cell-updates/s "
                f"(ratio {on / off:.4f})",
                file=sys.stderr,
            )
    finally:
        shutil.rmtree(workroot, ignore_errors=True)

    worst = min(r["on_over_off"] for r in results.values())
    payload = {
        "metric": "telemetry_on_over_off_serve_rate",
        "value": worst,
        "unit": "x",
        # The off column IS the baseline; the acceptance floor is 0.97.
        "vs_baseline": None,
        "load": {
            "boards": nboards,
            "rounds": rounds,
            "gen_limit": gen_limit,
            "max_batch": max_batch,
            "ring": ring,
            "buckets": [f"{s}x{s}" for s in sides],
            "total_cell_updates": total_work,
        },
        "telemetry_on": [
            "trace spans + job flow events", "flight recorder armed",
            "SLO engine (5 objectives) + dispatch-gap sampler at 0.25s",
        ],
        "lanes": results,
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r09.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if worst >= 0.97 else 1


def _bench_fleet(args) -> int:
    """Sharded-fleet scaling suite (--suite fleet) -> BENCH_r10.json.

    The horizontal question: does adding workers add throughput? One
    multi-bucket load — 16 equal-work padding buckets (one 160^2 canvas,
    16 distinct similarity frequencies, each a separately compiled
    program) — runs through

    1. a **fleet of N in {1, 2, 4} workers** behind the real router
       (workers are `gol serve` subprocesses on their own journal
       partitions; jobs submitted over HTTP through the router's
       bucket-consistent placement), and
    2. the **single-process resident lane** (Scheduler with resident
       rings, in-process — the PR-6 fastest solo configuration) as the
       no-fleet reference point (unpinned: the whole host is its device).

    Two controls keep the comparison about the FLEET tier, not about the
    shared host:

    - every fleet worker is pinned (`taskset`) to an equal core slice —
      the fixed per-worker resource budget a real deployment has (one
      worker per device/host); without it the N=1 worker borrows every
      core and the suite measures XLA's intra-op scaling instead;
    - the 16 bucket frequencies are chosen so the rendezvous placement is
      balanced (4/4/4/4 at N=4, 8/8 at N=2): the suite measures scale-out
      of a balanceable load — placement imbalance is a policy question the
      placement tests own, not a throughput question.

    Headline: N=4 aggregate jobs/sec over N=1 (the scale-out acceptance,
    >= 2.5x on the multi-bucket load). Per-lane aggregate jobs/sec and
    cell-updates/sec are recorded for `tools/bench_diff.py --metric`
    gating (e.g. --metric lanes.fleet_n4.jobs_per_sec). rc 0 iff the
    headline clears 2.5 and every job of every run lands DONE.
    """
    import concurrent.futures
    import shutil
    import tempfile

    import jax

    from gol_tpu.fleet import client as fleet_client
    from gol_tpu.fleet.router import RouterServer
    from gol_tpu.fleet.workers import Fleet, core_slice_prefix
    from gol_tpu.io import text_grid
    from gol_tpu.serve.jobs import DONE, JobJournal, new_job
    from gol_tpu.serve.scheduler import Scheduler

    repeats = args.repeats
    # Long enough that per-worker compute dominates the fixed
    # submit/route/poll overhead (~0.4 s per round): at 2000 the N=4 lane
    # finishes in under a second and the ratio measures the overhead, not
    # the fleet (2.30x measured); at 6000 compute dominates (3.1x).
    gen_limit = args.gen_limit if args.gen_limit is not None else 6000
    side = 160
    # 16 equal-work buckets: same canvas, distinct similarity frequencies
    # (a baked program constant, so each is its own bucket). This set
    # rendezvous-balances over w0..w3 AND over w0..w1 (see docstring).
    freqs = (2, 3, 4, 5, 6, 7, 8, 9, 10, 12, 14, 17, 18, 21, 24, 27)
    per_bucket = 8
    max_batch = 8
    njobs = len(freqs) * per_bucket
    cores = os.cpu_count() or 4
    slice_width = max(1, min(6, (cores - 2) // 4))
    workroot = tempfile.mkdtemp(prefix="gol-bench-fleet-")
    print(
        f"bench fleet: {njobs} jobs across {len(freqs)} equal-work "
        f"{side}^2 buckets, gen_limit {gen_limit}, repeats {repeats}, "
        f"{slice_width} cores/worker, platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )
    boards = {
        freq: [text_grid.generate(side, side, seed=4000 + 100 * freq + i)
               for i in range(per_bucket)]
        for freq in freqs
    }
    nominal_work = side * side * njobs * gen_limit

    # w<K> -> its own core slice; the big lane (unused here) and any
    # respawn keep the same slice. The production pinner: the bench
    # must pin exactly like `gol fleet --cores-per-worker`.
    pin = core_slice_prefix(slice_width, cores)

    def _http(method, url, body=None, timeout=120):
        # The one fleet stdlib client: HTTP error statuses come back as
        # (status, payload) so submit_all can REPORT a worker 4xx/5xx
        # instead of dying on an unhandled HTTPError.
        return fleet_client.http_json(method, url, body, timeout=timeout)

    def submit_all(base: str) -> None:
        def one(freq_board):
            freq, board = freq_board
            status, payload = _http("POST", f"{base}/jobs", {
                "width": side, "height": side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": gen_limit,
                "similarity_frequency": freq,
            })
            if status != 202:
                raise RuntimeError(f"submit rejected HTTP {status}: {payload}")

        work = [(freq, b) for freq, bs in boards.items() for b in bs]
        with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
            list(pool.map(one, work))

    def completed(base: str) -> tuple[int, int]:
        _, snap = _http("GET", f"{base}/metrics?format=json")
        return (int(snap["counters"].get("jobs_completed_total", 0)),
                int(snap["counters"].get("jobs_failed_total", 0)))

    def run_round(base: str) -> float:
        done0, _ = completed(base)
        t0 = time.perf_counter()
        submit_all(base)
        while True:
            done, failed = completed(base)
            if failed:
                raise RuntimeError(f"{failed} job(s) FAILED")
            if done - done0 >= njobs:
                return time.perf_counter() - t0
            time.sleep(0.05)

    def fleet_lane(n_workers: int) -> dict:
        fleet_dir = os.path.join(workroot, f"fleet-n{n_workers}")
        fleet = Fleet(fleet_dir, spawn_prefix=pin, serve_args=[
            "--flush-age", "0.2",
            "--max-batch", str(max_batch),
            "--pipeline-depth", "2",
            "--max-queue-depth", "4096",
        ])
        fleet.spawn_fleet(n_workers)
        router = RouterServer(fleet, port=0)
        router.start()
        try:
            run_round(router.url)  # warm: every bucket compiles on its owner
            best = min(run_round(router.url) for _ in range(repeats))
        finally:
            router.shutdown(cascade=True)
        rate = njobs / best
        print(f"  fleet n={n_workers}: {rate:.1f} jobs/s "
              f"({best:.2f}s for {njobs} jobs)", file=sys.stderr)
        return {
            "workers": n_workers,
            "seconds": round(best, 3),
            "jobs_per_sec": round(rate, 2),
            "cell_updates_per_sec": round(nominal_work / best, 1),
        }

    def solo_resident_lane() -> dict:
        ring = 4
        best = None
        for _ in range(repeats + 1):  # first round doubles as the warm run
            tmp = tempfile.mkdtemp(dir=workroot)
            journal = JobJournal(os.path.join(tmp, "journal"))
            sched = Scheduler(journal=journal, flush_age=0.2,
                              max_batch=max_batch, pipeline_depth=2 * ring,
                              resident_ring=ring, max_queue_depth=4096)
            jobs = [new_job(side, side, b, gen_limit=gen_limit,
                            similarity_frequency=freq)
                    for freq, bs in boards.items() for b in bs]
            for job in jobs:
                sched.submit(job)
            sched.start()
            t0 = time.perf_counter()
            ok = sched.drain(timeout=900)
            elapsed = time.perf_counter() - t0
            sched.stop(drain=False)
            journal.close()
            shutil.rmtree(tmp, ignore_errors=True)
            if not ok or any(j.state != DONE for j in jobs):
                raise RuntimeError("solo resident lane failed to drain DONE")
            best = elapsed if best is None else min(best, elapsed)
        rate = njobs / best
        print(f"  solo resident (ring {ring}): {rate:.1f} jobs/s "
              f"({best:.2f}s)", file=sys.stderr)
        return {
            "seconds": round(best, 3),
            "jobs_per_sec": round(rate, 2),
            "cell_updates_per_sec": round(nominal_work / best, 1),
        }

    lanes = {}
    try:
        lanes["solo_resident"] = solo_resident_lane()
        for n in (1, 2, 4):
            lanes[f"fleet_n{n}"] = fleet_lane(n)
    finally:
        shutil.rmtree(workroot, ignore_errors=True)

    scaling = (lanes["fleet_n4"]["jobs_per_sec"]
               / lanes["fleet_n1"]["jobs_per_sec"])
    payload = {
        "metric": "fleet_n4_over_n1_jobs_per_sec",
        "value": round(scaling, 3),
        "unit": "x",
        "vs_baseline": None,  # the N=1 lane IS the baseline; floor is 2.5
        "load": {
            "jobs": njobs,
            "buckets": [f"{side}x{side}/sim{f}" for f in freqs],
            "per_bucket": per_bucket,
            "gen_limit": gen_limit,
            "max_batch": max_batch,
            "cores_per_worker": slice_width,
            "nominal_cell_updates": nominal_work,
            "note": "fleet workers taskset-pinned to equal core slices "
            "(fixed per-worker budget; the solo resident lane is unpinned "
            "— whole host); cell_updates_per_sec figures assume gen_limit "
            "exits (identical boards exit identically across lanes); "
            "jobs_per_sec is the exact, gated figure",
        },
        "lanes": lanes,
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r10.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if scaling >= 2.5 else 1


def _bench_autoscale(args) -> int:
    """Elastic-fleet suite (--suite autoscale) -> BENCH_r15.json.

    The closed-loop question ROADMAP item 3 asks: does a min=1/max=4
    autoscaled fleet under a STEP LOAD reach the throughput a human
    would have had to provision up front? Protocol:

    1. **static n=1 lane** — the PR-8 fleet at a fixed single worker
       (core-pinned like every fleet bench lane): warm round + best-of
       measured rounds = the baseline rate.
    2. **autoscaled lane** — the same fleet config booted at n=1 with
       the autoscaler live (aggressive bench knobs: saturation threshold
       low enough that the step load drives it to 4, short cooldown). A
       feeder thread applies the step load (keeps ~3 rounds of jobs
       outstanding); the autoscaler must react (decision series
       recorded), spawn to 4, and the SAME measured round then runs at
       steady state. An oracle-gated sample job is submitted DURING the
       scale-up and again during scale-down: scale events must byte-
       change nothing.
    3. **scale-down + audit** — the load stops; the fleet must retire
       back to the floor (drain->retire, emptiest first), and every
       accepted id must hold EXACTLY one done record across all journal
       partitions — including partitions of retired workers, which stay
       on disk fully drained.

    Headline: autoscaled steady-state aggregate jobs/sec over the static
    n=1 rate (acceptance >= 2.0x, exit-code gated along with the floor,
    the audit, and the oracle gate). CI gates
    --metric lanes.autoscaled.jobs_per_sec.
    """
    import concurrent.futures
    import shutil
    import tempfile

    import jax

    from gol_tpu import oracle
    from gol_tpu.config import GameConfig
    from gol_tpu.fleet import client as fleet_client
    from gol_tpu.fleet.autoscale import AutoscaleConfig, Autoscaler
    from gol_tpu.fleet.router import RouterServer
    from gol_tpu.fleet.workers import Fleet, core_slice_prefix
    from gol_tpu.io import text_grid
    from gol_tpu.obs import history as obs_history

    repeats = args.repeats
    gen_limit = args.gen_limit if args.gen_limit is not None else 6000
    side = 160
    # The fleet suite's 16 equal-work buckets: rendezvous-balanced
    # 4/4/4/4 at n=4 (see _bench_fleet), so the scaled-out steady state
    # measures capacity, not placement luck.
    freqs = (2, 3, 4, 5, 6, 7, 8, 9, 10, 12, 14, 17, 18, 21, 24, 27)
    per_bucket = 8
    njobs = len(freqs) * per_bucket
    max_workers = 4
    queue_cap = 512  # per worker; the step load must saturate n=1 without 429s
    cores = os.cpu_count() or 4
    slice_width = max(1, min(6, (cores - 2) // max_workers))
    workroot = tempfile.mkdtemp(prefix="gol-bench-autoscale-")
    print(
        f"bench autoscale: step load over {len(freqs)} {side}^2 buckets, "
        f"gen_limit {gen_limit}, min 1 / max {max_workers} workers at "
        f"{slice_width} cores each, platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )
    boards = {
        freq: [text_grid.generate(side, side, seed=5000 + 100 * freq + i)
               for i in range(per_bucket)]
        for freq in freqs
    }
    work = [(freq, b) for freq, bs in boards.items() for b in bs]

    def _http(method, url, body=None, timeout=120):
        return fleet_client.http_json(method, url, body, timeout=timeout)

    pin = core_slice_prefix(slice_width, cores)

    def submit(base, freq, board, gens=None):
        status, payload = _http("POST", f"{base}/jobs", {
            "width": side, "height": side,
            "cells": text_grid.encode(board).decode("ascii"),
            "gen_limit": gens if gens is not None else gen_limit,
            "similarity_frequency": freq,
        })
        if status != 202:
            raise RuntimeError(f"submit rejected HTTP {status}: {payload}")
        return payload["id"]

    def completed(base):
        _, snap = _http("GET", f"{base}/metrics?format=json")
        return (int(snap["counters"].get("jobs_completed_total", 0)),
                int(snap["counters"].get("jobs_failed_total", 0)))

    def run_round(base, accepted=None):
        done0, _ = completed(base)
        t0 = time.perf_counter()

        def one(freq_board):
            job_id = submit(base, *freq_board)
            if accepted is not None:
                accepted.add(job_id)

        with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
            list(pool.map(one, work))
        while True:
            done, failed = completed(base)
            if failed:
                raise RuntimeError(f"{failed} job(s) FAILED")
            if done - done0 >= njobs:
                return time.perf_counter() - t0
            time.sleep(0.05)

    def serve_args():
        return [
            "--flush-age", "0.2",
            "--max-batch", "8",
            "--pipeline-depth", "2",
            "--max-queue-depth", str(queue_cap),
        ]

    # -- lane 1: the static n=1 fleet ---------------------------------------
    def static_lane():
        fleet = Fleet(os.path.join(workroot, "static"), spawn_prefix=pin,
                      serve_args=serve_args())
        fleet.spawn_fleet(1)
        router = RouterServer(fleet, port=0)
        router.start()
        try:
            run_round(router.url)  # warm: compiles every bucket
            best = min(run_round(router.url) for _ in range(repeats))
        finally:
            router.shutdown(cascade=True)
        rate = njobs / best
        print(f"  static n=1: {rate:.1f} jobs/s ({best:.2f}s)",
              file=sys.stderr)
        return {"workers": 1, "seconds": round(best, 3),
                "jobs_per_sec": round(rate, 2)}

    # -- lane 2: the autoscaled fleet ---------------------------------------
    def autoscaled_lane():
        fleet_dir = os.path.join(workroot, "auto")
        fleet = Fleet(fleet_dir, spawn_prefix=pin, serve_args=serve_args())
        fleet.spawn_fleet(1)
        router = RouterServer(fleet, port=0)
        router.start()
        ring_dir = os.path.join(fleet_dir, "autoscaler-history")
        scaler = Autoscaler(
            fleet, router,
            AutoscaleConfig(
                min_workers=1, max_workers=max_workers,
                # The step load keeps ~3 rounds queued: 384/512 = 0.75 of
                # the n=1 cap, 0.19 of the n=4 cap — 0.1 drives the loop
                # all the way to 4 and the 0.02 floor stays idle-only.
                up_saturation=0.10, up_sustain=2,
                down_occupancy=0.02, down_sustain=20,
                cooldown_s=2.0,
            ),
            queue_capacity=queue_cap,
            history=obs_history.HistoryWriter(ring_dir, source="autoscaler"),
        )
        router.autoscaler = scaler
        fleet.add_tick_hook(scaler.tick)
        fleet.start_health(0.3)

        accepted: set = set()
        acc_lock = threading.Lock()
        feeding = threading.Event()
        feeding.set()
        submitted = [0]

        feed_error = []

        def feeder():
            target = 3 * njobs
            i = 0
            try:
                while feeding.is_set():
                    done, _ = completed(router.url)
                    while (submitted[0] - done < target and feeding.is_set()):
                        freq, board = work[i % len(work)]
                        job_id = submit(router.url, freq, board)
                        with acc_lock:
                            accepted.add(job_id)
                        submitted[0] += 1
                        i += 1
                        if submitted[0] % njobs == 0:
                            break  # re-read completion between bursts
                    time.sleep(0.2)
            except Exception as err:  # noqa: BLE001 - re-raised below
                feed_error.append(err)

        def normals():
            return [w for w in fleet.workers() if not w.big and not w.retiring]

        spike_t0 = time.perf_counter()
        feeder_thread = threading.Thread(target=feeder, daemon=True)
        feeder_thread.start()

        # Oracle sample DURING the scale-up window.
        sample_freq = freqs[0]
        sample_board = boards[sample_freq][0]
        sample_up = submit(router.url, sample_freq, sample_board)
        accepted.add(sample_up)

        deadline = time.perf_counter() + 600
        while len(normals()) < max_workers:
            if feed_error:
                raise feed_error[0]
            if time.perf_counter() > deadline:
                raise RuntimeError(
                    f"fleet never scaled to {max_workers} "
                    f"(at {len(normals())}); decisions in {ring_dir}"
                )
            time.sleep(0.5)
        scaled_at = time.perf_counter()
        print(f"  scale-up 1 -> {max_workers} complete "
              f"{scaled_at - spike_t0:.1f}s after the spike",
              file=sys.stderr)

        # Stop the step load, drain the backlog, then measure steady state.
        feeding.clear()
        feeder_thread.join(timeout=30)
        if feed_error:
            raise feed_error[0]
        while True:
            done, failed = completed(router.url)
            if failed:
                raise RuntimeError(f"{failed} job(s) FAILED under the spike")
            if done >= submitted[0] + 1:  # + the sample job
                break
            time.sleep(0.2)

        def fetch_result(job_id, phase, timeout=120):
            # Fetched EAGERLY (while every worker is still up): results
            # live on the workers, and the scale-down about to happen
            # retires whoever holds them — the journal audit, not the
            # HTTP surface, is the durability story for the rest.
            deadline = time.perf_counter() + timeout
            while True:
                status, result = _http("GET",
                                       f"{router.url}/result/{job_id}")
                if status == 200:
                    return result
                if time.perf_counter() > deadline:
                    raise RuntimeError(
                        f"{phase} sample result HTTP {status}")
                time.sleep(0.2)

        sample_results = {"scale-up": fetch_result(sample_up, "scale-up")}

        run_round(router.url, accepted)  # warm the scaled-out placement
        best = min(run_round(router.url, accepted) for _ in range(repeats))
        rate = njobs / best
        print(f"  autoscaled n={max_workers}: {rate:.1f} jobs/s "
              f"({best:.2f}s)", file=sys.stderr)

        # Oracle sample THROUGH the scale-down window: submitted as the
        # load dies, its result collected as soon as it completes, the
        # retire wave following right behind.
        sample_down = submit(router.url, sample_freq, sample_board)
        accepted.add(sample_down)
        sample_results["scale-down"] = fetch_result(sample_down,
                                                    "scale-down")
        deadline = time.perf_counter() + 600
        while len(fleet.workers()) > 1:
            if time.perf_counter() > deadline:
                raise RuntimeError(
                    f"fleet never retired to the floor "
                    f"({len(fleet.workers())} workers left)"
                )
            time.sleep(0.5)
        floor_at = time.perf_counter()
        print(f"  scale-down to floor complete "
              f"({floor_at - scaled_at:.1f}s after steady state)",
              file=sys.stderr)

        # Oracle gate: both samples byte-identical to ground truth.
        cfg = GameConfig(gen_limit=gen_limit,
                         similarity_frequency=sample_freq)
        want = oracle.run(sample_board, cfg)
        for phase, result in sample_results.items():
            got = text_grid.decode(result["grid"].encode("ascii"),
                                   result["width"], result["height"])
            if (not np.array_equal(np.asarray(got), want.grid)
                    or result["generations"] != want.generations):
                raise RuntimeError(
                    f"{phase} sample diverges from the oracle: scale "
                    "events must byte-change nothing"
                )

        # The decision series: reaction = spike -> first UP decision (the
        # ring's "t" is perf_counter in THIS process, so it compares with
        # spike_t0 directly).
        records = [(r.get("t"), r["autoscaler"]) for r
                   in obs_history.read_records(ring_dir)
                   if "autoscaler" in r]
        ups = [(t, d) for t, d in records
               if d.get("action") == "up" and "record_kind" not in d]
        downs = [(t, d) for t, d in records
                 if d.get("action") == "down" and "record_kind" not in d]
        reaction_s = (ups[0][0] - spike_t0) if ups and ups[0][0] else None
        router.shutdown(cascade=True)

        # Fleet-wide exactly-once audit across ALL partitions (incl.
        # retired ones — their journals stay, fully drained). Enumerated
        # via compaction.iter_records (snapshot + sealed segments + live
        # file): this load writes tens of MB per partition, well past the
        # rotation threshold, so reading journal.jsonl alone would miss
        # most of the done records.
        from gol_tpu.serve import compaction as _compaction

        done_records: dict = {}
        for name in sorted(os.listdir(fleet_dir)):
            part = os.path.join(fleet_dir, name)
            if not os.path.isfile(os.path.join(part, "journal.jsonl")):
                continue
            for rec in _compaction.iter_records(part):
                if rec.get("event") == "done":
                    done_records.setdefault(rec["id"], []).append(name)
        lost = accepted - set(done_records)
        dup = {k: v for k, v in done_records.items()
               if k in accepted and len(v) != 1}
        if lost or dup:
            raise RuntimeError(
                f"exactly-once audit FAILED: lost={len(lost)} "
                f"duplicated={len(dup)}"
            )
        partitions = {p for v in done_records.values() for p in v}
        print(f"  audit: {len(accepted)} accepted jobs, exactly one done "
              f"record each across {len(partitions)} partitions",
              file=sys.stderr)
        return {
            "workers_reached": max_workers,
            "seconds": round(best, 3),
            "jobs_per_sec": round(rate, 2),
            "scale_up_reaction_s": (round(reaction_s, 2)
                                    if reaction_s is not None else None),
            "spike_to_full_fleet_s": round(scaled_at - spike_t0, 2),
            "scale_ups": len(ups),
            "scale_downs": len(downs),
            "floor_reached": True,
            "accepted_jobs": len(accepted),
            "partitions": len(partitions),
            "decisions_sampled": [d for _, d in (ups + downs)[:8]],
        }

    try:
        lanes = {"static_n1": static_lane()}
        lanes["autoscaled"] = autoscaled_lane()
    finally:
        shutil.rmtree(workroot, ignore_errors=True)

    ratio = (lanes["autoscaled"]["jobs_per_sec"]
             / lanes["static_n1"]["jobs_per_sec"])
    payload = {
        "metric": "autoscaled_over_static_n1_jobs_per_sec",
        "value": round(ratio, 3),
        "unit": "x",
        "vs_baseline": None,  # the static lane IS the baseline; floor 2.0
        "load": {
            "jobs_per_round": njobs,
            "buckets": [f"{side}x{side}/sim{f}" for f in freqs],
            "gen_limit": gen_limit,
            "queue_capacity_per_worker": queue_cap,
            "cores_per_worker": slice_width,
            "note": "step load keeps ~3 rounds outstanding until the "
            "fleet reaches max_workers; steady-state round measured "
            "after the backlog drains; scale-down + exactly-once audit "
            "+ oracle-gated samples are hard gates on this artifact",
        },
        "lanes": lanes,
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r15.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if ratio >= 2.0 else 1


def _bench_cache(args) -> int:
    """Content-addressed result cache on a Zipf-repeat load (--suite cache).

    The serving question ROADMAP item 5 asks: what does repeat traffic cost
    once the answer is already in hand? 128 jobs over 16 unique 256^2
    boards, repeat counts Zipf-distributed (rank r appears ~1/r of the
    time — the pattern-library/homework-soup shape), through the real
    Scheduler in three lanes:

    - **cold**: no cache mounted — every job takes the engine path (the
      padding-bucket batcher amortizes dispatch exactly as in production);
    - **warm**: every fingerprint pre-cached — every job completes at
      admission from the memory tier (the O(1) hit path, fingerprint
      hashing included);
    - **coalesced**: cache starts empty — the 16 unique boards run the
      engine once each, the other 112 submissions coalesce behind their
      in-flight leaders.

    The headline is the warm-hit rate; ``vs_baseline`` is warm/cold, gated
    at >= 10x (the acceptance). ``latency`` records the per-job end-to-end
    p50 of the hit path vs the engine path from each lane's own
    job_latency_seconds histogram. CI gates on the warm-hit leaf via
    ``tools/bench_diff.py --metric lanes.warm.jobs_per_sec``.
    """
    import jax

    from gol_tpu.cache import ResultCache
    from gol_tpu.cache.fingerprint import job_fingerprint
    from gol_tpu.serve.jobs import DONE, FAILED, new_job
    from gol_tpu.serve.metrics import Metrics
    from gol_tpu.serve.scheduler import Scheduler

    # The reference GEN_LIMIT (1000): the production-shaped request depth.
    # Short requests understate the engine path the cache exists to skip —
    # at gen_limit 4 the batcher amortizes dispatch so well that the
    # comparison measures Python submit overhead, not saved compute.
    if args.gen_limit is None:
        args.gen_limit = 1000
    size, uniques, njobs = 256, 16, 128
    rng = np.random.default_rng(42)
    boards = [
        rng.integers(0, 2, size=(size, size), dtype=np.uint8)
        for _ in range(uniques)
    ]
    # Zipf repeat counts: weight 1/rank, scaled to njobs, remainder to the
    # head (the hot pattern gets the spillover, as it would in the wild).
    weights = [1.0 / r for r in range(1, uniques + 1)]
    scale = njobs / sum(weights)
    counts = [max(1, int(w * scale)) for w in weights]
    counts[0] += njobs - sum(counts)
    order = [i for i, c in enumerate(counts) for _ in range(c)]
    rng.shuffle(order)
    print(
        f"bench cache: {njobs} jobs over {uniques} unique {size}x{size} "
        f"boards (Zipf counts {counts}), gen_limit={args.gen_limit}, "
        f"platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )

    def submit_all(scheduler):
        jobs = [
            scheduler.submit(
                new_job(size, size, boards[i], gen_limit=args.gen_limit)
            )
            for i in order
        ]
        while any(j.state not in (DONE, FAILED) for j in jobs):
            time.sleep(0.002)
        assert all(j.state == DONE for j in jobs)
        return jobs

    def run_lane(cache, warm_fps=None):
        metrics = Metrics()
        if cache is not None:
            cache.metrics = metrics
        scheduler = Scheduler(metrics=metrics, cache=cache, flush_age=0.01)
        scheduler.start()
        t0 = time.perf_counter()
        submit_all(scheduler)
        elapsed = time.perf_counter() - t0
        scheduler.stop()
        hist = metrics.snapshot()["histograms"].get("job_latency_seconds", {})
        counters = metrics.snapshot()["counters"]
        return {
            "jobs_per_sec": njobs / elapsed,
            "elapsed_s": elapsed,
            "job_latency_p50_s": hist.get("p50"),
            "cache_hits": counters.get("cache_hits_total", 0),
            "cache_misses": counters.get("cache_misses_total", 0),
            "coalesced": counters.get("cache_inflight_coalesced_total", 0),
        }

    # Warm the compiled bucket programs outside every timer (the server
    # pays this once per bucket for its whole life).
    warmup = Scheduler(metrics=Metrics(), flush_age=0.01)
    warmup.start()
    submit_all(warmup)
    warmup.stop()

    repeats = min(args.repeats, 3)
    lanes = {}
    for name in ("cold", "warm", "coalesced"):
        best = None
        for _ in range(repeats):
            if name == "cold":
                result = run_lane(None)
            elif name == "warm":
                # Pre-populate OUTSIDE the timer: one cached run of the
                # load, then a fresh scheduler sharing the warm tiers.
                cache = ResultCache(memory_entries=256)
                pre = Scheduler(metrics=Metrics(), cache=cache,
                                flush_age=0.01)
                pre.start()
                submit_all(pre)
                pre.stop()
                result = run_lane(cache)
                assert result["cache_hits"] == njobs, result
            else:
                result = run_lane(ResultCache(memory_entries=256))
                assert result["coalesced"] > 0, result
            if best is None or result["jobs_per_sec"] > best["jobs_per_sec"]:
                best = result
        lanes[name] = best
        print(
            f"  {name:>9}: {best['elapsed_s'] * 1000:8.1f} ms for {njobs} "
            f"jobs -> {best['jobs_per_sec']:8.1f} jobs/s "
            f"(hits {best['cache_hits']}, coalesced {best['coalesced']})",
            file=sys.stderr,
        )

    speedup = lanes["warm"]["jobs_per_sec"] / lanes["cold"]["jobs_per_sec"]
    print(f"  warm hit path = {speedup:.1f}x the cold engine path "
          f"(acceptance >= 10x)", file=sys.stderr)
    payload = {
        "metric": "cache_warm_jobs_per_sec",
        "value": lanes["warm"]["jobs_per_sec"],
        "unit": "jobs/s",
        "vs_baseline": speedup,  # warm over cold; gated at >= 10
        "lanes": lanes,
        "latency": {
            "hit_path_p50_s": lanes["warm"]["job_latency_p50_s"],
            "engine_path_p50_s": lanes["cold"]["job_latency_p50_s"],
        },
        "load": {
            "jobs": njobs,
            "unique_boards": uniques,
            "zipf_counts": counts,
            "grid": f"{size}x{size}",
            "gen_limit": args.gen_limit,
            "fingerprint_example": job_fingerprint(
                new_job(size, size, boards[0], gen_limit=args.gen_limit)
            ),
        },
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r11.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if speedup >= 10.0 else 1


def _bench_fleettrace(args) -> int:
    """Fleet-observability overhead suite (--suite fleettrace) -> BENCH_r12.

    ISSUE 10's cost acceptance: the fleet-granular tier — trace-context
    propagation (X-Gol-Trace stamped per routed submit, router
    submit/forward spans + flow starts, worker span rings + flow
    adoption) AND durable metrics history (per-worker partition rings fed
    by the sampler, the router's merged/floored ring ticking) — must cost
    < 3% of fleet throughput against the identical load with everything
    OFF (the PR-7 telemetry budget, applied one tier up).

    Two REAL 2-worker fleets (subprocess workers behind in-process
    routers) stay up for the whole measurement; rounds alternate
    off/on so machine drift lands on both columns. The headline is the
    on/off jobs-per-sec ratio (acceptance >= 0.97); ``lanes.on.
    jobs_per_sec`` is the absolute leaf CI gates with
    ``tools/bench_diff.py --metric lanes.on.jobs_per_sec``. rc 0 iff the
    ratio clears 0.97 and every job of every round lands DONE.
    """
    import concurrent.futures
    import shutil
    import tempfile

    import jax

    from gol_tpu.fleet import client as fleet_client
    from gol_tpu.fleet.router import RouterServer
    from gol_tpu.fleet.workers import Fleet
    from gol_tpu.io import text_grid
    from gol_tpu.obs import recorder as obs_recorder, trace as obs_trace

    repeats = args.repeats
    # Compute must dominate the fixed submit/route/poll overhead (the
    # fleet suite's lesson), but the suite also runs 2 lanes x (repeats+1)
    # rounds — 2500 keeps one round ~2-4s on CPU.
    gen_limit = args.gen_limit if args.gen_limit is not None else 2500
    side = 160
    freqs = (2, 3, 5, 9)  # 4 equal-work buckets (HRW-spread over 2 workers)
    per_bucket = 8
    max_batch = 8
    njobs = len(freqs) * per_bucket
    workroot = tempfile.mkdtemp(prefix="gol-bench-fleettrace-")
    print(
        f"bench fleettrace: {njobs} jobs across {len(freqs)} {side}^2 "
        f"buckets, gen_limit {gen_limit}, repeats {repeats}, 2 workers, "
        f"platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )
    boards = {
        freq: [text_grid.generate(side, side, seed=6000 + 100 * freq + i)
               for i in range(per_bucket)]
        for freq in freqs
    }

    def _http(method, url, body=None, timeout=120):
        return fleet_client.http_json(method, url, body, timeout=timeout)

    def submit_all(base: str) -> None:
        def one(freq_board):
            freq, board = freq_board
            status, payload = _http("POST", f"{base}/jobs", {
                "width": side, "height": side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": gen_limit,
                "similarity_frequency": freq,
            })
            if status != 202:
                raise RuntimeError(f"submit rejected HTTP {status}: {payload}")

        work = [(freq, b) for freq, bs in boards.items() for b in bs]
        with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
            list(pool.map(one, work))

    def completed(base: str) -> tuple[int, int]:
        _, snap = _http("GET", f"{base}/metrics?format=json")
        return (int(snap["counters"].get("jobs_completed_total", 0)),
                int(snap["counters"].get("jobs_failed_total", 0)))

    def run_round(base: str) -> float:
        done0, _ = completed(base)
        t0 = time.perf_counter()
        submit_all(base)
        while True:
            done, failed = completed(base)
            if failed:
                raise RuntimeError(f"{failed} job(s) FAILED")
            if done - done0 >= njobs:
                return time.perf_counter() - t0
            time.sleep(0.05)

    def boot(name: str, telemetry: bool):
        fleet_dir = os.path.join(workroot, f"fleet-{name}")
        serve_args = [
            "--flush-age", "0.2",
            "--max-batch", str(max_batch),
            "--pipeline-depth", "2",
            "--max-queue-depth", "4096",
        ]
        if telemetry:
            serve_args += ["--trace", os.path.join(workroot, "trace"),
                           "--metrics-history",
                           "--sample-interval", "0.25"]
        fleet = Fleet(fleet_dir, serve_args=serve_args)
        fleet.spawn_fleet(2)
        router = RouterServer(fleet, port=0)
        router.start()
        if telemetry:
            router.start_history(
                os.path.join(fleet_dir, "router-history"), interval=0.25
            )
        return router

    results = {}
    trace_dir = os.path.join(workroot, "trace")
    router_off = router_on = None
    try:
        router_off = boot("off", telemetry=False)
        router_on = boot("on", telemetry=True)
        obs_recorder.install(trace_dir)
        # Warm both lanes (every bucket compiles on its HRW owner). The ON
        # warm runs traced so the on-column rounds measure a steady state.
        run_round(router_off.url)
        obs_trace.enable()
        run_round(router_on.url)
        obs_trace.disable()
        off_runs, on_runs = [], []
        for _ in range(repeats):
            # Interleave off/on rounds: thermal/noisy-neighbor drift biases
            # both columns equally. The process-global tracer flag serves
            # the in-process ROUTER; each ON worker armed itself via
            # --trace at boot, each OFF worker never did.
            off_runs.append(run_round(router_off.url))
            obs_trace.enable()
            try:
                on_runs.append(run_round(router_on.url))
            finally:
                obs_trace.disable()
        off, on = min(off_runs), min(on_runs)
        results = {
            "off": {"seconds": round(off, 3),
                    "jobs_per_sec": round(njobs / off, 2)},
            "on": {"seconds": round(on, 3),
                   "jobs_per_sec": round(njobs / on, 2)},
        }
        print(
            f"  off {njobs / off:.1f} jobs/s, on {njobs / on:.1f} jobs/s "
            f"(ratio {(njobs / on) / (njobs / off):.4f})",
            file=sys.stderr,
        )
    finally:
        obs_trace.disable()
        obs_trace.clear()
        obs_recorder.uninstall()
        for router in (router_on, router_off):
            if router is not None:
                router.shutdown(cascade=True)
        shutil.rmtree(workroot, ignore_errors=True)

    ratio = results["on"]["jobs_per_sec"] / results["off"]["jobs_per_sec"]
    payload = {
        "metric": "fleet_telemetry_on_over_off_jobs_per_sec",
        "value": round(ratio, 4),
        "unit": "x",
        "vs_baseline": None,  # the off column IS the baseline; floor 0.97
        "load": {
            "jobs": njobs,
            "buckets": [f"{side}x{side}/sim{f}" for f in freqs],
            "per_bucket": per_bucket,
            "gen_limit": gen_limit,
            "max_batch": max_batch,
            "workers": 2,
            "note": "both lanes run real subprocess workers behind "
            "in-process routers; rounds interleave off/on. CI gates the "
            "absolute leaf with --metric lanes.on.jobs_per_sec",
        },
        "telemetry_on": [
            "router tracing + X-Gol-Trace propagation + submit/forward "
            "spans + flow starts",
            "worker --trace (span rings, flow adoption, flight recorder)",
            "worker --metrics-history partition rings (0.25s sampler)",
            "router merged/floored history ring (0.25s tick)",
        ],
        "lanes": results,
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r12.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if ratio >= 0.97 else 1


def _bench_wire(args) -> int:
    """Binary data plane suite (--suite wire) -> BENCH_r13.json.

    ISSUE 11's acceptance: bytes-on-wire per hop, submit->accepted
    latency, and router forward latency for text vs packed
    (application/x-gol-packed, io/wire.py) on 1024^2..4096^2 boards
    through a REAL 2-worker fleet (in-process workers behind an in-process
    router — the same rig the fleet tests drive, so every hop is the
    production code path: content negotiation at the worker, header-only
    placement + zero-copy forward at the router, packed CAS payloads).

    Measured per board size:

    - **bytes per hop**: client->router submit body (== router->worker:
      the raw buffer is forwarded verbatim, asserted), worker CAS entry
      on disk (meta + sidecar), worker->client result body. The headline
      is the 2048^2 round-trip ratio (text bytes / packed bytes), gated
      at >= 6x.
    - **submit->accepted latency**: POST /jobs RTT through the router,
      p50 per format lane (identical board seeds across lanes, so both
      formats move the same cell content; each lane gets a fresh rig so
      retained-job memory stays bounded).
    - **router forward latency**: through-router p50 minus direct-to-
      worker p50 — the router's own share, which for text includes
      JSON-parsing the multi-MB body and for packed reads ~24 bytes +
      meta. Gated: packed forward < text forward at 2048^2.

    Byte-identity is gated, not assumed: the same board submitted text
    and packed must fetch bit-identical grids through BOTH result
    formats (rc 1 otherwise, like every other gate).
    """
    import tempfile

    from gol_tpu.cache.store import CacheEntry, DiskCAS
    from gol_tpu.fleet import client as fleet_client
    from gol_tpu.fleet.router import RouterServer
    from gol_tpu.fleet.workers import Fleet
    from gol_tpu.io import text_grid, wire
    from gol_tpu.serve.server import GolServer

    if args.gen_limit is None:
        args.gen_limit = 1  # the data plane is the subject, not the engine
    sizes = (1024, 2048, 4096)
    iters = {1024: 9, 2048: 9, 4096: 3}

    tmp = tempfile.mkdtemp(prefix="gol_bench_wire_")
    rig_seq = [0]

    class _Rig:
        """One disposable 2-worker fleet. The single-process server keeps
        every job's board and result in memory for its life, so each
        measurement lane gets a FRESH rig and tears it down — peak RSS
        stays one lane's jobs, not the whole suite's (the compiled bucket
        programs are lru-cached module-wide, so rig churn pays no
        recompiles). No journal: journaling is format-independent text
        either way and only adds fsync noise to the RTTs under test."""

        def __init__(self):
            rig_seq[0] += 1
            self.workers = {}
            for wid in ("w0", "w1"):
                srv = GolServer(port=0, flush_age=0.01)
                srv.start()
                self.workers[wid] = srv
            self.fleet = Fleet(
                os.path.join(tmp, f"fleet{rig_seq[0]}")
            )
            for wid, srv in self.workers.items():
                self.fleet.attach(srv.url, wid)
            self.router = RouterServer(self.fleet, port=0, big_edge=8192)
            self.router.start()

        def close(self):
            self.router.shutdown(cascade=False)
            for srv in self.workers.values():
                srv.shutdown()

    def submit_text(base, board, seed_tag):
        body = {
            "width": board.shape[1], "height": board.shape[0],
            "cells": text_grid.encode(board).decode("ascii"),
            "gen_limit": args.gen_limit,
        }
        raw = json.dumps(body).encode("utf-8")
        t0 = time.perf_counter()
        status, _, resp = fleet_client.http_exchange(
            "POST", base + "/jobs", raw=raw, timeout=300)
        dt = time.perf_counter() - t0
        assert status == 202, (status, resp[:200])
        return json.loads(resp)["id"], len(raw), dt

    def submit_packed(base, board, seed_tag):
        raw = wire.encode_frame({"gen_limit": args.gen_limit}, grid=board)
        t0 = time.perf_counter()
        status, _, resp = fleet_client.http_exchange(
            "POST", base + "/jobs", raw=raw,
            content_type=wire.CONTENT_TYPE, timeout=300)
        dt = time.perf_counter() - t0
        assert status == 202, (status, resp[:200])
        return json.loads(resp)["id"], len(raw), dt

    def fetch(base, job_id, packed):
        deadline = time.perf_counter() + 300
        while time.perf_counter() < deadline:
            status, body = fleet_client.http_json(
                "GET", f"{base}/jobs/{job_id}", timeout=30)
            if status == 200 and body.get("state") == "done":
                break
            time.sleep(0.01)
        headers = {"Accept": wire.CONTENT_TYPE} if packed else None
        status, ctype, resp = fleet_client.http_exchange(
            "GET", f"{base}/result/{job_id}", timeout=30, headers=headers)
        assert status == 200, (status, resp[:200])
        if packed:
            assert wire.is_packed(ctype), ctype
            frame = wire.decode_frame(resp)
            return frame.grid(), len(resp)
        payload = json.loads(resp)
        grid = text_grid.decode(payload["grid"].encode("ascii"),
                                payload["width"], payload["height"])
        return np.asarray(grid), len(resp)

    def p50(vals):
        return sorted(vals)[len(vals) // 2]

    out_sizes = {}
    identity_ok = True
    identity_checked = 0
    for size in sizes:
        n = iters[size]
        lat = {"router_text": [], "router_packed": [],
               "direct_text": [], "direct_packed": []}
        bytes_rec = {}
        # One fresh rig per format lane (memory-bounded; same board seeds
        # across lanes, so both formats move the same cell content).
        for fmt, fn in (("text", submit_text), ("packed", submit_packed)):
            rig = _Rig()
            direct = rig.workers["w0"].url
            for i in range(n):
                board = text_grid.generate(size, size, seed=7000 + i)
                _, nbytes, dt = fn(rig.router.url, board, i)
                lat[f"router_{fmt}"].append(dt)
                bytes_rec[f"submit_{fmt}"] = nbytes
                _, _, dt = fn(direct, board, i)
                lat[f"direct_{fmt}"].append(dt)
            rig.close()
        # Byte-identity: ONE board, both formats, both result encodings.
        rig = _Rig()
        board = text_grid.generate(size, size, seed=99)
        jid_t, _, _ = submit_text(rig.router.url, board, "id")
        jid_p, _, _ = submit_packed(rig.router.url, board, "id")
        grid_tt, result_text_bytes = fetch(rig.router.url, jid_t, packed=False)
        grid_tp, result_packed_bytes = fetch(rig.router.url, jid_t, packed=True)
        grid_pt, _ = fetch(rig.router.url, jid_p, packed=False)
        grid_pp, _ = fetch(rig.router.url, jid_p, packed=True)
        rig.close()
        same = (np.array_equal(grid_tt, grid_tp)
                and np.array_equal(grid_tt, grid_pt)
                and np.array_equal(grid_tt, grid_pp))
        identity_ok = identity_ok and same
        identity_checked += 1
        bytes_rec["result_text"] = result_text_bytes
        bytes_rec["result_packed"] = result_packed_bytes
        # CAS bytes: the stored form of that result under each payload.
        entry = CacheEntry(grid=grid_tt, generations=args.gen_limit,
                           exit_reason="gen_limit")
        cas_bytes = {}
        for payload_kind in ("text", "packed"):
            cas_dir = os.path.join(tmp, f"cas_{payload_kind}_{size}")
            cas = DiskCAS(cas_dir, payload=payload_kind)
            cas.put("f" * 24, entry)
            total = 0
            for root, _dirs, files in os.walk(cas_dir):
                total += sum(os.path.getsize(os.path.join(root, f))
                             for f in files)
            cas_bytes[payload_kind] = total
        bytes_rec["cas_text"] = cas_bytes["text"]
        bytes_rec["cas_packed"] = cas_bytes["packed"]
        text_rt = bytes_rec["submit_text"] + bytes_rec["result_text"]
        packed_rt = bytes_rec["submit_packed"] + bytes_rec["result_packed"]
        fwd_text = p50(lat["router_text"]) - p50(lat["direct_text"])
        fwd_packed = p50(lat["router_packed"]) - p50(lat["direct_packed"])
        out_sizes[f"b{size}"] = {
            "bytes": {
                **bytes_rec,
                "ratio_submit": bytes_rec["submit_text"]
                / bytes_rec["submit_packed"],
                "ratio_result": bytes_rec["result_text"]
                / bytes_rec["result_packed"],
                "ratio_cas": bytes_rec["cas_text"] / bytes_rec["cas_packed"],
                "ratio_roundtrip": text_rt / packed_rt,
            },
            "latency": {
                "submit_text_p50_ms": p50(lat["router_text"]) * 1e3,
                "submit_packed_p50_ms": p50(lat["router_packed"]) * 1e3,
                "direct_text_p50_ms": p50(lat["direct_text"]) * 1e3,
                "direct_packed_p50_ms": p50(lat["direct_packed"]) * 1e3,
                "forward_text_ms": fwd_text * 1e3,
                "forward_packed_ms": fwd_packed * 1e3,
            },
        }
        s = out_sizes[f"b{size}"]
        print(
            f"  {size}^2: submit {bytes_rec['submit_text']} -> "
            f"{bytes_rec['submit_packed']} B "
            f"({s['bytes']['ratio_submit']:.1f}x), roundtrip "
            f"{s['bytes']['ratio_roundtrip']:.1f}x, forward "
            f"{s['latency']['forward_text_ms']:.1f} -> "
            f"{s['latency']['forward_packed_ms']:.1f} ms, "
            f"identity {'OK' if same else 'MISMATCH'}",
            file=sys.stderr,
        )

    head = out_sizes["b2048"]
    ratio = head["bytes"]["ratio_roundtrip"]
    fwd_win = (head["latency"]["forward_packed_ms"]
               < head["latency"]["forward_text_ms"])
    print(
        f"  headline: 2048^2 round-trip bytes {ratio:.1f}x smaller packed "
        f"(acceptance >= 6x), router forward win: {fwd_win}, "
        f"byte-identity: {identity_ok}",
        file=sys.stderr,
    )
    payload = {
        "metric": "wire_bytes_reduction_roundtrip_2048",
        "value": ratio,
        "unit": "x",
        "vs_baseline": ratio,  # text bytes over packed bytes; gated >= 6
        "sizes": out_sizes,
        "identity": {"checked": identity_checked, "ok": identity_ok},
        "gates": {
            "bytes_ratio_min": 6.0,
            "forward_latency_win": fwd_win,
        },
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r13.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if (ratio >= 6.0 and fwd_win and identity_ok) else 1


# Named measurement suites, table-driven: adding one is one line here (plus
# its _bench_* function) — no if/elif chain to grow. Each entry is
# (runner, one-line help shown by --list-suites). Suites pin their own
# workloads; the size/config resolution in main() is for the solo lanes.
def _bench_sparse(args) -> int:
    """Sparse tiled engine suite (--suite sparse) -> BENCH_r14.json.

    ISSUE 12's asymptotics claim: on sparse universes the dense engines
    cost O(area) per generation regardless of liveness, while the sparse
    lane costs O(active tiles). Fixed 5-glider load (the same five
    gliders, spread far apart so they never interact) on universes
    2^12^2 .. 2^16^2:

    - **sparse** lane at every size: per-generation wall time + the
      tiles-simulated counter (the load is ~5-20 active tiles at EVERY
      size, so sparse cost is flat while area grows 256x);
    - **dense** lane (the solo engine, kernel auto) where the canvas fits
      (2^12..2^14) — at 2^14^2 the occupancy is ~0.1%, far inside the
      <= 1% acceptance regime.

    Headline: dense/sparse per-generation ratio at 2^14^2, gated by exit
    code at >= 10x. CI gates the leaf via
    ``tools/bench_diff.py --metric sizes.u16384.ratio_dense_over_sparse``.
    """
    import jax

    from gol_tpu import engine
    from gol_tpu.config import GameConfig
    from gol_tpu.io import rle as rle_codec
    from gol_tpu.sparse import SparseBoard, TileMemo, simulate_sparse

    glider = rle_codec.read_file(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "patterns", "glider.rle")
    )
    tile = 256
    sparse_gens = 24
    dense_gens = 4
    sizes = [1 << 12, 1 << 13, 1 << 14, 1 << 15, 1 << 16]
    dense_max = 1 << 14
    config_for = lambda g: GameConfig(gen_limit=g)  # noqa: E731

    def five_gliders(u: int) -> SparseBoard:
        board = SparseBoard(u, u, tile)
        step = u // 5
        for k in range(5):
            board.place(glider, (k * step + step // 3) % (u - 8),
                        ((4 - k) * step + step // 2) % (u - 8))
        return board

    print(f"bench sparse: 5-glider load, tile {tile}, "
          f"platform={jax.devices()[0].platform}", file=sys.stderr)

    sizes_out = {}
    for u in sizes:
        board = five_gliders(u)
        occupancy = board.occupancy()
        # Warm the tile-step programs outside the timer (one compile per
        # ladder rung, paid once per process like every bucket program).
        simulate_sparse(five_gliders(u), config_for(1), TileMemo())
        t0 = time.perf_counter()
        result = simulate_sparse(board, config_for(sparse_gens), TileMemo())
        sparse_s = time.perf_counter() - t0
        assert result.generations == sparse_gens, result.generations
        entry = {
            "universe": f"{u}x{u}",
            "occupancy": occupancy,
            "sparse_s_per_gen": sparse_s / sparse_gens,
            "sparse_generations": sparse_gens,
            "tiles_simulated": result.stats.tiles_active,
            "tiles_per_generation": result.stats.tiles_per_generation(),
        }
        if u <= dense_max:
            dense_grid = board.to_dense()
            cfg = config_for(dense_gens)
            runner = engine.make_runner((u, u), cfg)
            device_grid = engine.put_grid(dense_grid)
            # Time the COMPILED executable: calling the jitted runner after
            # an AOT compile_runner would re-trace+re-compile inside the
            # timer and inflate the dense column (and so the gated ratio).
            compiled = engine.compile_runner(runner, device_grid)
            t0 = time.perf_counter()
            _final, gen = compiled(device_grid)
            gens = int(gen)  # blocks until the loop finishes
            dense_s = time.perf_counter() - t0
            assert gens == dense_gens, gens
            entry["dense_s_per_gen"] = dense_s / dense_gens
            entry["dense_generations"] = dense_gens
            entry["ratio_dense_over_sparse"] = (
                entry["dense_s_per_gen"] / entry["sparse_s_per_gen"]
            )
        print(
            f"  {u:>6}^2: sparse {entry['sparse_s_per_gen'] * 1000:9.2f} "
            f"ms/gen ({entry['tiles_per_generation']:.1f} tiles/gen, "
            f"occupancy {occupancy:.5f})"
            + (
                f"   dense {entry['dense_s_per_gen'] * 1000:9.2f} ms/gen "
                f"-> {entry['ratio_dense_over_sparse']:.1f}x"
                if "dense_s_per_gen" in entry else "   dense: skipped (area)"
            ),
            file=sys.stderr,
        )
        sizes_out[f"u{u}"] = entry

    headline = sizes_out["u16384"]["ratio_dense_over_sparse"]
    print(f"  sparse at 2^14^2 = {headline:.1f}x the dense engine per "
          f"generation (acceptance >= 10x)", file=sys.stderr)
    payload = {
        "metric": "sparse_speedup_16384",
        "value": headline,
        "unit": "x dense wall time per generation",
        "vs_baseline": headline / 10.0,  # over the acceptance floor
        "sizes": sizes_out,
        "load": {
            "pattern": "glider x5",
            "tile": tile,
            "sparse_generations": sparse_gens,
            "dense_generations": dense_gens,
        },
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r14.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if headline >= 10.0 else 1


def _bench_macro(args) -> int:
    """Hash-consed macrocell suite (--suite macro) -> BENCH_r19.json.

    ISSUE 17's deep-time claim: every per-generation engine costs
    Omega(gens), while the macrocell lane's memoized centered advance
    costs ~O(log gens) supersteps once the tree's working set is interned.
    The load is the Gosper gun — unbounded live growth (one glider every
    30 generations), so this is the HARD case for hashlife, not a still
    life it can collapse:

    - **macro** lane: the gun to 10^6 generations in a 2^20-per-side
      plane universe on a cold memo (fresh store, fresh CAS directory),
      plus a warm-restart lane (fresh process-local state, same CAS)
      showing the content tier eliminate the leaf device work;
    - **sparse** lane: the same gun, measured per-generation at a depth
      it can actually reach in bench time, then extrapolated LINEARLY to
      10^6 generations. The extrapolation is a deliberate lower bound on
      the true sparse cost: the glider stream grows the active-tile set
      linearly with depth, so real sparse cost is quadratic in
      generations — the reported ratio understates the win.

    Headline: sparse_estimated_s / macro_s at 10^6 generations, gated by
    exit code at >= 50x (the ISSUE 17 acceptance floor). CI gates the
    leaf via ``tools/bench_diff.py --metric lanes.macro.speedup_vs_sparse``.
    """
    import shutil
    import tempfile

    import jax

    from gol_tpu.config import GameConfig
    from gol_tpu.io import rle as rle_codec
    from gol_tpu.macro import MacroMemo, NodeStore, simulate_macro
    from gol_tpu.sparse import SparseBoard, TileMemo, simulate_sparse

    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "patterns", "gosper_gun.rle"),
              encoding="utf-8") as f:
        gun_rle = f.read()
    tile = 256
    macro_universe = 1 << 20
    macro_gens = 1_000_000
    sparse_universe = 1 << 13
    sparse_gens = 3000

    def gun_board(universe: int) -> SparseBoard:
        at = universe // 2
        return SparseBoard.from_rle(gun_rle, universe, universe, tile,
                                    x=at, y=at)

    print(f"bench macro: gosper gun, tile {tile}, "
          f"platform={jax.devices()[0].platform}", file=sys.stderr)

    # Sparse baseline: measured per-generation at a reachable depth. Warm
    # the tile-step programs outside the timer (one compile per ladder
    # rung, paid once per process) — the macro leaf base cases ride the
    # SAME compiled runners, so the warm-up serves both lanes.
    simulate_sparse(gun_board(sparse_universe), GameConfig(gen_limit=1),
                    TileMemo())
    t0 = time.perf_counter()
    sparse_result = simulate_sparse(gun_board(sparse_universe),
                                    GameConfig(gen_limit=sparse_gens),
                                    TileMemo())
    sparse_s = time.perf_counter() - t0
    assert sparse_result.generations == sparse_gens, sparse_result.generations
    sparse_s_per_gen = sparse_s / sparse_gens
    sparse_est_s = sparse_s_per_gen * macro_gens
    print(f"  sparse: {sparse_gens} generations in {sparse_s:.1f}s "
          f"({sparse_s_per_gen * 1000:.2f} ms/gen) -> linear lower bound "
          f"{sparse_est_s:.0f}s at {macro_gens} generations",
          file=sys.stderr)

    cas_dir = tempfile.mkdtemp(prefix="bench_macro_cas_")
    try:
        # Cold macro lane: fresh node store, fresh memo, empty CAS.
        memo = MacroMemo(NodeStore(tile), cas_dir=cas_dir)
        t0 = time.perf_counter()
        cold = simulate_macro(gun_board(macro_universe),
                              GameConfig(gen_limit=macro_gens), memo)
        macro_s = time.perf_counter() - t0
        assert cold.generations == macro_gens, cold.generations
        assert cold.exit_reason == "gen_limit", cold.exit_reason
        print(f"  macro (cold): {macro_gens} generations in {macro_s:.1f}s "
              f"({cold.stats.supersteps} supersteps, "
              f"{cold.stats.leaf_gen_steps} leaf device steps, "
              f"population {cold.board.population()})", file=sys.stderr)

        # Warm-restart lane: everything process-local discarded, only the
        # CAS directory survives (the serve-restart shape).
        memo2 = MacroMemo(NodeStore(tile), cas_dir=cas_dir)
        t0 = time.perf_counter()
        warm = simulate_macro(gun_board(macro_universe),
                              GameConfig(gen_limit=macro_gens), memo2)
        warm_s = time.perf_counter() - t0
        assert warm.board.population() == cold.board.population()
        print(f"  macro (warm CAS): rerun in {warm_s:.1f}s "
              f"({warm.stats.cas_hits} content hits, "
              f"{warm.stats.leaf_gen_steps} leaf device steps)",
              file=sys.stderr)
    finally:
        shutil.rmtree(cas_dir, ignore_errors=True)

    headline = sparse_est_s / macro_s
    print(f"  macro at 10^6 generations = {headline:.1f}x the sparse "
          f"lane's linear lower bound (acceptance >= 50x)", file=sys.stderr)
    payload = {
        "metric": "macro_deep_time_speedup",
        "value": headline,
        "unit": "x sparse wall time (linear lower bound) at 10^6 gens",
        "vs_baseline": headline / 50.0,  # over the acceptance floor
        "lanes": {
            "macro": {
                "universe": f"{macro_universe}x{macro_universe}",
                "generations": macro_gens,
                "cold_s": macro_s,
                "warm_cas_s": warm_s,
                "supersteps": cold.stats.supersteps,
                "leaf_gen_steps_cold": cold.stats.leaf_gen_steps,
                "leaf_gen_steps_warm": warm.stats.leaf_gen_steps,
                "cas_hits_warm": warm.stats.cas_hits,
                "population": cold.board.population(),
                "speedup_vs_sparse": headline,
            },
            "sparse": {
                "universe": f"{sparse_universe}x{sparse_universe}",
                "generations": sparse_gens,
                "measured_s": sparse_s,
                "s_per_gen": sparse_s_per_gen,
                "estimated_s_at_macro_gens": sparse_est_s,
                "extrapolation": "linear (lower bound; true cost is "
                                 "quadratic in the glider stream)",
            },
        },
        "load": {"pattern": "gosper_gun", "tile": tile},
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r19.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if headline >= 50.0 else 1


def _bench_shard(args) -> int:
    """Sharded single-job engine suite (--suite shard) -> BENCH_r20.json.

    ISSUE 18's strong-scaling question: one FIXED giant universe (2^16
    per side, a spread multi-glider load) split across N in {1, 2, 4}
    real `gol serve` workers by HRW tile ownership, driven through the
    router's shard coordinator lane — real HTTP step RPCs, real halo
    frames, real per-worker checkpoint fsyncs.

    The gated figure is **device-time** aggregate cell-updates/sec:
    cell updates (active tiles x tile^2, identical across lanes — the
    byte-exactness contract makes the active set partition-invariant,
    asserted here) divided by the MAKESPAN in per-worker CPU seconds
    (max over workers of /proc/<pid> utime+stime deltas around the
    timed job). Each worker is one emulated device: on a host with a
    core per worker this IS wall clock, and on the single-core CI host
    it still measures everything the shard tier controls — halo
    encode/exchange overhead, barrier bookkeeping, checkpoint encode,
    and HRW balance (imbalance inflates the max directly) — instead of
    measuring how many cores the CI box happens to have. Wall-clock
    seconds per lane are recorded alongside, un-gated.

    Headline: n4 aggregate rate over n1, gated by exit code at >= 2x
    (the ISSUE 18 acceptance floor: overhead + imbalance may cost at
    most half the ideal 4x). Per-lane rates land under lanes.shard_nN
    for `tools/bench_diff.py --metric lanes.shard_n4.cell_updates_per_sec`.
    Every lane's result board must be byte-identical (sha1 of the RLE)
    to every other lane's — a scaling number for a wrong board is
    noise, so the suite dies on digest drift.
    """
    import hashlib
    import shutil
    import tempfile

    import numpy as np

    import jax

    from gol_tpu.fleet import client as fleet_client
    from gol_tpu.fleet.router import RouterServer
    from gol_tpu.fleet.workers import Fleet, core_slice_prefix
    from gol_tpu.sparse import SparseBoard

    tile = 256
    universe = 1 << 16           # 256x256 tiles of 256^2
    gen_limit = args.gen_limit if args.gen_limit is not None else 48
    checkpoint_every = 16
    grid_n = 16                  # 16x16 gliders spread over the tile grid
    glider = np.zeros((3, 3), dtype=np.uint8)
    glider[0, 1] = glider[1, 2] = glider[2, 0] = glider[2, 1] = glider[2, 2] = 1

    board = SparseBoard(universe, universe, tile)
    for i in range(grid_n):
        for j in range(grid_n):
            arr = np.zeros((tile, tile), dtype=np.uint8)
            if (i + j) % 8 == 0:
                # A few gliders sit on a tile edge so halo frames carry
                # live rings (the rest keep the load HRW-balanceable).
                arr[1:4, 126:129] = glider
            else:
                arr[126:129, 126:129] = glider
            board.set_tile((8 + 15 * i, 8 + 15 * j), arr)
    rle = board.to_rle()
    cores = os.cpu_count() or 4
    pin = core_slice_prefix(max(1, min(6, (cores - 2) // 4)), cores)
    workroot = tempfile.mkdtemp(prefix="gol-bench-shard-")
    print(f"bench shard: {universe}^2 universe, {grid_n * grid_n} gliders, "
          f"gen_limit {gen_limit}, ckpt every {checkpoint_every}, "
          f"{cores} host core(s), platform={jax.devices()[0].platform}",
          file=sys.stderr)

    tck = float(os.sysconf("SC_CLK_TCK"))

    def cpu_seconds(pid: int) -> float:
        # utime+stime from /proc/<pid>/stat — fields 14/15, counted after
        # the ')' so a space in comm cannot shift the split.
        with open(f"/proc/{pid}/stat", "rb") as f:
            fields = f.read().rsplit(b")", 1)[1].split()
        return (int(fields[11]) + int(fields[12])) / tck

    def run_job(base: str, gens: int, ckpt: int) -> dict:
        status, payload = fleet_client.http_json("POST", f"{base}/jobs", {
            "shard": True, "rle": rle, "x": 0, "y": 0,
            "width": universe, "height": universe, "tile": tile,
            "convention": "c", "gen_limit": gens,
            "check_similarity": False, "checkpoint_every": ckpt,
        }, timeout=120)
        if status != 202:
            raise RuntimeError(f"shard submit HTTP {status}: {payload}")
        jid = payload["id"]
        while True:
            status, job = fleet_client.http_json(
                "GET", f"{base}/jobs/{jid}", timeout=30)
            if status == 200 and job.get("state") == "done":
                break
            if status != 200 or job.get("state") == "failed":
                raise RuntimeError(f"shard job {jid}: HTTP {status} {job}")
            time.sleep(0.05)
        status, result = fleet_client.http_json(
            "GET", f"{base}/result/{jid}", timeout=300)
        if status != 200:
            raise RuntimeError(f"shard result HTTP {status}: {result}")
        return result

    def shard_lane(n_workers: int) -> dict:
        fleet_dir = os.path.join(workroot, f"shard-n{n_workers}")
        fleet = Fleet(fleet_dir, spawn_prefix=pin)
        fleet.spawn_fleet(n_workers)
        router = RouterServer(fleet, port=0)
        router.start()
        try:
            # Warm lane: compiles the tile-step runner in EVERY worker
            # process and pages the RLE parse path, outside the meters.
            run_job(router.url, 4, 4)
            pids = {w.id: w.pid for w in fleet.shard_pool()}
            cpu0 = {wid: cpu_seconds(pid) for wid, pid in pids.items()}
            t0 = time.perf_counter()
            result = run_job(router.url, gen_limit, checkpoint_every)
            wall = time.perf_counter() - t0
            cpu = {wid: cpu_seconds(pid) - cpu0[wid]
                   for wid, pid in pids.items()}
        finally:
            router.shutdown(cascade=True)
        if result["generations"] != gen_limit or \
                result["exit_reason"] != "gen_limit":
            raise RuntimeError(f"shard lane n={n_workers}: unexpected exit "
                               f"{result['generations']}/"
                               f"{result['exit_reason']}")
        makespan = max(cpu.values())
        rate = result["cell_updates"] / makespan
        print(f"  shard n={n_workers}: {rate / 1e6:.1f}M cell-updates/s "
              f"(device makespan {makespan:.2f}s, wall {wall:.2f}s, "
              f"worker-cpu {' '.join(f'{wid}={s:.2f}' for wid, s in sorted(cpu.items()))}, "
              f"{result['supersteps']} supersteps)", file=sys.stderr)
        return {
            "workers": n_workers,
            "cell_updates": result["cell_updates"],
            "device_makespan_s": round(makespan, 3),
            "worker_cpu_s": {wid: round(s, 3)
                             for wid, s in sorted(cpu.items())},
            "wall_s": round(wall, 3),
            "supersteps": result["supersteps"],
            "ownership": result["ownership"],
            "cell_updates_per_sec": round(rate, 1),
            "digest": hashlib.sha1(
                result["rle"].encode("ascii")).hexdigest(),
        }

    lanes = {}
    try:
        for n in (1, 2, 4):
            lanes[f"shard_n{n}"] = shard_lane(n)
    finally:
        shutil.rmtree(workroot, ignore_errors=True)

    digests = {lane["digest"] for lane in lanes.values()}
    if len(digests) != 1:
        raise RuntimeError(f"result boards drifted across lanes: {digests}")
    updates = {lane["cell_updates"] for lane in lanes.values()}
    if len(updates) != 1:
        raise RuntimeError(f"active-tile work drifted across lanes "
                           f"(partition-variant active set): {updates}")

    scaling = (lanes["shard_n4"]["cell_updates_per_sec"]
               / lanes["shard_n1"]["cell_updates_per_sec"])
    print(f"  n4 over n1 aggregate = {scaling:.2f}x "
          f"(acceptance >= 2x)", file=sys.stderr)
    payload = {
        "metric": "shard_n4_over_n1_cell_updates_per_sec",
        "value": round(scaling, 3),
        "unit": "x",
        "vs_baseline": None,  # the n1 lane IS the baseline; floor is 2.0
        "load": {
            "universe": f"{universe}x{universe}",
            "tile": tile,
            "gliders": grid_n * grid_n,
            "gen_limit": gen_limit,
            "checkpoint_every": checkpoint_every,
            "host_cores": cores,
            "note": "device-time strong scaling: each worker is one "
            "emulated device; rates are cell updates over the MAX "
            "per-worker CPU-seconds delta (utime+stime), so the figure "
            "measures shard-tier overhead + HRW balance, not the CI "
            "host's core count — on a core-per-worker host it equals "
            "wall clock. wall_s per lane is recorded un-gated. Result "
            "boards sha1-compared across lanes.",
        },
        "lanes": lanes,
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r20.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if scaling >= 2.0 else 1


def _bench_chaos(args) -> int:
    """Chaos-hardened data path suite (--suite chaos) -> BENCH_r16.json.

    ISSUE 14's two-sided acceptance for the defensive machinery:

    - **overhead**: defenses ON (per-worker circuit breakers + the durable
      breaker ring, worker dispatch retry budgets, and an
      ``X-Gol-Deadline`` stamp on every submit) must cost <= 3% of the
      identical fault-free load with every defense OFF — the ratio
      defended/baseline jobs-per-sec is gated at >= 0.97;
    - **degradation**: the defended fleet with ONE worker's router->worker
      hop at 30% injected failure (``refuse=0.2,reset=0.1`` — hard
      connection kills: RSTs with zero response bytes, a third of them
      after half the response went out; NOTE both reach the router as a
      reset AFTER its request bytes left, so this lane exercises the
      ambiguous-504 contract — a true delivery-impossible spill
      (ECONNREFUSED on a closed port) cannot be produced by a proxy that
      has already accepted the connection, and is unit-pinned in
      tests/test_fleet.py instead) must keep goodput >= 70% of its own
      healthy number. This is the breaker's existence proof: open workers
      are ranked LAST, so the browned-out worker's share of the traffic
      spills to the healthy one instead of stalling the fleet, and
      half-open probes pull it back as soon as its hop answers.

    Both fleets are real subprocess workers behind in-process routers;
    overhead rounds interleave baseline/defended so machine drift lands
    on both columns (the fleettrace discipline). The headline is the
    overhead ratio; CI gates the absolute leaf with
    ``tools/bench_diff.py --metric lanes.defended.jobs_per_sec``.
    rc 0 iff overhead >= 0.97 AND degraded goodput >= 0.70x.
    """
    import concurrent.futures
    import shutil
    import tempfile

    import jax

    from gol_tpu.chaos import ChaosPlan, ProxyPool
    from gol_tpu.fleet import client as fleet_client
    from gol_tpu.fleet.breaker import BreakerConfig
    from gol_tpu.fleet.router import RouterServer
    from gol_tpu.fleet.workers import Fleet
    from gol_tpu.io import text_grid
    from gol_tpu.obs import propagate as obs_propagate
    from gol_tpu.obs.history import HistoryWriter

    repeats = args.repeats
    # The PR-8 fleet load shape (equal-work 160^2 buckets HRW-spread over
    # 2 workers), trimmed to 4 buckets x 8 jobs so three lanes x
    # (warm + repeats) rounds stay minutes, not tens of minutes. The
    # gen_limit is deliberately high for the degraded gate's honesty:
    # compute must dominate the round, so the injected faults' retry and
    # cooldown costs amortize the way they would on a real long-running
    # load rather than being measured against near-empty jobs.
    gen_limit = args.gen_limit if args.gen_limit is not None else 10000
    side = 160
    freqs = (2, 3, 5, 9)
    per_bucket = 8
    max_batch = 8
    njobs = len(freqs) * per_bucket
    # 30% hard failure on the victim's hop: refuse (RST before the request
    # is read) + reset (RST mid-response). Both are resets AFTER the
    # router's bytes went out, i.e. the ambiguous-504 lane — the accepting
    # proxy cannot fake a closed-port ECONNREFUSED, so the
    # delivery-impossible spill path is covered by unit tests, not here.
    degraded_plan = "seed=777,refuse=0.2,reset=0.1"
    deadline_s = 600.0  # generous: the stamp/decrement/enforce path runs
    # every hop, but nothing expires on the fault-free lane.
    workroot = tempfile.mkdtemp(prefix="gol-bench-chaos-")
    print(
        f"bench chaos: {njobs} jobs across {len(freqs)} {side}^2 buckets, "
        f"gen_limit {gen_limit}, repeats {repeats}, 2 workers/lane, "
        f"platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )
    boards = {
        freq: [text_grid.generate(side, side, seed=7000 + 100 * freq + i)
               for i in range(per_bucket)]
        for freq in freqs
    }
    work = [(freq, b) for freq, bs in boards.items() for b in bs]

    class _OneWorkerChaos(ProxyPool):
        """The degraded lane's mount: chaos fronts exactly ONE worker's
        hop; every other upstream resolves direct."""

        def __init__(self, plan: ChaosPlan, victim_url: str):
            super().__init__(plan)
            self._victim = victim_url.rstrip("/")

        def url_for(self, upstream_url: str) -> str:
            if upstream_url.rstrip("/") != self._victim:
                return upstream_url
            return super().url_for(upstream_url)

    def submit_one(base: str, freq, board, defended: bool) -> str:
        """One board -> one accepted job id, riding the documented fault
        contracts (ambiguous 504: resubmit knowingly; transient
        5xx/connection trouble: re-send)."""
        headers = None
        if defended:
            headers = {obs_propagate.DEADLINE_HEADER:
                       obs_propagate.encode_deadline(deadline_s)}
        body = {
            "width": side, "height": side,
            "cells": text_grid.encode(board).decode("ascii"),
            "gen_limit": gen_limit,
            "similarity_frequency": freq,
        }
        for _ in range(60):
            try:
                status, payload = fleet_client.http_json(
                    "POST", f"{base}/jobs", body, headers=headers,
                    timeout=60)
            except (OSError, ConnectionError):
                time.sleep(0.05)
                continue
            if status == 202 and isinstance(payload, dict):
                return payload["id"]
            if status in (504, 503, 502, 429):
                time.sleep(0.05)
                continue
            raise RuntimeError(f"submit rejected HTTP {status}: {payload}")
        raise RuntimeError("a submit never landed after 60 tries")

    def run_round(base: str, defended: bool) -> float:
        """Submit the whole load, wait until every accepted id is DONE ->
        seconds. Goodput counts the njobs the CALLER wanted; orphans an
        ambiguous 504 left behind burn worker time and slow this clock,
        which is exactly what goodput means."""
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
            ids = list(pool.map(
                lambda fb: submit_one(base, fb[0], fb[1], defended), work))
        pending = set(ids)
        while pending:
            for job_id in list(pending):
                try:
                    status, payload = fleet_client.http_json(
                        "GET", f"{base}/jobs/{job_id}", timeout=30)
                except (OSError, ConnectionError):
                    continue  # the faulty hop: ask again
                if status != 200 or not isinstance(payload, dict):
                    continue
                state = payload.get("state")
                if state == "done":
                    pending.discard(job_id)
                elif state in ("failed", "cancelled"):
                    raise RuntimeError(f"job {job_id} ended {state}")
            if pending:
                time.sleep(0.05)
        return time.perf_counter() - t0

    def boot(name: str, defended: bool, chaos_for=None) -> RouterServer:
        """One lane's fleet + router. ``chaos_for`` is an optional
        ``fleet -> ProxyPool`` factory, called after the workers spawn —
        the degraded lane's victim URL only exists then."""
        fleet_dir = os.path.join(workroot, f"fleet-{name}")
        serve_args = [
            "--flush-age", "0.2",
            "--max-batch", str(max_batch),
            "--pipeline-depth", "2",
            "--max-queue-depth", "4096",
        ]
        if defended:
            serve_args += ["--retry-budget", "50"]
        fleet = Fleet(fleet_dir, serve_args=serve_args)
        fleet.spawn_fleet(2)
        kwargs = {}
        if defended:
            kwargs = {
                "breakers": True,
                "breaker_config": BreakerConfig(cooldown_s=1.0),
                "breaker_history": HistoryWriter(
                    os.path.join(fleet_dir, "breaker-history"),
                    source="breaker"),
            }
        chaos = chaos_for(fleet) if chaos_for is not None else None
        router = RouterServer(fleet, port=0, chaos=chaos, **kwargs)
        router.start()
        return router

    results = {}
    chaos_stats = {}
    router_base = router_def = router_deg = None
    try:
        # -- overhead: baseline vs defended, rounds interleaved ----------
        router_base = boot("baseline", defended=False)
        router_def = boot("defended", defended=True)
        run_round(router_base.url, defended=False)  # warm (HRW compiles)
        run_round(router_def.url, defended=True)
        base_runs, def_runs = [], []
        for _ in range(repeats):
            base_runs.append(run_round(router_base.url, defended=False))
            def_runs.append(run_round(router_def.url, defended=True))
        base_s, def_s = min(base_runs), min(def_runs)
        router_base.shutdown(cascade=True)
        router_base = None

        # -- degradation: the defended config + 30% chaos on one hop -----
        def degraded_chaos(fleet) -> _OneWorkerChaos:
            victim = sorted(fleet.workers(), key=lambda w: w.id)[0]
            return _OneWorkerChaos(ChaosPlan.parse(degraded_plan),
                                   victim.url)

        router_deg = boot("degraded", defended=True,
                          chaos_for=degraded_chaos)
        chaos_pool = router_deg.chaos
        # Two warm rounds: the second covers the spill compiles (buckets
        # the victim owns land on the healthy worker while the breaker
        # holds the victim open).
        run_round(router_deg.url, defended=True)
        run_round(router_deg.url, defended=True)
        deg_runs = [run_round(router_deg.url, defended=True)
                    for _ in range(repeats)]
        deg_s = min(deg_runs)
        chaos_stats = chaos_pool.stats()
        breaker_final = router_deg.breaker_states()

        results = {
            "baseline": {"seconds": round(base_s, 3),
                         "jobs_per_sec": round(njobs / base_s, 2)},
            "defended": {"seconds": round(def_s, 3),
                         "jobs_per_sec": round(njobs / def_s, 2)},
            "degraded": {"seconds": round(deg_s, 3),
                         "jobs_per_sec": round(njobs / deg_s, 2)},
        }
    finally:
        for router in (router_deg, router_def, router_base):
            if router is not None:
                router.shutdown(cascade=True)
        shutil.rmtree(workroot, ignore_errors=True)

    overhead = results["defended"]["jobs_per_sec"] / results["baseline"][
        "jobs_per_sec"]
    goodput = results["degraded"]["jobs_per_sec"] / results["defended"][
        "jobs_per_sec"]
    print(
        f"  baseline {results['baseline']['jobs_per_sec']:.1f} jobs/s, "
        f"defended {results['defended']['jobs_per_sec']:.1f} jobs/s "
        f"(overhead ratio {overhead:.4f}, floor 0.97)",
        file=sys.stderr,
    )
    print(
        f"  degraded {results['degraded']['jobs_per_sec']:.1f} jobs/s = "
        f"{goodput:.2f}x defended (floor 0.70) under {degraded_plan} on "
        f"one hop; injected faults {chaos_stats}; final breakers "
        f"{breaker_final}",
        file=sys.stderr,
    )
    payload = {
        "metric": "chaos_defended_over_baseline_jobs_per_sec",
        "value": round(overhead, 4),
        "unit": "x",
        "vs_baseline": None,  # the baseline lane IS the off column
        "degraded_over_defended": round(goodput, 4),
        "gates": {"overhead_floor": 0.97, "degraded_goodput_floor": 0.70},
        "load": {
            "jobs": njobs,
            "buckets": [f"{side}x{side}/sim{f}" for f in freqs],
            "per_bucket": per_bucket,
            "gen_limit": gen_limit,
            "max_batch": max_batch,
            "workers": 2,
            "note": "real subprocess workers behind in-process routers; "
            "overhead rounds interleave baseline/defended. CI gates the "
            "absolute leaf with --metric lanes.defended.jobs_per_sec",
        },
        "defenses_on": [
            "router per-worker circuit breakers + durable breaker ring",
            "worker dispatch retry budget (--retry-budget 50)",
            f"X-Gol-Deadline stamped per submit ({deadline_s:.0f}s budget)",
        ],
        "chaos": {
            "plan": degraded_plan,
            "scope": "one worker's router->worker hop (the other direct)",
            "observed_faults": chaos_stats,
        },
        "lanes": results,
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r16.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    print(json.dumps(payload))
    return 0 if overhead >= 0.97 and goodput >= 0.70 else 1


def _bench_storage(args) -> int:
    """Storage-lifecycle suite (--suite storage) -> BENCH_r17.json.

    Measures what bounding the journal costs the hot path: the same
    churn load (240 jobs, 64^2 boards, short requests — the serving
    shape that writes the most journal bytes per unit compute) through a
    journaled scheduler with (a) the classic unbounded single-file
    journal and (b) segment rotation + a concurrent compaction ticker
    (the gol-serve-sampler's idle-time pass, run at bench cadence).

    Acceptance (exit-code gated): compaction-on steady-state throughput
    >= 0.97x compaction-off, AND the on-lane's durable footprint ends
    bounded (snapshot + live file; at most one uncompacted segment)
    while replaying state-identical to the unbounded log. CI gates the
    throughput leaf via ``--metric lanes.compaction_on.jobs_per_sec``.
    """
    import shutil
    import tempfile
    import threading

    import jax

    from gol_tpu.serve import compaction
    from gol_tpu.serve.jobs import DONE, FAILED, JobJournal, new_job
    from gol_tpu.serve.metrics import Metrics
    from gol_tpu.serve.scheduler import Scheduler

    size, njobs = 64, 240
    gen_limit = args.gen_limit if args.gen_limit is not None else 4
    rng = np.random.default_rng(17)
    boards = [rng.integers(0, 2, size=(size, size), dtype=np.uint8)
              for _ in range(njobs)]
    print(
        f"bench storage: {njobs} jobs of {size}x{size}, "
        f"gen_limit={gen_limit}, platform={jax.devices()[0].platform}",
        file=sys.stderr,
    )

    def submit_all(scheduler):
        jobs = [scheduler.submit(
            new_job(size, size, b, gen_limit=gen_limit)) for b in boards]
        while any(j.state not in (DONE, FAILED) for j in jobs):
            time.sleep(0.002)
        assert all(j.state == DONE for j in jobs)
        return jobs

    def run_lane(segment_bytes, compact_interval=None):
        workdir = tempfile.mkdtemp(prefix="gol-bench-storage-")
        journal = JobJournal(workdir, segment_bytes=segment_bytes)
        scheduler = Scheduler(journal=journal, metrics=Metrics(),
                              flush_age=0.01)
        scheduler.start()
        stop = threading.Event()
        compactions = [0]

        def ticker():
            while not stop.wait(compact_interval):
                if journal.compact().compacted:
                    compactions[0] += 1

        t = None
        if compact_interval is not None:
            t = threading.Thread(target=ticker, daemon=True)
            t.start()
        t0 = time.perf_counter()
        submit_all(scheduler)
        elapsed = time.perf_counter() - t0
        scheduler.stop()
        if t is not None:
            stop.set()
            t.join(timeout=10)
            if journal.compact().compacted:  # the final idle pass
                compactions[0] += 1
        journal.close()
        state = JobJournal(workdir, segment_bytes=0).replay()
        result = {
            "jobs_per_sec": njobs / elapsed,
            "elapsed_s": elapsed,
            "journal_bytes_end": journal.bytes_on_disk(),
            "sealed_segments_end": len(
                compaction.sealed_segments(workdir)),
            "compactions": compactions[0],
            "replayed_results": len(state.results),
            "replay_torn_lines": state.torn_lines,
        }
        shutil.rmtree(workdir, ignore_errors=True)
        return result

    # Warm the compiled bucket program outside every timer.
    warm = Scheduler(metrics=Metrics(), flush_age=0.01)
    warm.start()
    submit_all(warm)
    warm.stop()

    repeats = min(args.repeats, 3)
    lanes = {}
    for name, seg, interval in (
        ("compaction_off", 0, None),
        ("compaction_on", 128 << 10, 0.1),
    ):
        best = None
        for _ in range(repeats):
            result = run_lane(seg, interval)
            assert result["replayed_results"] == njobs, result
            assert result["replay_torn_lines"] == 0, result
            if best is None or result["jobs_per_sec"] > best["jobs_per_sec"]:
                best = result
        lanes[name] = best
        print(
            f"  {name:>15}: {best['elapsed_s'] * 1000:8.1f} ms -> "
            f"{best['jobs_per_sec']:7.1f} jobs/s, journal ends at "
            f"{best['journal_bytes_end']} bytes "
            f"({best['sealed_segments_end']} sealed segment(s), "
            f"{best['compactions']} compaction(s))",
            file=sys.stderr,
        )

    ratio = (lanes["compaction_on"]["jobs_per_sec"]
             / lanes["compaction_off"]["jobs_per_sec"])
    bounded = (lanes["compaction_on"]["sealed_segments_end"] <= 1
               and lanes["compaction_on"]["compactions"] >= 1)
    print(f"  compaction-on/off throughput ratio {ratio:.3f} "
          f"(acceptance >= 0.97), footprint bounded: {bounded}",
          file=sys.stderr)
    payload = {
        "metric": "storage_compaction_on_over_off",
        "value": ratio,
        "unit": "ratio",
        "vs_baseline": ratio,  # gated at >= 0.97
        "lanes": lanes,
        "bounded": bounded,
        "load": {"jobs": njobs, "grid": f"{size}x{size}",
                 "gen_limit": gen_limit,
                 "segment_bytes": 128 << 10},
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r17.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    return 0 if (ratio >= 0.97 and bounded) else 1


def _bench_control(args) -> int:
    """Horizontal control plane suite (--suite control) -> BENCH_r18.json.

    The router-tier scaling question: does adding a router replica add
    FORWARD throughput? One real fleet — 4 `gol serve` worker
    subprocesses behind `gol fleet --routers 2` — takes a small batch of
    jobs to completion, then a fixed client pool hammers the read
    forward path (`GET /jobs/<id>`: router -> owning worker -> back,
    the cheapest request that still exercises the full proxy hop) in
    two lanes:

    - **routers1**: every client thread targets the primary router
      alone — the single-router ceiling (one ThreadingHTTPServer
      process, ~one core of parse/forward/serialize);
    - **routers2**: the same pool splits round-robin across both
      replicas — both read the same manifest, either can look up any
      job, so the tier's capacity should approach 2x.

    The routers are real subprocesses (the lanes must scale across
    PROCESSES, not threads under one GIL); the 4 workers leave the
    worker tier with comfortable headroom so the router is the
    bottleneck in both lanes. Every measured GET must return 200 — an
    error-count gate keeps a flaky lane from inflating the ratio.

    Headline: routers2/routers1 forwards/sec (the replication
    acceptance, >= 1.8x). Per-lane forwards/sec recorded for
    `tools/bench_diff.py --metric` gating (CI gates
    --metric lanes.routers2.forwards_per_sec). rc 0 iff the headline
    clears 1.8 and both lanes are error-free.

    The scaling gate needs a host that can EXPRESS router-tier
    parallelism: two router processes plus workers plus the client
    pool require >= 3 usable cores (the fleet suite's taskset-pinned
    lanes have the same dependency). On a smaller host the two lanes
    time-slice one core and the ratio measures scheduler overhead, not
    the tier — the suite still runs both lanes and writes the
    artifact, but stamps ``gate.enforced: false`` with the reason and
    gates only on error-free lanes (never silently passes the ratio:
    the stamp makes a degenerate artifact impossible to misread as a
    scaling claim).
    """
    import concurrent.futures
    import shutil
    import signal as _signal
    import socket
    import subprocess
    import tempfile
    import threading

    from gol_tpu.fleet import client as fleet_client
    from gol_tpu.io import text_grid

    repeats = args.repeats
    gen_limit = args.gen_limit if args.gen_limit is not None else 64
    side = 32
    njobs = 16
    clients = 16
    window = 2.5  # seconds per measured round
    workroot = tempfile.mkdtemp(prefix="gol-bench-control-")
    fleet_dir = os.path.join(workroot, "fleet")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    r0_url = f"http://127.0.0.1:{port}"
    print(
        f"bench control: {clients} client threads over {njobs} done jobs, "
        f"{window}s windows, repeats {repeats}, 4 workers / 2 routers",
        file=sys.stderr,
    )

    def _http(method, url, body=None, timeout=30):
        return fleet_client.http_json(method, url, body, timeout=timeout)

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "gol_tpu", "fleet",
         "--port", str(port), "--workers", "4", "--routers", "2",
         "--fleet-dir", fleet_dir, "--flush-age", "0.05",
         "--health-interval", "1.0"],
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.perf_counter() + 300
        while True:
            if proc.poll() is not None:
                raise RuntimeError(f"fleet died on boot rc={proc.returncode}")
            try:
                status, payload = _http("GET", f"{r0_url}/healthz", timeout=2)
                if (status == 200
                        and payload.get("fleet", {}).get("workers") == 4):
                    break
            except (OSError, ValueError):
                pass
            if time.perf_counter() > deadline:
                raise RuntimeError("fleet never became healthy")
            time.sleep(0.2)
        advert_path = os.path.join(fleet_dir, "routers", "r1", "advert.json")
        deadline = time.perf_counter() + 120
        while True:
            try:
                with open(advert_path, encoding="utf-8") as f:
                    r1_url = json.load(f)["url"]
                status, payload = _http("GET", f"{r1_url}/healthz", timeout=2)
                if status == 200:
                    break
            except (OSError, ValueError, KeyError):
                pass
            if time.perf_counter() > deadline:
                raise RuntimeError("replica r1 never came up")
            time.sleep(0.2)

        # A small batch of jobs, run to DONE: the lookup targets. The
        # measured op is read-only, so both lanes forward identical work.
        ids = []
        for i in range(njobs):
            board = text_grid.generate(side, side, seed=8000 + i)
            status, payload = _http("POST", f"{r0_url}/jobs", {
                "width": side, "height": side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": gen_limit,
            })
            if status != 202:
                raise RuntimeError(f"submit rejected HTTP {status}")
            ids.append(payload["id"])
        deadline = time.perf_counter() + 300
        pending = set(ids)
        while pending:
            if time.perf_counter() > deadline:
                raise RuntimeError(f"{len(pending)} seed job(s) never DONE")
            for job_id in list(pending):
                status, payload = _http("GET", f"{r0_url}/jobs/{job_id}")
                if status == 200 and payload.get("state") == "done":
                    pending.discard(job_id)
            time.sleep(0.05)

        def lane(bases: list) -> dict:
            stop = threading.Event()
            counts = [0] * clients
            errors = [0] * clients

            def worker(k: int) -> None:
                base = bases[k % len(bases)]
                job_id = ids[k % len(ids)]
                n = 0
                while not stop.is_set():
                    try:
                        status, _ = _http(
                            "GET", f"{base}/jobs/{job_id}", timeout=10)
                    except (OSError, ValueError):
                        status = 0
                    if status == 200:
                        counts[k] += 1
                    else:
                        errors[k] += 1
                    n += 1
                    job_id = ids[(k + n * len(bases)) % len(ids)]

            best = None
            for _ in range(repeats + 1):  # first round doubles as warm-up
                stop.clear()
                counts[:] = [0] * clients
                errors[:] = [0] * clients
                pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=clients)
                futs = [pool.submit(worker, k) for k in range(clients)]
                t0 = time.perf_counter()
                time.sleep(window)
                stop.set()
                for fut in futs:
                    fut.result()
                elapsed = time.perf_counter() - t0
                pool.shutdown()
                rate = sum(counts) / elapsed
                if sum(errors):
                    raise RuntimeError(
                        f"{sum(errors)} forward(s) failed in a measured "
                        "round — the lane is not clean")
                best = rate if best is None else max(best, rate)
            tag = f"{len(bases)} router(s)"
            print(f"  {tag}: {best:.0f} forwards/s", file=sys.stderr)
            return {
                "routers": len(bases),
                "forwards_per_sec": round(best, 1),
                "window_seconds": window,
                "client_threads": clients,
            }

        lanes = {
            "routers1": lane([r0_url]),
            "routers2": lane([r0_url, r1_url]),
        }
    finally:
        if proc.poll() is None:
            proc.send_signal(_signal.SIGTERM)
            try:
                proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(workroot, ignore_errors=True)

    ratio = (lanes["routers2"]["forwards_per_sec"]
             / lanes["routers1"]["forwards_per_sec"])
    usable = len(os.sched_getaffinity(0))
    enforced = usable >= 3
    print(f"  routers2/routers1 forward ratio {ratio:.2f} "
          "(acceptance >= 1.8)", file=sys.stderr)
    if not enforced:
        print(f"  GATE NOT ENFORCED: {usable} usable core(s) — two router "
              "processes cannot scale on a time-sliced core; the ratio "
              "above measures the scheduler, not the tier", file=sys.stderr)
    payload = {
        "metric": "routers2_over_routers1_forwards_per_sec",
        "value": round(ratio, 3),
        "unit": "x",
        "vs_baseline": None,  # the routers1 lane IS the baseline; floor 1.8
        "gate": {
            "floor": 1.8,
            "enforced": enforced,
            **({} if enforced else {
                "reason": f"{usable} usable core(s); router-tier "
                "parallelism needs >= 3 (2 router processes + workers + "
                "client pool)"}),
        },
        "load": {
            "jobs": njobs, "grid": f"{side}x{side}", "gen_limit": gen_limit,
            "client_threads": clients, "window_seconds": window,
            "workers": 4,
            "note": "read forward path (GET /jobs/<id>) against DONE jobs "
            "— router parse/forward/serialize is the measured cost; 4 "
            "workers keep the worker tier out of the bottleneck",
        },
        "lanes": lanes,
        "env": _env_stamp(),
    }
    artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r18.json")
    with open(artifact, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {artifact}", file=sys.stderr)
    return 0 if (ratio >= 1.8 or not enforced) else 1


SUITES = {
    "control": (
        _bench_control,
        "horizontal control plane: forward throughput (GET /jobs/<id> "
        "through the proxy hop) with the client pool on one router vs "
        "split across two replicas of a real `gol fleet --routers 2` "
        "(acceptance: routers2 >= 1.8x routers1, error-free lanes; CI "
        "gates --metric lanes.routers2.forwards_per_sec); writes "
        "BENCH_r18.json",
    ),
    "storage": (
        _bench_storage,
        "storage lifecycle: churn-load throughput with journal "
        "segmentation + concurrent compaction vs the unbounded journal "
        "(acceptance: on >= 0.97x off AND the footprint stays bounded; "
        "CI gates --metric lanes.compaction_on.jobs_per_sec); writes "
        "BENCH_r17.json",
    ),
    "autoscale": (
        _bench_autoscale,
        "elastic fleet: a min=1/max=4 autoscaled fleet under a step-load "
        "spike vs the static n=1 fleet — steady-state aggregate jobs/sec "
        ">= 2x gated, with the scale-up decision series, scale-down to "
        "the floor, an exactly-once audit across all journal partitions, "
        "and oracle-gated samples through both scale events (CI gates "
        "--metric lanes.autoscaled.jobs_per_sec); writes BENCH_r15.json",
    ),
    "batch": (
        _bench_batch,
        "boards/sec and occupancy through the serve batcher at B in "
        "{1, 8, 64} on 256^2 boards (the amortized-dispatch serving win)",
    ),
    "chaos": (
        _bench_chaos,
        "chaos-hardened data path: defenses ON (breakers + retry budgets "
        "+ deadline stamps, no faults) vs OFF on the 2-worker fleet load "
        "(acceptance: >= 0.97x), plus a degraded lane with one worker's "
        "hop at 30% injected failure (acceptance: goodput >= 0.70x "
        "defended; CI gates --metric lanes.defended.jobs_per_sec); "
        "writes BENCH_r16.json",
    ),
    "cache": (
        _bench_cache,
        "content-addressed result cache on a Zipf-repeat load (128 jobs / "
        "16 unique 256^2 boards): cold engine path vs warm hit path vs "
        "in-flight coalescing, hit-path latency vs engine-path latency "
        "(acceptance: warm >= 10x cold); writes BENCH_r11.json",
    ),
    "sparse": (
        _bench_sparse,
        "sparse tiled engine: per-generation wall time dense vs sparse on "
        "a fixed 5-glider load over 2^12^2..2^16^2 universes with "
        "tiles-simulated counters (acceptance: sparse >= 10x dense at "
        "2^14^2, <= 1% occupancy; CI gates "
        "--metric sizes.u16384.ratio_dense_over_sparse); writes "
        "BENCH_r14.json",
    ),
    "macro": (
        _bench_macro,
        "hash-consed macrocell deep time: the Gosper gun to 10^6 "
        "generations in a 2^20^2 plane universe on a cold memo + a "
        "warm-CAS restart lane, vs the sparse lane's per-generation cost "
        "extrapolated linearly (a deliberate lower bound — true sparse "
        "cost is quadratic in the glider stream); acceptance: macro >= "
        "50x the sparse lower bound, exit-code gated (CI gates --metric "
        "lanes.macro.speedup_vs_sparse); writes BENCH_r19.json",
    ),
    "shard": (
        _bench_shard,
        "sharded single-job engine: one fixed 2^16^2 multi-glider "
        "universe split across N in {1, 2, 4} real workers by HRW tile "
        "ownership through the router's shard coordinator (real halo "
        "frames + checkpoint fsyncs); device-time aggregate "
        "cell-updates/sec, byte-identical boards across lanes "
        "(acceptance: n4 >= 2x n1; CI gates "
        "--metric lanes.shard_n4.cell_updates_per_sec); writes "
        "BENCH_r20.json",
    ),
    "tune": (
        _bench_tune,
        "tuned-vs-default via gol_tpu/tune on two engine shapes + the serve "
        "bucket geometry; writes BENCH_r06.json",
    ),
    "pipeline": (
        _bench_pipeline,
        "async-pipeline overlap: checkpointed wall-clock sync vs async "
        "writer at --checkpoint-every 8 (2048^2/4096^2) and serve "
        "boards/sec at pipeline depth 1 vs 2; writes BENCH_r07.json",
    ),
    "megabatch": (
        _bench_megabatch,
        "resident mega-batch engine: marginal kernel rate vs end-to-end "
        "serve rate at pipeline depth {1, 2, 4} and the resident ring, "
        "with the dispatch-gap ratio; writes BENCH_r08.json",
    ),
    "fleet": (
        _bench_fleet,
        "sharded-fleet scaling: aggregate jobs/sec through the router at "
        "N in {1, 2, 4} core-pinned workers vs the single-process resident "
        "lane on 16 equal-work 160^2 buckets; writes BENCH_r10.json",
    ),
    "telemetry": (
        _bench_telemetry,
        "telemetry overhead on the megabatch serve load: tracing + SLO "
        "engine + dispatch-gap sampler on vs off (acceptance: on >= 0.97x "
        "off); writes BENCH_r09.json",
    ),
    "wire": (
        _bench_wire,
        "binary data plane: bytes-on-wire per hop, submit latency, and "
        "router forward latency for text vs packed wire frames on "
        "1024^2..4096^2 boards through a real 2-worker fleet (acceptance: "
        ">= 6x round-trip bytes at 2048^2 + a packed forward-latency win "
        "+ byte-identical results; CI gates the headline or "
        "--metric sizes.b2048.bytes.ratio_roundtrip); writes BENCH_r13.json",
    ),
    "fleettrace": (
        _bench_fleettrace,
        "fleet-observability overhead: trace propagation + spans + durable "
        "metrics history on vs off through a real 2-worker fleet "
        "(acceptance: on >= 0.97x off; CI gates "
        "--metric lanes.on.jobs_per_sec); writes BENCH_r12.json",
    ),
}


def resolve_workload(args, n_devices: int | None = None) -> None:
    """Resolve --config presets and the default workload, in that order.

    Mutates ``args`` in place. Order matters (pinned by tests): presets fully
    determine size/mesh/gen-limit/lane, so the default-size rules only apply
    when neither --size nor --config was given. ``n_devices`` is injectable
    for tests; by default it is read from jax lazily and only when a preset
    names a mesh.
    """
    if args.config:
        # (size, mesh, gen_limit); mesh None = single device. Configs needing
        # more devices than available fall back to fewer mesh cells loudly.
        preset = {
            1: (512, None, 1000),
            2: (4096, None, 1000),
            3: (8192, "2x2", 1000),
            4: (16384, None, 1000),
            5: (65536, "4x4", 10000),
        }[args.config]
        args.size, args.mesh, args.gen_limit = preset
        if args.config == 5:
            # 65536^2 as bytes is 4.3GB — past HBM next to the word buffers.
            args.packed_state = True
        if args.mesh:
            if n_devices is None:
                import jax

                n_devices = len(jax.devices())
            r, c = (int(x) for x in args.mesh.split("x"))
            if r * c > n_devices:
                print(
                    f"config {args.config} wants a {args.mesh} mesh but only "
                    f"{n_devices} device(s) are attached; running single-device",
                    file=sys.stderr,
                )
                args.mesh = None

    if args.size is None:
        # Default workload (no --size, no --config): the north-star 65536^2
        # grid on the packed-state lane (the only lane where it fits HBM —
        # the uint8 form is 4.3GB). Byte-grid modes (kernel table, halo
        # latency, oracle verification) and ANY explicit --kernel — packed
        # included, so kernels are compared on the same byte-lane workload —
        # default to 16384.
        if args.compare or args.halo or args.verify or args.kernel is not None:
            args.size = 16384
        else:
            args.size = 65536
            args.packed_state = True


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--size",
        type=int,
        default=None,
        help="grid side length (default: 65536 on the packed-state lane — "
        "the BASELINE.md north-star grid, and the best amortization of the "
        "~90ms fixed per-call tunnel dispatch, measured +22%% over the byte "
        "lane's 32768 HBM ceiling; --compare/--halo/--verify and explicit "
        "--kernel default to 16384 on the byte lane instead)",
    )
    parser.add_argument(
        "--gen-limit", type=int, default=None,
        help="generations per run (default: 1000, the reference GEN_LIMIT; "
        "--suite batch defaults to 4 — short serving-shaped requests)",
    )
    parser.add_argument(
        "--kernel", default=None, help="auto | lax | pallas | packed (default: best)"
    )
    parser.add_argument("--mesh", default=None, help="RxC device mesh (default: single)")
    parser.add_argument(
        "--repeats",
        type=int,
        default=5,
        help="timed runs; the metric takes the best (the remote-attach tunnel "
        "adds tens of ms of per-call dispatch jitter, so more repeats tighten "
        "the min)",
    )
    parser.add_argument(
        "--verify",
        action="store_true",
        help="after timing, check the final grid against the NumPy oracle "
        "(implied by --config 1; slow for large grids)",
    )
    parser.add_argument(
        "--config",
        type=int,
        choices=range(1, 6),
        help="BASELINE.md config preset (overrides size/mesh/gen-limit): "
        "1=512^2 oracle-checked, 2=4096^2 single chip, 3=8192^2 2x2 mesh, "
        "4=16384^2 similarity path, 5=65536^2 4x4 mesh 10000 gens",
    )
    parser.add_argument(
        "--suite",
        choices=sorted(SUITES),
        default=None,
        help="named measurement suite (see --list-suites)",
    )
    parser.add_argument(
        "--list-suites",
        action="store_true",
        help="print the available suites and exit",
    )
    parser.add_argument(
        "--halo",
        action="store_true",
        help="measure halo-exchange p50 latency (BASELINE.md secondary metric) "
        "instead of cell throughput; needs a >1-device mesh",
    )
    parser.add_argument(
        "--compare",
        action="store_true",
        help="kernel-only table: every single-chip evolve path at --size "
        "(Pallas band kernels vs jnp fallbacks vs lax)",
    )
    parser.add_argument(
        "--packed-state",
        action="store_true",
        help="carry bitpacked uint32 word state end-to-end (the engine form "
        "behind the CLI's --packed-io): the uint8 grid never exists, so "
        "grids whose byte form exceeds HBM (65536^2) still bench; implied "
        "by --config 5; excludes --verify",
    )
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_suites:
        for name in sorted(SUITES):
            print(f"{name}\t{SUITES[name][1]}")
        return 0
    _honor_platform_env()
    if args.suite:
        return SUITES[args.suite][0](args)
    if args.gen_limit is None:
        args.gen_limit = 1000
    resolve_workload(args)

    if (args.compare or args.packed_state) and args.size % 32 != 0:
        # After --config unpacking so presets are covered too.
        print(f"word-state lanes (--compare/--packed-state) need --size "
              f"divisible by 32 (the packed word width), got {args.size}",
              file=sys.stderr)
        return 1
    if args.compare:
        return _bench_compare(args)

    if args.halo:
        return _bench_halo(args)

    import jax

    from gol_tpu import engine
    from gol_tpu.config import GameConfig
    from gol_tpu.parallel.mesh import make_mesh

    mesh = None
    n_chips = 1
    if args.mesh:
        r, c = (int(x) for x in args.mesh.split("x"))
        mesh = make_mesh(r, c)
        n_chips = r * c

    if args.packed_state and (args.verify or args.config == 1):
        print("--packed-state has no oracle lane; drop --verify "
              "(--config 1 implies the oracle check)", file=sys.stderr)
        return 1
    if args.packed_state and args.kernel not in (None, "packed"):
        # Word state admits only the packed kernel; mirror the CLI's loud
        # --packed-io + --kernel rejection rather than silently ignoring.
        print(f"--packed-state runs the packed kernel; drop --kernel "
              f"{args.kernel}", file=sys.stderr)
        return 1

    kernel = (
        "packed" if args.packed_state
        else resolve_kernel_name(args.kernel, args.size, mesh)
    )
    platform = jax.devices()[0].platform
    print(
        f"bench: {args.size}x{args.size}, gen_limit={args.gen_limit}, "
        f"kernel={'packed-state' if args.packed_state else kernel}, "
        f"platform={platform}, chips={n_chips}",
        file=sys.stderr,
    )

    rng = np.random.default_rng(42)
    # Random soup never stabilizes within 1000 generations, so the full
    # GEN_LIMIT runs with the similarity machinery still on the critical path
    # (the honest workload: src/game.c:6-9 constants, all checks enabled).
    config = GameConfig(gen_limit=args.gen_limit)

    if args.packed_state:
        # Uniform random words == uniform random cells; 32x less host memory
        # and transfer than the byte grid (512MB vs 4.3GB at 65536^2).
        words = rng.integers(
            0, np.iinfo(np.uint32).max, size=(args.size, args.size // 32),
            dtype=np.uint32, endpoint=True,
        )
        import jax.numpy as jnp

        from gol_tpu.parallel.mesh import grid_sharding

        device_grid = (
            jax.device_put(words, grid_sharding(mesh))
            if mesh is not None else jnp.asarray(words)
        )
        runner = engine.make_packed_runner((args.size, args.size), config, mesh)
    else:
        grid = rng.integers(0, 2, size=(args.size, args.size), dtype=np.uint8)
        device_grid = engine.put_grid(grid, mesh)
        # 'auto' (not the pre-resolved name) when the user named no kernel:
        # auto builds the _KernelFallback ladder, so a Mosaic compile
        # failure demotes like the CLI path; an explicit --kernel stays
        # strict — silent demotion would mislabel the bench.
        runner = engine.make_runner(grid.shape, config, mesh,
                                    args.kernel or "auto")
    # compile_runner, not runner.lower(): on a fallback-ladder runner a
    # Mosaic compile failure must demote (packed -> packed-jnp -> lax)
    # exactly as the CLI path does, not crash the bench.
    compiled = engine.compile_runner(runner, device_grid)
    # Post-compile, the ladder has settled: report the kernel that will
    # actually be measured (a demotion makes the pre-resolved header line
    # stale), and carry it in the JSON record.
    kernel = getattr(runner, "kernel_name", kernel)
    print(f"bench: compiled kernel={kernel}", file=sys.stderr)

    best_s = float("inf")
    generations = 0
    for i in range(args.repeats):
        t0 = time.perf_counter()
        final, gen = compiled(device_grid)
        # int(gen) blocks until the compiled program (the whole generation
        # loop) finishes; fetching the grid itself is the write phase's job
        # (and drags the full array over the wire on remote-attached TPUs).
        generations = int(gen)
        elapsed = time.perf_counter() - t0
        best_s = min(best_s, elapsed)
        print(
            f"  run {i}: {elapsed * 1000:.1f} ms, {generations} generations",
            file=sys.stderr,
        )

    if args.verify or args.config == 1:
        from gol_tpu import oracle

        expect = oracle.run(grid, config)
        final_np = np.asarray(jax.device_get(final), dtype=np.uint8)
        ok = (
            np.array_equal(final_np, expect.grid)
            and generations == expect.generations
        )
        print(f"oracle check: {'OK' if ok else 'MISMATCH'}", file=sys.stderr)
        if not ok:
            return 1

    cell_updates = args.size * args.size * generations
    value = cell_updates / best_s / n_chips
    print(
        json.dumps(
            {
                "metric": "cell_updates_per_sec_per_chip",
                "value": value,
                "unit": "cells/s/chip",
                "vs_baseline": value / TARGET_CELL_UPDATES_PER_SEC_PER_CHIP,
                # workload pin: round-over-round values are only comparable
                # at the same grid (the default moved 8192 -> 16384 -> 32768
                # across rounds as the kernels outgrew dispatch overhead)
                "grid": f"{args.size}x{args.size}",
                "chips": n_chips,
                # The post-compile (ladder-settled) kernel actually measured.
                "kernel": kernel,
                "env": _env_stamp(args.mesh),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
