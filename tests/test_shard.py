"""Sharded single-job engine (gol_tpu/shard) tests.

The acceptance surface of ISSUE 18:

- HRW tile ownership: total, deterministic, order-independent, and
  MINIMALLY disruptive — adding a worker moves only tiles the joiner
  now owns, retiring one moves exactly its tiles and nothing else;
- halo-neighbor map two-sided consistency: what A sends to B is exactly
  the ring set B's ghost assembly needs, for every ordered pair across
  every moved boundary;
- byte-identity (cells, generations, exit_reason) of a sharded run at
  N in {2, 3} against the single-process sparse engine — glider, Gosper
  gun, and r-pentomino loads, both conventions, all three exit reasons;
- SIGKILL-mid-super-step replay: a killed worker's shard replays from
  its own journal at the durable super-step, the survivors rewind in
  memory, and the finished board is still byte-identical;
- owned-filtered RLE loading: a worker owning one slice of a 2^20-wide
  document loads only its tiles;
- ghost-ring stepping: step_tiles over a partition's shards with halo
  ghosts unions to the solo step, byte for byte.
"""

import threading
import time

import numpy as np
import pytest

from gol_tpu.config import Convention, GameConfig
from gol_tpu.shard import halo
from gol_tpu.shard.coordinator import LocalCluster, ShardCoordinator
from gol_tpu.shard.partition import Partition, moved_tiles, tile_label
from gol_tpu.shard.worker import ShardHost
from gol_tpu.sparse import SparseBoard, SparseStats, TileMemo, simulate_sparse
from gol_tpu.sparse.engine import step_tiles

GLIDER_RLE = "x = 3, y = 3, rule = B3/S23\nbob$2bo$3o!"
RPENTO_RLE = "x = 3, y = 3\nb2o$2o$bo!"
DOMINO_RLE = "x = 2, y = 1\n2o!"  # dies in one generation -> empty
BLOCK_RLE = "x = 2, y = 2\n2o$2o!"  # still life -> similar
GOSPER_RLE = """x = 36, y = 9, rule = B3/S23
24bo$22bobo$12b2o6b2o12b2o$11bo3bo4b2o12b2o$2o8bo5bo3b2o$2o8bo3bob2o4b
obo$10bo5bo7bo$11bo3bo$12b2o!"""

H = W = 768
TILE = 256


def _ids(n):
    return [f"w{i}" for i in range(n)]


def _all_coords(part):
    return [(ty, tx) for ty in range(part.tiles_y)
            for tx in range(part.tiles_x)]


# ---------------------------------------------------------------------------
# HRW tile ownership


class TestPartition:
    def test_ownership_total_deterministic_order_independent(self):
        a = Partition(_ids(3), 8, 8)
        b = Partition(list(reversed(_ids(3))), 8, 8)
        for coord in _all_coords(a):
            owner = a.owner(coord)
            assert owner in a.worker_ids
            assert b.owner(coord) == owner  # id-set, not id-order

    def test_join_moves_only_tiles_the_joiner_now_owns(self):
        old = Partition(_ids(3), 16, 16)
        new = Partition(_ids(4), 16, 16)
        coords = _all_coords(old)
        moved = moved_tiles(old, new, coords)
        assert moved, "a 4th worker must win some tiles"
        for coord in moved:
            assert new.owner(coord) == "w3", (
                f"{coord} moved between SURVIVORS "
                f"({old.owner(coord)} -> {new.owner(coord)}) — HRW "
                "minimal disruption broken"
            )
        for coord in set(coords) - moved:
            assert new.owner(coord) == old.owner(coord)

    def test_retire_moves_exactly_the_departed_workers_tiles(self):
        old = Partition(_ids(3), 16, 16)
        new = Partition(["w0", "w2"], 16, 16)
        coords = _all_coords(old)
        moved = moved_tiles(old, new, coords)
        assert moved == {c for c in coords if old.owner(c) == "w1"}

    def test_for_universe_rejects_indivisible_geometry(self):
        with pytest.raises(ValueError):
            Partition.for_universe(_ids(2), 1000, 1024, 256)

    def test_label_is_stable(self):
        # The HRW key: labels are the placement contract — changing the
        # format reshuffles every deployed shard map.
        assert tile_label(3, 17) == "tile:3:17"


# ---------------------------------------------------------------------------
# Halo-neighbor map: two-sided consistency


class TestHaloMap:
    def _shard_boards(self, part):
        """Per-worker boards holding ONLY owned tiles (the production
        shape — each ShardHost loads its slice), every ring live."""
        boards = {wid: SparseBoard(part.tiles_y * TILE,
                                   part.tiles_x * TILE, TILE)
                  for wid in part.worker_ids}
        for coord in _all_coords(part):
            boards[part.owner(coord)].set_tile(
                coord, np.ones((TILE, TILE), dtype=np.uint8))
        return boards

    @pytest.mark.parametrize("n", [2, 3, 4])
    def test_sent_set_equals_needed_set_for_every_pair(self, n):
        part = Partition(_ids(n), 6, 6)
        boards = self._shard_boards(part)
        coords = set(_all_coords(part))
        sent = {wid: halo.outgoing(boards[wid], part, wid)
                for wid in part.worker_ids}
        for a in part.worker_ids:
            for b in part.worker_ids:
                if a == b:
                    continue
                got = set((sent[a].get(b) or {}).keys())
                # Sender's view: my tiles with a neighbor owned by b.
                want_send = {
                    c for c in coords
                    if part.owner(c) == a
                    and any(part.owner(nc) == b
                            for nc in part.neighbors(c))
                }
                # Receiver's view: a's tiles adjacent to MY tiles — the
                # rings b's ghost assembly will look up. The 8-neighbor
                # torus relation is symmetric, so the two sides must
                # name the same set; a mismatch is a halo deadlock (b
                # waits for a ring a never sends) or a wrong board.
                want_recv = {
                    nc
                    for c in coords if part.owner(c) == b
                    for nc in part.neighbors(c) if part.owner(nc) == a
                }
                assert got == want_send == want_recv, (a, b)

    def test_moved_boundary_recomputes_consistently_on_both_sides(self):
        old = Partition(_ids(3), 6, 6)
        new = Partition(_ids(4), 6, 6)
        boards = self._shard_boards(new)
        moved = moved_tiles(old, new, _all_coords(old))
        assert moved
        sent = {wid: halo.outgoing(boards[wid], new, wid)
                for wid in new.worker_ids}
        for coord in moved:
            for nc in new.neighbors(coord):
                a, b = new.owner(nc), new.owner(coord)
                if a == b:
                    continue
                # Every cross-owner edge of a moved tile appears in the
                # new sender's map toward the new owner...
                assert nc in sent[a][b], (coord, nc)
                # ...and the moved tile itself flows back the other way.
                assert coord in sent[b][a], (coord, nc)

    def test_dead_rings_are_not_sent(self):
        part = Partition(_ids(2), 3, 3)
        board = SparseBoard(3 * TILE, 3 * TILE, TILE)
        arr = np.zeros((TILE, TILE), dtype=np.uint8)
        arr[100:103, 100:103] = 1  # interior only: ring all-dead
        for coord in _all_coords(part):
            board.set_tile(coord, arr)
        for wid in part.worker_ids:
            assert not any(halo.outgoing(board, part, wid).values()), (
                "all-dead rings were sent — a remote live tile with a "
                "dead ring must be indistinguishable from an absent one"
            )

    def test_halo_frame_round_trip(self):
        part = Partition(_ids(2), 3, 3)
        board = self._shard_boards(part)["w0"]
        out = halo.outgoing(board, part, "w0")
        (peer, entries), = out.items()
        raw = halo.encode("job", 7, "w0", entries, TILE)
        meta, rings = halo.decode(raw)
        assert (meta["job"], meta["step"], meta["from"]) == ("job", 7, "w0")
        assert set(rings) == set(entries)
        for coord, ring in rings.items():
            for side, arr in zip(ring._fields, ring):
                np.testing.assert_array_equal(
                    arr, getattr(entries[coord], side))


# ---------------------------------------------------------------------------
# Byte-identity against the single-process sparse engine

_SOLO_CACHE: dict = {}


def _solo(rle, conv, gen_limit, x=250, y=250, h=H, w=W):
    key = (rle, conv, gen_limit, x, y, h, w)
    if key not in _SOLO_CACHE:
        cfg = GameConfig(gen_limit=gen_limit, convention=conv)
        board = SparseBoard.from_rle(rle, height=h, width=w, tile=TILE,
                                     x=x, y=y)
        res = simulate_sparse(board, cfg, TileMemo())
        _SOLO_CACHE[key] = (res.board.to_rle(), res.generations,
                            res.exit_reason)
    return _SOLO_CACHE[key]


def _shard_run(tmp_path, rle, conv, n, gen_limit, x=250, y=250, h=H, w=W,
               checkpoint_every=8):
    cfg = GameConfig(gen_limit=gen_limit, convention=conv)
    cluster = LocalCluster(_ids(n), journal_root=str(tmp_path))
    spec = {"rle": rle, "x": x, "y": y, "height": h, "width": w,
            "tile": TILE, "convention": conv, "gen_limit": gen_limit,
            "check_similarity": cfg.check_similarity,
            "similarity_frequency": cfg.similarity_frequency}
    coord = ShardCoordinator("job", spec, cluster.participants(),
                             checkpoint_every=checkpoint_every)
    return coord.run()


class TestByteIdentity:
    @pytest.mark.parametrize("conv", [Convention.C, Convention.CUDA])
    @pytest.mark.parametrize("n", [2, 3])
    def test_glider_gen_limit(self, tmp_path, conv, n):
        res = _shard_run(tmp_path, GLIDER_RLE, conv, n, 40)
        assert (res["rle"], res["generations"], res["exit_reason"]) == \
            _solo(GLIDER_RLE, conv, 40)

    @pytest.mark.parametrize("conv", [Convention.C, Convention.CUDA])
    @pytest.mark.parametrize("n", [2, 3])
    def test_gosper_gun(self, tmp_path, conv, n):
        res = _shard_run(tmp_path, GOSPER_RLE, conv, n, 36)
        assert (res["rle"], res["generations"], res["exit_reason"]) == \
            _solo(GOSPER_RLE, conv, 36)

    @pytest.mark.parametrize("conv", [Convention.C, Convention.CUDA])
    @pytest.mark.parametrize("n", [2, 3])
    def test_r_pentomino(self, tmp_path, conv, n):
        res = _shard_run(tmp_path, RPENTO_RLE, conv, n, 40)
        assert (res["rle"], res["generations"], res["exit_reason"]) == \
            _solo(RPENTO_RLE, conv, 40)

    @pytest.mark.parametrize("conv", [Convention.C, Convention.CUDA])
    def test_exit_empty(self, tmp_path, conv):
        res = _shard_run(tmp_path, DOMINO_RLE, conv, 2, 40)
        ref = _solo(DOMINO_RLE, conv, 40)
        assert ref[2] == "empty"  # the load must actually die
        assert (res["rle"], res["generations"], res["exit_reason"]) == ref

    @pytest.mark.parametrize("conv", [Convention.C, Convention.CUDA])
    def test_exit_similar(self, tmp_path, conv):
        res = _shard_run(tmp_path, BLOCK_RLE, conv, 2, 40)
        ref = _solo(BLOCK_RLE, conv, 40)
        assert ref[2] == "similar"
        assert (res["rle"], res["generations"], res["exit_reason"]) == ref

    def test_pattern_straddling_worker_boundary(self, tmp_path):
        # The r-pentomino dead on a tile corner: its growth crosses every
        # adjacent tile, so wrong/missing halos show up immediately.
        res = _shard_run(tmp_path, RPENTO_RLE, Convention.C, 3, 32,
                         x=TILE - 1, y=TILE - 1)
        assert (res["rle"], res["generations"], res["exit_reason"]) == \
            _solo(RPENTO_RLE, Convention.C, 32, x=TILE - 1, y=TILE - 1)


# ---------------------------------------------------------------------------
# SIGKILL-mid-super-step replay


class TestKillRestore:
    @pytest.mark.parametrize("conv", [Convention.C, Convention.CUDA])
    def test_killed_worker_replays_only_its_shard(self, tmp_path, conv):
        gen_limit = 40
        cfg = GameConfig(gen_limit=gen_limit, convention=conv)
        cluster = LocalCluster(_ids(3), journal_root=str(tmp_path))
        spec = {"rle": GLIDER_RLE, "x": 250, "y": 250, "height": H,
                "width": W, "tile": TILE, "convention": conv,
                "gen_limit": gen_limit,
                "check_similarity": cfg.check_similarity,
                "similarity_frequency": cfg.similarity_frequency}
        coord = ShardCoordinator("job", spec, cluster.participants(),
                                 checkpoint_every=4, probe_interval=0.05,
                                 recover_timeout=30)
        out: dict = {}
        t = threading.Thread(target=lambda: out.update(res=coord.run()))
        t.start()
        deadline = time.perf_counter() + 60
        while coord.k < 9:  # past the durable floor at 8, mid-super-step
            assert time.perf_counter() < deadline, "never reached step 9"
            assert t.is_alive(), "coordinator died before the kill"
            time.sleep(0.01)
        cluster.kill("w1")
        time.sleep(0.2)
        cluster.respawn("w1")  # fresh host, same journal dir
        t.join(timeout=120)
        assert not t.is_alive(), "coordinator hung after the kill"
        res = out["res"]
        assert res["recoveries"] >= 1, "the kill was never exercised"
        assert (res["rle"], res["generations"], res["exit_reason"]) == \
            _solo(GLIDER_RLE, conv, gen_limit)

    def test_respawned_host_restores_from_its_own_journal_only(
            self, tmp_path):
        # Direct host-level pin of "replays ONLY its shard": the restore
        # payload names a step; the fresh host rebuilds from the ckpt
        # record in ITS journal dir and answers status at that step.
        cfg = GameConfig(gen_limit=8, convention=Convention.C)
        cluster = LocalCluster(_ids(2), journal_root=str(tmp_path))
        spec = {"rle": GLIDER_RLE, "x": 250, "y": 250, "height": H,
                "width": W, "tile": TILE, "convention": Convention.C,
                "gen_limit": 8,
                "check_similarity": cfg.check_similarity,
                "similarity_frequency": cfg.similarity_frequency}
        coord = ShardCoordinator("job", spec, cluster.participants(),
                                 checkpoint_every=4)
        coord.run()
        # The job is finished; a fresh process on w1's journal can still
        # restore the durable step-4 checkpoint of w1's shard.
        cluster.kill("w1")
        host = cluster.respawn("w1")
        assert isinstance(host, ShardHost)
        reply = host.restore_job({
            "job": "job", "spec": spec, "self": "w1",
            "workers": _ids(2), "step": 4,
            "peers": {"w0": "local://w0"},
        })
        assert reply["step"] == 4
        status = host.status("job")
        assert status["known"] and status["step"] == 4


# ---------------------------------------------------------------------------
# Owned-filtered RLE loading (the giant-document slice contract)


class TestOwnedLoading:
    def test_owned_filter_loads_only_the_slice(self):
        full = SparseBoard.from_rle(GLIDER_RLE, height=H, width=W,
                                    tile=TILE, x=10, y=10)
        assert set(full.tiles) == {(0, 0)}
        sliced = SparseBoard.from_rle(
            GLIDER_RLE, height=H, width=W, tile=TILE, x=10, y=10,
            owned=lambda c: c == (0, 0))
        np.testing.assert_array_equal(sliced.tiles[(0, 0)],
                                      full.tiles[(0, 0)])
        empty = SparseBoard.from_rle(
            GLIDER_RLE, height=H, width=W, tile=TILE, x=10, y=10,
            owned=lambda c: c == (1, 1))
        assert not empty.tiles

    def test_two_to_the_twenty_document_loads_on_a_slice_owner(self):
        # A WHOLE-universe 2^20-per-side document: the glider sits half a
        # million blank rows and columns into the text itself (giant run
        # counts, not x/y placement), and a worker owning one 256^2 tile
        # of it must load just that slice.
        side = 1 << 20  # 4096x4096 tiles: far past any dense guard
        half = side // 2
        doc = (f"x = {side}, y = {side}\n"
               f"{half}${half}bbob${half}b2bo${half}b3o!")
        board = SparseBoard.from_rle(
            doc, height=side, width=side, tile=TILE,
            owned=lambda c: c == (half // TILE, half // TILE))
        assert set(board.tiles) == {(half // TILE, half // TILE)}
        assert board.population() == 5

    def test_partitioned_load_is_a_partition_of_the_full_load(self):
        part = Partition(_ids(3), H // TILE, W // TILE)
        full = SparseBoard.from_rle(GOSPER_RLE, height=H, width=W,
                                    tile=TILE, x=300, y=300)
        shards = {
            wid: SparseBoard.from_rle(GOSPER_RLE, height=H, width=W,
                                      tile=TILE, x=300, y=300,
                                      owned=part.owns(wid))
            for wid in part.worker_ids
        }
        seen = {}
        for wid, shard in shards.items():
            for coord, arr in shard.tiles.items():
                assert part.owner(coord) == wid
                assert coord not in seen
                seen[coord] = arr
        assert set(seen) == set(full.tiles)
        for coord, arr in seen.items():
            np.testing.assert_array_equal(arr, full.tiles[coord])


# ---------------------------------------------------------------------------
# Ghost-ring stepping: the distributed step IS the solo step


class TestGhostStep:
    def test_partitioned_step_unions_to_the_solo_step(self):
        part = Partition(_ids(3), H // TILE, W // TILE)
        solo = SparseBoard.from_rle(RPENTO_RLE, height=H, width=W,
                                    tile=TILE, x=TILE - 1, y=TILE - 1)
        want, _ = step_tiles(solo, TileMemo(), SparseStats())

        shards = {
            wid: SparseBoard.from_rle(RPENTO_RLE, height=H, width=W,
                                      tile=TILE, x=TILE - 1, y=TILE - 1,
                                      owned=part.owns(wid))
            for wid in part.worker_ids
        }
        merged = SparseBoard(H, W, TILE)
        for wid, shard in shards.items():
            ghost: dict = {}
            for other, board in shards.items():
                if other != wid:
                    ghost.update(
                        halo.outgoing(board, part, other).get(wid) or {})
            stepped, _ = step_tiles(shard, TileMemo(), SparseStats(),
                                    ghost=ghost, owned=part.owns(wid))
            for coord, arr in stepped.tiles.items():
                merged.set_tile(coord, arr)
        assert merged.to_rle() == want.to_rle()
