"""Pallas kernel tests (interpret mode on CPU; the compiled path runs on TPU).

Mirrors the differential-oracle strategy of SURVEY.md §4: the Pallas stencil
must agree with the NumPy oracle cell-for-cell, and its fused flags must agree
with the flags the engine would compute separately.
"""

import numpy as np
import pytest

from gol_tpu import engine, oracle
from gol_tpu.config import GameConfig
from gol_tpu.ops import get_kernel, resolve_kernel
from gol_tpu.ops.stencil_pallas import _pick_band, _step, supports
from gol_tpu.parallel.mesh import SINGLE_DEVICE, Topology

import jax.numpy as jnp


@pytest.mark.parametrize(
    "shape", [(8, 128), (16, 128), (128, 128), (64, 256), (24, 384)]
)
def test_step_matches_oracle(shape):
    rng = np.random.default_rng(7)
    g = rng.integers(0, 2, size=shape, dtype=np.uint8)
    new, alive, similar = _step(jnp.asarray(g), interpret=True)
    expect = oracle.evolve(g)
    np.testing.assert_array_equal(np.asarray(new), expect)
    assert bool(alive) == bool(expect.any())
    assert bool(similar) == bool(np.array_equal(expect, g))


def test_flags_on_still_life_and_empty():
    g = np.zeros((16, 128), np.uint8)
    g[4:6, 4:6] = 1  # block still life
    _, alive, similar = _step(jnp.asarray(g), interpret=True)
    assert bool(alive) and bool(similar)

    _, alive, similar = _step(jnp.asarray(np.zeros((16, 128), np.uint8)), interpret=True)
    assert not bool(alive)
    assert bool(similar)  # empty -> empty is a fixed point


def test_multi_generation_engine_run():
    """Full while_loop engine with the pallas kernel vs the oracle."""
    rng = np.random.default_rng(11)
    g = rng.integers(0, 2, size=(32, 128), dtype=np.uint8)
    config = GameConfig(gen_limit=50)
    expect = oracle.run(g, config)
    got = engine.simulate(g, config, kernel="pallas")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


def test_band_picker_divides():
    for h in (8, 16, 120, 4096, 8192):
        band = _pick_band(h, 4096)
        assert h % band == 0 and band % 8 == 0


def test_supports_gating():
    assert supports(4096, 4096, SINGLE_DEVICE)
    assert not supports(30, 30, SINGLE_DEVICE)  # default grid: lane-misaligned
    # Distributed shards run the same band kernel when the LOCAL shape tiles.
    assert supports(4096, 4096, Topology(shape=(2, 2), axes=("row", "col")))
    assert not supports(30, 128, Topology(shape=(2, 2), axes=("row", "col")))


def test_auto_resolution_on_cpu():
    # Tests run on CPU: auto must not pick the (interpret-only) byte pallas
    # kernel, but packed still wins where it fits — its off-TPU hot paths are
    # the jnp adder network, 18x the lax stencil on CPU at 4096².
    assert resolve_kernel("auto", 4096, 4096, SINGLE_DEVICE).name == "packed"
    # Widths that don't pack fall back to lax, never pallas.
    assert resolve_kernel("auto", 4096, 4090, SINGLE_DEVICE).name == "lax"
    # Lane-misaligned heights on one device can't tile the compiled Pallas
    # kernel but still pack: the jnp word network, not byte lax (r4 verdict
    # weak #5 — distributed shards always had this; now single-device too).
    assert resolve_kernel("auto", 30, 4096, SINGLE_DEVICE).name == "packed-jnp"
    assert resolve_kernel("auto", 100, 128, SINGLE_DEVICE).name == "packed-jnp"
    assert get_kernel("pallas").name == "pallas"


def test_auto_packed_jnp_odd_height_matches_oracle():
    """The auto lane's odd-height single-device route (packed-jnp) is
    oracle-identical end to end, temporal blocking engaged (its relaxed
    supports_multi admits any single-device packing shape)."""
    from gol_tpu import engine
    from gol_tpu.config import GameConfig
    from gol_tpu.ops import stencil_packed as sp

    assert sp.supports_multi_jnp(100, 128, SINGLE_DEVICE)
    assert not sp.supports(100, 128, SINGLE_DEVICE)
    rng = np.random.default_rng(31)
    g = rng.integers(0, 2, size=(100, 128), dtype=np.uint8)
    cfg = GameConfig(gen_limit=25)
    got = engine.simulate(g, cfg)  # kernel='auto'
    want = oracle.run(g, cfg)
    assert got.generations == want.generations
    np.testing.assert_array_equal(got.grid, want.grid)


def test_misaligned_distributed_pallas_rejected():
    topo = Topology(shape=(2, 2), axes=("row", "col"))
    with pytest.raises(ValueError, match="pallas kernel"):
        get_kernel("pallas").fused(jnp.zeros((30, 128), jnp.uint8), topo)


def test_dist_kernel_local_wrap_matches_oracle():
    """The distributed byte kernel with local-wrap ghosts == the torus.

    On CPU this runs interpret mode; on TPU it validates the Mosaic-compiled
    distributed kernel on one chip.
    """
    from gol_tpu.ops import stencil_pallas as spl

    rng = np.random.default_rng(22)
    for shape in [(64, 256), (8, 128), (24, 384)]:
        g = rng.integers(0, 2, size=shape, dtype=np.uint8)
        new, alive, similar = spl._distributed_step(jnp.asarray(g), SINGLE_DEVICE)
        expect = oracle.evolve(g)
        np.testing.assert_array_equal(np.asarray(new), expect)
        assert bool(alive) == bool(expect.any())
        assert bool(similar) == bool(np.array_equal(expect, g))


@pytest.mark.parametrize("rows,cols", [(2, 2), (2, 4), (1, 4), (4, 1)])
def test_distributed_pallas_matches_oracle(rows, cols):
    """The byte band kernel under a mesh: ppermute ghosts feed the kernel."""
    from gol_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(rows, cols)
    rng = np.random.default_rng(5)
    g = rng.integers(0, 2, size=(32, 512), dtype=np.uint8)
    config = GameConfig(gen_limit=40)
    expect = oracle.run(g, config)
    got = engine.simulate(g, config, mesh=mesh, kernel="pallas")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


def test_distributed_pallas_glider_crosses_seams():
    from gol_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2, 4)
    g = np.zeros((64, 512), np.uint8)
    glider = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    g[30:33, 126:129] = glider  # straddles the row seam and a column seam
    config = GameConfig(gen_limit=200)
    expect = oracle.run(g, config)
    got = engine.simulate(g, config, mesh=mesh, kernel="pallas")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations
