"""Bitpacked kernel tests (interpret mode on CPU; compiled path runs on TPU).

The packed kernel carries uint32 words through the generation loop; these
tests pin the pack/unpack bijection, the bit-sliced adder network against the
NumPy oracle, and the engine's encode/decode boundary.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from gol_tpu import engine, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.ops import get_kernel, stencil_packed as sp
from gol_tpu.parallel.mesh import SINGLE_DEVICE, Topology


def test_encode_decode_roundtrip():
    rng = np.random.default_rng(0)
    g = rng.integers(0, 2, size=(16, 256), dtype=np.uint8)
    words = sp.encode(jnp.asarray(g))
    assert words.dtype == jnp.uint32 and words.shape == (16, 8)
    np.testing.assert_array_equal(np.asarray(sp.decode(words)), g)


@pytest.mark.parametrize(
    "shape", [(8, 32), (16, 128), (64, 256), (24, 96), (8, 4096)]
)
def test_step_matches_oracle(shape):
    rng = np.random.default_rng(7)
    g = rng.integers(0, 2, size=shape, dtype=np.uint8)
    new_w, alive, similar = sp._step(sp.encode(jnp.asarray(g)), interpret=True)
    expect = oracle.evolve(g)
    np.testing.assert_array_equal(np.asarray(sp.decode(new_w)), expect)
    assert bool(alive) == bool(expect.any())
    assert bool(similar) == bool(np.array_equal(expect, g))


def test_word_boundary_glider():
    """A glider crossing a 32-bit word boundary exercises the shift carries."""
    g = np.zeros((16, 64), np.uint8)
    # Glider near columns 30-32 so it walks across the word seam.
    glider = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    g[4:7, 30:33] = glider
    cur = g
    state = sp.encode(jnp.asarray(g))
    for _ in range(12):
        state, _, _ = sp._step(state, interpret=True)
        cur = oracle.evolve(cur)
    np.testing.assert_array_equal(np.asarray(sp.decode(state)), cur)


def test_torus_words_matches_oracle():
    from gol_tpu.ops import packed_math as pm

    rng = np.random.default_rng(2)
    g = rng.integers(0, 2, size=(32, 256), dtype=np.uint8)
    got = np.asarray(pm.decode(pm.evolve_torus_words(pm.encode(jnp.asarray(g)))))
    np.testing.assert_array_equal(got, oracle.evolve(g))


def test_engine_run_both_conventions():
    rng = np.random.default_rng(11)
    g = rng.integers(0, 2, size=(32, 128), dtype=np.uint8)
    for convention in (Convention.C, Convention.CUDA):
        config = GameConfig(gen_limit=40, convention=convention)
        expect = oracle.run(g, config)
        got = engine.simulate(g, config, kernel="packed")
        np.testing.assert_array_equal(got.grid, expect.grid)
        assert got.generations == expect.generations


def test_engine_early_exits():
    # still life -> similarity exit at generation 2
    g = np.zeros((16, 128), np.uint8)
    g[4:6, 4:6] = 1
    res = engine.simulate(g, GameConfig(), kernel="packed")
    assert res.generations == 2
    np.testing.assert_array_equal(res.grid, g)
    # lone cell -> empty exit at generation 1
    g = np.zeros((16, 128), np.uint8)
    g[8, 64] = 1
    res = engine.simulate(g, GameConfig(), kernel="packed")
    assert res.generations == 1
    assert not res.grid.any()


def test_shape_gating():
    assert sp.supports(4096, 4096, SINGLE_DEVICE)
    assert not sp.supports(30, 30, SINGLE_DEVICE)  # width not a multiple of 32
    # Distributed: only the local width must pack; height is unconstrained.
    assert sp.supports(6, 64, Topology(shape=(2, 2), axes=("row", "col")))
    assert not sp.supports(6, 48, Topology(shape=(2, 2), axes=("row", "col")))
    with pytest.raises(ValueError, match="packed kernel"):
        get_kernel("packed").fused(jnp.zeros((12, 4), jnp.uint32), SINGLE_DEVICE)


def test_distributed_packed_matches_oracle():
    from gol_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2, 4)
    rng = np.random.default_rng(3)
    g = rng.integers(0, 2, size=(64, 256), dtype=np.uint8)
    config = GameConfig(gen_limit=60)
    expect = oracle.run(g, config)
    got = engine.simulate(g, config, mesh=mesh, kernel="packed")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


def test_dist_kernel_local_wrap_matches_oracle():
    """The distributed band kernel with local-wrap ghosts == the torus.

    On CPU this runs interpret mode; on TPU it validates the Mosaic-compiled
    distributed kernel on one chip (the ghosts of a 1-shard torus are the
    local edge wraps, src/game_cuda.cu:52-74).
    """
    rng = np.random.default_rng(21)
    for shape in [(64, 256), (16, 32), (24, 96)]:
        g = rng.integers(0, 2, size=shape, dtype=np.uint8)
        new, alive, similar = sp._distributed_step(
            sp.encode(jnp.asarray(g)), SINGLE_DEVICE
        )
        expect = oracle.evolve(g)
        np.testing.assert_array_equal(np.asarray(sp.decode(new)), expect)
        assert bool(alive) == bool(expect.any())
        assert bool(similar) == bool(np.array_equal(expect, g))


def test_distributed_packed_runs_pallas_kernel(monkeypatch):
    """On TPU the mesh path's hot loop is the Pallas band kernel, not the
    jnp net; off TPU the kernel='packed-interp' lane takes that route
    (interpret mode) — engaged here so CI pins the composition."""
    from gol_tpu.parallel.mesh import make_mesh

    calls = []
    real = sp._dist_step_pallas

    def spy(*args, **kwargs):
        calls.append(args[0].shape)
        return real(*args, **kwargs)

    monkeypatch.setattr(sp, "_dist_step_pallas", spy)
    mesh = make_mesh(2, 4)
    rng = np.random.default_rng(3)
    g = rng.integers(0, 2, size=(64, 256), dtype=np.uint8)
    got = engine.simulate(g, GameConfig(gen_limit=5), mesh=mesh,
                          kernel="packed-interp")
    expect = oracle.run(g, GameConfig(gen_limit=5))
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert calls and calls[0] == (32, 2)  # 32-row, 2-word local shard


def test_distributed_packed_odd_height_falls_back_to_jnp():
    """Shard heights that don't tile (h % 8 != 0) use the jnp ghost path."""
    from gol_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2, 2)
    rng = np.random.default_rng(9)
    g = rng.integers(0, 2, size=(12, 64), dtype=np.uint8)  # 6-row shards
    config = GameConfig(gen_limit=30)
    expect = oracle.run(g, config)
    got = engine.simulate(g, config, mesh=mesh, kernel="packed")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


def test_distributed_packed_single_word_shards():
    """One uint32 word per shard row: both carries come from ghost bits."""
    from gol_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2, 4)
    rng = np.random.default_rng(13)
    g = rng.integers(0, 2, size=(16, 128), dtype=np.uint8)  # 8x32 shards
    config = GameConfig(gen_limit=30)
    expect = oracle.run(g, config)
    got = engine.simulate(g, config, mesh=mesh, kernel="packed")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


def test_distributed_packed_glider_crosses_shard_and_word_seams():
    from gol_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2, 4)
    g = np.zeros((64, 256), np.uint8)
    glider = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], np.uint8)
    g[30:33, 62:65] = glider  # straddles the row-shard seam and a col seam
    config = GameConfig(gen_limit=300)
    expect = oracle.run(g, config)
    got = engine.simulate(g, config, mesh=mesh, kernel="packed")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


@pytest.mark.parametrize("shape", [(8, 32), (32, 128), (64, 256), (48, 96)])
def test_temporal_kernel_matches_oracle(shape):
    """The temporal Pallas band kernel in interpret mode: roll-seam
    garbage must never reach the interior, per-generation flags must match
    the oracle for every fused generation."""
    rng = np.random.default_rng(17)
    g = rng.integers(0, 2, size=shape, dtype=np.uint8)
    new_w, alive, similar = sp._step_t(sp.encode(jnp.asarray(g)), interpret=True)
    states = [g]
    for _ in range(sp.TEMPORAL_GENS):
        states.append(oracle.evolve(states[-1]))
    np.testing.assert_array_equal(np.asarray(sp.decode(new_w)), states[-1])
    for t in range(sp.TEMPORAL_GENS):
        assert int(alive[t]) == int(states[t + 1].any()), t
        assert int(similar[t]) == int(np.array_equal(states[t + 1], states[t])), t


def test_temporal_kernel_still_life_and_empty_flags():
    # Still life: similar flags all set from gen 1; lone cell: dead after gen
    # 1, alive flags 0 throughout, grid stays empty (fixed point).
    g = np.zeros((16, 64), np.uint8)
    g[4:6, 4:6] = 1
    new_w, alive, similar = sp._step_t(sp.encode(jnp.asarray(g)), interpret=True)
    np.testing.assert_array_equal(np.asarray(sp.decode(new_w)), g)
    assert all(int(a) == 1 for a in alive) and all(int(s) == 1 for s in similar)
    g = np.zeros((16, 64), np.uint8)
    g[3, 3] = 1
    new_w, alive, similar = sp._step_t(sp.encode(jnp.asarray(g)), interpret=True)
    assert not np.asarray(sp.decode(new_w)).any()
    assert all(int(a) == 0 for a in alive)


@pytest.mark.parametrize("shape", [(16, 64), (32, 128), (48, 96)])
def test_distributed_temporal_kernel_interpret(shape):
    """The deep-halo temporal form: ghost-extended block + interior-masked
    flags, compiled via interpret mode (local torus wrap = 1x1 topology)."""
    rng = np.random.default_rng(23)
    g = rng.integers(0, 2, size=shape, dtype=np.uint8)
    h, nwords = shape[0], shape[1] // 32
    T = sp.TEMPORAL_GENS
    xe = sp.exchange_packed_deep(sp.encode(jnp.asarray(g)), SINGLE_DEVICE)
    assert xe.shape == (h + 2 * T, nwords + 2)
    new_ext, alive, similar = sp._step_t(
        xe, interpret=True, interior=(T, T + h, 1, nwords + 1)
    )
    got = np.asarray(sp.decode(new_ext[T : T + h, 1 : nwords + 1]))
    states = [g]
    for _ in range(T):
        states.append(oracle.evolve(states[-1]))
    np.testing.assert_array_equal(got, states[-1])
    for t in range(T):
        assert int(alive[t]) == int(states[t + 1].any()), t
        assert int(similar[t]) == int(np.array_equal(states[t + 1], states[t])), t


def test_distributed_temporal_flags_ignore_ghosts():
    # A lone block near the seam: ghost rows/columns hold live neighbor
    # copies, but masked flags must still report the interior truth.
    g = np.zeros((16, 64), np.uint8)
    g[0:2, 0:2] = 1  # still life touching the wrap seam
    xe = sp.exchange_packed_deep(sp.encode(jnp.asarray(g)), SINGLE_DEVICE)
    T = sp.TEMPORAL_GENS
    new_ext, alive, similar = sp._step_t(
        xe, interpret=True, interior=(T, T + 16, 1, 3)
    )
    np.testing.assert_array_equal(
        np.asarray(sp.decode(new_ext[T : T + 16, 1 : 3])), g
    )
    assert all(int(a) == 1 for a in alive) and all(int(s) == 1 for s in similar)


def test_mesh_engine_runs_deep_halo_temporal_pass(monkeypatch):
    """A mesh run's hot loop is the deep-halo temporal pass, not the
    per-generation fallback: gen_limit >= TEMPORAL_GENS makes the blocked
    loop take at least one fused multi-generation step per block."""
    from gol_tpu.parallel.mesh import make_mesh

    calls = []
    real = sp._distributed_step_multi

    def spy(words, topology, force_jnp=False, force_interp=False):
        calls.append(tuple(words.shape))
        return real(words, topology, force_jnp, force_interp)

    monkeypatch.setattr(sp, "_distributed_step_multi", spy)
    engine.make_runner.cache_clear()
    mesh = make_mesh(2, 4)
    rng = np.random.default_rng(31)
    g = rng.integers(0, 2, size=(64, 256), dtype=np.uint8)
    lim = 2 * sp.TEMPORAL_GENS + 1
    got = engine.simulate(g, GameConfig(gen_limit=lim), mesh=mesh, kernel="packed")
    expect = oracle.run(g, GameConfig(gen_limit=lim))
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations
    assert calls and calls[0] == (32, 2)  # 32-row, 2-word local shard
    engine.make_runner.cache_clear()


def test_pick_band_width_aware_target():
    """Wide rows (64KB+, i.e. 16K+ words) keep the compile-validated 1MB
    band target; narrower rows get the full 2MB target (bands clamp to
    height and 8-row alignment either way)."""
    # 512 words = 2KB rows: 2MB target -> 1024-row bands.
    assert sp._pick_band(16384, 512) == 1024
    # 16384 words = 64KB rows: clamped to 1MB -> 16-row bands.
    assert sp._pick_band(64, 16384) == 16
    # 32768 words = 128KB rows: 1MB -> the minimum 8-row bands.
    assert sp._pick_band(64, 32768) == 8
    # Short grids clamp to height.
    assert sp._pick_band(8, 512) == 8
    # Explicit targets bypass the width-aware default (the temporal kernel).
    assert sp._pick_band(64, 32768, 4 << 20) == 32


@pytest.mark.parametrize("shape", [(16, 64), (16, 128 * 32), (32, 96)])
def test_ghost_operand_temporal_kernel_interpret(shape):
    """The banded ghost-operand temporal form (_step_tgb): ghost row blocks
    and the E/W ghost-column plane ride as kernel operands, the edge words'
    carries are patched per generation, and the ghosts evolve in-kernel.
    State and per-generation flags must match the oracle exactly (local
    torus wrap = 1x1 topology)."""
    h, w = shape
    rng = np.random.default_rng(29)
    g = rng.integers(0, 2, size=shape, dtype=np.uint8)
    T = sp.TEMPORAL_GENS
    words = sp.encode(jnp.asarray(g))
    gtop, gbot, G_ext = sp.deep_ghost_operands(words, SINGLE_DEVICE)
    assert gtop.shape == (T, w // 32) and G_ext.shape == (h + 2 * T, 2)
    new, alive, similar = sp._step_tgb(words, gtop, gbot, G_ext, interpret=True)
    got = np.asarray(sp.decode(new))
    states = [g]
    for _ in range(T):
        states.append(oracle.evolve(states[-1]))
    np.testing.assert_array_equal(got, states[-1])
    for t in range(T):
        assert int(alive[t]) == int(states[t + 1].any()), t
        assert int(similar[t]) == int(np.array_equal(states[t + 1], states[t])), t


def test_ghost_operand_temporal_edge_word_activity():
    # All life confined to the two edge words: the cross-seam carries and
    # in-kernel ghost evolution alone determine their fate.
    h, nwords = 16, 128
    g = np.zeros((h, nwords * 32), np.uint8)
    g[7:10, 1] = 1    # blinker in word 0, feeding across the wrap seam
    g[3:5, nwords * 32 - 2 : nwords * 32] = 1  # block in the east word
    words = sp.encode(jnp.asarray(g))
    gtop, gbot, G_ext = sp.deep_ghost_operands(words, SINGLE_DEVICE)
    new, alive, similar = sp._step_tgb(words, gtop, gbot, G_ext, interpret=True)
    expect = g
    for _ in range(sp.TEMPORAL_GENS):
        expect = oracle.evolve(expect)
    np.testing.assert_array_equal(np.asarray(sp.decode(new)), expect)
    assert all(int(a) == 1 for a in alive)


def test_ghost_operand_temporal_multi_band(monkeypatch):
    """Multiple bands per pass: the first/last band's ghost-block selection,
    interior bands' neighbor blocks, the ghost plane's banded specs, and the
    i>0 SMEM flag accumulation must agree with the single-band result (the
    default 2MB target would put these shapes in one band, so the target is
    shrunk to force banding; the unjitted entry re-reads the constant)."""
    h, w = 48, 64  # 8KB target -> 16-row bands -> grid (3,)
    rng = np.random.default_rng(41)
    g = rng.integers(0, 2, size=(h, w), dtype=np.uint8)
    T = sp.TEMPORAL_GENS
    words = sp.encode(jnp.asarray(g))
    gtop, gbot, G_ext = sp.deep_ghost_operands(words, SINGLE_DEVICE)
    monkeypatch.setattr(sp, "_BANDT_BYTES", 8 << 10)
    assert sp._pick_band(h, w // 32, sp._BANDT_BYTES) == 16
    new, alive, similar = sp._step_tgb.__wrapped__(
        words, gtop, gbot, G_ext, interpret=True
    )
    got = np.asarray(sp.decode(new))
    states = [g]
    for _ in range(T):
        states.append(oracle.evolve(states[-1]))
    np.testing.assert_array_equal(got, states[-1])
    for t in range(T):
        assert int(alive[t]) == int(states[t + 1].any()), t


@pytest.mark.parametrize("shape", [(16, 64), (16, 128 * 32), (32, 96)])
def test_rows_only_temporal_kernel_interpret(shape):
    """The rows-only temporal form (_step_trow, R x 1 meshes): full-width
    shards take their E/W torus wrap from the shard's own lane roll; only
    the N/S ghost blocks ride as operands. State and per-generation flags
    must match the oracle exactly (local wrap = 1-row topology)."""
    from gol_tpu.parallel import halo

    h, w = shape
    rng = np.random.default_rng(31)
    g = rng.integers(0, 2, size=shape, dtype=np.uint8)
    T = sp.TEMPORAL_GENS
    words = sp.encode(jnp.asarray(g))
    gtop, gbot = halo.ghost_slices(words, 0, None, 1, depth=T)
    assert gtop.shape == (T, w // 32)
    new, alive, similar = sp._step_trow(words, gtop, gbot, interpret=True)
    got = np.asarray(sp.decode(new))
    states = [g]
    for _ in range(T):
        states.append(oracle.evolve(states[-1]))
    np.testing.assert_array_equal(got, states[-1])
    for t in range(T):
        assert int(alive[t]) == int(states[t + 1].any()), t
        assert int(similar[t]) == int(np.array_equal(states[t + 1], states[t])), t


def test_rows_only_routing_and_multi_band(monkeypatch):
    """cols == 1 topologies route _distributed_step_multi through the
    rows-only kernel (force_interp engages it off-TPU), including across
    multiple bands with the i>0 SMEM flag accumulation."""
    h, w = 48, 64
    rng = np.random.default_rng(43)
    g = rng.integers(0, 2, size=(h, w), dtype=np.uint8)
    T = sp.TEMPORAL_GENS
    words = sp.encode(jnp.asarray(g))
    monkeypatch.setattr(sp, "_BANDT_BYTES", 8 << 10)  # force 16-row bands
    new, alive, similar = sp._distributed_step_multi(
        words, SINGLE_DEVICE, force_interp=True
    )
    got = np.asarray(sp.decode(new))
    states = [g]
    for _ in range(T):
        states.append(oracle.evolve(states[-1]))
    np.testing.assert_array_equal(got, states[-1])
    for t in range(T):
        assert int(alive[t]) == int(states[t + 1].any()), t


def test_rows_only_kernel_under_real_mesh():
    """The rows-only kernel composed with REAL shard_map ppermutes on a
    4x1 CPU mesh (kernel='packed-interp' routes the temporal pass through
    _step_trow in interpret mode); glider crosses the N/S shard seams."""
    from gol_tpu import engine as eng
    from gol_tpu.config import GameConfig as GC
    from gol_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(59)
    g = rng.integers(0, 2, size=(64, 128), dtype=np.uint8)
    lim = 2 * sp.TEMPORAL_GENS + 3
    got = eng.simulate(
        g, GC(gen_limit=lim), mesh=make_mesh(4, 1), kernel="packed-interp"
    )
    expect = oracle.run(g, GC(gen_limit=lim))
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


def test_banded_kernel_under_real_mesh():
    """The banded ghost-operand kernels composed with REAL shard_map
    ppermutes: kernel='packed-interp' routes the CPU-mesh temporal pass
    through the banded ghost-operand kernel in interpret mode, so the
    exchanged gtop/gbot/G_ext operands (not the jnp-network equivalent)
    produce the mesh result."""
    from gol_tpu import engine
    from gol_tpu.config import GameConfig
    from gol_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(53)
    g = rng.integers(0, 2, size=(64, 256), dtype=np.uint8)
    # 2T+3 generations: two fused temporal blocks plus a 3-generation tail
    # through the single-generation dist kernel (also interpret mode here).
    lim = 2 * sp.TEMPORAL_GENS + 3
    cfg = GameConfig(gen_limit=lim)
    got = engine.simulate(g, cfg, mesh=make_mesh(2, 4), kernel="packed-interp")
    expect = oracle.run(g, cfg)
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations
    # 8-row shards (16 rows over 2 mesh rows): supports_multi fails, so the
    # engine strips fused_multi and EVERY generation runs the single-gen
    # dist kernel — the ppermuted ghost-row/bit-column operands composed
    # with the interpret-mode kernel under a real mesh.
    g8 = rng.integers(0, 2, size=(16, 256), dtype=np.uint8)
    cfg8 = GameConfig(gen_limit=6)
    got8 = engine.simulate(g8, cfg8, mesh=make_mesh(2, 4),
                           kernel="packed-interp")
    expect8 = oracle.run(g8, cfg8)
    np.testing.assert_array_equal(got8.grid, expect8.grid)
    assert got8.generations == expect8.generations


# ---------------------------------------------------------------------------
# The split-edge 2D mesh form (_step_tsplit): rows-only main pass with
# edge-masked flags + lane-folded exact edge strip + stitch. Replaces the
# ghost-plane form for nwords >= 2 shards (r4; VERDICT r3 item 1).


def test_fold_count():
    # Largest divisor of h/8 with 6F lanes within one 128-lane tile.
    assert sp._fold_count(16384) == 16    # 2048 -> 16 (powers of two cap at 16)
    assert sp._fold_count(32768) == 16
    assert sp._fold_count(16) == 2
    assert sp._fold_count(24) == 3
    assert sp._fold_count(1344) == 21     # 168 = 8*21 -> the full tile
    assert sp._fold_count(344) == 1       # 43 prime > 21: no folding
    assert sp._MAX_FOLDS * 6 <= 128


@pytest.mark.parametrize("shape", [(16, 64), (16, 96), (32, 96), (16, 128 * 32)])
def test_split_edge_temporal_kernel_interpret(shape):
    """State and per-generation flags must match the oracle exactly (local
    torus wrap = 1x1 topology), including the nwords == 2 degenerate strip
    (duplicated columns, main pass fully overwritten) and nwords == 3
    (w1 == w_{n-2})."""
    h, w = shape
    rng = np.random.default_rng(67)
    g = rng.integers(0, 2, size=shape, dtype=np.uint8)
    T = sp.TEMPORAL_GENS
    words = sp.encode(jnp.asarray(g))
    gtop, gbot, cols4, G_ext = sp._tsplit_operands(words, SINGLE_DEVICE)
    new, alive, similar = sp._step_tsplit(words, gtop, gbot, cols4, G_ext,
                                          interpret=True)
    got = np.asarray(sp.decode(new))
    states = [g]
    for _ in range(T):
        states.append(oracle.evolve(states[-1]))
    np.testing.assert_array_equal(got, states[-1])
    for t in range(T):
        assert int(alive[t]) == int(states[t + 1].any()), t
        assert int(similar[t]) == int(np.array_equal(states[t + 1], states[t])), t


def test_split_edge_strip_owns_edge_words():
    # All life confined to the two edge word columns: the main pass sees
    # nothing (its flags exclude those lanes), so the strip pass alone must
    # produce both the exact state and the per-generation flags.
    h, nwords = 16, 128
    g = np.zeros((h, nwords * 32), np.uint8)
    g[7:10, 1] = 1    # blinker in word 0, feeding across the wrap seam
    g[3:5, nwords * 32 - 2 : nwords * 32] = 1  # block (still life) east edge
    words = sp.encode(jnp.asarray(g))
    gtop, gbot, cols4, G_ext = sp._tsplit_operands(words, SINGLE_DEVICE)
    new, alive, similar = sp._step_tsplit(words, gtop, gbot, cols4, G_ext,
                                          interpret=True)
    expect = g
    for _ in range(sp.TEMPORAL_GENS):
        expect = oracle.evolve(expect)
    np.testing.assert_array_equal(np.asarray(sp.decode(new)), expect)
    assert all(int(a) == 1 for a in alive)
    # Blinker keeps flipping: never similar.
    assert all(int(s) == 0 for s in similar)


def test_split_edge_still_life_similarity():
    # A block fully inside the west edge word: similar must be 1 every
    # generation — the strip's similarity plane is exact, and the main
    # pass's masked flags stay neutral (similar=1) rather than poisoning
    # the AND.
    h, nwords = 16, 8
    g = np.zeros((h, nwords * 32), np.uint8)
    g[6:8, 2:4] = 1
    words = sp.encode(jnp.asarray(g))
    gtop, gbot, cols4, G_ext = sp._tsplit_operands(words, SINGLE_DEVICE)
    new, alive, similar = sp._step_tsplit(words, gtop, gbot, cols4, G_ext,
                                          interpret=True)
    np.testing.assert_array_equal(np.asarray(sp.decode(new)), g)
    assert all(int(a) == 1 for a in alive)
    assert all(int(s) == 1 for s in similar)


def test_split_edge_multi_band_and_folds(monkeypatch):
    """Banding in BOTH passes (main bands + strip bands) and h with a
    non-power-of-two fold count; the unjitted entries re-read the patched
    band constant."""
    h, w = 48, 160  # base h/8 = 6 -> F = 6; 5-word strip indices distinct
    rng = np.random.default_rng(71)
    g = rng.integers(0, 2, size=(h, w), dtype=np.uint8)
    T = sp.TEMPORAL_GENS
    words = sp.encode(jnp.asarray(g))
    monkeypatch.setattr(sp, "_BANDT_BYTES", 8 << 10)
    gtop, gbot, cols4, G_ext = sp._tsplit_operands(words, SINGLE_DEVICE)
    new, alive, similar = sp._step_tsplit(words, gtop, gbot, cols4, G_ext,
                                          interpret=True)
    got = np.asarray(sp.decode(new))
    states = [g]
    for _ in range(T):
        states.append(oracle.evolve(states[-1]))
    np.testing.assert_array_equal(got, states[-1])
    for t in range(T):
        assert int(alive[t]) == int(states[t + 1].any()), t
        assert int(similar[t]) == int(np.array_equal(states[t + 1], states[t])), t


def test_split_edge_routing(monkeypatch):
    """cols > 1 topologies with nwords >= 2 route _distributed_step_multi
    through the FAST split-edge form (with the topology threaded in, so
    the summary vote sees the mesh); single-word shards keep the
    ghost-plane form (_step_tgb)."""
    calls = []
    real = sp._step_tsplit_fast

    def spy(words, gtop, gbot, cols4, G_ext, topology=None, interpret=False):
        calls.append((words.shape, topology))
        return real(words, gtop, gbot, cols4, G_ext, topology=topology,
                    interpret=interpret)

    monkeypatch.setattr(sp, "_step_tsplit_fast", spy)
    rng = np.random.default_rng(73)
    g = rng.integers(0, 2, size=(16, 128), dtype=np.uint8)
    words = sp.encode(jnp.asarray(g))
    from gol_tpu.parallel.mesh import PROXY_2D

    new, alive, _ = sp._distributed_step_multi(words, PROXY_2D, force_interp=True)
    assert calls == [((16, 4), PROXY_2D)]
    expect = g
    for _ in range(sp.TEMPORAL_GENS):
        expect = oracle.evolve(expect)
    np.testing.assert_array_equal(np.asarray(sp.decode(new)), expect)

    # Single-word shards: still the ghost-plane form.
    calls.clear()
    g1 = rng.integers(0, 2, size=(16, 32), dtype=np.uint8)
    sp._distributed_step_multi(sp.encode(jnp.asarray(g1)), PROXY_2D,
                               force_interp=True)
    assert calls == []


def test_bandt_target_width_continuous():
    """The temporal band target shrinks BEFORE the width cap (advisor r3
    medium): every chosen band keeps the padded extended block within the
    probed compile budget, and the measured-fast configs are preserved."""
    for nwords in [64, 512, 2048, 4096, 5120, 7168, 7680, 8184, 8192]:
        target = sp._bandt_target(1024, nwords)
        band = sp._pick_band(1024, nwords, target)
        padded = max(-(-nwords // 128) * 128, 128) * 4
        assert (band + 16) * padded <= sp._BANDT_EXT_BUDGET, nwords
    # The measured-fast configs survive: 65536^2 single chip (2048 words,
    # 256-row bands) and 16384^2 (512 words, 1024-row bands).
    assert sp._bandt_target(65536, 2048) == sp._BANDT_BYTES
    assert sp._pick_band(65536, 2048, sp._bandt_target(65536, 2048)) == 256
    assert sp._pick_band(16384, 512, sp._bandt_target(16384, 512)) == 1024
    # Near-cap widths drop the target before the cap, not at it: 7680 words
    # at the 2MB target Mosaic-OOMed on v5e (benchmarks/vmem_probe_r4.json).
    assert sp._bandt_target(1024, 7680) < sp._BANDT_BYTES
    assert sp._bandt_target(1024, 8184) < sp._BANDT_BYTES


# ---------------------------------------------------------------------------
# Fast-flag passes (r4): pass-level summaries + monotone derivation, with the
# exact kernel replayed under lax.cond only when an exit fires mid-pass.


class TestFastFlagPasses:
    """_step_t_fast/_step_trow_fast must produce bit-identical state AND
    per-generation flag vectors to the exact kernels across every monotone
    case: no-exit soup, death inside the pass (rerun), stillness onset
    inside the pass (rerun), already-still input, and empty input."""

    def _grids(self):
        rng = np.random.default_rng(83)
        soup = rng.integers(0, 2, size=(32, 128), dtype=np.uint8)
        death = np.zeros((32, 128), np.uint8)
        death[10, 10:12] = 1  # domino: dies at generation 1 (in-pass death)
        onset = np.zeros((32, 128), np.uint8)
        onset[10:12, 10] = onset[10, 11] = 1  # L-tromino -> block at gen 1:
        # similarity first true at generation 2 (g2 == g1), sim1 == 0
        still = np.zeros((32, 128), np.uint8)
        still[10:12, 10:12] = 1  # block: already still, sim1 == 1
        empty = np.zeros((32, 128), np.uint8)
        return {"soup": soup, "death": death, "onset": onset,
                "still": still, "empty": empty}

    def test_torus_fast_matches_exact(self):
        for name, g in self._grids().items():
            words = sp.encode(jnp.asarray(g))
            new_e, a_e, s_e = sp._step_t(words, interpret=True)
            new_f, a_f, s_f = sp._step_t_fast(words, interpret=True)
            np.testing.assert_array_equal(
                np.asarray(new_f), np.asarray(new_e), err_msg=name)
            assert np.asarray(a_f).tolist() == np.asarray(a_e).tolist(), name
            assert np.asarray(s_f).tolist() == np.asarray(s_e).tolist(), name

    def test_rows_only_fast_matches_exact(self):
        from gol_tpu.parallel import halo

        for name, g in self._grids().items():
            words = sp.encode(jnp.asarray(g))
            gtop, gbot = halo.ghost_slices(words, 0, None, 1,
                                           depth=sp.TEMPORAL_GENS)
            new_e, a_e, s_e = sp._step_trow(words, gtop, gbot, interpret=True)
            new_f, a_f, s_f = sp._step_trow_fast(words, gtop, gbot,
                                                 interpret=True)
            np.testing.assert_array_equal(
                np.asarray(new_f), np.asarray(new_e), err_msg=name)
            assert np.asarray(a_f).tolist() == np.asarray(a_e).tolist(), name
            assert np.asarray(s_f).tolist() == np.asarray(s_e).tolist(), name

    def test_derivation_against_oracle_per_generation(self):
        # Independent ground truth (not just exact-kernel agreement): flag
        # vectors vs the oracle's per-generation states.
        for name, g in self._grids().items():
            words = sp.encode(jnp.asarray(g))
            _, a_f, s_f = sp._step_t_fast(words, interpret=True)
            states = [g]
            for _ in range(sp.TEMPORAL_GENS):
                states.append(oracle.evolve(states[-1]))
            for t in range(sp.TEMPORAL_GENS):
                assert int(a_f[t]) == int(states[t + 1].any()), (name, t)
                assert int(s_f[t]) == int(
                    np.array_equal(states[t + 1], states[t])), (name, t)


def test_fast_flag_early_exits_under_real_mesh():
    """Engine-level integration of the fast-flag pass: the blocked replay
    consumes the DERIVED vectors (and the lax.cond replay on exit passes)
    under real shard_map on a 4x1 mesh — exit generations must match the
    oracle exactly for both exit kinds."""
    from gol_tpu.parallel.mesh import make_mesh

    still = np.zeros((32, 128), np.uint8)
    still[14:16, 60:62] = 1
    dying = np.zeros((32, 128), np.uint8)
    dying[15, 60:62] = 1
    onset = np.zeros((32, 128), np.uint8)
    onset[14:16, 60] = onset[14, 61] = 1  # becomes a block at gen 1
    for name, g in (("still", still), ("dying", dying), ("onset", onset)):
        cfg = GameConfig(gen_limit=50)
        got = engine.simulate(g, cfg, mesh=make_mesh(4, 1),
                              kernel="packed-interp")
        want = oracle.run(g, cfg)
        assert got.generations == want.generations, name
        np.testing.assert_array_equal(got.grid, want.grid, err_msg=name)


def test_fast_flag_cross_shard_transient():
    """Adversarial counterexample for the fast-flag derivation (found by
    search, r4 code review): a shard is an OPEN system, so monotonicity
    does not hold per shard — here a cross-boundary transient enters
    shard 2 after its g0/g1 summary taps and dies before g7/g8, so the
    shard's LOCAL summary claims stillness for the whole pass. Without
    voting the four summary scalars globally before deriving
    (_derive_or_replay), the engine-voted similarity vector fires a
    generation early. Pinned end-to-end on a real 4x1 mesh with
    similarity checked every generation."""
    from gol_tpu.parallel.mesh import make_mesh

    # 16-row shards (supports_multi needs h >= 16, or the temporal fast
    # pass never engages — 8-row shards run the per-generation kernels).
    cfg = GameConfig(gen_limit=30, similarity_frequency=1)
    cases = [
        ([31, 27, 30, 31, 29, 27, 28, 30, 29, 30, 27],
         [68, 70, 68, 67, 70, 60, 69, 70, 65, 60, 65]),
        ([29, 30, 30, 29, 30, 31], [64, 65, 63, 66, 66, 68]),
    ]
    for rows, cols in cases:
        g = np.zeros((64, 128), np.uint8)
        g[rows, cols] = 1
        want = oracle.run(g, cfg)
        got = engine.simulate(g, cfg, mesh=make_mesh(4, 1),
                              kernel="packed-interp")
        assert got.generations == want.generations, (rows, cols)
        np.testing.assert_array_equal(got.grid, want.grid)


class TestSplitFastFlags:
    """The fast-flag split-edge composition (_step_tsplit_fast) must be
    bit-identical — state AND per-generation flag vectors — to the exact
    split form across every monotone case, including life confined to the
    edge columns (strip-owned summary) and mid-pass transitions (replay)."""

    def _grids(self):
        rng = np.random.default_rng(97)
        soup = rng.integers(0, 2, size=(32, 128), dtype=np.uint8)
        death = np.zeros((32, 128), np.uint8)
        death[10, 10:12] = 1  # domino: dies at generation 1 (in-pass death)
        onset = np.zeros((32, 128), np.uint8)
        onset[10:12, 10] = onset[10, 11] = 1  # L-tromino -> block at gen 1
        still = np.zeros((32, 128), np.uint8)
        still[10:12, 10:12] = 1
        empty = np.zeros((32, 128), np.uint8)
        edge = np.zeros((32, 128), np.uint8)
        edge[7:10, 1] = 1  # blinker inside the west edge word: only the
        edge[3:5, 126:128] = 1  # strip's summary sees any of this
        edge_death = np.zeros((32, 128), np.uint8)
        edge_death[10, 126:128] = 1  # domino in the east edge word
        return {"soup": soup, "death": death, "onset": onset, "still": still,
                "empty": empty, "edge": edge, "edge_death": edge_death}

    def test_split_fast_matches_exact(self):
        for name, g in self._grids().items():
            words = sp.encode(jnp.asarray(g))
            ops = sp._tsplit_operands(words, SINGLE_DEVICE)
            new_e, a_e, s_e = sp._step_tsplit(words, *ops, interpret=True)
            new_f, a_f, s_f = sp._step_tsplit_fast(words, *ops, interpret=True)
            np.testing.assert_array_equal(
                np.asarray(new_f), np.asarray(new_e), err_msg=name)
            assert np.asarray(a_f).tolist() == np.asarray(a_e).tolist(), name
            assert np.asarray(s_f).tolist() == np.asarray(s_e).tolist(), name

    def test_split_fast_derivation_against_oracle(self):
        for name, g in self._grids().items():
            words = sp.encode(jnp.asarray(g))
            ops = sp._tsplit_operands(words, SINGLE_DEVICE)
            _, a_f, s_f = sp._step_tsplit_fast(words, *ops, interpret=True)
            states = [g]
            for _ in range(sp.TEMPORAL_GENS):
                states.append(oracle.evolve(states[-1]))
            for t in range(sp.TEMPORAL_GENS):
                assert int(a_f[t]) == int(states[t + 1].any()), (name, t)
                assert int(s_f[t]) == int(
                    np.array_equal(states[t + 1], states[t])), (name, t)

    def test_split_fast_multi_band_and_folds(self, monkeypatch):
        # Banding engaged in both fast passes at a non-power-of-two fold
        # count (distinct shape from the exact-form test so the patched
        # band constant is read at a fresh trace).
        h, w = 48, 224
        rng = np.random.default_rng(101)
        g = rng.integers(0, 2, size=(h, w), dtype=np.uint8)
        monkeypatch.setattr(sp, "_BANDT_BYTES", 8 << 10)
        words = sp.encode(jnp.asarray(g))
        ops = sp._tsplit_operands(words, SINGLE_DEVICE)
        new_f, a_f, s_f = sp._step_tsplit_fast(words, *ops, interpret=True)
        states = [g]
        for _ in range(sp.TEMPORAL_GENS):
            states.append(oracle.evolve(states[-1]))
        np.testing.assert_array_equal(np.asarray(sp.decode(new_f)), states[-1])
        for t in range(sp.TEMPORAL_GENS):
            assert int(a_f[t]) == int(states[t + 1].any()), t
            assert int(s_f[t]) == int(
                np.array_equal(states[t + 1], states[t])), t


def test_split_fast_cross_shard_transient():
    """The split-composition analog of test_fast_flag_cross_shard_transient
    on an R x C mesh with C > 1: transients clustered on BOTH shard seams
    (row 32, column 128) of a 2x2 mesh die inside a temporal pass, so
    per-shard summaries lie about stillness. Cases found by simulating the
    derivation + blocked replay from oracle states over random seeds
    (tools/search_split_transient.py): deriving from UNVOTED per-shard
    summaries reports 3 and 1 generations respectively; the shipped
    globally-voted derivation must match the oracle (4 and 3)."""
    from gol_tpu.parallel.mesh import make_mesh

    cfg = GameConfig(gen_limit=30, similarity_frequency=1)
    cases = [
        ([32, 33, 32, 32, 34, 33, 34, 32, 32, 31, 34, 32, 34],
         [130, 128, 125, 127, 128, 129, 128, 129, 131, 131, 124, 130, 132]),
        ([32, 33, 32, 34, 34, 31], [130, 131, 127, 130, 131, 129]),
    ]
    for rows, cols in cases:
        g = np.zeros((64, 256), np.uint8)
        g[rows, cols] = 1
        want = oracle.run(g, cfg)
        got = engine.simulate(g, cfg, mesh=make_mesh(2, 2),
                              kernel="packed-interp")
        assert got.generations == want.generations, (rows, cols)
        np.testing.assert_array_equal(got.grid, want.grid)
