"""Horizontal control plane (PR 16): replicated routers, durable
coordination state, zero SPOFs.

The load-bearing blocks:

- TestFlockLease pins the SIGKILL-safety the whole leadership design
  rests on: the kernel drops a flock with its holder, including a
  ``kill -9``'d one — no heartbeat files, no timeouts, no clocks.
- TestManifestFlock is the satellite regression for the two-writer
  manifest race: ``write_manifest`` used to hold only a threading.Lock,
  so a second ROUTER PROCESS could interleave its tmp-write/rename and
  tear the membership record both replicas route from.
- TestRouterReplicaChaos is the client->router chaos matrix (the PR-14
  matrix covered router->worker): latency, reset, refusal on the hop the
  CLIENT dials, plus a router dropped mid-load with a second replica up
  — every accepted job still ends DONE exactly once and byte-identical
  to the oracle.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error

import numpy as np
import pytest

from gol_tpu import oracle
from gol_tpu.chaos import ChaosPlan, ProxyPool
from gol_tpu.config import GameConfig
from gol_tpu.fleet import client as fleet_client
from gol_tpu.fleet import lease, replicate
from gol_tpu.fleet.breaker import CLOSED, OPEN, BreakerConfig, CircuitBreaker
from gol_tpu.fleet.router import MonotonicCounters, RouterServer
from gol_tpu.fleet.workers import LEADER_LOCK, MANIFEST_LOCK, Fleet, Worker
from gol_tpu.io import text_grid
from gol_tpu.obs.history import HistoryWriter
from gol_tpu.serve.server import GolServer


def _http(method, url, body=None, timeout=30, headers=None):
    return fleet_client.http_json(method, url, body, timeout=timeout,
                                  headers=headers)


def _wait(predicate, timeout=60.0, interval=0.02):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------------------
# The flock lease primitive


class TestFlockLease:
    def test_exclusive_and_idempotent_within_process(self, tmp_path):
        """flock is per-OPEN-FILE, not per-process: two FlockLease
        objects in ONE process conflict exactly like two processes do —
        which is what makes the whole election testable in-process."""
        path = str(tmp_path / "leader.lock")
        a = lease.FlockLease(path, label="a")
        b = lease.FlockLease(path, label="b")
        assert a.try_acquire() is True
        assert a.try_acquire() is True  # idempotent re-contest
        assert b.try_acquire() is False
        assert a.held and not b.held
        a.release()
        assert not a.held
        assert b.try_acquire() is True
        b.release()

    def test_module_acquire_release(self, tmp_path):
        path = str(tmp_path / "some.lock")
        fd = lease.acquire(path)
        assert fd is not None
        assert lease.acquire(path) is None  # held: non-blocking refusal
        lease.release(fd)
        fd2 = lease.acquire(path)
        assert fd2 is not None
        lease.release(fd2)

    def test_blocking_acquire_waits_for_the_holder(self, tmp_path):
        path = str(tmp_path / "serial.lock")
        fd = lease.acquire(path)
        got = {}

        def contend():
            got["fd"] = lease.acquire(path, blocking=True)

        t = threading.Thread(target=contend)
        t.start()
        time.sleep(0.1)
        assert "fd" not in got  # still blocked behind the holder
        lease.release(fd)
        t.join(timeout=10)
        assert got.get("fd") is not None
        lease.release(got["fd"])

    def test_sigkill_drops_the_lock(self, tmp_path):
        """The design's keystone: a ``kill -9``'d holder releases by
        KERNEL action — the survivor acquires without any timeout or
        heartbeat protocol."""
        path = str(tmp_path / "leader.lock")
        ready = str(tmp_path / "ready")
        holder = subprocess.Popen([
            sys.executable, "-c",
            "import fcntl, os, sys, time\n"
            f"fd = os.open({path!r}, os.O_WRONLY | os.O_CREAT, 0o644)\n"
            "fcntl.flock(fd, fcntl.LOCK_EX)\n"
            f"open({ready!r}, 'w').close()\n"
            "time.sleep(600)\n",
        ])
        try:
            assert _wait(lambda: os.path.exists(ready), timeout=30)
            assert lease.acquire(path) is None  # the child really holds it
            os.kill(holder.pid, signal.SIGKILL)
            holder.wait(timeout=30)
            fd = lease.acquire(path)
            assert fd is not None  # dropped with the corpse, instantly
            lease.release(fd)
        finally:
            if holder.poll() is None:
                holder.kill()
                holder.wait()


# ---------------------------------------------------------------------------
# Satellite regression: cross-process manifest writes are flock-serialized


class TestManifestFlock:
    def test_writer_blocks_behind_a_foreign_lock_holder(self, tmp_path):
        """``write_manifest`` used to take only ``self._lock`` — a
        threading.Lock, invisible to a second router PROCESS, whose
        interleaved tmp-write/rename could tear the membership both
        replicas route from. Now the write blocks on the cross-process
        ``manifest.lock`` flock first."""
        fleet = Fleet(str(tmp_path))
        fleet.attach("http://127.0.0.1:1/", "w0")
        lock_fd = lease.acquire(os.path.join(str(tmp_path), MANIFEST_LOCK))
        assert lock_fd is not None
        os.remove(fleet.manifest_path)
        done = threading.Event()

        def write():
            fleet.write_manifest()
            done.set()

        t = threading.Thread(target=write)
        t.start()
        try:
            time.sleep(0.15)
            assert not done.is_set()  # serialized behind the foreign lock
            assert not os.path.exists(fleet.manifest_path)
        finally:
            lease.release(lock_fd)
            t.join(timeout=10)
        assert done.is_set()
        with open(fleet.manifest_path, encoding="utf-8") as f:
            doc = json.load(f)
        assert [p["id"] for p in doc["partitions"]] == ["w0"]

    def test_two_writer_hammering_never_tears_the_manifest(self, tmp_path):
        """Two Fleet instances over ONE fleet dir (two open files — a
        real flock conflict, same as two processes) hammer writes
        concurrently; every intermediate read parses and the final doc is
        whole."""
        a = Fleet(str(tmp_path))
        b = Fleet(str(tmp_path))
        a.attach("http://127.0.0.1:1/", "wa")
        b.attach("http://127.0.0.1:2/", "wb")
        stop = threading.Event()
        torn = []

        def hammer(fleet):
            while not stop.is_set():
                fleet.write_manifest()

        def read():
            while not stop.is_set():
                try:
                    with open(a.manifest_path, encoding="utf-8") as f:
                        doc = json.load(f)
                    if doc.get("version") != 1:
                        torn.append(doc)
                except FileNotFoundError:
                    pass
                except ValueError as err:
                    torn.append(repr(err))

        threads = [threading.Thread(target=hammer, args=(a,)),
                   threading.Thread(target=hammer, args=(b,)),
                   threading.Thread(target=read)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not torn
        with open(a.manifest_path, encoding="utf-8") as f:
            doc = json.load(f)
        assert doc["version"] == 1 and len(doc["partitions"]) == 1

    def test_follower_replica_never_writes(self, tmp_path):
        primary = Fleet(str(tmp_path))
        primary.attach("http://127.0.0.1:1/", "w0")
        assert primary.enable_leader_election("r0") is True
        follower = Fleet(str(tmp_path), replica=True)
        follower.load()
        assert follower.enable_leader_election("r1") is False
        before = open(primary.manifest_path, "rb").read()
        follower.attach("http://127.0.0.1:9/", "w9")  # in-memory only
        assert open(primary.manifest_path, "rb").read() == before
        primary.release_leadership()
        follower.release_leadership()

    def test_config_block_round_trips(self, tmp_path):
        primary = Fleet(str(tmp_path))
        primary.manifest_config = {"serve_args": ["--max-batch", "8"],
                                   "big_edge": 2048}
        primary.attach("http://127.0.0.1:1/", "w0")
        replica = Fleet(str(tmp_path), replica=True)
        replica.load()
        assert replica.manifest_config == primary.manifest_config


# ---------------------------------------------------------------------------
# Leader election over the shared fleet dir


class TestLeaderElection:
    def test_lease_less_fleet_supervises_unconditionally(self, tmp_path):
        fleet = Fleet(str(tmp_path))
        assert fleet.leading is True  # exactly as before elections existed

    def test_follower_takes_over_on_release(self, tmp_path):
        primary = Fleet(str(tmp_path))
        primary.attach("http://127.0.0.1:1/", "w0")
        assert primary.enable_leader_election("r0") is True
        replica = Fleet(str(tmp_path), replica=True)
        replica.load()
        assert replica.enable_leader_election("r1") is False
        assert not replica.leading
        # While following, a health tick re-contests but cannot win.
        replica._poll_leadership()
        assert not replica.leading
        primary.release_leadership()
        replica._poll_leadership()  # what every health tick runs
        assert replica.leading
        assert not os.path.exists(os.path.join(str(tmp_path), "nonsense"))
        replica.release_leadership()
        assert not replica.leading  # a replica demotes on voluntary release

    def test_replica_load_adopts_dead_partitions_without_respawn(
            self, tmp_path):
        primary = Fleet(str(tmp_path))
        # A LOCAL partition record (journal set, not attached) whose
        # process is gone: the old load() would have respawned it.
        primary._workers["w0"] = Worker(id="w0", url="http://127.0.0.1:1",
                                        journal_dir=str(tmp_path / "w0"))
        primary.write_manifest()
        replica = Fleet(str(tmp_path), replica=True)
        n = replica.load()
        assert n == 1
        worker = replica.worker("w0")
        assert worker is not None
        assert worker.proc is None  # adopted, never spawned
        assert worker.healthy is False  # probed, not trusted

    def test_reconcile_follows_the_leaders_manifest(self, tmp_path):
        primary = Fleet(str(tmp_path))
        primary.attach("http://127.0.0.1:1/", "w0")
        replica = Fleet(str(tmp_path), replica=True)
        replica.load()
        assert {w.id for w in replica.workers()} == {"w0"}
        # Scale-up appears...
        primary.attach("http://127.0.0.1:2/", "w1")
        assert replica.reconcile_from_manifest() >= 1
        assert {w.id for w in replica.workers()} \
            == {"w0", "w1"}
        # ...a respawn's fresh URL replaces the dead one...
        primary.worker("w0").url = "http://127.0.0.1:3"
        primary.write_manifest()
        replica.reconcile_from_manifest()
        assert replica.worker("w0").url == "http://127.0.0.1:3"
        # ...and a retire drops out.
        with primary._lock:
            del primary._workers["w1"]
        primary.write_manifest()
        replica.reconcile_from_manifest()
        assert {w.id for w in replica.workers()} == {"w0"}


# ---------------------------------------------------------------------------
# Durable counter floors


class TestDurableFloors:
    def _snap(self, value):
        return {"counters": {"jobs_completed_total": value}}

    def test_state_seed_round_trip_survives_router_restart(self):
        """The regression the floors exist to prevent, now for ROUTER
        death: worker respawns banked into a router's floors must not
        reset when the router itself is replaced."""
        counters = MonotonicCounters()
        counters.adjust({"w0": self._snap(100.0)})
        # The worker respawns: its raw counter regresses, the floor banks
        # the old run.
        snap = counters.adjust({"w0": self._snap(5.0)})
        assert snap["w0"]["counters"]["jobs_completed_total"] == 105.0
        state = json.loads(json.dumps(counters.state()))  # disk-shaped
        successor = MonotonicCounters()
        successor.seed(state)
        snap = successor.adjust({"w0": self._snap(7.0)})
        assert snap["w0"]["counters"]["jobs_completed_total"] == 107.0

    def test_seed_banks_a_respawn_during_the_router_outage(self):
        """A worker that restarted while NO router watched answers the
        successor's first scrape with value < the seeded last — the
        regression fallback banks the lost run."""
        counters = MonotonicCounters()
        counters.adjust({"w0": self._snap(50.0)})
        successor = MonotonicCounters()
        successor.seed(counters.state())
        snap = successor.adjust({"w0": self._snap(2.0)})
        assert snap["w0"]["counters"]["jobs_completed_total"] == 52.0

    def test_seed_is_first_writer_only(self):
        counters = MonotonicCounters()
        counters.adjust({"w0": self._snap(10.0)})
        counters.seed({"version": 1, "base": [], "incarnations": {},
                       "last": [["w0", ["c", "jobs_completed_total"],
                                 999.0]]})
        snap = counters.adjust({"w0": self._snap(11.0)})
        assert snap["w0"]["counters"]["jobs_completed_total"] == 11.0

    def test_floors_store_roundtrip_and_tolerance(self, tmp_path):
        store = replicate.FloorsStore(str(tmp_path / "r0"))
        assert store.load() is None
        state = {"version": 1, "base": [], "last": [], "incarnations": {}}
        store.save(state)
        assert replicate.FloorsStore(str(tmp_path / "r0")).load() == state
        # Damage tolerance: garbage loads as None, never raises.
        with open(store.path, "w", encoding="utf-8") as f:
            f.write("{torn")
        assert replicate.FloorsStore(str(tmp_path / "r0")).load() is None

    def test_save_skips_unchanged_state(self, tmp_path):
        store = replicate.FloorsStore(str(tmp_path / "r0"))
        state = {"version": 1, "base": [["w0", ["c", "x"], 5.0]],
                 "last": [], "incarnations": {}}
        store.save(state)
        stamp = os.stat(store.path).st_mtime_ns
        store.save(dict(state))
        assert os.stat(store.path).st_mtime_ns == stamp  # zero I/O idle

    def test_merged_floors_take_the_larger_total(self, tmp_path):
        key = ["counters", "jobs_completed_total"]
        replicate.FloorsStore(
            str(tmp_path / replicate.ROUTERS_SUBDIR / "r0")).save({
                "version": 1, "base": [["w0", key, 100.0]],
                "last": [["w0", key, 5.0]], "incarnations": {"w0": 2}})
        replicate.FloorsStore(
            str(tmp_path / replicate.ROUTERS_SUBDIR / "r1")).save({
                "version": 1, "base": [["w0", key, 40.0]],
                "last": [["w0", key, 9.0]], "incarnations": {"w0": 3}})
        merged = replicate.load_merged_floors(str(tmp_path))
        assert merged is not None
        assert merged["base"] == [["w0", key, 100.0]]  # 105 beats 49
        assert merged["last"] == [["w0", key, 5.0]]
        assert merged["incarnations"] == {"w0": 3}  # max wins

    def test_merged_floors_none_when_nothing_persisted(self, tmp_path):
        assert replicate.load_merged_floors(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# Breaker warm-start


class TestBreakerWarmStart:
    def test_reopen_trips_only_from_closed(self):
        transitions = []
        br = CircuitBreaker(BreakerConfig(cooldown_s=60.0),
                            on_transition=lambda *a: transitions.append(a),
                            label="w0")
        assert br.state == CLOSED
        br.reopen()
        assert br.state == OPEN
        assert br.penalty() == 1  # fresh cooldown from NOW
        br.reopen()  # idempotent: already open
        assert transitions == [("w0", CLOSED, OPEN)]

    def _ring(self, tmp_path, rid, events):
        ring = HistoryWriter(
            os.path.join(replicate.state_dir(str(tmp_path), rid),
                         replicate.BREAKER_RING),
            source="breaker")
        for worker, old, new in events:
            ring.append({"breaker": {"worker": worker, "from": old,
                                     "to": new}})
        ring.close()

    def test_warm_states_fold_to_last_word_per_worker(self, tmp_path):
        self._ring(tmp_path, "r0", [
            ("w0", "closed", "open"),
            ("w0", "open", "half-open"),
            ("w0", "half-open", "closed"),  # recovered: NOT warm
            ("w1", "closed", "open"),       # died open: warm
        ])
        assert replicate.warm_breaker_states(str(tmp_path)) == {"w1": "open"}

    def test_half_open_at_death_rearms_open(self, tmp_path):
        self._ring(tmp_path, "r0", [("w0", "open", "half-open")])
        assert replicate.warm_breaker_states(str(tmp_path)) == {"w0": "open"}

    def test_any_replicas_open_verdict_wins(self, tmp_path):
        self._ring(tmp_path, "r0", [("w0", "half-open", "closed")])
        self._ring(tmp_path, "r1", [("w0", "closed", "open")])
        assert replicate.warm_breaker_states(str(tmp_path)) == {"w0": "open"}

    def test_empty_fleet_dir_is_cold(self, tmp_path):
        assert replicate.warm_breaker_states(str(tmp_path)) == {}


# ---------------------------------------------------------------------------
# Router advertisement / roster


class TestRouterRoster:
    def test_advertise_and_list(self, tmp_path):
        replicate.advertise(str(tmp_path), "r0", "http://127.0.0.1:8000")
        routers = replicate.list_routers(str(tmp_path))
        assert len(routers) == 1
        advert = routers[0]
        assert advert["id"] == "r0"
        assert advert["url"] == "http://127.0.0.1:8000"
        assert advert["pid"] == os.getpid()
        assert advert["alive"] is True  # our own pid exists

    def test_dead_pid_reads_gone(self, tmp_path):
        directory = replicate.state_dir(str(tmp_path), "rX")
        os.makedirs(directory)
        with open(os.path.join(directory, replicate.ADVERT_FILENAME),
                  "w", encoding="utf-8") as f:
            json.dump({"id": "rX", "url": "http://x", "pid": 2 ** 22 + 9},
                      f)
        routers = replicate.list_routers(str(tmp_path))
        assert routers and routers[0]["alive"] is False


# ---------------------------------------------------------------------------
# The client->router chaos matrix (satellite: the hop PR 14 left bare)


@pytest.fixture(scope="module")
def control_workers(tmp_path_factory):
    root = tmp_path_factory.mktemp("control-fleet")
    workers = {}
    for wid in ("w0", "w1"):
        srv = GolServer(port=0, journal_dir=str(root / wid), flush_age=0.01)
        srv.start()
        workers[wid] = srv
    yield root, workers
    for srv in workers.values():
        srv.shutdown()


_HOP_PLANS = {
    "latency": "seed=201,latency=0.3,latency_ms=30",
    "reset": "seed=202,reset=0.15",
    "refuse": "seed=203,refuse=0.2",
}


class TestRouterReplicaChaos:
    """Two replica routers over ONE fleet, chaos on the CLIENT->ROUTER
    hop (PR 14's matrix chaoses router->worker; this is the other hop).
    The client stance mirrors `gol submit --servers`: POSTs rotate to the
    sibling only on connection-level trouble, GETs rotate freely — and
    the audit is the same: every accepted job DONE exactly once, every
    result oracle-byte-identical, and no id EVER holds two done records
    (a reset-after-accept orphan completes exactly once under its own
    id)."""

    GENS = 6
    JOBS = 6

    def _rig(self, tmp_path, workers):
        primary = Fleet(str(tmp_path / "fleet"))
        for wid, srv in workers.items():
            primary.attach(srv.url, wid)
        assert primary.enable_leader_election("r0") is True
        r0 = RouterServer(primary, port=0, router_id="r0",
                          state_dir=replicate.state_dir(
                              primary.fleet_dir, "r0"))
        r0.start()
        follower = Fleet(str(tmp_path / "fleet"), replica=True)
        follower.load()
        assert follower.enable_leader_election("r1") is False
        r1 = RouterServer(follower, port=0, router_id="r1",
                          state_dir=replicate.state_dir(
                              follower.fleet_dir, "r1"))
        r1.start()
        return r0, r1

    def _boards(self, salt):
        return [text_grid.generate(32, 32, seed=9000 + 64 * salt + i)
                for i in range(self.JOBS)]

    def _submit_one(self, bases, board):
        meta = {"gen_limit": self.GENS}
        body = {"width": 32, "height": 32,
                "cells": text_grid.encode(board).decode("ascii"), **meta}
        for attempt in range(200):
            base = bases[attempt % len(bases)]
            try:
                status, payload = _http("POST", f"{base}/jobs", body,
                                        timeout=10)
            except (urllib.error.URLError, ConnectionError, OSError):
                # Refused: rotate to the sibling replica. A reset is
                # ambiguous — the production client surfaces it; the
                # matrix resubmits KNOWINGLY (fresh id), and the audit
                # proves the possible orphan still lands exactly one
                # done record under its own id.
                time.sleep(0.02)
                continue
            if status == 202 and isinstance(payload, dict) \
                    and payload.get("id"):
                return payload["id"], base
            if status in (429, 503, 504):
                time.sleep(0.02)
                continue
            raise AssertionError(f"unexpected submit answer {status}: "
                                 f"{payload}")
        raise AssertionError("submit never landed")

    def _await_done(self, bases, job_id):
        for attempt in range(600):
            base = bases[attempt % len(bases)]
            try:
                status, payload = _http("GET", f"{base}/jobs/{job_id}",
                                        timeout=10)
            except (urllib.error.URLError, ConnectionError, OSError):
                time.sleep(0.02)
                continue
            state = (payload.get("state")
                     if isinstance(payload, dict) else None)
            if state == "done":
                return
            if state in ("failed", "cancelled"):
                raise AssertionError(f"job {job_id} ended {state}")
            time.sleep(0.02)
        raise AssertionError(f"job {job_id} never finished")

    def _fetch_result(self, bases, job_id):
        for attempt in range(300):
            base = bases[attempt % len(bases)]
            try:
                status, payload = _http("GET", f"{base}/result/{job_id}",
                                        timeout=10)
            except (urllib.error.URLError, ConnectionError, OSError):
                time.sleep(0.02)
                continue
            if status != 200 or not isinstance(payload, dict):
                time.sleep(0.02)
                continue
            grid = text_grid.decode(payload["grid"].encode("ascii"),
                                    payload["width"], payload["height"])
            return payload, grid
        raise AssertionError(f"result {job_id} never fetched")

    def _audit(self, root, workers, accepted):
        def done():
            records: dict = {}
            for wid in workers:
                path = root / wid / "journal.jsonl"
                if not path.exists():
                    continue
                for line in path.read_bytes().split(b"\n"):
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("event") == "done":
                        records.setdefault(rec["id"], []).append(wid)
            return records

        assert _wait(lambda: set(accepted) <= set(done()), timeout=20)
        records = done()
        for job_id in accepted:
            assert len(records[job_id]) == 1, (job_id, records[job_id])
        # NO id anywhere holds two done records — the exactly-once
        # catch-all that also covers reset-after-accept orphans.
        for job_id, where in records.items():
            assert len(where) == 1, (job_id, where)

    @pytest.mark.parametrize("fault", sorted(_HOP_PLANS))
    def test_hop_fault_class(self, fault, tmp_path, control_workers):
        root, workers = control_workers
        r0, r1 = self._rig(tmp_path, workers)
        pool = ProxyPool(ChaosPlan.parse(_HOP_PLANS[fault]))
        try:
            # Chaos fronts the CLIENT->ROUTER hop: the client dials the
            # proxies; the routers themselves stay clean.
            bases = [pool.url_for(r0.url), pool.url_for(r1.url)]
            accepted = {}
            for board in self._boards(sorted(_HOP_PLANS).index(fault)):
                job_id, _ = self._submit_one(bases, board)
                accepted[job_id] = board
            for job_id in accepted:
                self._await_done(bases, job_id)
            for job_id, board in accepted.items():
                result, got = self._fetch_result(bases, job_id)
                want = oracle.run(board, GameConfig(gen_limit=self.GENS))
                np.testing.assert_array_equal(np.asarray(got), want.grid)
                assert result["generations"] == want.generations
            assert pool.stats().get(fault, 0) > 0  # the fault FIRED
        finally:
            pool.close()
            r1.shutdown(cascade=False)
            r0.shutdown(cascade=False)
        self._audit(root, workers, accepted)

    def test_router_down_mid_load_is_invisible(self, tmp_path,
                                               control_workers):
        """The tentpole's acceptance row: with N=2 replicas, dropping the
        leader router mid-load costs nothing — the client fails over to
        the survivor, the survivor takes the lease, and every accepted
        job (submitted via EITHER router) ends DONE exactly once and
        oracle-identical."""
        root, workers = control_workers
        r0, r1 = self._rig(tmp_path, workers)
        killed = False
        try:
            accepted = {}
            boards = self._boards(11)
            for board in boards[:self.JOBS // 2]:
                job_id, _ = self._submit_one([r0.url], board)
                accepted[job_id] = board
            # Drop the leader mid-load. In-process the shutdown releases
            # the lease the way the kernel would on SIGKILL (the
            # kernel-drop itself is pinned in TestFlockLease); the REAL
            # kill -9 end-to-end runs in tools/control_smoke.py.
            r0.shutdown(cascade=False)
            killed = True
            with pytest.raises((urllib.error.URLError, ConnectionError,
                                OSError)):
                _http("GET", f"{r0.url}/healthz", timeout=2)
            # The survivor takes the lease on its next tick...
            r1.fleet._poll_leadership()
            assert r1.fleet.leading
            # ...and carries the rest of the load alone.
            for board in boards[self.JOBS // 2:]:
                job_id, _ = self._submit_one([r1.url], board)
                accepted[job_id] = board
            for job_id in accepted:
                self._await_done([r1.url], job_id)
            for job_id, board in accepted.items():
                result, got = self._fetch_result([r1.url], job_id)
                want = oracle.run(board, GameConfig(gen_limit=self.GENS))
                np.testing.assert_array_equal(np.asarray(got), want.grid)
        finally:
            r1.shutdown(cascade=False)
            if not killed:
                r0.shutdown(cascade=False)
        self._audit(root, workers, accepted)

    def test_floors_survive_router_replacement(self, tmp_path,
                                               control_workers):
        """Durable coordination state end to end: a router that scraped
        real workers persists its floors; a SUCCESSOR router (fresh id,
        fresh process state) seeds from the merged files and its merged
        counters never regress."""
        root, workers = control_workers
        primary = Fleet(str(tmp_path / "fleet"))
        for wid, srv in workers.items():
            primary.attach(srv.url, wid)
        r0 = RouterServer(primary, port=0, router_id="r0",
                          state_dir=replicate.state_dir(
                              primary.fleet_dir, "r0"))
        r0.start()
        try:
            board = text_grid.generate(32, 32, seed=321)
            job_id, _ = self._submit_one([r0.url], board)
            self._await_done([r0.url], job_id)
            status, merged = _http("GET", f"{r0.url}/metrics?format=json")
            assert status == 200
            floors_path = os.path.join(
                replicate.state_dir(primary.fleet_dir, "r0"),
                replicate.FLOORS_FILENAME)
            assert _wait(lambda: os.path.exists(floors_path), timeout=10)
        finally:
            r0.shutdown(cascade=False)
        successor = Fleet(str(tmp_path / "fleet"), replica=True)
        successor.load()
        r2 = RouterServer(successor, port=0, router_id="r2",
                          state_dir=replicate.state_dir(
                              successor.fleet_dir, "r2"))
        r2.start()
        try:
            status, merged2 = _http("GET", f"{r2.url}/metrics?format=json")
            assert status == 200
            done_before = sum(
                (w.get("counters") or {}).get("jobs_completed_total", 0)
                for w in (merged.get("workers") or {}).values())
            done_after = sum(
                (w.get("counters") or {}).get("jobs_completed_total", 0)
                for w in (merged2.get("workers") or {}).values())
            assert done_after >= done_before  # monotonic across routers
            assert merged2["fleet"]["router_id"] == "r2"
        finally:
            r2.shutdown(cascade=False)


# ---------------------------------------------------------------------------
# The --servers client ring (satellite: `gol top` against a dead router)


class TestServerRing:
    def test_ring_parsing_and_rotation(self):
        from gol_tpu.cli import _ServerRing

        ring = _ServerRing("http://a:1, http://b:2/,http://c:3")
        assert ring.bases == ["http://a:1", "http://b:2", "http://c:3"]
        assert ring.current == "http://a:1"
        assert ring.others("http://b:2") == ["http://c:3", "http://a:1"]
        ring.prefer("http://c:3")
        assert ring.rotation() == ["http://c:3", "http://a:1", "http://b:2"]
        assert _ServerRing("http://solo:1").others("http://solo:1") == []
        with pytest.raises(ValueError):
            _ServerRing(" , ")

    def test_top_fails_over_and_names_the_answering_router(
            self, tmp_path, capsys, monkeypatch):
        """Satellite regression: `gol top` against a DEAD router used to
        render empty frames forever. With --servers it walks the ring,
        renders the survivor's view, and the title names which replica
        answered."""
        import argparse

        from gol_tpu import cli

        primary = Fleet(str(tmp_path / "fleet"))
        primary.attach("http://127.0.0.1:1/", "w0")
        live = RouterServer(primary, port=0, router_id="r1",
                            state_dir=replicate.state_dir(
                                primary.fleet_dir, "r1"))
        live.start()
        try:
            dead = "http://127.0.0.1:9"  # discard port: refuses instantly
            args = argparse.Namespace(
                server=dead, servers=f"{dead},{live.url}",
                interval=0.05, iterations=1, no_ansi=True)
            rc = cli._top(args)
            out = capsys.readouterr().out
        finally:
            live.shutdown(cascade=False)
        assert rc == 0
        assert f"gol top — {live.url.rstrip('/')}" in out
        assert "answered by" in out
        assert "router" in out  # the replica panel rendered

    def test_top_single_server_title_is_pinned(self, tmp_path, capsys):
        import argparse

        from gol_tpu import cli

        args = argparse.Namespace(
            server="http://127.0.0.1:9", servers=None,
            interval=0.05, iterations=1, no_ansi=True)
        assert cli._top(args) == 0
        out = capsys.readouterr().out
        assert "gol top — http://127.0.0.1:9" in out
        assert "answered by" not in out
        assert "routers unreachable" not in out  # no ring annotations

    def test_collect_results_rehomes_polling_to_a_live_replica(
            self, tmp_path, control_workers, capsys):
        """`gol submit --wait --servers`: a job recorded against the dead
        router's base is polled (and its result fetched) via the
        surviving replica — any replica can look up any job."""
        import argparse

        from gol_tpu import cli

        root, workers = control_workers
        primary = Fleet(str(tmp_path / "fleet"))
        for wid, srv in workers.items():
            primary.attach(srv.url, wid)
        live = RouterServer(primary, port=0, router_id="r1",
                            state_dir=replicate.state_dir(
                                primary.fleet_dir, "r1"))
        live.start()
        try:
            board = text_grid.generate(32, 32, seed=77)
            status, payload = _http("POST", f"{live.url}/jobs", {
                "width": 32, "height": 32,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": 4})
            assert status == 202
            dead = "http://127.0.0.1:9"
            src = str(tmp_path / "in.txt")
            text_grid.write_grid(src, board)
            args = argparse.Namespace(
                poll_interval=0.05, server_timeout=30.0, wire="text")
            ring = cli._ServerRing([dead, live.url])
            rc = cli._collect_results(
                {payload["id"]: (src, dead)}, args, str(tmp_path),
                ring=ring)
            err = capsys.readouterr().err
        finally:
            live.shutdown(cascade=False)
        assert rc == 0
        assert "polling job" in err and live.url.rstrip("/") in err
        got = text_grid.read_grid(
            os.path.join(str(tmp_path), "in.txt.out"), 32, 32)
        want = oracle.run(board, GameConfig(gen_limit=4))
        np.testing.assert_array_equal(np.asarray(got), want.grid)


# ---------------------------------------------------------------------------
# Leader-gated ticks


class TestLeaderGatedTicks:
    def test_follower_autoscaler_tick_noops(self, tmp_path):
        from gol_tpu.fleet.autoscale import AutoscaleConfig, Autoscaler

        primary = Fleet(str(tmp_path))
        primary.attach("http://127.0.0.1:1/", "w0")
        assert primary.enable_leader_election("r0")
        follower = Fleet(str(tmp_path), replica=True)
        follower.load()
        follower.enable_leader_election("r1")

        class _Router:
            def slo_json(self):
                raise AssertionError("a follower must not even scrape")

            url = "http://x"

        scaler = Autoscaler(follower, _Router(),
                            AutoscaleConfig(min_workers=1, max_workers=4))
        assert scaler.tick() is None  # gated before any work
        primary.release_leadership()
        follower.release_leadership()

    def test_follower_health_tick_reconciles_membership(self, tmp_path):
        primary = Fleet(str(tmp_path))
        primary.attach("http://127.0.0.1:1/", "w0")
        assert primary.enable_leader_election("r0")
        follower = Fleet(str(tmp_path), replica=True)
        follower.load()
        follower.enable_leader_election("r1")
        primary.attach("http://127.0.0.1:2/", "w1")
        follower.health_tick()  # reconciles BEFORE probing
        assert {w.id for w in follower.workers()} \
            == {"w0", "w1"}
        primary.release_leadership()
        follower.release_leadership()
