"""Content-addressed result cache (gol_tpu/cache) + its serve/fleet tiers.

Covers the ISSUE 9 acceptance surface:

- fingerprint stability: same board under different layouts/shardings and
  different QoS/padding decompositions -> same key; every answer-changing
  config axis -> different key; the router's jax-free ``body_fingerprint``
  agrees with the worker's ``job_fingerprint``.
- tiers: LRU bound + recency, CAS round-trip, torn/corrupt/mismatched
  entries evicted loudly (and re-runnable), the optional TensorStore
  payload lane, disk->memory promotion.
- scheduler: hits byte-identical to engine results and journaled as normal
  DONE records (replay-after-hit exactly-once), in-flight dedup (one
  engine run, N journaled completions), cancel semantics for leaders and
  followers, no_cache opt-out, corrupt-entry re-run.
- fleet tier: deterministic fingerprint-HRW routing (repeats land on the
  owner), fallbacks for no_cache and unfingerprintable bodies.
"""

import json
import threading
import time

import numpy as np
import pytest

from gol_tpu.cache import CacheEntry, DiskCAS, MemoryLRU, ResultCache
from gol_tpu.cache.fingerprint import (
    board_digest,
    body_fingerprint,
    job_fingerprint,
    result_fingerprint,
)
from gol_tpu.io import text_grid
from gol_tpu.serve import batcher
from gol_tpu.serve.jobs import CANCELLED, DONE, FAILED, JobJournal, new_job
from gol_tpu.serve.metrics import Metrics
from gol_tpu.serve.scheduler import Scheduler


def _board(seed: int, shape=(16, 16)) -> np.ndarray:
    return np.random.default_rng(seed).integers(
        0, 2, size=shape, dtype=np.uint8
    )


def _entry(seed: int = 0, shape=(16, 16)) -> CacheEntry:
    return CacheEntry(grid=_board(seed, shape), generations=seed + 1,
                      exit_reason="gen_limit")


def _wait_done(jobs, timeout=30.0):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if all(j.state in (DONE, FAILED, CANCELLED) for j in jobs):
            return
        time.sleep(0.005)
    raise AssertionError(
        f"jobs not terminal: {[(j.id, j.state) for j in jobs]}"
    )


# ---------------------------------------------------------------------------
class TestFingerprint:
    def test_stable_across_layouts(self):
        g = _board(3)
        assert board_digest(g) == board_digest(g.copy())
        assert board_digest(g) == board_digest(np.asfortranarray(g))
        assert board_digest(g) == board_digest(g.astype(np.int64))

    def test_sharding_independent(self):
        # Same shard-faking scheme as the checkpoint fingerprint tests:
        # the digest must not depend on how the cells are decomposed.
        g = _board(4, (8, 8))

        def sharded(cuts):
            shards = [
                type("S", (), {"data": g[rs, cs], "index": (rs, cs)})()
                for rs, cs in cuts
            ]
            return type("A", (), {"shape": g.shape,
                                  "addressable_shards": shards})()

        rows = sharded([(slice(0, 4), slice(0, 8)),
                        (slice(4, 8), slice(0, 8))])
        quads = sharded([
            (slice(0, 4), slice(0, 4)), (slice(0, 4), slice(4, 8)),
            (slice(4, 8), slice(0, 4)), (slice(4, 8), slice(4, 8)),
        ])
        assert board_digest(rows) == board_digest(g)
        assert board_digest(quads) == board_digest(g)

    def test_decomposition_fields_do_not_enter_the_key(self):
        # Priority, deadline, and padding/batching are decomposition — the
        # engine contract makes the answer identical across them, so two
        # jobs differing only there MUST share a key (that is the hit).
        g = _board(5, (30, 30))
        a = new_job(30, 30, g, gen_limit=8)
        b = new_job(30, 30, g, gen_limit=8, priority=7, deadline_s=1.0)
        assert job_fingerprint(a) == job_fingerprint(b)

    def test_answer_axes_change_the_key(self):
        g = _board(6)
        base = result_fingerprint(g, "c", 8, True, 3)
        assert result_fingerprint(g, "cuda", 8, True, 3) != base
        assert result_fingerprint(g, "c", 9, True, 3) != base
        assert result_fingerprint(g, "c", 8, False, 3) != base
        assert result_fingerprint(g, "c", 8, True, 4) != base
        assert result_fingerprint(_board(7), "c", 8, True, 3) != base

    def test_geometry_is_part_of_the_key(self):
        # All-dead boards digest identically at any shape (zero cells
        # contribute zero; equal byte counts CRC equally) — the declared
        # extents in the key are what keeps 8x16 and 4x32 from aliasing.
        a, b = np.zeros((8, 16), np.uint8), np.zeros((4, 32), np.uint8)
        assert board_digest(a) == board_digest(b)
        assert result_fingerprint(a) != result_fingerprint(b)

    def test_body_fingerprint_matches_job_fingerprint(self):
        g = _board(8, (30, 30))
        job = new_job(30, 30, g, gen_limit=12, convention="cuda",
                      similarity_frequency=5)
        body = {
            "width": 30, "height": 30,
            "cells": text_grid.encode(g).decode("ascii"),
            "convention": "cuda", "gen_limit": 12,
            "similarity_frequency": 5,
        }
        assert body_fingerprint(body) == job_fingerprint(job)
        # Defaults applied router-side match the worker's defaults.
        job_d = new_job(30, 30, g)
        assert body_fingerprint({
            "width": 30, "height": 30,
            "cells": text_grid.encode(g).decode("ascii"),
        }) == job_fingerprint(job_d)


# ---------------------------------------------------------------------------
class TestMemoryLRU:
    def test_bound_and_recency(self):
        lru = MemoryLRU(max_entries=2)
        for i in range(3):
            lru.put(f"k{i}", _entry(i))
        assert len(lru) == 2
        assert lru.get("k0") is None  # oldest evicted
        assert lru.evictions == 1
        # A get refreshes recency: k1 survives the next insert, k2 goes.
        assert lru.get("k1") is not None
        lru.put("k3", _entry(3))
        assert lru.get("k1") is not None and lru.get("k2") is None

    def test_min_bound(self):
        with pytest.raises(ValueError):
            MemoryLRU(max_entries=0)


# ---------------------------------------------------------------------------
class TestDiskCAS:
    def test_round_trip(self, tmp_path):
        cas = DiskCAS(str(tmp_path))
        e = _entry(1)
        cas.put("fp1", e)
        back = cas.get("fp1")
        assert back is not None
        assert np.array_equal(back.grid, e.grid)
        assert (back.generations, back.exit_reason) == (2, "gen_limit")
        assert cas.get("missing") is None

    def test_torn_entry_evicts_and_reruns(self, tmp_path):
        evicted = []
        cas = DiskCAS(str(tmp_path), on_evict=lambda fp, r: evicted.append(fp))
        cas.put("fp1", _entry(1))
        path = cas.meta_path("fp1")
        raw = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(raw[: len(raw) // 2])  # torn mid-line
        assert cas.get("fp1") is None
        assert evicted == ["fp1"]
        import os

        assert not os.path.exists(path)  # evicted, not left to re-fail
        cas.put("fp1", _entry(1))  # re-run repopulates cleanly
        assert cas.get("fp1") is not None

    def test_corrupt_payload_fails_crc(self, tmp_path):
        cas = DiskCAS(str(tmp_path))
        cas.put("fp1", _entry(1))
        path = cas.meta_path("fp1")
        meta = json.load(open(path))
        meta["generations"] = 999  # poison a scalar: CRC covers it too
        with open(path, "w") as f:
            json.dump(meta, f)
        assert cas.get("fp1") is None

    def test_foreign_entry_fingerprint_mismatch(self, tmp_path):
        import shutil

        cas = DiskCAS(str(tmp_path))
        cas.put("fp1", _entry(1))
        other = cas.meta_path("fp9")
        import os

        os.makedirs(os.path.dirname(other), exist_ok=True)
        shutil.copy(cas.meta_path("fp1"), other)
        assert cas.get("fp9") is None  # stored fingerprint disagrees

    def test_ts_payload_round_trip(self, tmp_path):
        # Exact-fit packable width -> the TensorStore zarr lane.
        cas = DiskCAS(str(tmp_path), payload="ts")
        e = _entry(2, shape=(16, 32))
        cas.put("fp32", e)
        meta = json.load(open(cas.meta_path("fp32")))
        assert meta["payload"] == "ts" and "grid" not in meta
        back = cas.get("fp32")
        assert back is not None and np.array_equal(back.grid, e.grid)

    def test_ts_lane_falls_back_for_unpackable_width(self, tmp_path):
        cas = DiskCAS(str(tmp_path), payload="ts")
        e = _entry(3, shape=(16, 30))  # 30 % 32 != 0
        cas.put("fp30", e)
        meta = json.load(open(cas.meta_path("fp30")))
        assert meta["payload"] == "text"
        back = cas.get("fp30")
        assert back is not None and np.array_equal(back.grid, e.grid)


# ---------------------------------------------------------------------------
class TestTiered:
    def test_disk_hit_promotes_to_memory(self, tmp_path):
        m = Metrics()
        warm = ResultCache(cas_dir=str(tmp_path), metrics=m)
        warm.put("fp", _entry(1))
        cold = ResultCache(cas_dir=str(tmp_path), metrics=m)
        entry, tier = cold.get("fp")
        assert tier == "disk"
        entry, tier = cold.get("fp")
        assert tier == "memory"  # promoted
        snap = m.snapshot()["counters"]
        assert snap["cache_hits_total"] == 2
        assert snap["cache_hits_total_disk"] == 1
        assert snap["cache_hits_total_memory"] == 1

    def test_miss_counted(self):
        m = Metrics()
        c = ResultCache(metrics=m)
        assert c.get("nope") is None
        assert m.snapshot()["counters"]["cache_misses_total"] == 1


# ---------------------------------------------------------------------------
class TestSchedulerCache:
    def _scheduler(self, tmp_path=None, journal=None, **kw):
        m = kw.pop("metrics", Metrics())
        cache = kw.pop("cache", ResultCache(
            cas_dir=str(tmp_path / "cas") if tmp_path is not None else None,
            metrics=m,
        ))
        s = Scheduler(journal=journal, metrics=m, cache=cache,
                      flush_age=0.01, **kw)
        return s, m

    def test_hit_is_byte_identical_and_marked(self, tmp_path):
        g = _board(11, (32, 32))
        # Reference: a cache-DISABLED scheduler's engine answer.
        ref = Scheduler(metrics=Metrics(), flush_age=0.01)
        ref.start()
        r = ref.submit(new_job(32, 32, g, gen_limit=8))
        _wait_done([r])
        ref.stop()

        s, m = self._scheduler(tmp_path)
        s.start()
        first = s.submit(new_job(32, 32, g, gen_limit=8))
        _wait_done([first])
        assert first.result.cached is None
        hit = s.submit(new_job(32, 32, g, gen_limit=8))
        # Completed AT admission: no waiting, no batch.
        assert hit.state == DONE and hit.result.cached == "memory"
        for got in (first, hit):
            assert np.array_equal(got.result.grid, r.result.grid)
            assert got.result.generations == r.result.generations
            assert got.result.exit_reason == r.result.exit_reason
        s.stop()
        snap = m.snapshot()["counters"]
        assert snap["cache_hits_total"] == 1
        assert snap["cache_misses_total"] == 1

    def test_replay_after_hit_exactly_once(self, tmp_path):
        g = _board(12, (32, 32))
        journal = JobJournal(str(tmp_path / "j"))
        s, _ = self._scheduler(tmp_path, journal=journal)
        s.start()
        first = s.submit(new_job(32, 32, g, gen_limit=8))
        _wait_done([first])
        hit = s.submit(new_job(32, 32, g, gen_limit=8))
        assert hit.state == DONE and hit.result.cached == "memory"
        s.stop()
        journal.close()

        # The hit is a completely normal DONE record: replay serves both
        # results and re-queues NOTHING (exactly-once across restart).
        journal2 = JobJournal(str(tmp_path / "j"))
        replay = journal2.replay()
        assert not replay.pending
        assert set(replay.results) == {first.id, hit.id}
        assert replay.results[hit.id].cached == "memory"
        assert np.array_equal(replay.results[hit.id].grid,
                              replay.results[first.id].grid)
        # One submit + one done record per id, by raw line audit.
        events = {}
        for line in open(journal2.path, "rb").read().splitlines():
            rec = json.loads(line)
            events.setdefault(rec["event"], []).append(
                rec.get("id") or rec["job"]["id"]
            )
        assert sorted(events["submit"]) == sorted([first.id, hit.id])
        assert sorted(events["done"]) == sorted([first.id, hit.id])
        journal2.close()

    def test_cas_tier_survives_restart(self, tmp_path):
        g = _board(13, (32, 32))
        s1, _ = self._scheduler(tmp_path)
        s1.start()
        first = s1.submit(new_job(32, 32, g, gen_limit=8))
        _wait_done([first])
        s1.stop()
        # Fresh process-equivalent: new scheduler, new memory tier, same
        # CAS directory.
        s2, m2 = self._scheduler(tmp_path)
        s2.start()
        hit = s2.submit(new_job(32, 32, g, gen_limit=8))
        assert hit.state == DONE and hit.result.cached == "disk"
        assert np.array_equal(hit.result.grid, first.result.grid)
        s2.stop()
        assert m2.snapshot()["counters"]["cache_hits_total_disk"] == 1

    def test_corrupt_cas_entry_reruns_correctly(self, tmp_path):
        g = _board(14, (32, 32))
        s1, _ = self._scheduler(tmp_path)
        s1.start()
        first = s1.submit(new_job(32, 32, g, gen_limit=8))
        _wait_done([first])
        s1.stop()
        cas = DiskCAS(str(tmp_path / "cas"))
        fp = job_fingerprint(first)
        # Poison the packed sidecar (the default payload): flip payload
        # bytes without touching the meta commit point — the wire frame's
        # CRC gate must catch it on read.
        with open(cas.packed_path(fp), "rb") as f:
            frame = bytearray(f.read())
        frame[-1] ^= 0xFF
        with open(cas.packed_path(fp), "wb") as f:
            f.write(bytes(frame))
        s2, m2 = self._scheduler(tmp_path)
        s2.start()
        rerun = s2.submit(new_job(32, 32, g, gen_limit=8))
        _wait_done([rerun])  # loud evict -> engine path
        assert rerun.result.cached is None
        assert np.array_equal(rerun.result.grid, first.result.grid)
        s2.stop()
        snap = m2.snapshot()["counters"]
        assert snap["cache_corrupt_evictions_total"] == 1
        assert snap.get("cache_hits_total", 0) == 0

    def test_inflight_dedup_runs_engine_once(self, tmp_path):
        g = _board(15, (32, 32))
        release = threading.Event()
        calls = []

        def gated(key, jobs):
            calls.append([j.id for j in jobs])
            release.wait(10)
            return batcher.run_batch(key, jobs)

        journal = JobJournal(str(tmp_path / "j"))
        m = Metrics()
        s = Scheduler(journal=journal, metrics=m,
                      cache=ResultCache(metrics=m), run_batch=gated,
                      flush_age=0.01)
        s.start()
        jobs = [s.submit(new_job(32, 32, g, gen_limit=8)) for _ in range(4)]
        time.sleep(0.2)  # let followers coalesce behind the gated leader
        release.set()
        _wait_done(jobs)
        s.stop()
        journal.close()
        assert len(calls) == 1 and len(calls[0]) == 1  # ONE engine run
        assert jobs[0].result.cached is None
        for f in jobs[1:]:
            assert f.result.cached == "coalesced"
            assert np.array_equal(f.result.grid, jobs[0].result.grid)
        # N journaled completions — one done record per id.
        done = [json.loads(line)["id"]
                for line in open(journal.path, "rb").read().splitlines()
                if json.loads(line)["event"] == "done"]
        assert sorted(done) == sorted(j.id for j in jobs)
        snap = m.snapshot()["counters"]
        assert snap["cache_inflight_coalesced_total"] == 3

    def test_followers_share_leader_failure(self, tmp_path):
        g = _board(16, (32, 32))
        release = threading.Event()

        def doomed(key, jobs):
            release.wait(10)
            raise RuntimeError("engine down")

        m = Metrics()
        s = Scheduler(metrics=m, cache=ResultCache(metrics=m),
                      run_batch=doomed, retryable=lambda e: False,
                      flush_age=0.01)
        s.start()
        jobs = [s.submit(new_job(32, 32, g, gen_limit=8)) for _ in range(3)]
        time.sleep(0.2)
        release.set()
        _wait_done(jobs)
        s.stop()
        assert all(j.state == FAILED for j in jobs)
        assert all("engine down" in j.error for j in jobs)

    def test_cancel_follower_and_leader_promotion(self):
        g = _board(17, (32, 32))
        m = Metrics()
        s = Scheduler(metrics=m, cache=ResultCache(metrics=m),
                      flush_age=0.01)
        # NOT started: everything stays QUEUED so cancel windows are open.
        leader = s.submit(new_job(32, 32, g, gen_limit=8))
        f1 = s.submit(new_job(32, 32, g, gen_limit=8))
        f2 = s.submit(new_job(32, 32, g, gen_limit=8))
        assert s.cancel(f1.id) and f1.state == CANCELLED
        # Cancelling the LEADER hands the engine run to the next follower.
        assert s.cancel(leader.id) and leader.state == CANCELLED
        s.start()
        _wait_done([f2])
        assert f2.state == DONE and f2.result.cached is None  # promoted
        s.stop()

    def test_no_cache_opts_out(self, tmp_path):
        g = _board(18, (32, 32))
        calls = []

        def counting(key, jobs):
            calls.append(len(jobs))
            return batcher.run_batch(key, jobs)

        m = Metrics()
        s = Scheduler(metrics=m, cache=ResultCache(metrics=m),
                      run_batch=counting, flush_age=0.01)
        s.start()
        a = s.submit(new_job(32, 32, g, gen_limit=8))
        _wait_done([a])
        b = s.submit(new_job(32, 32, g, gen_limit=8, no_cache=True))
        _wait_done([b])
        s.stop()
        assert len(calls) == 2  # the repeat ran the engine again
        assert b.result.cached is None
        assert np.array_equal(a.result.grid, b.result.grid)

    def test_no_cache_requires_json_boolean(self):
        with pytest.raises(TypeError):
            new_job(8, 8, np.zeros((8, 8), np.uint8), no_cache="true")

    def test_follower_urgency_folds_into_queued_leader(self):
        # A coalesced follower never sits in a bucket, so its priority and
        # deadline MUST fold into the leader or the dispatch-ordering
        # guarantee silently breaks for repeat traffic.
        g = _board(27, (32, 32))
        m = Metrics()
        s = Scheduler(metrics=m, cache=ResultCache(metrics=m),
                      flush_age=10.0)  # unstarted: all stay QUEUED
        leader = s.submit(new_job(32, 32, g, gen_limit=8))
        assert leader.priority == 0 and leader.deadline_s is None
        first_follower = s.submit(new_job(32, 32, g, gen_limit=8, priority=5))
        assert leader.priority == 5
        s.submit(new_job(32, 32, g, gen_limit=8, deadline_s=0.25))
        assert leader.deadline_s is not None
        # Promotion (FIFO: the first follower takes over) inherits the
        # REMAINING followers' folded urgency too.
        s.submit(new_job(32, 32, g, gen_limit=8, priority=9))
        assert leader.priority == 9
        assert s.cancel(leader.id)
        promoted = s._inflight_fp[first_follower.fingerprint]
        assert promoted is first_follower
        assert promoted.priority == 9 and promoted.deadline_s is not None

    def test_rejected_submissions_skip_the_consult(self):
        # A submission that will be 429'd must not do CAS I/O nor count a
        # consult — the reject path must not amplify overload or skew the
        # hit/miss series.
        g = _board(28, (32, 32))
        m = Metrics()
        s = Scheduler(metrics=m, cache=ResultCache(metrics=m),
                      max_queue_depth=1, flush_age=10.0)  # unstarted
        s.submit(new_job(32, 32, g, gen_limit=8))
        misses_before = m.snapshot()["counters"]["cache_misses_total"]
        from gol_tpu.serve.scheduler import QueueFull

        with pytest.raises(QueueFull):
            s.submit(new_job(32, 32, g, gen_limit=8))
        assert (m.snapshot()["counters"]["cache_misses_total"]
                == misses_before)

    def test_bitpack_is_the_engine_convention(self):
        # The cache's ts-lane packing and the engine's batch staging must
        # share ONE bit convention — pinned by construction (both delegate
        # to io/bitpack) and by value here.
        from gol_tpu import engine
        from gol_tpu.io import bitpack

        stacked = np.stack([_board(29, (8, 64)), _board(30, (8, 64))])
        words = engine._pack_board_words(stacked)
        assert np.array_equal(words, bitpack.pack_words(stacked))
        assert np.array_equal(engine._unpack_board_words(words), stacked)
        assert np.array_equal(
            bitpack.unpack_words(bitpack.pack_words(stacked[0]), 64),
            stacked[0],
        )


# ---------------------------------------------------------------------------
class TestServerCache:
    def test_http_hit_marker_and_bad_type_400(self, tmp_path):
        import urllib.request

        from gol_tpu.serve.server import GolServer

        def http(method, url, body=None):
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(url, data=data, method=method)
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as err:
                return err.code, json.loads(err.read())

        import urllib.error

        srv = GolServer(port=0, flush_age=0.01, result_cache=True,
                        cache_dir=str(tmp_path / "cas"))
        srv.start()
        try:
            base = srv.url
            g = _board(19, (32, 32))
            body = {"width": 32, "height": 32,
                    "cells": text_grid.encode(g).decode("ascii"),
                    "gen_limit": 8}
            status, first = http("POST", f"{base}/jobs", body)
            assert status == 202
            deadline = time.perf_counter() + 30
            while time.perf_counter() < deadline:
                status, res1 = http("GET", f"{base}/result/{first['id']}")
                if status == 200:
                    break
                time.sleep(0.02)
            assert status == 200 and "cached" not in res1
            status, second = http("POST", f"{base}/jobs", body)
            assert status == 202
            status, res2 = http("GET", f"{base}/result/{second['id']}")
            assert status == 200 and res2["cached"] == "memory"
            assert res2["grid"] == res1["grid"]
            # Wrong-typed no_cache is a 400, exactly like check_similarity.
            status, err = http("POST", f"{base}/jobs",
                               {**body, "no_cache": "yes"})
            assert status == 400 and "no_cache" in err["error"]
            # Hit counters ride the serving registry's exposition formats.
            status, snap = http("GET", f"{base}/metrics?format=json")
            assert snap["counters"]["cache_hits_total"] == 1
            req = urllib.request.urlopen(f"{base}/metrics", timeout=10)
            prom = req.read().decode()
            assert "gol_serve_cache_hits_total 1" in prom
        finally:
            srv.shutdown()

    def test_gol_top_renders_hit_ratio(self):
        from gol_tpu.obs import top

        frame = top.render_frame(
            {"counters": {"cache_hits_total": 6, "cache_misses_total": 2,
                          "cache_inflight_coalesced_total": 1,
                          "cache_hits_total_memory": 5,
                          "cache_hits_total_disk": 1}},
            None, ansi=False,
        )
        assert "cache: hit ratio" in frame
        assert "0.88" in frame  # (6 + 1) / (6 + 2)
        frame_plain = top.render_frame({"counters": {}}, None, ansi=False)
        assert "cache:" not in frame_plain  # no cache mounted -> no line


# ---------------------------------------------------------------------------
class TestFleetCacheTier:
    def _fleet(self, tmp_path, ids=("wa", "wb", "wc")):
        from gol_tpu.fleet.workers import Fleet

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        for wid in ids:
            fleet.attach(f"http://{wid}.invalid", wid)
        return fleet

    def _router(self, tmp_path, sink, **kw):
        from gol_tpu.fleet.router import RouterServer

        def stub_http(method, url, body=None, raw=None, timeout=0):
            sink.append(url.split("//")[1].split(".")[0])
            return 202, {"id": f"j{len(sink)}", "state": "queued"}

        return RouterServer(self._fleet(tmp_path), port=0, http=stub_http,
                            **kw)

    @staticmethod
    def _body(seed: int, extra=None) -> bytes:
        g = _board(seed, (32, 32))
        body = {"width": 32, "height": 32,
                "cells": text_grid.encode(g).decode("ascii")}
        return json.dumps({**body, **(extra or {})}).encode()

    def test_fingerprint_rank_is_deterministic(self):
        from gol_tpu.fleet import placement

        fp = "fp:" + result_fingerprint(_board(20))
        ids = ["w0", "w1", "w2", "w3"]
        assert placement.rank(fp, ids) == placement.rank(fp, list(ids))
        # Removing a worker moves only that worker's keys (HRW property,
        # already pinned for buckets — restated for fingerprint keys).
        full = placement.rank(fp, ids)
        without = placement.rank(fp, [w for w in ids if w != full[-1]])
        assert without == full[:-1]

    def test_repeats_land_on_the_fingerprint_owner(self, tmp_path):
        from gol_tpu.fleet import placement

        sink = []
        router = self._router(tmp_path, sink, cache_route=True)
        try:
            # Same 32x32 bucket, different boards: with cache routing the
            # targets follow each board's fingerprint owner...
            for seed in (21, 22, 23):
                fp = "fp:" + body_fingerprint(
                    json.loads(self._body(seed).decode())
                )
                owner = placement.rank(fp, ["wa", "wb", "wc"])[0]
                for _ in range(2):  # ...and repeats land on the SAME one
                    status, payload = router.route_submit(self._body(seed))
                    assert status == 202
                    assert sink[-1] == owner == payload["worker"]
            assert router.registry.counter("jobs_cache_routed_total") == 6
        finally:
            router.httpd.server_close()

    def test_no_cache_body_keeps_bucket_routing(self, tmp_path):
        from gol_tpu.fleet import placement

        sink = []
        router = self._router(tmp_path, sink, cache_route=True)
        try:
            key = placement.key_for(json.loads(self._body(24).decode()))
            bucket_owner = placement.rank(key.label(),
                                          ["wa", "wb", "wc"])[0]
            status, _ = router.route_submit(
                self._body(24, {"no_cache": True})
            )
            assert status == 202 and sink[-1] == bucket_owner
            assert router.registry.counter("jobs_cache_routed_total") == 0
        finally:
            router.httpd.server_close()

    def test_unfingerprintable_body_falls_back_to_bucket(self, tmp_path):
        from gol_tpu.fleet import placement

        sink = []
        router = self._router(tmp_path, sink, cache_route=True)
        try:
            body = {"width": 32, "height": 32}  # no cells: cannot key
            key = placement.key_for(body)
            bucket_owner = placement.rank(key.label(),
                                          ["wa", "wb", "wc"])[0]
            status, _ = router.route_submit(json.dumps(body).encode())
            assert status == 202 and sink[-1] == bucket_owner
        finally:
            router.httpd.server_close()

    def test_default_router_keeps_bucket_affinity(self, tmp_path):
        from gol_tpu.fleet import placement

        sink = []
        router = self._router(tmp_path, sink)  # cache_route off (default)
        try:
            key = placement.key_for(json.loads(self._body(25).decode()))
            bucket_owner = placement.rank(key.label(),
                                          ["wa", "wb", "wc"])[0]
            for seed in (25, 26):  # different boards, same bucket
                router.route_submit(self._body(seed))
                assert sink[-1] == bucket_owner
        finally:
            router.httpd.server_close()
