"""Chaos-hardened data path (PR 14): seeded network fault injection
(gol_tpu/chaos), per-worker circuit breakers (fleet/breaker.py), token-bucket
retry budgets (resilience/retry.RetryBudget), and end-to-end deadline
propagation (X-Gol-Deadline).

The load-bearing block is TestChaosMatrix: every fault class the plan
grammar can inject (latency, refusal, reset mid-exchange, slow-loris,
truncation, bit-flip) runs against a REAL 2-worker fleet, and each must end
in either transparent recovery or the documented error contract — never a
hang, a double-run, or a silently wrong board. Corrupted ``GOLP`` frames
are 100% caught by the PR-11 CRC (pinned bit-by-bit in TestFlipBit).
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from gol_tpu import oracle
from gol_tpu.chaos import ChaosPlan, ProxyPool
from gol_tpu.chaos.plan import FAULT_KINDS
from gol_tpu.chaos.proxy import ChaosProxy, _flip_bit
from gol_tpu.config import GameConfig
from gol_tpu.fleet import client as fleet_client
from gol_tpu.fleet import placement
from gol_tpu.fleet.breaker import (
    CLOSED, HALF_OPEN, OPEN, BreakerConfig, CircuitBreaker,
)
from gol_tpu.fleet.router import RouterServer
from gol_tpu.fleet.workers import Fleet
from gol_tpu.io import text_grid, wire
from gol_tpu.obs import propagate
from gol_tpu.resilience.retry import RetryBudget, RetryPolicy
from gol_tpu.serve.server import GolServer


class _Clock:
    def __init__(self, now=0.0):
        self.now = now

    def __call__(self):
        return self.now


def _http(method, url, body=None, timeout=30, headers=None):
    return fleet_client.http_json(method, url, body, timeout=timeout,
                                  headers=headers)


def _wait(predicate, timeout=60.0, interval=0.02):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------------------
# The plan grammar + seeded schedules


class TestChaosPlan:
    def test_parse_round_trip_and_defaults(self):
        plan = ChaosPlan.parse(
            "seed=7,reset=0.05,latency=0.2,latency_ms=50,bitflip=0.125"
        )
        assert plan.seed == 7
        assert plan.reset == 0.05
        assert plan.latency == 0.2
        assert plan.latency_ms == 50
        assert plan.bitflip == 0.125
        assert plan.refuse == 0.0 and plan.truncate == 0.0
        assert plan.slow_ms == 20 and plan.slow_chunk == 256
        assert plan.any_faults()
        assert not ChaosPlan.parse("seed=3").any_faults()
        assert ChaosPlan.parse("") == ChaosPlan()

    def test_unknown_key_is_a_loud_error(self):
        # The FaultPlan.parse contract: a typo'd injection must never
        # silently test nothing.
        with pytest.raises(ValueError, match="unknown chaos plan key"):
            ChaosPlan.parse("restet=0.5")
        with pytest.raises(ValueError, match="not k=v"):
            ChaosPlan.parse("reset")

    def test_validation(self):
        with pytest.raises(ValueError, match="probability"):
            ChaosPlan.parse("reset=1.5")
        with pytest.raises(ValueError, match="probability"):
            ChaosPlan(bitflip=-0.1)
        with pytest.raises(ValueError, match="delays"):
            ChaosPlan(latency_ms=-1)
        with pytest.raises(ValueError, match="slow_chunk"):
            ChaosPlan(slow_chunk=0)

    def test_seed_determinism(self):
        """Same (seed, salt) -> identical decision stream, run to run;
        different salts -> independent streams for pool-mounted proxies."""
        plan = ChaosPlan(seed=11, reset=0.3, latency=0.3, bitflip=0.2)
        s1, s2 = plan.schedule(salt=0), plan.schedule(salt=0)
        run1 = [s1.next_fault() for _ in range(64)]
        run2 = [s2.next_fault() for _ in range(64)]
        assert run1 == run2
        s3 = plan.schedule(salt=1)
        salted = [s3.next_fault() for _ in range(64)]
        assert salted != run1

    def test_roll_alignment_across_fault_mixes(self):
        """Every class is rolled every exchange, so the Nth exchange's
        underlying draws depend only on (seed, salt, N) — never on which
        classes happened to fire before. Pinned by comparing the bitflip
        position draw between a latency-only and a truncate-only plan."""
        sched_a = ChaosPlan(seed=5, latency=1.0).schedule()
        sched_b = ChaosPlan(seed=5, truncate=1.0).schedule()
        for _ in range(32):
            fault_a, draw_a, flip_a = sched_a.next_fault()
            fault_b, draw_b, flip_b = sched_b.next_fault()
            assert fault_a == "latency" and fault_b == "truncate"
            assert draw_a == draw_b and flip_a == flip_b

    def test_fault_kinds_vocabulary(self):
        assert FAULT_KINDS == ("refuse", "reset", "truncate", "slowloris",
                               "bitflip", "latency")


# ---------------------------------------------------------------------------
# Bit flips vs the PR-11 CRC gate (pinned: 100% caught)


class TestFlipBit:
    def _frame(self):
        grid = text_grid.generate(32, 32, seed=9)
        return wire.encode_frame({"gen_limit": 4}, grid=grid)

    def test_flips_exactly_one_bit(self):
        frame = self._frame()
        flipped = _flip_bit(frame, 0.37)
        diff = [(a ^ b) for a, b in zip(frame, flipped)]
        assert sum(bin(d).count("1") for d in diff) == 1

    def test_every_flip_position_is_caught_by_the_crc(self):
        """The pinned contract: a GOLP frame flip lands INSIDE the
        CRC-covered words payload, so decode_frame must reject EVERY
        draw — a transit bit-flip can never decode into a wrong board."""
        frame = self._frame()
        for i in range(256):
            flipped = _flip_bit(frame, i / 256.0)
            assert flipped != frame
            with pytest.raises(wire.WireError, match="CRC"):
                wire.decode_frame(flipped)

    def test_non_golp_body_flips_in_the_trailing_half(self):
        body = bytes(range(200)) + bytes(200)
        flipped = _flip_bit(body, 0.5)
        assert flipped != body
        assert flipped[: len(body) // 2] == body[: len(body) // 2]

    def test_tiny_body_passes_untouched(self):
        assert _flip_bit(b"", 0.5) == b""
        assert _flip_bit(b"x", 0.0) != b"x"  # 1 byte still flips


# ---------------------------------------------------------------------------
# The breaker state machine (injected clock; no sleeps)


class TestCircuitBreaker:
    def _breaker(self, clock, transitions=None, **cfg):
        config = BreakerConfig(**{"fail_threshold": 3, "cooldown_s": 5.0,
                                  **cfg})
        on_transition = None
        if transitions is not None:
            on_transition = lambda label, old, new: transitions.append(  # noqa: E731
                (old, new))
        return CircuitBreaker(config, clock=clock,
                              on_transition=on_transition, label="w0")

    def test_consecutive_failures_trip_at_threshold(self):
        clock = _Clock()
        transitions = []
        br = self._breaker(clock, transitions)
        br.on_failure()
        br.on_failure()
        assert br.state == CLOSED
        br.on_failure()
        assert br.state == OPEN
        assert br.opens == 1
        assert transitions == [(CLOSED, OPEN)]

    def test_success_resets_the_consecutive_count(self):
        clock = _Clock()
        # min_volume above the window keeps the (separately tested)
        # degraded-rate trip quiet: this test pins ONLY the consecutive
        # counter reset.
        br = self._breaker(clock, min_volume=100)
        for _ in range(4):
            br.on_failure()
            br.on_failure()
            br.on_success(0.01)
        assert br.state == CLOSED

    def test_degraded_rate_trips_with_min_volume(self):
        """A brownout — slow answers mixed into successes — trips the
        windowed rate even with zero consecutive failures."""
        clock = _Clock()
        br = self._breaker(clock, window=10, min_volume=10,
                           degraded_rate=0.5, slow_s=1.0,
                           fail_threshold=100)
        for i in range(9):
            br.on_success(2.0 if i % 2 == 0 else 0.01)  # alternating slow
        assert br.state == CLOSED  # below min_volume
        br.on_success(2.0)  # 6 degraded / 10 >= 0.5
        assert br.state == OPEN

    def test_penalty_and_cooldown(self):
        clock = _Clock()
        br = self._breaker(clock, cooldown_s=5.0)
        assert br.penalty() == 0
        for _ in range(3):
            br.on_failure()
        assert br.penalty() == 1  # OPEN inside cooldown: rank last
        clock.now += 5.1
        # Past cooldown the would-be probe ranks NORMALLY (or recovery
        # never gets traffic).
        assert br.penalty() == 0

    def test_half_open_admits_exactly_one_probe(self):
        clock = _Clock()
        br = self._breaker(clock)
        for _ in range(3):
            br.on_failure()
        clock.now += 6.0
        br.on_attempt()
        assert br.state == HALF_OPEN
        # While the probe is in flight, the worker ranks last again —
        # a recovering worker sees a trickle, not a stampede.
        assert br.penalty() == 1
        br.on_attempt()  # a second attempt does not become a second probe
        assert br.state == HALF_OPEN
        br.on_success(0.01)
        assert br.state == CLOSED
        assert br.penalty() == 0

    def test_failed_probe_reopens_and_rearms_cooldown(self):
        clock = _Clock()
        transitions = []
        br = self._breaker(clock, transitions)
        for _ in range(3):
            br.on_failure()
        clock.now += 6.0
        br.on_attempt()
        br.on_failure()
        assert br.state == OPEN and br.opens == 2
        assert br.penalty() == 1  # cooldown re-armed from the fresh failure
        clock.now += 5.1
        assert br.penalty() == 0
        assert transitions == [(CLOSED, OPEN), (OPEN, HALF_OPEN),
                               (HALF_OPEN, OPEN)]

    def test_slow_probe_success_is_not_recovery(self):
        clock = _Clock()
        br = self._breaker(clock, slow_s=1.0)
        for _ in range(3):
            br.on_failure()
        clock.now += 6.0
        br.on_attempt()
        br.on_success(3.0)  # answered, but degraded
        assert br.state == OPEN

    def test_public_shape(self):
        br = self._breaker(_Clock())
        br.on_failure()
        snap = br.public()
        assert snap["state"] == CLOSED
        assert snap["consecutive_failures"] == 1
        assert snap["opens"] == 0
        assert 0.0 <= snap["degraded"] <= 1.0

    def test_config_validation(self):
        with pytest.raises(ValueError):
            BreakerConfig(fail_threshold=0)
        with pytest.raises(ValueError):
            BreakerConfig(degraded_rate=0.0)
        with pytest.raises(ValueError):
            BreakerConfig(slow_s=0.0)
        with pytest.raises(ValueError):
            BreakerConfig(cooldown_s=-1.0)


# ---------------------------------------------------------------------------
# Breakers inside the router: ranking, recovery, the richer 504 body


class TestBreakerRouting:
    def _fleet(self, tmp_path, ids=("wa", "wb")):
        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        for wid in ids:
            fleet.attach(f"http://{wid}.invalid", wid)
        return fleet

    def test_open_breaker_ranks_last_not_removed(self, tmp_path):
        body = json.dumps({"width": 32, "height": 32}).encode()
        key = placement.key_for(json.loads(body))
        first, second = placement.rank(key.label(), ["wa", "wb"])

        def stub_http(method, url, body=None, raw=None, timeout=0, **kw):
            if first in url:
                raise ConnectionRefusedError("down")
            return 202, {"id": "j1", "state": "queued"}

        fleet = self._fleet(tmp_path)
        router = RouterServer(
            fleet, port=0, http=stub_http, breakers=True,
            breaker_config=BreakerConfig(fail_threshold=2, cooldown_s=100.0),
        )
        try:
            for _ in range(2):
                status, payload = router.route_submit(body)
                assert status == 202 and payload["worker"] == second
            states = router.breaker_states()
            assert states[first] == OPEN and states[second] == CLOSED
            # Re-RANKED, never removed: the open worker sinks to the tail
            # of its tier but stays a candidate (HRW affinity survives).
            order = [w.id for w in router.candidates(key)]
            assert order == [second, first]
            # The breaker surfaces on metrics_json for `gol top`.
            assert router.metrics_json()["fleet"]["breakers"][first] == OPEN
        finally:
            router.httpd.server_close()

    def test_recovery_reranks_through_half_open_probe(self, tmp_path):
        body = json.dumps({"width": 32, "height": 32}).encode()
        key = placement.key_for(json.loads(body))
        first, second = placement.rank(key.label(), ["wa", "wb"])
        down = {"down": True}

        def stub_http(method, url, body=None, raw=None, timeout=0, **kw):
            if first in url and down["down"]:
                raise ConnectionRefusedError("down")
            return 202, {"id": "j1", "state": "queued"}

        fleet = self._fleet(tmp_path)
        router = RouterServer(
            fleet, port=0, http=stub_http, breakers=True,
            # cooldown 0: the next ranked attempt IS the half-open probe.
            breaker_config=BreakerConfig(fail_threshold=2, cooldown_s=0.0),
        )
        try:
            for _ in range(2):
                router.route_submit(body)
            assert router.breaker_states()[first] == OPEN
            down["down"] = False
            # Past the cooldown the would-be probe ranks normally again,
            # the probe succeeds, and the breaker closes.
            status, payload = router.route_submit(body)
            assert status == 202 and payload["worker"] == first
            assert router.breaker_states()[first] == CLOSED
            assert router.registry.counter("breaker_opens_total") == 1
            assert router.registry.counter("breaker_closes_total") == 1
        finally:
            router.httpd.server_close()

    def test_probe_in_flight_defers_to_next_candidate(self, tmp_path):
        """The single-probe contract under concurrency: a submit that
        ranked an open-past-cooldown worker normally but lost the probe
        slot to a concurrent caller forwards to the NEXT candidate, not
        onto the still-recovering worker."""
        body = json.dumps({"width": 32, "height": 32}).encode()
        key = placement.key_for(json.loads(body))
        first, second = placement.rank(key.label(), ["wa", "wb"])
        forwarded = []

        def stub_http(method, url, body=None, raw=None, timeout=0, **kw):
            forwarded.append(url)
            return 202, {"id": f"j{len(forwarded)}", "state": "queued"}

        fleet = self._fleet(tmp_path)
        router = RouterServer(
            fleet, port=0, http=stub_http, breakers=True,
            breaker_config=BreakerConfig(fail_threshold=1, cooldown_s=0.0),
        )
        try:
            br = router.breaker(first)
            br.on_failure()  # OPEN; cooldown 0 = instantly probe-eligible
            assert br.on_attempt()  # "concurrent" caller claims the probe
            assert br.state == HALF_OPEN
            status, payload = router.route_submit(body)
            assert status == 202 and payload["worker"] == second
            assert all(first not in url for url in forwarded)
        finally:
            router.httpd.server_close()

    def test_probe_in_flight_worker_stays_last_resort(self, tmp_path):
        """Deferred, never removed: when every other candidate is gone,
        the probing worker still gets the forward (capacity over purity —
        the alternative is a 503 with a live worker standing)."""
        body = json.dumps({"width": 32, "height": 32}).encode()

        def stub_http(method, url, body=None, raw=None, timeout=0, **kw):
            return 202, {"id": "j1", "state": "queued"}

        fleet = self._fleet(tmp_path, ids=("wa",))
        router = RouterServer(
            fleet, port=0, http=stub_http, breakers=True,
            breaker_config=BreakerConfig(fail_threshold=1, cooldown_s=0.0),
        )
        try:
            br = router.breaker("wa")
            br.on_failure()
            assert br.on_attempt()  # probe claimed elsewhere
            status, payload = router.route_submit(body)
            assert status == 202 and payload["worker"] == "wa"
        finally:
            router.httpd.server_close()

    def test_prometheus_deadline_counters_survive_no_breakers(self, tmp_path):
        """Deadline enforcement and CRC retries run breakers-or-not; a
        --no-breakers fleet must still export their counters (a dashboard
        showing zero expiries while clients get 504s is a lie)."""
        fleet = self._fleet(tmp_path)
        router = RouterServer(fleet, port=0, breakers=False)
        try:
            router.registry.inc("deadline_expired_total")
            text = router.metrics_prometheus()
            assert "gol_fleet_deadline_expired_total 1" in text
            assert "gol_fleet_wire_crc_retries_total 0" in text
            assert "breaker_state" not in text
        finally:
            router.httpd.server_close()

    def test_ambiguous_504_names_worker_and_breaker_state(self, tmp_path):
        """The PR-8 fix: an ambiguous submit outcome must say WHERE the
        outcome is unknown (and that worker's breaker state) so the
        client knows which partition to audit before resubmitting."""
        def stub_http(method, url, body=None, raw=None, timeout=0, **kw):
            raise TimeoutError("timed out mid-exchange")

        fleet = self._fleet(tmp_path)
        router = RouterServer(fleet, port=0, http=stub_http, breakers=True)
        try:
            status, payload = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode()
            )
            assert status == 504
            assert "outcome unknown" in payload["error"]
            assert payload["worker"] in ("wa", "wb")
            assert payload["worker"] in payload["error"]
            assert payload["breaker"] == CLOSED  # one timeout < threshold
        finally:
            router.httpd.server_close()

    def test_ambiguous_504_without_breakers_keeps_worker_field(
            self, tmp_path):
        def stub_http(method, url, body=None, raw=None, timeout=0, **kw):
            raise TimeoutError("timed out mid-exchange")

        fleet = self._fleet(tmp_path)
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode()
            )
            assert status == 504
            assert payload["worker"] in ("wa", "wb")
            assert "breaker" not in payload  # feature off: no new key
        finally:
            router.httpd.server_close()

    def test_breakers_default_off_and_states_empty(self, tmp_path):
        fleet = self._fleet(tmp_path)
        router = RouterServer(fleet, port=0, http=lambda *a, **k: (202, {}))
        try:
            assert not router.breakers_enabled
            assert router.breaker_states() == {}
            assert router.breaker("wa") is None
            assert "breakers" not in router.metrics_json()["fleet"]
        finally:
            router.httpd.server_close()


# ---------------------------------------------------------------------------
# Deadline propagation: header codec, router enforcement, hop decrement


class TestDeadlineHeader:
    def test_codec(self):
        assert propagate.decode_deadline(
            propagate.encode_deadline(1.25)) == 1.25
        assert propagate.decode_deadline("0.5") == 0.5
        assert propagate.decode_deadline("-0.1") == -0.1  # expired is VALID
        assert propagate.decode_deadline(None) is None
        assert propagate.decode_deadline("") is None
        assert propagate.decode_deadline("soon") is None
        assert propagate.decode_deadline("nan") is None
        assert propagate.decode_deadline("inf") is None
        assert propagate.decode_deadline(7) is None  # non-str degrades

    def test_router_rejects_spent_budget_without_forwarding(self, tmp_path):
        calls = []

        def stub_http(method, url, body=None, raw=None, timeout=0, **kw):
            calls.append(url)
            return 202, {"id": "j", "state": "queued"}

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        fleet.attach("http://wa.invalid", "wa")
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode(),
                deadline_header="-0.5",
            )
            assert status == 504
            assert "deadline budget spent" in payload["error"]
            assert calls == []  # no forward: no batch slot burned anywhere
            assert router.registry.counter("deadline_expired_total") == 1
        finally:
            router.httpd.server_close()

    def test_router_decrements_and_caps_hop_timeout(self, tmp_path):
        seen = []

        def stub_http(method, url, body=None, raw=None, timeout=0, **kw):
            seen.append((timeout, (kw.get("headers") or {}).get(
                propagate.DEADLINE_HEADER)))
            return 202, {"id": "j", "state": "queued"}

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        fleet.attach("http://wa.invalid", "wa")
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, _ = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode(),
                deadline_header="5.0",
            )
            assert status == 202
            timeout, header = seen[0]
            forwarded = propagate.decode_deadline(header)
            # Decremented by the router's own elapsed time, never grown.
            assert forwarded is not None and 0 < forwarded <= 5.0
            # The hop timeout is capped by what the client has left.
            assert timeout <= 5.0
        finally:
            router.httpd.server_close()

    def test_no_header_keeps_the_call_shape_byte_identical(self, tmp_path):
        """The old-peer compat pin (the X-Gol-Trace standard): without a
        deadline the forward carries no headers kwarg at all — the PR-8
        call shape, byte-identical on the wire."""
        kwargs_seen = []

        def stub_http(method, url, body=None, raw=None, timeout=0, **kw):
            kwargs_seen.append(dict(kw))
            return 202, {"id": "j", "state": "queued"}

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        fleet.attach("http://wa.invalid", "wa")
        router = RouterServer(fleet, port=0, http=stub_http, breakers=True)
        try:
            status, _ = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode()
            )
            assert status == 202
            assert kwargs_seen == [{}]
        finally:
            router.httpd.server_close()

    def test_malformed_header_degrades_to_no_deadline(self, tmp_path):
        def stub_http(method, url, body=None, raw=None, timeout=0, **kw):
            assert propagate.DEADLINE_HEADER not in (kw.get("headers") or {})
            return 202, {"id": "j", "state": "queued"}

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        fleet.attach("http://wa.invalid", "wa")
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, _ = router.route_submit(
                json.dumps({"width": 32, "height": 32}).encode(),
                deadline_header="whenever",
            )
            assert status == 202  # malformed drops silently, never 400s/504s
        finally:
            router.httpd.server_close()


class TestDeadlineAtWorker:
    def test_admission_rejects_spent_budget_with_504(self, tmp_path):
        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        flush_age=0.01)
        srv.start()
        try:
            board = text_grid.generate(32, 32, seed=3)
            status, payload = _http(
                "POST", f"{srv.url}/jobs",
                {"width": 32, "height": 32,
                 "cells": text_grid.encode(board).decode("ascii"),
                 "gen_limit": 4},
                headers={propagate.DEADLINE_HEADER: "-1.0"},
            )
            assert status == 504
            assert "deadline budget spent" in payload["error"]
            # No job was created: no journal record, no queue slot.
            assert srv.metrics.counter("jobs_accepted_total") == 0
            assert srv.metrics.counter("deadline_expired_total") == 1
        finally:
            srv.shutdown()

    def test_expired_in_queue_fails_504_with_timeline(self, tmp_path):
        """The dispatch gate: a job whose budget runs out while queued
        terminates with the 504 contract and its timeline attached —
        instead of burning a batch slot on an answer nobody awaits."""
        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        flush_age=0.5)  # hold the batch open past expiry
        srv.start()
        try:
            board = text_grid.generate(32, 32, seed=4)
            status, payload = _http(
                "POST", f"{srv.url}/jobs",
                {"width": 32, "height": 32,
                 "cells": text_grid.encode(board).decode("ascii"),
                 "gen_limit": 4},
                headers={propagate.DEADLINE_HEADER: "0.05"},
            )
            assert status == 202, payload
            job_id = payload["id"]
            assert _wait(lambda: _http(
                "GET", f"{srv.url}/jobs/{job_id}")[1].get("state")
                == "failed")
            status, result = _http("GET", f"{srv.url}/result/{job_id}")
            assert status == 504
            assert result["error"].startswith("DeadlineExceeded")
            assert "segments" in result  # the PR-7 timeline rode along
            assert srv.metrics.counter("deadline_expired_total") >= 1
        finally:
            srv.shutdown()

    def test_generous_budget_runs_normally(self, tmp_path):
        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        flush_age=0.01)
        srv.start()
        try:
            board = text_grid.generate(32, 32, seed=5)
            status, payload = _http(
                "POST", f"{srv.url}/jobs",
                {"width": 32, "height": 32,
                 "cells": text_grid.encode(board).decode("ascii"),
                 "gen_limit": 6},
                headers={propagate.DEADLINE_HEADER: "120.0"},
            )
            assert status == 202
            job_id = payload["id"]
            assert _wait(lambda: _http(
                "GET", f"{srv.url}/jobs/{job_id}")[1].get("state") == "done")
            status, result = _http("GET", f"{srv.url}/result/{job_id}")
            assert status == 200
            want = oracle.run(board, GameConfig(gen_limit=6))
            got = text_grid.decode(result["grid"].encode("ascii"),
                                   result["width"], result["height"])
            np.testing.assert_array_equal(np.asarray(got), want.grid)
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# Retry budgets + jitter (the storm governor)


class TestRetryBudget:
    def test_tokens_drain_and_refill(self):
        clock = _Clock()
        budget = RetryBudget(capacity=2.0, refill_per_s=1.0, clock=clock)
        assert budget.try_take() and budget.try_take()
        assert not budget.try_take()  # empty
        clock.now += 1.5
        assert budget.remaining() == pytest.approx(1.5)
        assert budget.try_take()
        assert not budget.try_take()

    def test_refill_caps_at_capacity(self):
        clock = _Clock()
        budget = RetryBudget(capacity=3.0, refill_per_s=10.0, clock=clock)
        clock.now += 100.0
        assert budget.remaining() == 3.0

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryBudget(capacity=0)
        with pytest.raises(ValueError):
            RetryBudget(refill_per_s=-1)

    def test_exhausted_budget_surfaces_the_original_error(self):
        """The liveness pin: an empty bucket must raise the error the
        attempt ACTUALLY produced — degrading to at-most-one-attempt —
        not a synthetic budget error, and never keep retrying."""
        clock = _Clock()
        budget = RetryBudget(capacity=1.0, refill_per_s=0.0, clock=clock)
        calls = []

        def fn():
            calls.append(1)
            raise ConnectionResetError("connection reset by peer")

        policy = RetryPolicy(attempts=5, base_delay=0.0)
        with pytest.raises(ConnectionResetError):
            policy.call(fn, budget=budget, sleep=lambda s: None)
        # First attempt + the single budgeted retry; attempts 3..5 never
        # ran because the bucket was empty.
        assert len(calls) == 2

    def test_first_attempts_never_spend_tokens(self):
        clock = _Clock()
        budget = RetryBudget(capacity=1.0, refill_per_s=0.0, clock=clock)
        policy = RetryPolicy(attempts=3, base_delay=0.0)
        for _ in range(5):
            assert policy.call(lambda: "ok", budget=budget) == "ok"
        assert budget.remaining() == 1.0

    def test_jitter_spreads_backoff_and_zero_is_byte_identical(self):
        sleeps = []
        policy = RetryPolicy(attempts=3, base_delay=1.0, multiplier=2.0,
                             max_delay=8.0, jitter=0.5)

        def fail():
            raise ConnectionResetError("connection reset")

        with pytest.raises(ConnectionResetError):
            policy.call(fail, sleep=sleeps.append, rng=lambda: 0.0)
        assert sleeps == [0.5, 1.0]  # 1-j of the nominal 1.0, 2.0
        sleeps.clear()
        with pytest.raises(ConnectionResetError):
            policy.call(fail, sleep=sleeps.append, rng=lambda: 1.0)
        assert sleeps == [1.5, 3.0]  # 1+j
        sleeps.clear()
        nojitter = RetryPolicy(attempts=3, base_delay=1.0, multiplier=2.0,
                               max_delay=8.0)
        with pytest.raises(ConnectionResetError):
            nojitter.call(fail, sleep=sleeps.append,
                          rng=lambda: 1.0)  # rng unused at jitter=0
        assert sleeps == [1.0, 2.0]  # the pre-jitter sleeps, untouched

    def test_jitter_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(jitter=1.0)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=-0.1)


# ---------------------------------------------------------------------------
# The proxy itself, against a tiny stdlib upstream


class _EchoHandler(BaseHTTPRequestHandler):
    payload = json.dumps({"ok": True, "filler": "x" * 2048}).encode()

    def do_GET(self):
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(self.payload)))
        self.end_headers()
        self.wfile.write(self.payload)

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


@pytest.fixture
def upstream():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _EchoHandler)
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    url = "http://127.0.0.1:%d" % httpd.server_address[1]
    yield url
    httpd.shutdown()
    httpd.server_close()


class TestChaosProxy:
    def _proxy(self, upstream, **plan_kwargs):
        proxy = ChaosProxy(upstream, ChaosPlan(**plan_kwargs))
        return proxy

    def test_transparent_relay_and_keepalive(self, upstream):
        proxy = self._proxy(upstream)
        try:
            status, ctype, body = fleet_client.http_exchange(
                "GET", proxy.url + "/anything")
            assert status == 200 and body == _EchoHandler.payload
            status, _, echoed = fleet_client.http_exchange(
                "POST", proxy.url + "/echo", raw=b"hello-bytes",
                content_type="application/octet-stream")
            assert status == 200 and echoed == b"hello-bytes"
            stats = proxy.stats()
            assert stats["exchanges"] == 2
            assert all(stats[k] == 0 for k in FAULT_KINDS)
        finally:
            proxy.close()

    def test_latency_fault_delays_the_response(self, upstream):
        proxy = self._proxy(upstream, latency=1.0, latency_ms=80)
        try:
            t0 = time.perf_counter()
            status, _, _ = fleet_client.http_exchange("GET", proxy.url + "/")
            assert status == 200
            assert time.perf_counter() - t0 >= 0.08
            assert proxy.stats()["latency"] == 1
        finally:
            proxy.close()

    def test_refuse_fault_resets_before_the_request_is_read(self, upstream):
        proxy = self._proxy(upstream, refuse=1.0)
        try:
            with pytest.raises((urllib.error.URLError, ConnectionError,
                                OSError)):
                fleet_client.http_exchange("GET", proxy.url + "/")
            assert proxy.stats()["refuse"] == 1
        finally:
            proxy.close()

    def test_reset_mid_exchange_raises_connection_trouble(self, upstream):
        proxy = self._proxy(upstream, reset=1.0)
        try:
            with pytest.raises((urllib.error.URLError, ConnectionError,
                                OSError)):
                fleet_client.http_exchange("GET", proxy.url + "/")
            assert proxy.stats()["reset"] == 1
        finally:
            proxy.close()

    def test_truncation_normalizes_to_connection_error(self, upstream):
        """A cleanly-closed half response raises IncompleteRead — an
        HTTPException only — which fleet/client.py must normalize to
        ConnectionError so every liveness classifier treats the torn
        payload as connection trouble (the PR-14 client hardening)."""
        proxy = self._proxy(upstream, truncate=1.0)
        try:
            with pytest.raises((ConnectionError, OSError,
                                urllib.error.URLError)):
                fleet_client.http_exchange("GET", proxy.url + "/")
            assert proxy.stats()["truncate"] == 1
        finally:
            proxy.close()

    def test_slowloris_trickles_but_completes(self, upstream):
        proxy = self._proxy(upstream, slowloris=1.0, slow_ms=5,
                            slow_chunk=256)
        try:
            t0 = time.perf_counter()
            status, _, body = fleet_client.http_exchange(
                "GET", proxy.url + "/")
            assert status == 200 and body == _EchoHandler.payload
            assert time.perf_counter() - t0 >= 0.02
            assert proxy.stats()["slowloris"] == 1
        finally:
            proxy.close()

    def test_bitflip_corrupts_exactly_one_bit_of_a_body(self, upstream):
        proxy = self._proxy(upstream, bitflip=1.0, seed=2)
        try:
            flipped = 0
            for _ in range(8):
                status, _, body = fleet_client.http_exchange(
                    "POST", proxy.url + "/echo", raw=b"A" * 512,
                    content_type="application/octet-stream")
                assert status == 200 and len(body) == 512
                diff = sum(bin(a ^ b).count("1")
                           for a, b in zip(b"A" * 512, body))
                assert diff in (0, 1, 2)  # request flip, response flip, both
                flipped += 1 if diff else 0
            assert flipped > 0
            assert proxy.stats()["bitflip"] > 0
        finally:
            proxy.close()

    def test_pool_mounts_one_proxy_per_upstream(self, upstream):
        pool = ProxyPool(ChaosPlan(seed=1))
        try:
            url1 = pool.url_for(upstream)
            assert url1 == pool.url_for(upstream + "/")  # normalized
            assert url1 != upstream
            status, _, _ = fleet_client.http_exchange("GET", url1 + "/")
            assert status == 200
            assert set(pool.proxies()) == {upstream}
            assert pool.stats()["exchanges"] == 1
        finally:
            pool.close()
        # Closed pools pass upstreams through untouched.
        assert pool.url_for(upstream) == upstream

    def test_pool_prunes_dead_upstreams(self, upstream):
        """A respawned worker gets a fresh hop via url_for; prune() must
        close the DEAD port's proxy (listener + accept thread) instead of
        leaking one per respawn for the fleet's lifetime."""
        pool = ProxyPool(ChaosPlan(seed=1))
        try:
            pool.url_for("http://127.0.0.1:9")  # the "old port" hop
            live_url = pool.url_for(upstream)
            dead_proxy = pool.proxies()["http://127.0.0.1:9"]
            pool.prune([upstream, None])  # None = a mid-boot worker
            assert set(pool.proxies()) == {upstream}
            assert dead_proxy._closed
            dead_proxy._thread.join(timeout=5)
            assert not dead_proxy._thread.is_alive()
            # The survivor still relays, and a remount after the prune
            # takes a FRESH salt — never a pruned proxy's stream.
            status, _, _ = fleet_client.http_exchange("GET", live_url + "/")
            assert status == 200
            pool.url_for("http://127.0.0.1:19")
            assert pool._created == 3
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# The chaos matrix: every fault class against a REAL 2-worker fleet


@pytest.fixture(scope="module")
def matrix_workers(tmp_path_factory):
    root = tmp_path_factory.mktemp("chaos-fleet")
    workers = {}
    for wid in ("w0", "w1"):
        srv = GolServer(port=0, journal_dir=str(root / wid), flush_age=0.01)
        srv.start()
        workers[wid] = srv
    yield root, workers
    for srv in workers.values():
        srv.shutdown()


_MATRIX_PLANS = {
    "latency": "seed=101,latency=0.3,latency_ms=30",
    "refuse": "seed=102,refuse=0.2",
    "reset": "seed=103,reset=0.2",
    "slowloris": "seed=104,slowloris=0.3,slow_ms=2,slow_chunk=128",
    "truncate": "seed=105,truncate=0.2",
    "bitflip": "seed=106,bitflip=0.25",
}


class TestChaosMatrix:
    """Each fault class runs real jobs through a real router+2 workers with
    the chaos proxy on the data path and breakers armed. The contract per
    class: every ACCEPTED job ends DONE exactly once (journal audit), every
    collected result is oracle-byte-identical, ambiguous outcomes surface
    as the documented 504 (with the worker named), and the injected fault
    class actually fired (proxy stats) — never a hang, a double-run, or a
    silently wrong board."""

    GENS = 6
    JOBS = 8

    def _rig(self, tmp_path, workers, plan_spec):
        fleet = Fleet(str(tmp_path / "fleet"))
        for wid, srv in workers.items():
            fleet.attach(srv.url, wid)
        pool = ProxyPool(ChaosPlan.parse(plan_spec))
        router = RouterServer(
            fleet, port=0, breakers=True,
            breaker_config=BreakerConfig(fail_threshold=3, cooldown_s=0.2),
            chaos=pool,
        )
        router.start()
        return router, pool

    def _boards(self, fault):
        seed0 = 7000 + 100 * sorted(_MATRIX_PLANS).index(fault)
        return [text_grid.generate(32, 32, seed=seed0 + i)
                for i in range(self.JOBS)]

    def _robust(self, fn, tries=200, pause=0.05, retryable=()):
        last = None
        for _ in range(tries):
            try:
                return fn()
            except (urllib.error.URLError, ConnectionError, OSError,
                    wire.WireError, *retryable) as err:
                last = err
                time.sleep(pause)
        raise AssertionError(f"never recovered: {last!r}")

    def _reachable(self, base, job_id):
        """True when the id answers a state at least once — the check that
        catches a bit-flipped 202 body (garbled id): the job exists under
        its TRUE id on the worker, but THIS id 404s forever."""
        for _ in range(20):
            try:
                status, payload = _http("GET", f"{base}/jobs/{job_id}")
            except (urllib.error.URLError, ConnectionError, OSError):
                time.sleep(0.05)
                continue
            if status == 404:
                return False
            if isinstance(payload, dict) and payload.get("state"):
                return True
            time.sleep(0.05)
        return False

    def _submit_one(self, base, board, packed, ambiguous):
        """Submit with the documented client stance: spills/refusals retry,
        ambiguous 504s are counted and knowingly resubmitted (a fresh id),
        CRC 400s re-send (a corrupted frame created no job), and a
        corrupted 202 body (garbled/torn id) is detected by the id never
        answering — resubmit; the orphan still lands exactly one done
        record under its true id."""
        meta = {"gen_limit": self.GENS}

        def post():
            if packed:
                frame = wire.encode_frame(meta, grid=board)
                return fleet_client.http_json(
                    "POST", f"{base}/jobs", raw=frame,
                    content_type=wire.CONTENT_TYPE)
            return _http("POST", f"{base}/jobs", {
                "width": 32, "height": 32,
                "cells": text_grid.encode(board).decode("ascii"), **meta,
            })

        for _ in range(60):
            try:
                status, payload = post()
            except (urllib.error.URLError, ConnectionError, OSError):
                time.sleep(0.05)
                continue
            if status == 202:
                job_id = (payload.get("id")
                          if isinstance(payload, dict) else None)
                if job_id and self._reachable(base, job_id):
                    return job_id
                ambiguous.append(payload)  # corrupted 202 body: resubmit
                time.sleep(0.05)
                continue
            if status == 504:
                # The documented ambiguity contract: the body names the
                # worker whose outcome is unknown; the client resubmits
                # KNOWINGLY (fresh id — never a double-run of the old id).
                assert "worker" in payload, payload
                ambiguous.append(payload)
                time.sleep(0.05)
                continue
            if status in (400, 503):
                # 400 here is the CRC gate catching a flipped frame (no
                # job was created: a re-send is unconditionally safe);
                # 503 is both workers momentarily refused.
                if status == 400:
                    assert "crc" in str(payload.get("error", "")).lower(), \
                        payload
                time.sleep(0.05)
                continue
            raise AssertionError(f"unexpected submit answer {status}: "
                                 f"{payload}")
        raise AssertionError("submit never landed")

    @pytest.mark.parametrize("fault", sorted(_MATRIX_PLANS))
    def test_fault_class(self, fault, tmp_path, matrix_workers):
        root, workers = matrix_workers
        router, pool = self._rig(tmp_path, workers, _MATRIX_PLANS[fault])
        packed = fault == "bitflip"  # the CRC-gated lane end to end
        boards = self._boards(fault)
        ambiguous: list = []
        try:
            base = router.url
            accepted = {}
            for board in boards:
                job_id = self._submit_one(base, board, packed, ambiguous)
                accepted[job_id] = board

            def state_of(job_id):
                status, payload = _http("GET", f"{base}/jobs/{job_id}")
                if status >= 500:
                    raise ConnectionError(f"transient {status}")
                return payload.get("state") if isinstance(payload, dict) \
                    else None

            def terminal(job_id):
                # A bit-flipped poll answer parses to garbage: treat any
                # non-terminal/garbled state as "ask again" — the NEXT
                # poll answers truthfully (faults never touch the job).
                state = state_of(job_id)
                if state not in ("done", "failed", "cancelled"):
                    raise ConnectionError(f"not terminal yet: {state}")
                return state

            for job_id in accepted:
                state = self._robust(lambda j=job_id: terminal(j),
                                     tries=600)
                assert state == "done", (fault, job_id, state)

            for job_id, board in accepted.items():
                if packed:
                    def fetch(j=job_id):
                        status, ctype, body = fleet_client.http_exchange(
                            "GET", f"{base}/result/{j}",
                            headers={"Accept": wire.CONTENT_TYPE})
                        if status >= 500:
                            raise ConnectionError(f"transient {status}")
                        assert status == 200
                        assert wire.is_packed(ctype)
                        frame = wire.decode_frame(body)  # CRC gate HERE
                        return dict(frame.meta), frame.grid()
                    result, got = self._robust(fetch)
                else:
                    def fetch(j=job_id):
                        status, payload = _http("GET", f"{base}/result/{j}")
                        if status >= 500:
                            raise ConnectionError(f"transient {status}")
                        assert status == 200, payload
                        grid = text_grid.decode(
                            payload["grid"].encode("ascii"),
                            payload["width"], payload["height"])
                        return payload, grid
                    result, got = self._robust(fetch)
                want = oracle.run(board, GameConfig(gen_limit=self.GENS))
                np.testing.assert_array_equal(np.asarray(got), want.grid)
                assert result["generations"] == want.generations

            # The schedule actually fired: an idle proxy proves nothing.
            stats = pool.stats()
            assert stats.get(fault, 0) > 0, stats
        finally:
            router.shutdown(cascade=False)

        # Fleet-wide exactly-once: every accepted id holds EXACTLY one
        # done record across both partitions' journals (flush is async;
        # poll briefly).
        def audit():
            done: dict = {}
            for wid in workers:
                path = root / wid / "journal.jsonl"
                if not path.exists():
                    continue
                for line in path.read_bytes().split(b"\n"):
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("event") == "done":
                        done.setdefault(rec["id"], []).append(wid)
            return done

        assert _wait(lambda: set(accepted) <= set(audit()), timeout=20)
        done = audit()
        for job_id in accepted:
            assert len(done[job_id]) == 1, (fault, job_id, done[job_id])


# ---------------------------------------------------------------------------
# The chaos matrix, shard lane: reset mid-frame on the halo hop


class TestShardHaloChaos:
    """ISSUE 18's halo-hop matrix entry: ``reset`` mid-exchange on the
    worker<->worker data path of a SHARDED job. The proxy delivers the
    halo frame whole, then resets the reply — the sender's retry ladder
    re-sends bytes the receiver already holds, and the receiver's
    (step, sender) inbox idempotency makes the duplicate a no-op. The
    same faults hit the coordinator's step RPCs, which must surface as
    ShardPeerDown -> recovery from the durable floor, never a wrong
    board. Contract: the job completes, the board is byte-identical to
    the solo sparse engine, each partition's shard journal holds exactly
    ONE done record, and the reset class actually fired."""

    def test_reset_mid_frame_is_exactly_once_and_byte_identical(
            self, tmp_path, matrix_workers):
        from gol_tpu.config import Convention
        from gol_tpu.sparse import SparseBoard, TileMemo, simulate_sparse

        root, workers = matrix_workers
        rle = "x = 3, y = 3\nb2o$2o$bo!"  # r-pentomino on a tile corner
        height = width = 512
        tile, gens = 256, 12
        fleet = Fleet(str(tmp_path / "fleet"))
        for wid, srv in workers.items():
            fleet.attach(srv.url, wid)
        pool = ProxyPool(ChaosPlan.parse("seed=107,reset=0.25"))
        router = RouterServer(fleet, port=0, chaos=pool)
        router.start()
        try:
            base = router.url
            status, payload = _http("POST", f"{base}/jobs", {
                "shard": True, "rle": rle, "x": tile - 1, "y": tile - 1,
                "width": width, "height": height, "tile": tile,
                "convention": "c", "gen_limit": gens,
                "check_similarity": False, "checkpoint_every": 4,
            })
            assert status == 202, payload
            job_id = payload["id"]

            def state():
                try:
                    st, job = _http("GET", f"{base}/jobs/{job_id}")
                except (urllib.error.URLError, ConnectionError, OSError):
                    return None
                return job.get("state") if st == 200 else None

            assert _wait(lambda: state() in ("done", "failed"),
                         timeout=240), "shard job hung under reset chaos"
            assert state() == "done"
            status, result = _http("GET", f"{base}/result/{job_id}")
            assert status == 200, result

            cfg = GameConfig(gen_limit=gens, check_similarity=False,
                             convention=Convention.C)
            solo = simulate_sparse(
                SparseBoard.from_rle(rle, height=height, width=width,
                                     tile=tile, x=tile - 1, y=tile - 1),
                cfg, TileMemo())
            assert result["rle"] == solo.board.to_rle()
            assert result["generations"] == solo.generations
            assert result["exit_reason"] == solo.exit_reason

            # The schedule actually fired: an idle proxy proves nothing.
            assert pool.stats().get("reset", 0) > 0, pool.stats()
        finally:
            router.shutdown(cascade=False)

        # Exactly-once across every partition's shard journal.
        for wid in workers:
            path = root / wid / f"shard-{job_id}.jsonl"
            assert path.exists(), f"{wid} never journaled its shard"
            dones = [json.loads(line)
                     for line in path.read_text().splitlines()
                     if line.strip()
                     and json.loads(line).get("kind") == "done"]
            assert len(dones) == 1, (wid, dones)


# ---------------------------------------------------------------------------
# The serve-side retry budget rides the scheduler


class TestSchedulerRetryBudget:
    def test_budget_exhaustion_degrades_to_first_attempt(self, tmp_path):
        """With an empty bucket a failing batch surfaces its ORIGINAL
        error after one attempt instead of the policy's full ladder."""
        from gol_tpu.serve.jobs import new_job
        from gol_tpu.serve.scheduler import Scheduler

        calls = []

        def run_batch(key, jobs):
            calls.append(len(jobs))
            raise RuntimeError("injected transient brownout")

        clock = _Clock()
        budget = RetryBudget(capacity=1.0, refill_per_s=0.0, clock=clock)
        sched = Scheduler(run_batch=run_batch, flush_age=0.01,
                          retry_budget=budget,
                          retryable=lambda e: True)
        sched.start()
        try:
            board = text_grid.generate(32, 32, seed=8)
            job = new_job(32, 32, board, gen_limit=4)
            sched.submit(job)
            assert _wait(lambda: job.state == "failed", timeout=30)
            # attempt 1 + the single budgeted retry = 2 dispatches, and
            # the surfaced error is the batch's own.
            assert len(calls) == 2
            assert "injected transient" in job.error
        finally:
            sched.stop()


# ---------------------------------------------------------------------------
# The submit client's CRC-failure bound: never-a-hang under a hop that
# corrupts every result frame


class TestSubmitWireFailureBound:
    def test_persistent_crc_failure_gives_up_instead_of_polling_forever(
            self, tmp_path, capsys, monkeypatch):
        """Status polls answer 200 (refreshing last_contact), so the
        no-contact cutoff can never fire for a job whose RESULT frame
        deterministically fails CRC — a corruptor parked on the hop, or a
        worker emitting bad frames. The sweep bound turns what was an
        infinite --wait loop into rc 1 with the job named."""
        import argparse

        from gol_tpu import cli
        from gol_tpu.io.wire import WireError

        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        flush_age=0.01)
        srv.start()
        try:
            board = text_grid.generate(32, 32, seed=9)
            status, payload = _http(
                "POST", f"{srv.url}/jobs",
                {"width": 32, "height": 32,
                 "cells": text_grid.encode(board).decode("ascii"),
                 "gen_limit": 4},
            )
            assert status == 202

            fetches = []

            def corrupt_fetch(base, job_id, wire_pref):
                fetches.append(job_id)
                raise WireError("payload CRC mismatch")

            monkeypatch.setattr(cli, "_fetch_result", corrupt_fetch)
            pending = {payload["id"]: (str(tmp_path / "in.txt"), srv.url)}
            args = argparse.Namespace(poll_interval=0.02, server_timeout=30.0,
                                      wire="packed")
            rc = cli._collect_results(pending, args, str(tmp_path))
            assert rc == 1
            err = capsys.readouterr().err
            assert "unusable response body" in err and payload["id"] in err
            # 3 sweeps x the policy's in-sweep retries — bounded, not one
            # sweep (a transit flip must still heal on refetch).
            assert 3 <= len(fetches) <= 9
        finally:
            srv.shutdown()

    def test_garbled_status_poll_bounded_not_a_crash(self, tmp_path,
                                                     capsys, monkeypatch):
        """The text lane's version of the same hazard: a bit-flipped hop
        garbling a 200 status body used to escape the collection loop as
        a KeyError traceback, abandoning EVERY pending job. The fleet
        client's _parse turns an unparseable body into an {"error": ...}
        dict (it never raises), so EVERY corrupted status poll arrives
        here as 200-with-no-state — now a bounded strike-out."""
        import argparse

        from gol_tpu import cli

        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        flush_age=0.01)
        srv.start()
        try:
            board = text_grid.generate(32, 32, seed=10)
            status, payload = _http(
                "POST", f"{srv.url}/jobs",
                {"width": 32, "height": 32,
                 "cells": text_grid.encode(board).decode("ascii"),
                 "gen_limit": 4},
            )
            assert status == 202
            calls = []

            def garbled(method, url, body=None, timeout=30, **kw):
                calls.append(url)
                # What fleet_client.http_json ACTUALLY returns for a 200
                # whose body no longer parses as JSON (_parse never
                # raises): a dict that is not a job answer.
                return 200, {"error": "\x7fgarbled\x01body"}

            monkeypatch.setattr(cli, "_http_json", garbled)
            pending = {payload["id"]: (str(tmp_path / "in.txt"), srv.url)}
            args = argparse.Namespace(poll_interval=0.02,
                                      server_timeout=30.0)
            rc = cli._collect_results(pending, args, str(tmp_path))
            assert rc == 1
            err = capsys.readouterr().err
            assert "unusable response body" in err and payload["id"] in err
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# Submit-side corruption contracts: the 202 ack and the packed CRC 400


class TestSubmitCorruptionContracts:
    def _board_file(self, tmp_path):
        board = text_grid.generate(32, 32, seed=11)
        path = tmp_path / "in.txt"
        path.write_bytes(text_grid.encode(board))
        return str(path)

    def test_corrupted_202_ack_abandons_loudly_not_a_crash(
            self, tmp_path, capsys, monkeypatch):
        """A 202 whose ack body was garbled in transit has no id to poll
        — and the job WAS accepted, so a resend would double-run the
        board. The client must abandon loudly (the ambiguous-504
        contract), not die on a KeyError traceback."""
        from gol_tpu import cli

        def garbled_ack(method, url, body=None, timeout=30, **kw):
            return 202, {"error": "\x7fgarbled ack"}

        monkeypatch.setattr(cli, "_http_json", garbled_ack)
        rc = cli.main([
            "submit", "--server", "http://t.invalid", "--no-wait",
            "32", "32", self._board_file(tmp_path),
        ])
        assert rc == 1
        err = capsys.readouterr().err
        assert "ack body arrived corrupted" in err
        assert "audit the server" in err

    def test_crc_400_resends_packed_never_downgrades_to_text(
            self, tmp_path, capsys, monkeypatch):
        """A CRC-mismatch 400 is the packed wire WORKING on a lossy hop
        (the 400 created no job; a resend is safe) — it must be resent
        PACKED, not misread as format rejection: downgrading to text on
        exactly the link that corrupts would swap detected corruption
        for the text lane's undetectable kind."""
        from gol_tpu import cli
        from gol_tpu.io import wire

        calls = []

        def flaky_hop(method, url, body=None, timeout=30, **kw):
            calls.append(kw.get("content_type"))
            if len(calls) == 1:
                return 400, {"error": "payload CRC mismatch "
                                      "(got 0x1, want 0x2)"}
            return 202, {"id": "j9", "state": "queued"}

        monkeypatch.setattr(cli, "_http_json", flaky_hop)
        rc = cli.main([
            "submit", "--server", "http://t.invalid", "--no-wait",
            "--wire", "packed", "32", "32", self._board_file(tmp_path),
        ])
        assert rc == 0
        # BOTH attempts went out packed: no downgrade happened.
        assert calls == [wire.CONTENT_TYPE, wire.CONTENT_TYPE]
        err = capsys.readouterr().err
        assert "resending packed (1/2)" in err
        assert "does not accept the packed wire format" not in err

    def test_persistent_crc_400_surfaces_the_400_still_packed(
            self, tmp_path, capsys, monkeypatch):
        """A hop corrupting EVERY frame: two bounded packed resends, then
        the 400 surfaces loudly (rc 1) — never a silent text downgrade,
        never an unbounded loop."""
        from gol_tpu import cli
        from gol_tpu.io import wire

        calls = []

        def dead_hop(method, url, body=None, timeout=30, **kw):
            calls.append(kw.get("content_type"))
            return 400, {"error": "payload CRC mismatch"}

        monkeypatch.setattr(cli, "_http_json", dead_hop)
        rc = cli.main([
            "submit", "--server", "http://t.invalid", "--no-wait",
            "--wire", "packed", "32", "32", self._board_file(tmp_path),
        ])
        assert rc == 1
        assert calls == [wire.CONTENT_TYPE] * 3  # initial + 2 resends
        err = capsys.readouterr().err
        assert "HTTP 400" in err
        assert "does not accept the packed wire format" not in err


class TestBreakerPruning:
    def test_prune_drops_retired_workers_breaker_and_gauge(self, tmp_path):
        """The chaos-proxy prune's sibling: a retired worker's breaker
        (and its state gauge) must leave with its membership row —
        scale-up reuses the lowest free partition id, so a stale OPEN
        breaker would be inherited by brand-new capacity."""
        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        fleet.attach("http://wa.invalid", "wa")
        router = RouterServer(fleet, port=0, breakers=True)
        try:
            live = router.breaker("wa")
            gone = router.breaker("retired")
            for _ in range(3):
                live.on_failure()  # live history survives the prune
                gone.on_failure()
            assert router.breaker_states() == {"wa": OPEN, "retired": OPEN}
            router.prune_breakers()
            assert router.breaker_states() == {"wa": OPEN}
            gauges = router.registry.snapshot()["gauges"]
            assert "breaker_state_retired" not in gauges
            assert gauges["breaker_state_wa"] == 2  # open
            # The same id re-learned later starts FRESH.
            assert router.breaker("retired").state == CLOSED
        finally:
            router.httpd.server_close()


class TestStrikesAreConsecutive:
    def test_intermittent_garbled_polls_never_strike_out_a_long_job(
            self, tmp_path, capsys, monkeypatch):
        """The strike bound is on CONSECUTIVE corrupt sweeps: a long job
        under a low-rate bitflip hop sees garbled status bodies
        interleaved with good ones for its whole runtime, and the old
        lifetime-cumulative counter abandoned it after 3 independent,
        self-healed flips. A usable answer must clear the strikes."""
        import argparse

        from gol_tpu import cli

        polls = {"n": 0}
        # Garbled/usable alternating for 10 sweeps (5 garbled answers —
        # past the old lifetime bound), then done.
        def hop(method, url, body=None, timeout=30, **kw):
            if "/timeline" in url:
                return 200, {}
            polls["n"] += 1
            if polls["n"] > 10:
                return 200, {"state": "done"}
            if polls["n"] % 2:
                return 200, {"error": "\x7fgarbled"}
            return 200, {"state": "running"}

        board = text_grid.generate(32, 32, seed=12)

        def fetch(base, job_id, wire_pref):
            return 200, {"generations": 1, "exit_reason": "gen_limit"}, board

        monkeypatch.setattr(cli, "_http_json", hop)
        monkeypatch.setattr(cli, "_fetch_result", fetch)
        pending = {"j1": (str(tmp_path / "in.txt"), "http://t.invalid")}
        args = argparse.Namespace(poll_interval=0.01, server_timeout=30.0)
        rc = cli._collect_results(pending, args, str(tmp_path))
        assert rc == 0
        assert "unusable response body" not in capsys.readouterr().err
        assert (tmp_path / "in.txt.out").exists()

    def test_result_meta_missing_key_is_bounded_not_a_keyerror(
            self, tmp_path, capsys, monkeypatch):
        """A flip can eat a meta KEY and leave valid JSON + a decodable
        grid ('generations' -> 'genersations'): the result print used to
        die on an uncaught KeyError, abandoning every pending job. Now
        the suspect body is refetched on the same bounded strike-out —
        and never written to disk."""
        import argparse

        from gol_tpu import cli

        def hop(method, url, body=None, timeout=30, **kw):
            return 200, {"state": "done"}

        board = text_grid.generate(32, 32, seed=13)

        def fetch_missing_key(base, job_id, wire_pref):
            return 200, {"exit_reason": "gen_limit",
                         "genersations": 1}, board

        monkeypatch.setattr(cli, "_http_json", hop)
        monkeypatch.setattr(cli, "_fetch_result", fetch_missing_key)
        pending = {"j1": (str(tmp_path / "in.txt"), "http://t.invalid")}
        args = argparse.Namespace(poll_interval=0.01, server_timeout=30.0)
        rc = cli._collect_results(pending, args, str(tmp_path))
        assert rc == 1
        err = capsys.readouterr().err
        assert "unusable response body" in err
        assert "result meta incomplete" in err
        assert not (tmp_path / "in.txt.out").exists()


class TestDeadlineRestampOnCrcRetry:
    def test_crc_retry_restamps_the_remaining_budget(self, tmp_path):
        """The router's CRC re-forward must re-derive X-Gol-Deadline: the
        first (corrupted, slow) attempt already spent budget, and
        resending the original header would hand the worker time the
        client no longer has."""
        body = json.dumps({"width": 32, "height": 32}).encode()
        seen = []

        def stub_http(method, url, body=None, raw=None, timeout=0,
                      headers=None, **kw):
            seen.append((dict(headers or {}), timeout))
            if len(seen) == 1:
                return 400, {"error": "payload CRC mismatch"}
            return 202, {"id": "j1", "state": "queued"}

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        fleet.attach("http://wa.invalid", "wa")
        router = RouterServer(fleet, port=0, http=stub_http)
        try:
            status, payload = router.route_submit(
                body, deadline_header="60.0"
            )
            assert status == 202
            assert len(seen) == 2
            first = float(seen[0][0][propagate.DEADLINE_HEADER])
            second = float(seen[1][0][propagate.DEADLINE_HEADER])
            # Both stamped, and the retry's stamp is derived FRESH (the
            # walk's elapsed time only ever shrinks the budget).
            assert 0 < second <= first <= 60.0
        finally:
            router.httpd.server_close()


class TestJitteredDeadlineGuard:
    def test_up_jittered_pause_never_overruns_the_deadline(self):
        """The deadline guard tests the ACTUAL jittered pause: with rng
        pinned high, a nominal delay that fits but jitters past the
        deadline must refuse the retry instead of sleeping through it."""
        clock = _Clock()
        sleeps = []

        def fail():
            raise ConnectionResetError("connection reset by peer")

        policy = RetryPolicy(attempts=5, base_delay=0.9, multiplier=1.0,
                             jitter=0.25, deadline=1.0)
        with pytest.raises(ConnectionResetError):
            # 0 + 0.9*1.25 = 1.125 > 1.0: no retry taken, no sleep.
            policy.call(fail, sleep=sleeps.append, clock=clock,
                        rng=lambda: 1.0)
        assert sleeps == []
        # Down-jittered, the same nominal delay fits: 0.9*0.75 = 0.675.
        with pytest.raises(ConnectionResetError):
            policy.call(fail, sleep=sleeps.append, clock=clock,
                        rng=lambda: 0.0)
        assert len(sleeps) >= 1 and sleeps[0] == pytest.approx(0.675)
