"""Property-based differential tests: random grids, every path == oracle.

The reference's de-facto methodology — agreement on generate.sh random inputs
(SURVEY.md §4.2) — upgraded to generated shapes, densities, and configs.
"""

import numpy as np
from hypothesis import given, settings, strategies as st

from gol_tpu import engine, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.ops import packed_math

import jax.numpy as jnp


grids = st.builds(
    lambda h, w, density, seed: (
        np.random.default_rng(seed).random((h, w)) < density
    ).astype(np.uint8),
    h=st.integers(1, 48),
    w=st.integers(1, 48),
    density=st.floats(0.0, 1.0),
    seed=st.integers(0, 2**31),
)


@given(grid=grids)
@settings(max_examples=40, deadline=None)
def test_lax_engine_matches_oracle(grid):
    config = GameConfig(gen_limit=12)
    expect = oracle.run(grid, config)
    got = engine.simulate(grid, config, kernel="lax")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


@given(grid=grids)
@settings(max_examples=40, deadline=None)
def test_cuda_convention_matches_oracle(grid):
    config = GameConfig(gen_limit=12, convention=Convention.CUDA)
    expect = oracle.run(grid, config)
    got = engine.simulate(grid, config, kernel="lax")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


@given(
    h=st.integers(1, 24),
    words=st.integers(1, 4),
    density=st.floats(0.0, 1.0),
    seed=st.integers(0, 2**31),
)
@settings(max_examples=40, deadline=None)
def test_packed_torus_matches_oracle(h, words, density, seed):
    grid = (np.random.default_rng(seed).random((h, words * 32)) < density).astype(
        np.uint8
    )
    got = packed_math.decode(
        packed_math.evolve_torus_words(packed_math.encode(jnp.asarray(grid)))
    )
    np.testing.assert_array_equal(np.asarray(got), oracle.evolve(grid))
