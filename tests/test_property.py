"""Property-based differential tests: random grids, every path == oracle.

The reference's de-facto methodology — agreement on generate.sh random inputs
(SURVEY.md §4.2) — upgraded to generated shapes, densities, and configs.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="hypothesis not installed")
from hypothesis import given, settings, strategies as st

from gol_tpu import engine, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.ops import packed_math

import jax.numpy as jnp


grids = st.builds(
    lambda h, w, density, seed: (
        np.random.default_rng(seed).random((h, w)) < density
    ).astype(np.uint8),
    h=st.integers(1, 48),
    w=st.integers(1, 48),
    density=st.floats(0.0, 1.0),
    seed=st.integers(0, 2**31),
)


@given(grid=grids)
@settings(max_examples=40, deadline=None)
def test_lax_engine_matches_oracle(grid):
    config = GameConfig(gen_limit=12)
    expect = oracle.run(grid, config)
    got = engine.simulate(grid, config, kernel="lax")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


@given(grid=grids)
@settings(max_examples=40, deadline=None)
def test_cuda_convention_matches_oracle(grid):
    config = GameConfig(gen_limit=12, convention=Convention.CUDA)
    expect = oracle.run(grid, config)
    got = engine.simulate(grid, config, kernel="lax")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


@given(
    h=st.integers(1, 24),
    words=st.integers(1, 4),
    density=st.floats(0.0, 1.0),
    seed=st.integers(0, 2**31),
)
@settings(max_examples=40, deadline=None)
def test_packed_torus_matches_oracle(h, words, density, seed):
    grid = (np.random.default_rng(seed).random((h, words * 32)) < density).astype(
        np.uint8
    )
    got = packed_math.decode(
        packed_math.evolve_torus_words(packed_math.encode(jnp.asarray(grid)))
    )
    np.testing.assert_array_equal(np.asarray(got), oracle.evolve(grid))


@given(
    freq=st.integers(1, 5),
    check=st.booleans(),
    convention=st.sampled_from([Convention.C, Convention.CUDA]),
    grid=grids,
)
@settings(max_examples=40, deadline=None)
def test_similarity_frequency_matches_oracle(freq, check, convention, grid):
    # The blocked loops replay similarity counters from per-generation flag
    # vectors; the firing phase must survive any frequency, toggled checks,
    # and both exit conventions.
    config = GameConfig(
        gen_limit=14,
        similarity_frequency=freq,
        check_similarity=check,
        convention=convention,
    )
    expect = oracle.run(grid, config)
    got = engine.simulate(grid, config, kernel="lax")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


@given(
    h=st.integers(1, 6),
    words=st.integers(1, 3),
    density=st.floats(0.0, 1.0),
    seed=st.integers(0, 2**31),
    convention=st.sampled_from([Convention.C, Convention.CUDA]),
)
@settings(max_examples=30, deadline=None)
def test_packed_engine_matches_oracle(h, words, density, seed, convention):
    # The packed kernel's engine path (fused flags + temporal blocking where
    # eligible) across heights 8..48 and word counts, both conventions.
    grid = (
        np.random.default_rng(seed).random((h * 8, words * 32)) < density
    ).astype(np.uint8)
    config = GameConfig(gen_limit=20, convention=convention)
    expect = oracle.run(grid, config)
    got = engine.simulate(grid, config, kernel="packed")
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations


@given(
    mesh_shape=st.sampled_from([(1, 2), (2, 1), (2, 2), (2, 4), (4, 2)]),
    hk=st.integers(1, 3),
    wk=st.integers(1, 2),
    density=st.floats(0.0, 1.0),
    seed=st.integers(0, 2**31),
    kernel=st.sampled_from(["lax", "auto"]),
)
@settings(max_examples=25, deadline=None)
def test_mesh_engine_matches_oracle(mesh_shape, hk, wk, density, seed, kernel):
    # Random grids over random mesh shapes: halo exchange + psum votes on
    # every axis split, auto kernel routing per local shard shape.
    from gol_tpu.parallel import make_mesh

    r, c = mesh_shape
    h, w = r * hk * 8, c * wk * 32
    grid = (np.random.default_rng(seed).random((h, w)) < density).astype(np.uint8)
    config = GameConfig(gen_limit=12)
    expect = oracle.run(grid, config)
    got = engine.simulate(grid, config, mesh=make_mesh(r, c), kernel=kernel)
    np.testing.assert_array_equal(got.grid, expect.grid)
    assert got.generations == expect.generations
