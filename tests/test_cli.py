"""CLI contract tests: argv semantics, printed lines, output files.

Pins the behaviors catalogued in SURVEY.md §1 L6 and the per-variant print
contracts (src/game.c:201-203,241; src/game_mpi_collective.c:203,370,450,485;
src/game_openmp.c:501; src/game_cuda.cu:294-297).
"""

import os

import numpy as np
import pytest

from gol_tpu import cli, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.io import text_grid


@pytest.fixture(autouse=True)
def _obs_reset():
    """--trace runs arm the PROCESS-global tracer/recorder; leave every test
    with observability back at its default-off state."""
    yield
    from gol_tpu.obs import recorder, registry, trace

    trace.disable()
    trace.clear()
    recorder.uninstall()
    registry.reset_default()


@pytest.fixture
def block_file(tmp_path):
    g = np.zeros((8, 8), np.uint8)
    g[3:5, 3:5] = 1
    p = tmp_path / "block.txt"
    text_grid.write_grid(str(p), g)
    return str(p), g


@pytest.fixture
def random16(tmp_path):
    g = text_grid.generate(16, 16, seed=13)
    p = tmp_path / "rand.txt"
    text_grid.write_grid(str(p), g)
    return str(p), g


def run_cli(args):
    return cli.main(args)


class TestArgContract:
    def test_no_args_prints_finished(self, capsys):
        assert run_cli([]) == 0
        assert capsys.readouterr().out == "Finished\n"

    def test_openmp_no_args_prints_nothing(self, capsys):
        # game_openmp.c:501 — the final printf is commented out.
        assert run_cli(["--variant", "openmp"]) == 0
        assert capsys.readouterr().out == ""

    def test_two_args_skips_simulation(self, capsys):
        assert run_cli(["16", "16"]) == 0
        assert capsys.readouterr().out == "Finished\n"

    def test_atoi_garbage_defaults_to_30(self, capsys, tmp_path):
        g = text_grid.generate(30, 30, seed=1)
        p = tmp_path / "g.txt"
        text_grid.write_grid(str(p), g)
        assert run_cli(["abc", "xyz", str(p), "--variant", "game",
                        "--gen-limit", "2", "--output", str(tmp_path / "o.out")]) == 0
        out = capsys.readouterr().out
        assert "Generations:\t2" in out

    def test_unknown_variant_fails(self, capsys):
        with pytest.raises(SystemExit):
            run_cli(["--variant", "nope"])


class TestSerialVariant:
    def test_block_run_output_and_stdout(self, capsys, block_file, tmp_path, monkeypatch):
        path, g = block_file
        monkeypatch.chdir(tmp_path)
        assert run_cli(["8", "8", path, "--variant", "game"]) == 0
        out = capsys.readouterr().out
        # Exact line sequence of src/game.c:201-203,241.
        assert out.startswith("Finished.\n\nGenerations:\t2\nExecution time:\t")
        assert out.endswith("msecs\nFinished\n")
        assert (tmp_path / "game_output.out").read_bytes() == text_grid.encode(g)

    def test_host_flag_matches_device(self, capsys, random16, tmp_path):
        path, g = random16
        dev_out = tmp_path / "dev.out"
        host_out = tmp_path / "host.out"
        run_cli(["16", "16", path, "--variant", "game", "--gen-limit", "10",
                 "--output", str(dev_out)])
        run_cli(["16", "16", path, "--variant", "game", "--gen-limit", "10",
                 "--host", "--output", str(host_out)])
        assert dev_out.read_bytes() == host_out.read_bytes()

    @pytest.mark.parametrize("variant", ["game", "collective", "openmp", "cuda"])
    def test_host_prints_same_line_set_as_device(
        self, capsys, variant, random16, tmp_path
    ):
        """--host emits exactly the lines the device lane prints — including
        Reading/Writing for io_timings variants
        (src/game_mpi_collective.c:200-203,447-450)."""
        path, g = random16

        def lines(extra):
            run_cli(
                ["16", "16", path, "--variant", variant, "--gen-limit", "5",
                 "--output", str(tmp_path / "o.out")] + extra
            )
            # Timing values differ run to run; compare the line *labels*.
            return [
                line.split("\t")[0]
                for line in capsys.readouterr().out.splitlines()
            ]

        assert lines([]) == lines(["--host"])


class TestDistributedVariants:
    @pytest.mark.parametrize("variant", ["mpi", "collective", "async", "openmp"])
    def test_output_matches_oracle(self, capsys, variant, random16, tmp_path):
        path, g = random16
        out_file = tmp_path / f"{variant}.out"
        assert run_cli(["16", "16", path, "--variant", variant, "--mesh", "2x4",
                        "--gen-limit", "15", "--output", str(out_file)]) == 0
        stdout = capsys.readouterr().out
        want = oracle.run(g, GameConfig(gen_limit=15))
        assert out_file.read_bytes() == text_grid.encode(want.grid)
        assert f"Generations:\t{want.generations}" in stdout
        assert "Reading file:\t" in stdout
        assert "Writing file:\t" in stdout
        if variant == "openmp":
            assert not stdout.rstrip().endswith("Finished")
        else:
            assert stdout.rstrip().endswith("Finished")

    def test_force_square_uses_width(self, capsys, random16, tmp_path):
        # `height = width` before defaulting (src/game_mpi.c:504): passing a
        # wrong height must still read a 16x16 grid.
        path, g = random16
        out_file = tmp_path / "sq.out"
        assert run_cli(["16", "999", path, "--variant", "collective",
                        "--mesh", "2x2", "--gen-limit", "5",
                        "--output", str(out_file)]) == 0
        want = oracle.run(g, GameConfig(gen_limit=5))
        assert out_file.read_bytes() == text_grid.encode(want.grid)

    def test_indivisible_mesh_errors_cleanly(self, capsys, random16):
        path, _ = random16
        assert run_cli(["16", "16", path, "--variant", "collective",
                        "--mesh", "3x1"]) == 1
        assert "does not divide" in capsys.readouterr().err

    def test_default_mesh_divides_odd_height(self, capsys, tmp_path):
        # 24 rows on 8 devices: the row-only (8, 1) default divides this
        # one, but 20 rows would not — choose_mesh_shape must fall back to
        # a dividing factorization instead of erroring (advisor r3). Forced
        # square variants can't express it, so use the tpu variant with
        # explicit height.
        g = text_grid.generate(32, 20, seed=21)  # width 32, height 20
        p = tmp_path / "odd.txt"
        text_grid.write_grid(str(p), g)
        out_file = tmp_path / "odd.out"
        assert run_cli(["32", "20", str(p), "--variant", "tpu",
                        "--gen-limit", "7", "--output", str(out_file)]) == 0
        want = oracle.run(g, GameConfig(gen_limit=7))
        assert out_file.read_bytes() == text_grid.encode(want.grid)

    def test_width_cap_seam_default_mesh_and_routing(self, capsys, random16,
                                                     tmp_path, monkeypatch):
        # Pin the fast/slow-lane seam (VERDICT r3 item 8): with the temporal
        # width cap shrunk to CPU scale, the default mesh adds just enough
        # columns past the cap, supports_multi flips the kernel routing at
        # the boundary, and both sides stay byte-identical to the oracle.
        from gol_tpu.ops import stencil_packed as sp
        from gol_tpu.parallel.mesh import choose_mesh_shape

        monkeypatch.setattr(sp, "_MAX_WORDS_T", 2)
        # Mesh seam: just under the (patched) cap keeps row-only; just over
        # adds exactly enough columns.
        assert choose_mesh_shape(8, width=64, height=64) == (8, 1)    # 2 words
        assert choose_mesh_shape(8, width=128, height=64) == (4, 2)   # 4 words
        assert choose_mesh_shape(8, width=512, height=512) == (1, 8)  # 16 words
        # Routing seam end-to-end: a (64, 128) grid on the default mesh —
        # full-width 4-word shards exceed the patched cap, so the default
        # becomes (4, 2) with 2-word shards right AT the cap (temporal lane
        # kept); the run must stay byte-identical to the oracle.
        g = text_grid.generate(128, 64, seed=23)
        p = tmp_path / "seam.txt"
        text_grid.write_grid(str(p), g)
        out_file = tmp_path / "seam.out"
        assert run_cli(["128", "64", str(p), "--variant", "tpu",
                        "--gen-limit", "12", "--output", str(out_file)]) == 0
        want = oracle.run(g, GameConfig(gen_limit=12))
        assert out_file.read_bytes() == text_grid.encode(want.grid)
        from gol_tpu import engine as engine_mod

        # Drop runners compiled under the patched cap: the cache key can't
        # see the cap, so entries would leak stale routing into later tests.
        engine_mod.make_runner.cache_clear()


class TestCudaVariant:
    def test_cuda_accounting_and_output(self, capsys, tmp_path, monkeypatch):
        lone = np.zeros((8, 8), np.uint8)
        lone[4, 4] = 1
        p = tmp_path / "lone.txt"
        text_grid.write_grid(str(p), lone)
        monkeypatch.chdir(tmp_path)
        assert run_cli(["8", "8", str(p), "--variant", "cuda"]) == 0
        out = capsys.readouterr().out
        # CUDA convention: empty-exit keeps the pre-evolve grid, reports 0
        # (src/game_cuda.cu:259-268,294), and prints no I/O timing lines.
        assert "Generations:\t0" in out
        assert "Reading file" not in out
        assert (tmp_path / "cuda_output.out").read_bytes() == text_grid.encode(lone)


class TestProfileGuard:
    """--profile DIR is start/stop-guarded (gol_tpu/obs/profiler.py): a run
    with nothing to capture must not die, and a crashed run must not leave a
    torn trace directory behind."""

    def test_profile_with_gen0_empty_input_succeeds(self, tmp_path, capsys,
                                                    monkeypatch):
        # An all-dead grid exits on generation 0 — the case that used to
        # start the profiler for a run with no device loop and leave a torn
        # capture when start/stop misbehaved. With the profiler backend
        # refusing to start (the observed failure shape), the run must
        # complete unprofiled, rc 0.
        import jax

        def refuse(*a, **k):
            raise RuntimeError("profiler had nothing to capture")

        monkeypatch.setattr(jax.profiler, "start_trace", refuse)
        empty = np.zeros((8, 8), np.uint8)
        p = tmp_path / "empty.txt"
        text_grid.write_grid(str(p), empty)
        prof = tmp_path / "prof"
        assert run_cli(["8", "8", str(p), "--variant", "cuda",
                        "--profile", str(prof),
                        "--output", str(tmp_path / "o.out")]) == 0
        out = capsys.readouterr().out
        assert "Generations:\t0" in out
        # No torn capture: the guard never created partial profiler output.
        assert not prof.exists() or list(prof.iterdir()) == []

    def test_profile_crashed_run_leaves_no_torn_capture(self, tmp_path,
                                                        monkeypatch):
        import jax

        from gol_tpu.resilience import faults
        from gol_tpu.resilience.faults import InjectedCrash

        prof = tmp_path / "prof"

        def fake_start(d, *a, **k):
            os.makedirs(os.path.join(d, "plugins", "profile"), exist_ok=True)

        monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
        g = text_grid.generate(16, 16, seed=9)
        p = tmp_path / "g.txt"
        text_grid.write_grid(str(p), g)
        try:
            with pytest.raises(InjectedCrash):
                run_cli(["16", "16", str(p), "--variant", "tpu",
                         "--gen-limit", "10",
                         "--checkpoint-every", "1",
                         "--checkpoint-dir", str(tmp_path / "ckpt"),
                         "--fault-plan", "kill_at_gen=2",
                         "--profile", str(prof),
                         "--output", str(tmp_path / "o.out")])
        finally:
            faults.clear()
        # The capture the crash interrupted was swept, not left torn.
        assert not prof.exists() or list(prof.iterdir()) == []


class TestTraceFlag:
    def test_bad_trace_path_gets_cli_error_contract(self, tmp_path, capsys):
        """--trace pointing at a FILE must produce the `gol: <error>` line
        and rc 1 (review regression: arming ran outside the error handler
        and leaked a raw traceback)."""
        not_a_dir = tmp_path / "occupied"
        not_a_dir.write_text("file, not a directory")
        g = text_grid.generate(8, 8, seed=1)
        p = tmp_path / "g.txt"
        text_grid.write_grid(str(p), g)
        assert run_cli(["8", "8", str(p), "--variant", "game",
                        "--gen-limit", "2", "--trace", str(not_a_dir),
                        "--output", str(tmp_path / "o.out")]) == 1
        assert capsys.readouterr().err.startswith("gol: ")

    def test_export_failure_does_not_mask_success(self, tmp_path, capsys,
                                                  monkeypatch):
        """A trace export that fails at the end (dir deleted mid-run, disk
        full) warns on stderr but keeps the lane's rc 0."""
        import shutil

        from gol_tpu.obs import trace as obs_trace

        real_export = obs_trace.export_chrome

        def deleted_then_export(path):
            shutil.rmtree(os.path.dirname(path))
            return real_export(path)

        monkeypatch.setattr(obs_trace, "export_chrome", deleted_then_export)
        g = text_grid.generate(8, 8, seed=2)
        p = tmp_path / "g.txt"
        text_grid.write_grid(str(p), g)
        assert run_cli(["8", "8", str(p), "--variant", "game",
                        "--gen-limit", "2", "--trace", str(tmp_path / "tr"),
                        "--output", str(tmp_path / "o.out")]) == 0
        err = capsys.readouterr().err
        assert "trace export failed" in err


class TestGenerate:
    def test_generate_to_file(self, tmp_path):
        out = tmp_path / "gen.txt"
        assert run_cli(["generate", "12", "7", "-o", str(out), "--seed", "3"]) == 0
        g = text_grid.read_grid(str(out), 12, 7)
        assert g.shape == (7, 12)

    def test_generate_stdout(self, capsys):
        assert run_cli(["generate", "4", "2", "--seed", "0"]) == 0
        out = capsys.readouterr().out
        lines = out.splitlines()
        assert len(lines) == 2 and all(len(l) == 4 for l in lines)

    def test_generate_then_run_roundtrip(self, tmp_path, capsys):
        src = tmp_path / "in.txt"
        dst = tmp_path / "out.txt"
        run_cli(["generate", "16", "16", "-o", str(src), "--seed", "5"])
        assert run_cli(["16", "16", str(src), "--variant", "tpu", "--mesh", "2x2",
                        "--gen-limit", "10", "--output", str(dst)]) == 0
        g = text_grid.read_grid(str(src), 16, 16)
        want = oracle.run(g, GameConfig(gen_limit=10))
        assert dst.read_bytes() == text_grid.encode(want.grid)


def test_huge_byte_lane_warning(capsys):
    from gol_tpu.cli import _warn_if_huge_byte_lane
    from gol_tpu.parallel.mesh import make_mesh

    assert _warn_if_huge_byte_lane(65536, 65536)
    err = capsys.readouterr().err
    assert "--packed-io" in err and "4.0 GB" in err
    # Below the per-device threshold, or widths --packed-io would reject
    # (no packed lane to offer): silent.
    assert not _warn_if_huge_byte_lane(16384, 16384)
    assert not _warn_if_huge_byte_lane(65537, 65536)
    # Sharded over 8 devices the same grid is 512MB/buffer/device — no
    # warning; and the width gate scales to 32 x mesh cols.
    mesh = make_mesh(2, 4)
    assert not _warn_if_huge_byte_lane(65536, 65536, mesh)
    assert not _warn_if_huge_byte_lane(65536, 262144, make_mesh(1, 3))
    assert capsys.readouterr().err == ""
    assert _warn_if_huge_byte_lane(65536, 262144, mesh)
    assert "2.0 GB" in capsys.readouterr().err


def test_dynamic_stderr_handler_honors_stream_contract():
    # Review regression: the getter-only ``stream`` property broke the
    # StreamHandler contract — ``setStream()`` (and direct assignment, which
    # some test harnesses and logging utilities do) raised AttributeError.
    # Assignment must be accepted; the handler stays dynamic regardless,
    # always emitting to the CURRENT sys.stderr.
    import io
    import sys

    from gol_tpu.platform_env import _DynamicStderrHandler

    h = _DynamicStderrHandler()
    assert h.setStream(io.StringIO()) is sys.stderr
    h.stream = io.StringIO()
    assert h.stream is sys.stderr  # still dynamic
