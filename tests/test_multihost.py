"""Two real OS processes form a cluster and run the collective program.

The reference's multi-node operation is only exercised by actually running
``mpiexec -n <x>`` (README.md:50-57); this is that, for the TPU build: two
processes join via jax.distributed (bootstrap.initialize = MPI_Init), each
contributes its CPU device to the mesh, the halo ppermute and psum votes ride
the gloo cross-process collectives, and each process reads/writes ONLY its
addressable windows of the shared files (the MPI-IO file-view property,
src/game_mpi_collective.c:186-196).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from gol_tpu import oracle
from gol_tpu.config import GameConfig
from gol_tpu.io import text_grid

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module", params=[2, 4])
def cluster_run(request, tmp_path_factory):
    """One n-process cluster run shared by the lane assertions below.

    2 processes = 1x2 mesh (E/W halo crosses processes); 4 = 2x2 mesh
    (both halo axes cross processes — the full Cartesian topology)."""
    nprocs = request.param
    tmp_path = tmp_path_factory.mktemp(f"cluster{nprocs}")
    g = text_grid.generate(64, 64, seed=3)
    text_grid.write_grid(str(tmp_path / "input.txt"), g)
    port = _free_port()

    env = dict(os.environ)
    # The workers form their own n-device world; the parent's 8-virtual-CPU
    # flag must not multiply each worker's device count.
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), str(nprocs), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=360)
            outs.append(out)
    finally:
        # Never leak workers: a hung/died peer leaves the other blocked in a
        # gloo collective forever.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker rc={p.returncode}:\n{out[-3000:]}"
    return tmp_path, g


def test_process_cluster_matches_oracle(cluster_run):
    tmp_path, g = cluster_run
    expect = oracle.run(g, GameConfig(gen_limit=40))
    for lane in ("lax", "packed", "mpi", "packedio"):
        got = text_grid.read_grid(str(tmp_path / f"out_{lane}.txt"), 64, 64)
        gens = int((tmp_path / f"gens_{lane}.txt").read_text())
        np.testing.assert_array_equal(np.asarray(got), expect.grid)
        assert gens == expect.generations


def test_tensorstore_lane_across_processes(cluster_run):
    """TensorStore round trip across the process cluster: every process
    wrote only its shard-aligned chunks, none clobbered a peer's. A
    separate test so lost tensorstore coverage shows as a SKIP in the
    report, never as silent green."""
    import importlib.util

    if importlib.util.find_spec("tensorstore") is None:
        pytest.skip("tensorstore not installed — TS multi-writer lane not run")
    tmp_path, g = cluster_run
    expect = oracle.run(g, GameConfig(gen_limit=40))
    got = text_grid.read_grid(str(tmp_path / "out_tsstore.txt"), 64, 64)
    np.testing.assert_array_equal(np.asarray(got), expect.grid)
