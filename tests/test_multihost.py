"""Two real OS processes form a cluster and run the collective program.

The reference's multi-node operation is only exercised by actually running
``mpiexec -n <x>`` (README.md:50-57); this is that, for the TPU build: two
processes join via jax.distributed (bootstrap.initialize = MPI_Init), each
contributes its CPU device to the mesh, the halo ppermute and psum votes ride
the gloo cross-process collectives, and each process reads/writes ONLY its
addressable windows of the shared files (the MPI-IO file-view property,
src/game_mpi_collective.c:186-196).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from gol_tpu import oracle
from gol_tpu.config import GameConfig
from gol_tpu.io import text_grid

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# Probe result cache: None = not probed yet, "" = available, else the skip
# reason. Computed once per session — the probe spawns real processes.
_CLUSTER_UNAVAILABLE: str | None = None

_PROBE = r"""
import sys
import jax
jax.distributed.initialize(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from gol_tpu.parallel.mesh import shard_map
devs = jax.devices()
mesh = jax.make_mesh((len(devs),), ("i",), devices=devs)
f = jax.jit(shard_map(lambda x: jax.lax.psum(x, "i"), mesh=mesh,
                      in_specs=P("i"), out_specs=P()))
assert int(f(jnp.ones((len(devs),), jnp.int32))) == len(devs)
"""


def _cluster_unavailable() -> str:
    """Empty string when 2-process gloo collectives work here, else why not.

    Some environments carry a jax whose CPU backend cannot run cross-process
    collectives at all ("Multiprocess computations aren't implemented on the
    CPU backend"), lack the gloo transport, or cannot bind/connect local
    sockets. Those are facts about the environment, not regressions; the
    suite must SKIP with the real reason instead of erroring. The probe runs
    the exact machinery the tests need — jax.distributed + a cross-process
    psum through shard_map — in two tiny subprocesses.
    """
    global _CLUSTER_UNAVAILABLE
    if _CLUSTER_UNAVAILABLE is not None:
        return _CLUSTER_UNAVAILABLE
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    try:
        port = _free_port()
    except OSError as e:
        _CLUSTER_UNAVAILABLE = f"cannot bind a local socket: {e}"
        return _CLUSTER_UNAVAILABLE
    addr = f"127.0.0.1:{port}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE, addr, "2", str(pid)],
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
        _CLUSTER_UNAVAILABLE = "distributed-backend probe timed out"
        return _CLUSTER_UNAVAILABLE
    if all(p.returncode == 0 for p in procs):
        _CLUSTER_UNAVAILABLE = ""
    else:
        # Surface the probe's last error line as the skip reason.
        lines = [ln for out in outs for ln in out.strip().splitlines()]
        reason = lines[-1] if lines else "unknown probe failure"
        _CLUSTER_UNAVAILABLE = f"distributed backend unavailable: {reason}"
    return _CLUSTER_UNAVAILABLE


@pytest.fixture(scope="module", params=[2, 4])
def cluster_run(request, tmp_path_factory):
    """One n-process cluster run shared by the lane assertions below.

    2 processes = 1x2 mesh (E/W halo crosses processes); 4 = 2x2 mesh
    (both halo axes cross processes — the full Cartesian topology)."""
    unavailable = _cluster_unavailable()
    if unavailable:
        pytest.skip(unavailable)
    nprocs = request.param
    tmp_path = tmp_path_factory.mktemp(f"cluster{nprocs}")
    g = text_grid.generate(64, 64, seed=3)
    text_grid.write_grid(str(tmp_path / "input.txt"), g)
    port = _free_port()

    env = dict(os.environ)
    # The workers form their own n-device world; the parent's 8-virtual-CPU
    # flag must not multiply each worker's device count.
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), str(nprocs), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=360)
            outs.append(out)
    finally:
        # Never leak workers: a hung/died peer leaves the other blocked in a
        # gloo collective forever.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker rc={p.returncode}:\n{out[-3000:]}"
    return tmp_path, g


def test_process_cluster_matches_oracle(cluster_run):
    tmp_path, g = cluster_run
    expect = oracle.run(g, GameConfig(gen_limit=40))
    for lane in ("lax", "packed", "mpi", "packedio"):
        got = text_grid.read_grid(str(tmp_path / f"out_{lane}.txt"), 64, 64)
        gens = int((tmp_path / f"gens_{lane}.txt").read_text())
        np.testing.assert_array_equal(np.asarray(got), expect.grid)
        assert gens == expect.generations


def test_tensorstore_lane_across_processes(cluster_run):
    """TensorStore round trip across the process cluster: every process
    wrote only its shard-aligned chunks, none clobbered a peer's. A
    separate test so lost tensorstore coverage shows as a SKIP in the
    report, never as silent green."""
    import importlib.util

    if importlib.util.find_spec("tensorstore") is None:
        pytest.skip("tensorstore not installed — TS multi-writer lane not run")
    tmp_path, g = cluster_run
    expect = oracle.run(g, GameConfig(gen_limit=40))
    got = text_grid.read_grid(str(tmp_path / "out_tsstore.txt"), 64, 64)
    np.testing.assert_array_equal(np.asarray(got), expect.grid)
