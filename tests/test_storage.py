"""Storage lifecycle (ISSUE 15): journal compaction, CAS garbage
collection, disk-pressure survival, and the filesystem fault plane.

The load-bearing assertions: (1) replay after any compaction — including
one SIGKILLed at either durability boundary — is state-identical to
full-log replay; (2) CAS eviction never corrupts (survivors read back
CRC-clean, evicted fingerprints are clean misses); (3) a full disk
degrades the service in tiers (shed CAS writes -> shed checkpoints ->
refuse admission 507) and recovers unattended, with zero torn records at
any stage; (4) an ENOSPC on the SUBMIT append refuses the accept (503) —
an acknowledged job absent from the journal would vanish on replay.
"""

import errno
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from gol_tpu import oracle
from gol_tpu.cache import gc as cas_gc
from gol_tpu.cache.store import CacheEntry, DiskCAS
from gol_tpu.config import GameConfig
from gol_tpu.io import text_grid
from gol_tpu.obs import history as obs_history
from gol_tpu.resilience import diskguard, faults, fsio
from gol_tpu.resilience.faults import FaultPlan, InjectedCrash
from gol_tpu.serve import compaction
from gol_tpu.serve.jobs import DONE, JobJournal, JobResult, new_job
from gol_tpu.serve.scheduler import JournalUnavailable, Scheduler
from gol_tpu.serve.server import GolServer


@pytest.fixture(autouse=True)
def _disarmed():
    faults.clear()
    yield
    faults.clear()


def _wait(predicate, timeout=30.0, interval=0.01):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _http(method, url, body=None, timeout=10):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


# ---------------------------------------------------------------------------
# The filesystem fault plane


class TestFaultPlanGrammar:
    def test_parse_exhaustion_knobs(self):
        plan = FaultPlan.parse(
            "enospc_after_bytes=100,eio_every=3,full_disk=1,"
            "disk_free_bytes=42,kill_during_compaction=retire,"
            "kill_during_cas_gc=2,kill_during_prune=1"
        )
        assert plan.enospc_after_bytes == 100
        assert plan.eio_every == 3
        assert plan.full_disk == 1
        assert plan.disk_free_bytes == 42
        assert plan.kill_during_compaction == "retire"
        assert plan.kill_during_cas_gc == 2
        assert plan.kill_during_prune == 1

    def test_bad_compaction_stage_is_loud(self):
        with pytest.raises(ValueError, match="kill_during_compaction"):
            FaultPlan.parse("kill_during_compaction=sideways")

    def test_enospc_after_bytes_budget(self, tmp_path):
        faults.install(FaultPlan(enospc_after_bytes=100))
        path = tmp_path / "f"
        fd = os.open(str(path), os.O_WRONLY | os.O_CREAT)
        try:
            fsio.write_all(fd, b"x" * 60, "test")
            fsio.write_all(fd, b"x" * 40, "test")  # exactly at budget: ok
            with pytest.raises(OSError) as exc:
                fsio.write_all(fd, b"x", "test")
            assert exc.value.errno == errno.ENOSPC
            # And it stays failed — the disk does not un-fill itself.
            with pytest.raises(OSError):
                fsio.write_all(fd, b"x", "test")
        finally:
            os.close(fd)
        assert path.stat().st_size == 100

    def test_eio_every_nth_write(self, tmp_path):
        faults.install(FaultPlan(eio_every=3))
        fd = os.open(str(tmp_path / "f"), os.O_WRONLY | os.O_CREAT)
        try:
            fsio.write_all(fd, b"a", "test")
            fsio.write_all(fd, b"b", "test")
            with pytest.raises(OSError) as exc:
                fsio.write_all(fd, b"c", "test")
            assert exc.value.errno == errno.EIO
            fsio.write_all(fd, b"d", "test")  # the next two pass again
        finally:
            os.close(fd)

    def test_full_disk_fails_everything_and_reports_zero_free(self, tmp_path):
        faults.install(FaultPlan(full_disk=1))
        fd = os.open(str(tmp_path / "f"), os.O_WRONLY | os.O_CREAT)
        try:
            with pytest.raises(OSError) as exc:
                fsio.write_all(fd, b"x", "test")
            assert exc.value.errno == errno.ENOSPC
        finally:
            os.close(fd)
        assert fsio.free_bytes(str(tmp_path)) == 0

    def test_pinned_free_bytes_and_real_statvfs(self, tmp_path):
        faults.install(FaultPlan(disk_free_bytes=4096))
        assert fsio.free_bytes(str(tmp_path)) == 4096
        faults.clear()
        assert fsio.free_bytes(str(tmp_path)) > 0  # the real filesystem


# ---------------------------------------------------------------------------
# Journal segmentation


def _submit_n(journal, n, done_every=2, seed0=0):
    """n tiny jobs journaled; every ``done_every``-th also gets a done
    record. Returns (all ids, done ids)."""
    ids, done = [], []
    for i in range(n):
        job = new_job(8, 8, text_grid.generate(8, 8, seed=seed0 + i))
        journal.record_submit(job)
        ids.append(job.id)
        if i % done_every == 0:
            job.result = JobResult(
                grid=text_grid.generate(8, 8, seed=1000 + i),
                generations=i, exit_reason="gen_limit",
            )
            journal.record_done(job)
            done.append(job.id)
    return ids, done


def _replay_state(directory):
    j = JobJournal(directory, segment_bytes=0)
    try:
        return j.replay()
    finally:
        j.close()


def _assert_state_equal(a, b):
    assert sorted(x.id for x in a.pending) == sorted(x.id for x in b.pending)
    assert a.results.keys() == b.results.keys()
    for k in a.results:
        np.testing.assert_array_equal(a.results[k].grid, b.results[k].grid)
        assert a.results[k].generations == b.results[k].generations
        assert a.results[k].exit_reason == b.results[k].exit_reason
    assert a.failed == b.failed
    assert a.cancelled == b.cancelled


class TestJournalSegments:
    def test_rotation_seals_segments_and_replay_is_complete(self, tmp_path):
        j = JobJournal(str(tmp_path), segment_bytes=500)
        ids, done = _submit_n(j, 16)
        j.close()
        assert compaction.sealed_segments(str(tmp_path))
        state = _replay_state(str(tmp_path))
        assert sorted(x.id for x in state.pending) == sorted(
            set(ids) - set(done))
        assert state.results.keys() == set(done)
        assert state.torn_lines == 0

    def test_unsegmented_layout_still_replays(self, tmp_path):
        j = JobJournal(str(tmp_path), segment_bytes=0)
        ids, done = _submit_n(j, 8)
        j.close()
        assert not compaction.sealed_segments(str(tmp_path))
        state = _replay_state(str(tmp_path))
        assert state.results.keys() == set(done)

    def test_torn_tail_in_active_only_loses_the_tail(self, tmp_path):
        j = JobJournal(str(tmp_path), segment_bytes=400)
        ids, done = _submit_n(j, 10)
        j.close()
        with open(os.path.join(str(tmp_path), compaction.ACTIVE_FILENAME),
                  "ab") as f:
            f.write(b'{"event": "done", "id": "xyz", "gen')
        state = _replay_state(str(tmp_path))
        assert state.torn_lines == 1
        assert state.results.keys() == set(done)

    def test_next_index_never_reuses_a_folded_seq(self, tmp_path):
        j = JobJournal(str(tmp_path), segment_bytes=400)
        _submit_n(j, 10)
        report = j.compact()
        assert report.compacted
        # Every sealed segment is gone; a fresh rotation must mint a seq
        # PAST the snapshot's covers, or replay would skip it as folded.
        assert compaction.next_index(str(tmp_path)) == report.covers + 1
        _submit_n(j, 10, seed0=50)
        j.close()
        segs = compaction.sealed_segments(str(tmp_path))
        assert segs and all(seq > report.covers for seq, _p in segs)
        state = _replay_state(str(tmp_path))
        assert state.torn_lines == 0
        assert len(state.results) == 10  # 5 + 5 across the compaction

    def test_enospc_on_append_raises(self, tmp_path):
        j = JobJournal(str(tmp_path))
        job = new_job(8, 8, np.zeros((8, 8), np.uint8))
        faults.install(FaultPlan(full_disk=1))
        with pytest.raises(OSError):
            j.record_submit(job)
        faults.clear()
        j.record_submit(job)  # space returned: the journal still works
        j.close()


# ---------------------------------------------------------------------------
# Compaction


class TestCompaction:
    def _churn(self, tmp_path, n=20):
        j = JobJournal(str(tmp_path), segment_bytes=500)
        _submit_n(j, n)
        return j

    def test_replay_identical_to_full_log(self, tmp_path):
        j = self._churn(tmp_path)
        before = _replay_state(str(tmp_path))
        report = j.compact()
        assert report.compacted and report.segments_retired > 0
        assert report.bytes_after < report.bytes_before
        after = _replay_state(str(tmp_path))
        _assert_state_equal(before, after)
        j.close()

    def test_compact_covers_failed_and_cancelled(self, tmp_path):
        j = JobJournal(str(tmp_path), segment_bytes=300)
        jobs = [new_job(8, 8, text_grid.generate(8, 8, seed=i))
                for i in range(6)]
        for job in jobs:
            j.record_submit(job)
        jobs[0].error = "boom"
        j.record_failed(jobs[0])
        j.record_cancelled(jobs[1])
        jobs[2].result = JobResult(grid=np.zeros((8, 8), np.uint8),
                                   generations=1, exit_reason="empty")
        j.record_done(jobs[2])
        before = _replay_state(str(tmp_path))
        j.compact()
        after = _replay_state(str(tmp_path))
        _assert_state_equal(before, after)
        assert after.failed == {jobs[0].id: "boom"}
        assert after.cancelled == {jobs[1].id}
        j.close()

    def test_repeated_compaction_is_idempotent(self, tmp_path):
        j = self._churn(tmp_path)
        j.compact()
        state1 = _replay_state(str(tmp_path))
        report = j.compact()
        assert not report.compacted and report.segments_retired == 0
        _assert_state_equal(state1, _replay_state(str(tmp_path)))
        j.close()

    def test_bounded_footprint_under_churn(self, tmp_path):
        """The acceptance shape, scaled down: continuous submit+done churn
        with per-round compaction keeps the file COUNT bounded (snapshot +
        live file, at most one uncompacted segment) while replay keeps
        every result."""
        j = JobJournal(str(tmp_path), segment_bytes=600)
        done_total = []
        for r in range(6):
            _, done = _submit_n(j, 10, done_every=1, seed0=100 * r)
            done_total.extend(done)
            j.compact()
        assert j.sealed_count() <= 1
        files = [n for n in os.listdir(str(tmp_path))
                 if n != compaction.LOCK_FILENAME]
        assert len(files) <= 3  # snapshot + active + (maybe) one sealed
        state = _replay_state(str(tmp_path))
        assert state.results.keys() == set(done_total)
        assert not state.pending
        j.close()

    def test_retention_window_drops_oldest_terminals(self, tmp_path):
        j = JobJournal(str(tmp_path), segment_bytes=300)
        _ids, done = _submit_n(j, 12, done_every=1)
        report = j.compact(retain_results=4)
        assert report.compacted and report.terminal_dropped == len(done) - 4
        state = _replay_state(str(tmp_path))
        assert state.results.keys() == set(done[-4:])
        assert not state.pending  # dropped terminals do NOT resurrect
        j.close()

    def test_torn_snapshot_is_ignored_and_rewritten(self, tmp_path):
        j = self._churn(tmp_path)
        before = _replay_state(str(tmp_path))
        # A snapshot whose commit never landed (simulated external tear):
        # stage a garbage snapshot in place, with the segments still there.
        snap = compaction.snapshot_path(str(tmp_path))
        with open(snap, "wb") as f:
            f.write(b'{"event":"snapshot_header","version":1,"covers":99}\n'
                    b"garbage\n")
        assert compaction.read_snapshot(str(tmp_path)) is None
        _assert_state_equal(before, _replay_state(str(tmp_path)))
        report = j.compact()  # retried: rewrites a valid snapshot
        assert report.compacted
        _assert_state_equal(before, _replay_state(str(tmp_path)))
        j.close()

    def test_crc_catches_corrupted_snapshot_body(self, tmp_path):
        j = self._churn(tmp_path)
        j.compact()
        snap = compaction.snapshot_path(str(tmp_path))
        raw = bytearray(open(snap, "rb").read())
        # Flip a digit inside a record line (still valid JSON overall).
        idx = raw.index(b'"width":8')
        raw[idx + 8:idx + 9] = b"9"
        with open(snap, "wb") as f:
            f.write(bytes(raw))
        assert compaction.read_snapshot(str(tmp_path)) is None
        j.close()

    @pytest.mark.parametrize("stage", ["snapshot", "retire"])
    def test_kill_at_either_boundary_replays_identically(self, tmp_path,
                                                         stage):
        """The SIGKILL matrix, in-process (kill_mode=exception is the same
        crash semantics — InjectedCrash unwinds through everything): a
        compaction killed at the staged-but-uncommitted boundary loses
        nothing; killed after the commit, the folded segments coexist with
        the snapshot and replay must NOT double-apply them."""
        j = self._churn(tmp_path)
        before = _replay_state(str(tmp_path))
        faults.install(FaultPlan(kill_during_compaction=stage))
        with pytest.raises(InjectedCrash):
            j.compact()
        faults.clear()
        if stage == "snapshot":
            assert compaction.read_snapshot(str(tmp_path)) is None
            assert compaction.sealed_segments(str(tmp_path))
        else:
            assert compaction.read_snapshot(str(tmp_path)) is not None
            assert compaction.sealed_segments(str(tmp_path))  # not retired
        _assert_state_equal(before, _replay_state(str(tmp_path)))
        # The restart's compaction finishes the job either way.
        report = j.compact()
        assert (report.compacted if stage == "snapshot"
                else report.segments_retired > 0)
        assert not compaction.sealed_segments(str(tmp_path))
        _assert_state_equal(before, _replay_state(str(tmp_path)))
        j.close()

    def test_concurrent_compaction_excluded_by_lock(self, tmp_path):
        """Two interleaved compactions could commit a stale snapshot over
        a newer one whose segments are already deleted — the advisory
        flock makes the loser skip (and a SIGKILLed holder releases it
        with its process, so the lock can never go stale)."""
        import fcntl

        j = self._churn(tmp_path)
        before = _replay_state(str(tmp_path))
        lock_fd = os.open(
            os.path.join(str(tmp_path), compaction.LOCK_FILENAME),
            os.O_WRONLY | os.O_CREAT)
        try:
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
            report = j.compact()  # the loser: skips, touches nothing
            assert not report.compacted and report.segments_retired == 0
            assert compaction.sealed_segments(str(tmp_path))
        finally:
            os.close(lock_fd)
        assert j.compact().compacted  # released: the next pass proceeds
        _assert_state_equal(before, _replay_state(str(tmp_path)))
        j.close()

    def test_snapshot_covers_header_only_read(self, tmp_path):
        """Seq minting reads only the snapshot HEADER — and still reads a
        valid covers off a snapshot whose BODY was corrupted after commit
        (under-minting a seq replay would skip is the unsafe direction;
        over-minting is a skipped number)."""
        j = self._churn(tmp_path)
        report = j.compact()
        assert compaction.snapshot_covers(str(tmp_path)) == report.covers
        raw = bytearray(open(compaction.snapshot_path(str(tmp_path)),
                             "rb").read())
        raw[-10:-9] = b"Z"  # corrupt the trailer: full validation fails
        with open(compaction.snapshot_path(str(tmp_path)), "wb") as f:
            f.write(bytes(raw))
        assert compaction.read_snapshot(str(tmp_path)) is None
        assert compaction.snapshot_covers(str(tmp_path)) == report.covers
        assert compaction.next_index(str(tmp_path)) == report.covers + 1
        j.close()

    def test_half_failed_rotation_rolls_back(self, tmp_path, monkeypatch):
        """Rename-succeeded-reopen-failed must NOT leave the appender
        writing a sealed-named file (compaction would fold and delete it
        under the live stream): the rotation renames back and keeps
        appending to the live name."""
        from gol_tpu.serve import jobs as jobs_mod

        j = JobJournal(str(tmp_path), segment_bytes=300)
        real_open = os.open
        fail_next = {"armed": False}

        def flaky_open(path, *a, **k):
            if fail_next["armed"] and path == j.path:
                fail_next["armed"] = False
                raise OSError(errno.EMFILE, "injected open failure")
            return real_open(path, *a, **k)

        monkeypatch.setattr(jobs_mod.os, "open", flaky_open)
        fail_next["armed"] = True
        ids, done = _submit_n(j, 6)  # crosses the threshold mid-way
        monkeypatch.undo()
        # The live name exists and owns the stream; nothing is stranded
        # under a sealed name that compaction could retire.
        assert os.path.exists(j.path)
        j.compact()
        _ids2, done2 = _submit_n(j, 4, seed0=70)
        j.close()
        state = _replay_state(str(tmp_path))
        assert state.results.keys() == set(done) | set(done2)
        assert state.torn_lines == 0

    def test_new_appends_during_compaction_survive(self, tmp_path):
        """Records landing in the ACTIVE file while sealed segments
        compact are untouched: compaction never reads or moves the live
        file."""
        j = self._churn(tmp_path)
        live = new_job(8, 8, text_grid.generate(8, 8, seed=999))
        j.record_submit(live)
        j.compact()
        state = _replay_state(str(tmp_path))
        assert live.id in {x.id for x in state.pending}
        j.close()


# ---------------------------------------------------------------------------
# Satellite 1: the submit-record append must refuse the accept


class TestSubmitJournalFailure:
    def test_scheduler_refuses_and_admits_nothing(self, tmp_path):
        journal = JobJournal(str(tmp_path))
        sched = Scheduler(journal=journal)
        job = new_job(8, 8, np.zeros((8, 8), np.uint8))
        faults.install(FaultPlan(full_disk=1))
        with pytest.raises(JournalUnavailable):
            sched.submit(job)
        faults.clear()
        assert sched.job(job.id) is None  # nothing admitted
        assert sched.stats()["queued"] == 0
        snap = sched.metrics.snapshot()
        assert snap["counters"]["journal_errors_total"] == 1
        assert snap["counters"]["jobs_rejected_total"] == 1
        assert snap["counters"].get("jobs_accepted_total", 0) == 0
        # The journal heard of nothing: a replay is empty.
        journal.close()
        state = _replay_state(str(tmp_path))
        assert not state.pending and not state.results

    def test_http_503_then_accepts_after_recovery(self, tmp_path):
        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        sample_interval=0, flush_age=0.01)
        srv.start()
        try:
            board = text_grid.generate(16, 16, seed=3)
            body = {"width": 16, "height": 16,
                    "cells": text_grid.encode(board).decode("ascii"),
                    "gen_limit": 5}
            faults.install(FaultPlan(full_disk=1))
            code, payload = _http("POST", srv.url + "/jobs", body)
            assert code == 503 and "journal" in payload["error"]
            faults.clear()
            code, payload = _http("POST", srv.url + "/jobs", body)
            assert code == 202
            job_id = payload["id"]
            assert _wait(lambda: _http(
                "GET", f"{srv.url}/jobs/{job_id}")[1].get("state") == "done")
        finally:
            srv.shutdown()

    def test_terminal_append_failure_still_completes(self, tmp_path):
        """The OTHER ordering: a job accepted BEFORE the disk filled still
        terminates (in-memory DONE, result served); only its done record
        is lost — the idempotent-re-run contract, not a 5xx."""
        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        sample_interval=0, flush_age=0.01)
        srv.start()
        try:
            board = text_grid.generate(16, 16, seed=4)
            body = {"width": 16, "height": 16,
                    "cells": text_grid.encode(board).decode("ascii"),
                    "gen_limit": 5}
            code, payload = _http("POST", srv.url + "/jobs", body)
            assert code == 202
            job_id = payload["id"]
            faults.install(FaultPlan(full_disk=1))  # fills AFTER the accept
            assert _wait(lambda: _http(
                "GET", f"{srv.url}/jobs/{job_id}")[1].get("state") == "done")
            faults.clear()
            code, result = _http("GET", f"{srv.url}/result/{job_id}")
            assert code == 200
            want = oracle.run(board, GameConfig(gen_limit=5))
            got = text_grid.decode(result["grid"].encode("ascii"), 16, 16)
            np.testing.assert_array_equal(got, want.grid)
            snap = srv.metrics.snapshot()
            assert snap["counters"]["journal_errors_total"] >= 1
        finally:
            srv.shutdown()


# ---------------------------------------------------------------------------
# CAS garbage collection


def _fp(i):
    return f"{i:02x}" + "ab" * 31


def _entry(i, h=16, w=16):
    g = np.zeros((h, w), np.uint8)
    g[0, i % w] = 1
    return CacheEntry(grid=g, generations=i, exit_reason="gen_limit")


class TestCasGC:
    def test_scan_classifies_entries_and_garbage(self, tmp_path):
        cas = DiskCAS(str(tmp_path), payload="text")
        for i in range(3):
            cas.put(_fp(i), _entry(i))
        sub = tmp_path / _fp(0)[:2]
        (sub / (_fp(9) + ".golp")).write_bytes(b"orphan")  # meta-less
        (sub / ("x" + faults.__name__)).write_bytes(b"foreign")
        staging = sub / (_fp(0) + ".xyz.inprogress")
        staging.write_bytes(b"staging")
        entries, mtimes, orphans = cas_gc.scan(str(tmp_path))
        assert set(entries) == {_fp(0), _fp(1), _fp(2)}
        assert set(mtimes) == set(entries)
        assert len(orphans) == 3

    def test_eviction_order_cold_first_then_lru(self):
        entries = {"a": 1, "b": 1, "c": 1, "d": 1}
        mtimes = {"a": 5.0, "b": 2.0, "c": 9.0, "d": 1.0}
        access = {"a": 100.0, "c": 50.0}
        # b and d are cold (no stamp): oldest mtime first; then c (older
        # stamp), then a.
        assert cas_gc.eviction_order(entries, mtimes, access) == [
            "d", "b", "c", "a"]

    def test_dry_run_touches_nothing(self, tmp_path):
        cas = DiskCAS(str(tmp_path), payload="text")
        for i in range(4):
            cas.put(_fp(i), _entry(i))
        report = cas_gc.collect(str(tmp_path), budget=1, apply=False)
        assert report.dry_run and report.evicted
        for i in range(4):
            assert cas.get(_fp(i)) is not None  # all still there

    def test_budget_evicts_lru_and_survivors_verify(self, tmp_path):
        clock = iter(range(1, 1000))
        cas = DiskCAS(str(tmp_path), payload="text",
                      clock=lambda: float(next(clock)))
        for i in range(6):
            cas.put(_fp(i), _entry(i))
        cas.get(_fp(0))  # 0 becomes the most recently used
        per_entry = cas.usage_bytes() // 6
        report = cas.gc(budget=3 * per_entry + 10, apply=True)
        assert report.evicted
        assert _fp(0) not in report.evicted  # MRU survives
        assert cas.usage_bytes() <= 3 * per_entry + 10
        # Survivors decode + CRC-verify; evicted fingerprints are misses.
        for i in range(6):
            got = cas.get(_fp(i))
            if _fp(i) in report.evicted:
                assert got is None
            else:
                np.testing.assert_array_equal(got.grid, _entry(i).grid)

    def test_put_enforces_budget_inline(self, tmp_path):
        clock = iter(range(1, 10000))
        cas = DiskCAS(str(tmp_path), payload="text", max_bytes=2500,
                      clock=lambda: float(next(clock)))
        for i in range(20):
            cas.put(_fp(i), _entry(i))
            assert cas.usage_bytes() <= 2500
        # Zipf-ish reuse: the hot entry keeps surviving...
        for i in range(20, 30):
            assert cas.get(_fp(i - 1)) is not None  # most recent still hit
            cas.put(_fp(i), _entry(i))
        # ...and nothing ever corrupts: every present entry verifies.
        alive = sum(1 for i in range(30) if cas.get(_fp(i)) is not None)
        assert 0 < alive < 30  # degraded hit ratio, bounded bytes

    def test_kill_mid_evict_leaves_orphan_next_sweep_collects(self,
                                                              tmp_path):
        cas = DiskCAS(str(tmp_path), payload="packed")
        for i in range(3):
            cas.put(_fp(i), _entry(i))
        faults.install(FaultPlan(kill_during_cas_gc=1))
        with pytest.raises(InjectedCrash):
            cas.gc(budget=1, apply=True)
        faults.clear()
        entries, _mtimes, orphans = cas_gc.scan(str(tmp_path))
        assert orphans  # the victim's sidecar, meta already gone
        assert len(entries) == 2
        report = cas_gc.collect(str(tmp_path), None, apply=True)
        assert report.orphan_bytes > 0
        _entries2, _m2, orphans2 = cas_gc.scan(str(tmp_path))
        assert not orphans2
        # The two untouched entries still serve.
        alive = sum(1 for i in range(3) if cas.get(_fp(i)) is not None)
        assert alive == 2


# ---------------------------------------------------------------------------
# The disk-pressure watchdog


class TestDiskGuard:
    def _guard(self, tmp_path, free, **kwargs):
        state = {"free": free}
        g = diskguard.DiskGuard(
            str(tmp_path), admission_bytes=1000,
            free_fn=lambda: state["free"], **kwargs,
        )
        return g, state

    def test_watermark_ordering_validated(self, tmp_path):
        with pytest.raises(ValueError, match="order"):
            diskguard.DiskGuard(str(tmp_path), admission_bytes=1000,
                                checkpoint_bytes=500)
        with pytest.raises(ValueError, match=">= 1"):
            diskguard.DiskGuard(str(tmp_path), admission_bytes=0)

    def test_degrades_in_order_and_recovers_with_hysteresis(self, tmp_path):
        g, state = self._guard(tmp_path, 10_000)
        assert g.tick() == diskguard.OK
        assert g.allow_cas_writes() and g.allow_checkpoints()
        state["free"] = 3500  # < cas (4000)
        assert g.tick() == diskguard.SHED_CAS
        assert not g.allow_cas_writes() and g.allow_checkpoints()
        state["free"] = 1500  # < checkpoint (2000)
        assert g.tick() == diskguard.SHED_CHECKPOINTS
        assert not g.allow_checkpoints() and not g.refuse_admission()
        state["free"] = 900  # < admission (1000)
        assert g.tick() == diskguard.REFUSE_ADMISSION
        assert g.refuse_admission()
        # Recovery: just above a watermark is NOT enough (hysteresis)...
        state["free"] = 1100
        assert g.tick() == diskguard.REFUSE_ADMISSION
        # ...but past watermark * 1.25 the level steps back out, and a big
        # jump recovers multiple tiers at once.
        state["free"] = 1300
        assert g.tick() == diskguard.SHED_CHECKPOINTS
        state["free"] = 100_000
        assert g.tick() == diskguard.OK
        assert g.allow_cas_writes()

    def test_skips_straight_to_deepest_level(self, tmp_path):
        g, state = self._guard(tmp_path, 10_000)
        g.tick()
        state["free"] = 10
        assert g.tick() == diskguard.REFUSE_ADMISSION

    def test_transitions_export_and_ring_records(self, tmp_path):
        ring_dir = str(tmp_path / "ring")
        history = obs_history.HistoryWriter(ring_dir, source="test")
        from gol_tpu.serve.metrics import Metrics

        metrics = Metrics()
        g, state = self._guard(tmp_path, 10_000, registry=metrics,
                               history=history)
        g.tick()
        state["free"] = 500
        g.tick()
        state["free"] = 100_000
        g.tick()
        history.close()
        snap = metrics.snapshot()
        assert snap["counters"]["disk_guard_transitions_total"] == 2
        assert snap["gauges"]["disk_free_bytes"] == 100_000
        assert snap["gauges"]["disk_pressure_level"] == 0
        records = [r["diskguard"] for r in obs_history.read_records(ring_dir)
                   if "diskguard" in r]
        assert [(r["from"], r["to"]) for r in records] == [
            ("ok", "refuse-admission"), ("refuse-admission", "ok")]
        assert records[0]["free_bytes"] == 500

    def test_failing_read_holds_level(self, tmp_path):
        calls = {"n": 0}

        def free():
            calls["n"] += 1
            if calls["n"] > 1:
                raise OSError("statvfs broke")
            return 500

        g = diskguard.DiskGuard(str(tmp_path), admission_bytes=1000,
                                free_fn=free)
        assert g.tick() == diskguard.REFUSE_ADMISSION
        assert g.tick() == diskguard.REFUSE_ADMISSION  # held, not reset


# ---------------------------------------------------------------------------
# Serving under disk pressure (single worker + the fleet matrix)


class TestServeDiskPressure:
    def test_507_refuses_new_while_inflight_completes(self, tmp_path):
        srv = GolServer(port=0, journal_dir=str(tmp_path / "j"),
                        disk_reserve=1 << 20, sample_interval=0,
                        flush_age=0.2)
        free = {"v": 10 << 30}
        srv.disk_guard._free_fn = lambda: free["v"]
        srv.start()
        try:
            board = text_grid.generate(16, 16, seed=5)
            body = {"width": 16, "height": 16,
                    "cells": text_grid.encode(board).decode("ascii"),
                    "gen_limit": 200}
            code, payload = _http("POST", srv.url + "/jobs", body)
            assert code == 202
            accepted = payload["id"]
            # The disk fills while the job is queued/running.
            free["v"] = 10
            srv.storage_tick()
            code, payload = _http("POST", srv.url + "/jobs", body)
            assert code == 507
            assert payload["partition"] == str(tmp_path / "j")
            assert payload["free_bytes"] == 10
            # The ACCEPTED job still terminates and its done record lands.
            assert _wait(lambda: _http(
                "GET", f"{srv.url}/jobs/{accepted}")[1].get("state")
                == "done")
            # Space returns: admission recovers unattended.
            free["v"] = 10 << 30
            srv.storage_tick()
            code, _ = _http("POST", srv.url + "/jobs", body)
            assert code == 202
        finally:
            srv.shutdown()
        state = _replay_state(str(tmp_path / "j"))
        assert accepted in state.results  # the done record landed
        assert state.torn_lines == 0

    def test_fleet_with_one_full_disk_partition(self, tmp_path):
        """The chaos-matrix acceptance, in-process: one starved worker
        answers 507 through the router, the other keeps serving, zero torn
        records anywhere, and the fleet recovers unattended."""
        from gol_tpu.fleet.router import RouterServer
        from gol_tpu.fleet.workers import Fleet

        workers, frees = {}, {}
        for wid in ("w0", "w1"):
            srv = GolServer(port=0, journal_dir=str(tmp_path / wid),
                            disk_reserve=1 << 20, sample_interval=0,
                            flush_age=0.01)
            frees[wid] = {"v": 10 << 30}
            srv.disk_guard._free_fn = (
                lambda st=frees[wid]: st["v"])
            srv.start()
            workers[wid] = srv
        fleet = Fleet(str(tmp_path / "fleet"))
        for wid, srv in workers.items():
            fleet.attach(srv.url, wid)
        router = RouterServer(fleet, port=0)
        router.start()
        try:
            base = router.url
            # Find sizes owned by DIFFERENT workers while everything is
            # healthy (HRW is deterministic; probe until both appear).
            owner = {}
            ids = []
            for side in (32, 30, 64, 62, 96, 94):
                board = text_grid.generate(side, side, seed=side)
                code, payload = _http("POST", base + "/jobs", {
                    "width": side, "height": side,
                    "cells": text_grid.encode(board).decode("ascii"),
                    "gen_limit": 5,
                })
                assert code == 202
                owner[side] = payload["worker"]
                ids.append(payload["id"])
                if len(set(owner.values())) == 2:
                    break
            assert _wait(lambda: all(
                _http("GET", f"{base}/jobs/{j}")[1].get("state") == "done"
                for j in ids))
            assert len(set(owner.values())) == 2, owner
            # Starve ONE partition; keep a size the other worker owns as
            # the healthy control.
            starved_side, starved = next(iter(owner.items()))
            healthy_side = next(
                s for s, w in owner.items() if w != starved)
            frees[starved]["v"] = 0
            workers[starved].storage_tick()
            board = text_grid.generate(starved_side, starved_side, seed=9)
            code, payload = _http("POST", base + "/jobs", {
                "width": starved_side, "height": starved_side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": 5,
            })
            assert code == 507, payload  # propagated, names the partition
            assert payload["partition"] == str(tmp_path / starved)
            # The OTHER worker's buckets still serve.
            board = text_grid.generate(healthy_side, healthy_side, seed=10)
            code, payload = _http("POST", base + "/jobs", {
                "width": healthy_side, "height": healthy_side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": 5,
            })
            assert code == 202, (payload, owner)
            assert payload["worker"] == owner[healthy_side]
            # Fleet-merged gauges: free bytes by MIN, level by MAX.
            code, snap = _http("GET", base + "/metrics?format=json")
            assert code == 200
            assert snap["gauges"]["disk_free_bytes"] == 0
            assert snap["gauges"]["disk_pressure_level"] == 3
            # Space returns on the starved partition: recovery, unattended.
            frees[starved]["v"] = 10 << 30
            workers[starved].storage_tick()
            board = text_grid.generate(starved_side, starved_side, seed=11)
            code, payload = _http("POST", base + "/jobs", {
                "width": starved_side, "height": starved_side,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": 5,
            })
            assert code == 202
        finally:
            router.shutdown(cascade=False)
            for srv in workers.values():
                srv.shutdown()
        for wid in workers:
            state = _replay_state(str(tmp_path / wid))
            assert state.torn_lines == 0  # zero torn records anywhere


# ---------------------------------------------------------------------------
# Satellite 2: --checkpoint-keep pruning vs the async writer


def _np_codec():
    from gol_tpu.resilience.checkpoint import PayloadCodec

    return PayloadCodec(
        format="npy", suffix=".npy",
        write=lambda path, state: np.save(path, np.asarray(state)),
        read=lambda path: np.load(path),
    )


def _grid(seed, h=8, w=8):
    return np.random.default_rng(seed).integers(
        0, 2, size=(h, w)).astype(np.uint8)


def _assert_no_dangling_manifest(ckdir):
    for name in os.listdir(ckdir):
        if name.endswith(".manifest.json"):
            with open(os.path.join(ckdir, name)) as f:
                manifest = json.load(f)
            assert os.path.exists(os.path.join(ckdir, manifest["payload"]))


class TestCheckpointPrune:
    def _mgr(self, tmp_path, **kwargs):
        from gol_tpu.resilience.checkpoint import CheckpointManager

        return CheckpointManager(str(tmp_path), height=8, width=8,
                                 codec=_np_codec(), **kwargs)

    def test_sync_prune_behind_commit(self, tmp_path):
        mgr = self._mgr(tmp_path, keep=2)
        for gen in (2, 4, 6, 8):
            mgr.save(_grid(gen), gen, 0)
        gens = mgr._list_generations()
        assert gens == [8, 6]
        _assert_no_dangling_manifest(str(tmp_path))

    def test_async_writer_prunes_after_deferred_commit(self, tmp_path):
        from gol_tpu.pipeline.writer import AsyncCheckpointWriter

        mgr = self._mgr(tmp_path, keep=1)
        writer = AsyncCheckpointWriter(mgr)
        try:
            for gen in (2, 4, 6):
                writer.save(_grid(gen), gen, 0)
            writer.drain()
        finally:
            writer.close()
        assert mgr._list_generations() == [6]
        _assert_no_dangling_manifest(str(tmp_path))
        state, info = mgr.restore()
        np.testing.assert_array_equal(np.asarray(state), _grid(6))
        assert info.generation == 6

    def test_kill_during_prune_restores_newest(self, tmp_path):
        """The kill-during-prune crash window: manifest deleted, payload
        orphaned mid-prune. The newest checkpoint must restore
        byte-identically, no manifest may dangle, and the next prune
        sweeps the orphan."""
        mgr = self._mgr(tmp_path, keep=1)
        mgr.save(_grid(2), 2, 0)
        faults.install(FaultPlan(kill_during_prune=1))
        with pytest.raises(InjectedCrash):
            mgr.save(_grid(4), 4, 0)
        faults.clear()
        _assert_no_dangling_manifest(str(tmp_path))
        state, info = mgr.restore()
        assert info.generation == 4  # the commit preceded the prune
        np.testing.assert_array_equal(np.asarray(state), _grid(4))
        # The orphaned payload of generation 2 is swept by the next save.
        mgr.save(_grid(6), 6, 0)
        leftovers = [n for n in os.listdir(str(tmp_path))
                     if "00000002" in n or "00000004" in n]
        assert not leftovers
        state, info = mgr.restore()
        assert info.generation == 6

    def test_kill_during_prune_async_lane(self, tmp_path):
        from gol_tpu.pipeline.writer import AsyncCheckpointWriter

        mgr = self._mgr(tmp_path, keep=1)
        writer = AsyncCheckpointWriter(mgr)
        faults.install(FaultPlan(kill_during_prune=1))
        try:
            writer.save(_grid(2), 2, 0)
            writer.save(_grid(4), 4, 0)
            with pytest.raises(InjectedCrash):
                writer.drain()  # gen 4 commits, then the prune dies
        finally:
            writer.close()
            faults.clear()
        _assert_no_dangling_manifest(str(tmp_path))
        state, info = self._mgr(tmp_path, keep=1).restore()
        assert info.generation == 4
        np.testing.assert_array_equal(np.asarray(state), _grid(4))

    def test_guard_sheds_saves_under_pressure(self, tmp_path):
        free = {"v": 10 << 30}
        guard = diskguard.DiskGuard(str(tmp_path), admission_bytes=1000,
                                    free_fn=lambda: free["v"])
        mgr = self._mgr(tmp_path, keep=2, guard=guard)
        mgr.save(_grid(2), 2, 0)
        free["v"] = 1500  # below the checkpoint watermark (2000)
        mgr.save(_grid(4), 4, 0)  # shed: no new checkpoint
        assert mgr._list_generations() == [2]
        state, info = mgr.restore()
        assert info.generation == 2  # the previous one remains the anchor
        free["v"] = 10 << 30
        mgr.save(_grid(6), 6, 0)  # recovered
        assert 6 in mgr._list_generations()


# ---------------------------------------------------------------------------
# The real thing: a `gol serve` subprocess SIGKILLed mid-compaction


def _boot_serve(tmp_path, journal_dir, env_extra=None, *extra_args):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **(env_extra or {})}
    proc = subprocess.Popen(
        [sys.executable, "-m", "gol_tpu", "serve", "--port", "0",
         "--journal-dir", journal_dir,
         "--journal-segment-bytes", "600",
         "--sample-interval", "0.2",
         "--flush-age", "0.01", *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )
    url = None
    deadline = time.perf_counter() + 120
    while time.perf_counter() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("serving on "):
            url = line.split("serving on ", 1)[1].strip()
            break
    assert url, "serve subprocess never printed its URL"
    return proc, url


@pytest.mark.parametrize("stage", ["snapshot", "retire"])
def test_sigkill_mid_compaction_replays_exactly_once(tmp_path, stage):
    """End to end with a REAL process and a REAL SIGKILL: the server
    rotates segments under load, the fault plan SIGKILLs it at a
    compaction boundary, and the restart must serve every accepted job's
    result exactly once, byte-identical to the oracle."""
    journal_dir = str(tmp_path / "j")
    proc, url = _boot_serve(
        tmp_path, journal_dir,
        {"GOL_FAULTS":
         f"kill_during_compaction={stage},kill_mode=sigkill"},
    )
    boards = {}
    try:
        for i in range(10):
            board = text_grid.generate(16, 16, seed=200 + i)
            code, payload = _http("POST", url + "/jobs", {
                "width": 16, "height": 16,
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": 8,
            }, timeout=60)
            assert code == 202
            boards[payload["id"]] = board
        # The sampler tick compacts once the queue quiets — and dies there.
        assert _wait(lambda: proc.poll() is not None, timeout=60), \
            "the injected SIGKILL never fired"
        assert proc.poll() == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stdout.close()
        proc.wait()
    # Restart, faults disarmed: replay + finish everything.
    proc, url = _boot_serve(tmp_path, journal_dir)
    try:
        def all_done():
            return all(
                _http("GET", f"{url}/jobs/{j}")[1].get("state") == "done"
                for j in boards)
        assert _wait(all_done, timeout=120)
        for job_id, board in boards.items():
            code, result = _http("GET", f"{url}/result/{job_id}")
            assert code == 200
            want = oracle.run(board, GameConfig(gen_limit=8))
            got = text_grid.decode(result["grid"].encode("ascii"), 16, 16)
            np.testing.assert_array_equal(got, want.grid)
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        proc.stdout.close()
    # Exactly-once audit over the replay-visible record set (the one
    # enumeration auditors use: compaction.iter_records).
    state = _replay_state(journal_dir)
    assert state.results.keys() == set(boards)
    assert not state.pending and state.torn_lines == 0
    done_counts = {}
    for rec in compaction.iter_records(journal_dir):
        if rec.get("event") == "done":
            done_counts[rec["id"]] = done_counts.get(rec["id"], 0) + 1
    assert set(done_counts) == set(boards)
    assert all(n == 1 for n in done_counts.values()), done_counts
