"""TensorStore-backed sharded word storage (BASELINE.md config 5's
"sharded TensorStore I/O") — round trips, mesh sharding, and the CLI
zarr-snapshot/resume lane."""

import os

import numpy as np
import pytest

from gol_tpu import cli, oracle
from gol_tpu.config import GameConfig
from gol_tpu.io import text_grid, ts_store
from gol_tpu.ops import packed_math
from gol_tpu.parallel import make_mesh

pytestmark = pytest.mark.skipif(
    not ts_store.HAVE_TENSORSTORE, reason="tensorstore not installed"
)


def test_round_trip_single_device(tmp_path):
    g = text_grid.generate(64, 32, seed=5)
    words = packed_math.encode(g)
    path = str(tmp_path / "state.zarr")
    ts_store.write_words(path, words, 64)
    back = ts_store.read_words(path, 64, 32)
    assert np.array_equal(np.asarray(back), np.asarray(words))


def test_round_trip_mesh_shard_aligned_chunks(tmp_path):
    mesh = make_mesh(2, 2)
    g = text_grid.generate(128, 32, seed=6)
    import jax
    from gol_tpu.io.packed_io import words_sharding

    words = jax.device_put(np.asarray(packed_math.encode(g)), words_sharding(mesh))
    path = str(tmp_path / "state.zarr")
    ts_store.write_words(path, words, 128)
    # Read back onto a DIFFERENT mesh factorization: the store is
    # topology-independent (elastic reconfiguration for checkpoints).
    back = ts_store.read_words(path, 128, 32, make_mesh(1, 4))
    assert np.array_equal(np.asarray(back), np.asarray(packed_math.encode(g)))
    back1 = ts_store.read_words(path, 128, 32)
    assert np.array_equal(np.asarray(back1), np.asarray(packed_math.encode(g)))


def test_shape_mismatch_rejected(tmp_path):
    g = text_grid.generate(32, 16, seed=7)
    path = str(tmp_path / "state.zarr")
    ts_store.write_words(path, packed_math.encode(g), 32)
    with pytest.raises(ValueError, match="stored shape"):
        ts_store.read_words(path, 64, 16)


def test_cli_zarr_snapshots_resume_exactly(tmp_path, monkeypatch, capsys):
    """--snapshot-format zarr mid-run state resumed via a .zarr input file
    reproduces the uninterrupted run's count and output bytes."""
    monkeypatch.chdir(tmp_path)
    g = text_grid.generate(128, 128, seed=8)
    text_grid.write_grid("in.txt", g)

    rc = cli.main(["128", "128", "in.txt", "--variant", "tpu", "--packed-io",
                   "--gen-limit", "40"])
    assert rc in (0, None)
    capsys.readouterr()
    whole = open("tpu_output.out", "rb").read()

    rc = cli.main(["128", "128", "in.txt", "--variant", "tpu", "--packed-io",
                   "--gen-limit", "40", "--snapshot-every", "15",
                   "--snapshot-format", "zarr", "--snapshot-dir", "snaps"])
    assert rc in (0, None)
    capsys.readouterr()
    import os

    assert os.path.isdir("snaps/gen_000015.zarr")

    rc = cli.main(["128", "128", "snaps/gen_000015.zarr", "--variant", "tpu",
                   "--packed-io", "--gen-limit", "40", "--resume-gen", "15"])
    assert rc in (0, None)
    out = capsys.readouterr().out
    gens = int([l for l in out.splitlines() if l.startswith("Generations")][0]
               .split("\t")[1])
    want = oracle.run(g, GameConfig(gen_limit=40))
    assert gens == want.generations
    assert open("tpu_output.out", "rb").read() == whole


def test_zarr_flags_rejected_off_packed_lane(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    g = text_grid.generate(32, 32, seed=9)
    text_grid.write_grid("in.txt", g)
    rc = cli.main(["32", "32", "in.txt", "--variant", "game",
                   "--snapshot-every", "5", "--snapshot-format", "zarr"])
    assert rc == 1
    assert "--packed-io" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Failure semantics (resilience pass): the writer never deletes the only
# durable copy, awaits every shard, and retries transients.

from gol_tpu.resilience import faults as _faults
from gol_tpu.resilience.faults import FaultPlan


@pytest.fixture(autouse=True)
def _disarmed():
    _faults.clear()
    yield
    _faults.clear()


def _words(seed):
    return packed_math.encode(text_grid.generate(64, 32, seed=seed))


def test_overwrite_crash_preserves_prior_store(tmp_path):
    path = str(tmp_path / "state.zarr")
    w1, w2 = _words(40), _words(41)
    ts_store.write_words(path, w1, 64)
    _faults.install(FaultPlan(ts_write_fail=1))
    with pytest.raises(OSError, match=r"shard indices \[0\]"):
        ts_store.write_words(path, w2, 64)
    _faults.clear()
    # The failed overwrite went to a staging sibling; the prior store is
    # byte-for-byte intact.
    back = ts_store.read_words(path, 64, 32)
    assert np.array_equal(np.asarray(back), np.asarray(w1))
    # A healthy rewrite then commits and sweeps the staging path.
    ts_store.write_words(path, w2, 64)
    back = ts_store.read_words(path, 64, 32)
    assert np.array_equal(np.asarray(back), np.asarray(w2))
    leftovers = [n for n in os.listdir(tmp_path)
                 if n.endswith((".inprogress", ".replaced"))]
    assert leftovers == []


def test_overwrite_transient_faults_heal(tmp_path):
    path = str(tmp_path / "state.zarr")
    w1, w2 = _words(42), _words(43)
    ts_store.write_words(path, w1, 64)
    _faults.install(FaultPlan(ts_write_fail=1, ts_write_error="transient",
                              ts_open_transient=1))
    ts_store.write_words(path, w2, 64)  # open + write both hiccup, both heal
    back = ts_store.read_words(path, 64, 32)
    assert np.array_equal(np.asarray(back), np.asarray(w2))


def test_mesh_write_awaits_all_shards_and_names_failures(tmp_path):
    import jax
    from gol_tpu.io.packed_io import words_sharding

    mesh = make_mesh(2, 2)
    g = text_grid.generate(128, 32, seed=44)
    words = jax.device_put(np.asarray(packed_math.encode(g)),
                           words_sharding(mesh))
    _faults.install(FaultPlan(ts_write_fail=3))
    with pytest.raises(OSError, match=r"shard indices \[2\]"):
        ts_store.write_words(str(tmp_path / "s.zarr"), words, 128)


def test_read_words_recovers_displaced_store(tmp_path):
    """A crash between _swap_in's two renames leaves only path.replaced;
    read_words must recover it instead of failing the resume."""
    path = str(tmp_path / "state.zarr")
    w1 = _words(45)
    ts_store.write_words(path, w1, 64)
    os.rename(path, path + ".replaced")
    back = ts_store.read_words(path, 64, 32)
    assert np.array_equal(np.asarray(back), np.asarray(w1))
    assert os.path.isdir(path) and not os.path.exists(path + ".replaced")


def test_multihost_staged_write_failure_votes_before_commit_barrier(
    tmp_path, monkeypatch
):
    """Review regression: one process's failed shard writes must vote the
    cluster out of the staged overwrite BEFORE the commit barrier — not exit
    write_words alone and leave peers parked there until the
    distributed-runtime timeout."""
    import jax
    from jax.experimental import multihost_utils

    from gol_tpu.parallel import collectives

    path = str(tmp_path / "state.zarr")
    w1, w2 = _words(46), _words(47)
    ts_store.write_words(path, w1, 64)

    barriers, votes = [], []
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setattr(multihost_utils, "sync_global_devices",
                        lambda name: barriers.append(name))
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda x: np.asarray([x]))
    monkeypatch.setattr(collectives, "host_all_agree",
                        lambda flag: votes.append(flag) or flag)
    _faults.install(FaultPlan(ts_write_fail=1))
    with pytest.raises(OSError, match=r"shard indices \[0\]"):
        ts_store.write_words(path, w2, 64)
    # create vote passed, then the failing process voted False and raised
    assert votes == [True, False]
    assert not any("commit" in b for b in barriers)  # never reached it
    _faults.clear()
    # The live store was never touched by the abandoned overwrite.
    back = ts_store.read_words(path, 64, 32)
    assert np.array_equal(np.asarray(back), np.asarray(w1))
