"""The serving subsystem: batched engine exactness, batcher buckets, journal
replay, scheduler policy, and the HTTP API.

The load-bearing assertion, repeated at every layer: a board's result coming
out of a batch — even a board that exits early while the rest of the batch
keeps running — is byte/value-identical to a solo ``engine`` run AND to the
NumPy oracle, for BOTH loop-accounting conventions.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from gol_tpu import engine, oracle
from gol_tpu.config import Convention, GameConfig
from gol_tpu.io import text_grid
from gol_tpu.resilience.retry import RetryPolicy
from gol_tpu.serve import batcher
from gol_tpu.serve.jobs import (
    CANCELLED, DONE, FAILED, QUEUED,
    JobJournal, JobResult, new_job,
)
from gol_tpu.serve.scheduler import Draining, QueueFull, Scheduler
from gol_tpu.serve.server import GolServer

CONVENTIONS = [Convention.C, Convention.CUDA]


def _mixed_fate_boards():
    """Three 32x32 boards with three different fates at gen_limit=60."""
    dies = np.zeros((32, 32), np.uint8)
    dies[4, 4] = 1  # lone cell: dead after one generation
    still = np.zeros((32, 32), np.uint8)
    still[3:5, 3:5] = 1  # block still life: similarity exit
    soup = text_grid.generate(32, 32, seed=7)  # runs to the limit
    return [("dies", dies, "empty"), ("still", still, "similar"),
            ("soup", soup, "gen_limit")]


class TestBatchEngine:
    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_mixed_fate_batch_matches_solo_and_oracle(self, convention):
        """One bucket, three fates: early-empty, similarity exit, and
        runs-to-limit — each board's (grid, count, exit reason) must equal a
        solo engine run and the oracle, while the batch as a whole keeps
        stepping to the last live board."""
        named = _mixed_fate_boards()
        cfg = GameConfig(gen_limit=60, convention=convention)
        results = engine.simulate_batch([b for _, b, _ in named], cfg)
        for (name, board, reason), got in zip(named, results):
            want = oracle.run(board, cfg)
            solo = engine.simulate(board, cfg)
            assert np.array_equal(got.grid, want.grid), (convention, name)
            assert np.array_equal(got.grid, solo.grid), (convention, name)
            assert got.generations == want.generations == solo.generations, (
                convention, name,
            )
            assert got.exit_reason == reason, (convention, name)

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_masked_bucket_mixed_shapes(self, convention):
        """Different true extents share one padded canvas: the masked kernel
        wraps each board at its own (h, w), so every result still matches
        the solo torus bit-for-bit — including an early exit mid-batch."""
        b1 = text_grid.generate(30, 30, seed=1)
        b2 = text_grid.generate(18, 24, seed=2)
        b3 = np.zeros((10, 13), np.uint8)
        b3[2:4, 2:4] = 1  # block: similarity exit inside a running batch
        cfg = GameConfig(gen_limit=40, convention=convention)
        results = engine.simulate_batch(
            [b1, b2, b3], cfg, padded_shape=(32, 32), pad_batch_to=4
        )
        for board, got in zip((b1, b2, b3), results):
            want = oracle.run(board, cfg)
            solo = engine.simulate(board, cfg)
            assert np.array_equal(got.grid, want.grid), board.shape
            assert got.generations == want.generations == solo.generations
        assert results[2].exit_reason == "similar"

    def test_byte_mode_unpackable_width(self):
        """Exact-fit boards whose width does not pack (33) take the byte
        kernel; results still match the oracle."""
        board = text_grid.generate(33, 20, seed=5)  # width=33, height=20
        assert engine.resolve_batch_mode([20], [33], (20, 33)) == "byte"
        cfg = GameConfig(gen_limit=25)
        got = engine.simulate_batch([board], cfg)[0]
        want = oracle.run(board, cfg)
        assert np.array_equal(got.grid, want.grid)
        assert got.generations == want.generations

    def test_per_board_gen_limits_share_one_program(self):
        """gen_limit is a dynamic operand: three different limits hit the
        same compiled runner (one cache entry), results all oracle-exact."""
        soup = text_grid.generate(32, 32, seed=9)
        before = engine.make_batch_runner.cache_info()
        cfgs = [GameConfig(gen_limit=g) for g in (5, 17, 60)]
        results = engine.simulate_batch([soup] * 3, cfgs)
        after = engine.make_batch_runner.cache_info()
        assert after.currsize - before.currsize <= 1
        for cfg, got in zip(cfgs, results):
            want = oracle.run(soup, cfg)
            assert np.array_equal(got.grid, want.grid)
            assert got.generations == want.generations

    def test_batch_rejects_mixed_conventions(self):
        soup = text_grid.generate(32, 32, seed=9)
        with pytest.raises(ValueError, match="share convention"):
            engine.simulate_batch(
                [soup, soup],
                [GameConfig(convention=Convention.C),
                 GameConfig(convention=Convention.CUDA)],
            )

    def test_board_exceeding_canvas_rejected(self):
        with pytest.raises(ValueError, match="exceeds"):
            engine.resolve_batch_mode([40], [40], (32, 32))

    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(3)
        stacked = rng.integers(0, 2, size=(3, 16, 64), dtype=np.uint8)
        words = engine._pack_board_words(stacked)
        assert words.shape == (3, 16, 2) and words.dtype == np.uint32
        np.testing.assert_array_equal(
            engine._unpack_board_words(words), stacked
        )


class TestBatcher:
    def test_bucket_assignment(self):
        j30 = new_job(30, 30, np.zeros((30, 30), np.uint8))
        j32 = new_job(32, 32, np.zeros((32, 32), np.uint8))
        jc = new_job(32, 32, np.zeros((32, 32), np.uint8),
                     convention=Convention.CUDA)
        k30, k32, kc = (batcher.bucket_for(j) for j in (j30, j32, jc))
        assert (k30.height, k30.width, k30.kernel) == (32, 32, "masked")
        assert (k32.height, k32.width, k32.kernel) == (32, 32, "packed")
        assert k32 != k30  # padded vs exact-fit never share a program
        assert kc != k32  # conventions never share a program
        assert batcher.pad_dim(1) == 32 and batcher.pad_dim(33) == 64

    def test_pad_batch_ladder(self):
        assert [batcher.pad_batch(n) for n in (1, 2, 3, 8, 9, 48, 64)] == [
            1, 2, 4, 8, 16, 64, 64,
        ]
        # Never rounds DOWN: the rung is the denominator of occupancy.
        for n in range(1, batcher.MAX_BATCH + 1):
            assert batcher.pad_batch(n) >= n
        with pytest.raises(ValueError):
            batcher.pad_batch(batcher.MAX_BATCH + 1)
        with pytest.raises(ValueError):
            batcher.pad_batch(0)

    def test_run_batch_rejects_foreign_job(self):
        j30 = new_job(30, 30, np.zeros((30, 30), np.uint8))
        j32 = new_job(32, 32, np.zeros((32, 32), np.uint8))
        with pytest.raises(ValueError, match="belongs to bucket"):
            batcher.run_batch(batcher.bucket_for(j32), [j30])

    def test_run_batch_results_in_job_order(self):
        boards = [text_grid.generate(32, 32, seed=s) for s in (1, 2, 3)]
        jobs = [new_job(32, 32, b, gen_limit=20) for b in boards]
        key = batcher.bucket_for(jobs[0])
        results = batcher.run_batch(key, jobs)
        for board, res in zip(boards, results):
            want = oracle.run(board, GameConfig(gen_limit=20))
            assert np.array_equal(res.grid, want.grid)
            assert res.generations == want.generations


class TestJournal:
    def test_replay_roundtrip(self, tmp_path):
        journal = JobJournal(str(tmp_path))
        a = new_job(8, 8, np.zeros((8, 8), np.uint8))
        b = new_job(8, 8, np.ones((8, 8), np.uint8), gen_limit=7, priority=3)
        c = new_job(8, 8, np.zeros((8, 8), np.uint8))
        d = new_job(8, 8, np.zeros((8, 8), np.uint8))
        for j in (a, b, c, d):
            journal.record_submit(j)
        a.result = JobResult(
            grid=np.ones((8, 8), np.uint8), generations=4, exit_reason="empty"
        )
        journal.record_done(a)
        c.error = "boom"
        journal.record_failed(c)
        journal.record_cancelled(d)
        journal.close()

        replay = JobJournal(str(tmp_path)).replay()
        assert [j.id for j in replay.pending] == [b.id]
        assert replay.pending[0].gen_limit == 7
        assert replay.pending[0].priority == 3
        assert replay.results.keys() == {a.id}
        np.testing.assert_array_equal(
            replay.results[a.id].grid, np.ones((8, 8), np.uint8)
        )
        assert replay.results[a.id].generations == 4
        assert replay.failed == {c.id: "boom"}
        assert replay.cancelled == {d.id}
        assert replay.torn_lines == 0

    def test_torn_tail_dropped(self, tmp_path):
        journal = JobJournal(str(tmp_path))
        job = new_job(8, 8, np.zeros((8, 8), np.uint8))
        journal.record_submit(job)
        journal.close()
        # A crash mid-append: the tail line is half a record.
        with open(journal.path, "ab") as f:
            f.write(b'{"event": "done", "id": "xyz", "gen')
        replay = JobJournal(str(tmp_path)).replay()
        assert [j.id for j in replay.pending] == [job.id]
        assert replay.torn_lines == 1


def _wait(predicate, timeout=30.0, interval=0.01):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


class TestScheduler:
    def test_end_to_end_mixed_buckets(self, tmp_path):
        journal = JobJournal(str(tmp_path))
        sched = Scheduler(journal=journal, flush_age=0.01)
        boards = [
            text_grid.generate(32, 32, seed=1),
            text_grid.generate(30, 30, seed=2),  # different bucket (masked)
            text_grid.generate(32, 32, seed=3),
        ]
        jobs = [new_job(b.shape[1], b.shape[0], b, gen_limit=15) for b in boards]
        sched.start()
        try:
            for j in jobs:
                sched.submit(j)
            assert _wait(lambda: all(j.state == DONE for j in jobs)), [
                j.state for j in jobs
            ]
        finally:
            sched.stop()
        for board, j in zip(boards, jobs):
            want = oracle.run(board, GameConfig(gen_limit=15))
            assert np.array_equal(j.result.grid, want.grid)
            assert j.result.generations == want.generations
        assert sched.metrics.counter("jobs_completed_total") == 3
        replay = JobJournal(str(tmp_path)).replay()
        assert not replay.pending  # every accepted job reached a terminal record
        assert set(replay.results) == {j.id for j in jobs}

    def test_queue_full_rejects(self):
        sched = Scheduler(max_queue_depth=2)  # never started: jobs sit queued
        for seed in (1, 2):
            sched.submit(new_job(8, 8, text_grid.generate(8, 8, seed=seed)))
        with pytest.raises(QueueFull):
            sched.submit(new_job(8, 8, text_grid.generate(8, 8, seed=3)))
        assert sched.metrics.counter("jobs_rejected_total") == 1

    def test_replay_bypasses_admission_cap(self, tmp_path):
        """Journal replay can exceed max_queue_depth: replayed jobs were
        already accepted once, and bouncing them would turn a full-queue
        crash into an unrecoverable restart loop."""
        journal = JobJournal(str(tmp_path))
        for seed in range(3):
            journal.record_submit(
                new_job(8, 8, text_grid.generate(8, 8, seed=seed))
            )
        journal.close()
        replay = JobJournal(str(tmp_path)).replay()
        sched = Scheduler(max_queue_depth=1)  # smaller than the backlog
        assert sched.resubmit_replayed(replay.pending) == 3
        assert sched.stats()["queued"] == 3
        # Fresh admissions still hit the cap.
        with pytest.raises(QueueFull):
            sched.submit(new_job(8, 8, text_grid.generate(8, 8, seed=9)))

    def test_draining_rejects(self):
        sched = Scheduler()
        sched.drain(timeout=0.1)
        with pytest.raises(Draining):
            sched.submit(new_job(8, 8, np.zeros((8, 8), np.uint8)))

    def test_cancel_queued_job(self):
        sched = Scheduler()  # not started
        job = sched.submit(new_job(8, 8, np.zeros((8, 8), np.uint8)))
        assert sched.cancel(job.id) is True
        assert job.state == CANCELLED
        assert sched.cancel(job.id) is False  # already terminal
        assert sched.stats()["queued"] == 0

    def test_priority_and_deadline_order_dispatch(self):
        sched = Scheduler(max_batch=2, flush_age=0.0)  # not started
        low = sched.submit(new_job(8, 8, np.zeros((8, 8), np.uint8), priority=0))
        high = sched.submit(new_job(8, 8, np.zeros((8, 8), np.uint8), priority=5))
        mid = sched.submit(
            new_job(8, 8, np.zeros((8, 8), np.uint8), priority=0, deadline_s=0.5)
        )
        with sched._cv:
            _key, take = sched._claim_locked(time.perf_counter() + 1)
        # priority first, then nearest deadline beats plain arrival.
        assert [j.id for j in take] == [high.id, mid.id]
        assert low.state == QUEUED

    def test_transient_dispatch_error_retries(self):
        calls = {"n": 0}
        real = batcher.run_batch

        def flaky(key, jobs):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("UNAVAILABLE: injected transient hiccup")
            return real(key, jobs)

        sched = Scheduler(
            flush_age=0.0,
            retry=RetryPolicy(attempts=3, base_delay=0.0),
            run_batch=flaky,
        )
        job = sched.submit(new_job(8, 8, text_grid.generate(8, 8, seed=4),
                                   gen_limit=5))
        sched.start()
        try:
            assert _wait(lambda: job.state == DONE), job.state
        finally:
            sched.stop()
        assert calls["n"] == 3
        assert sched.metrics.counter("batch_retries_total") == 2
        want = oracle.run(job.board, GameConfig(gen_limit=5))
        assert np.array_equal(job.result.grid, want.grid)

    def test_persistent_dispatch_error_fails_jobs(self, tmp_path):
        def broken(key, jobs):
            raise ValueError("bad batch")  # never classified transient

        journal = JobJournal(str(tmp_path))
        sched = Scheduler(journal=journal, flush_age=0.0, run_batch=broken)
        job = sched.submit(new_job(8, 8, text_grid.generate(8, 8, seed=4)))
        sched.start()
        try:
            assert _wait(lambda: job.state == FAILED), job.state
        finally:
            sched.stop()
        assert "bad batch" in job.error
        assert JobJournal(str(tmp_path)).replay().failed.keys() == {job.id}


def _http(method, url, body=None, timeout=30):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if body else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestServer:
    @pytest.fixture
    def server(self, tmp_path):
        srv = GolServer(port=0, journal_dir=str(tmp_path / "journal"),
                        flush_age=0.01)
        srv.start()
        yield srv
        srv.shutdown()

    def test_submit_poll_result_metrics_drain(self, server):
        base = server.url
        boards = {
            "a": text_grid.generate(32, 32, seed=11),
            "b": text_grid.generate(30, 30, seed=12),  # second bucket shape
        }
        ids = {}
        for name, board in boards.items():
            status, raw = _http("POST", f"{base}/jobs", {
                "width": board.shape[1],
                "height": board.shape[0],
                "cells": text_grid.encode(board).decode("ascii"),
                "gen_limit": 12,
            })
            assert status == 202, raw
            ids[name] = json.loads(raw)["id"]

        for name, board in boards.items():
            jid = ids[name]
            assert _wait(lambda: json.loads(
                _http("GET", f"{base}/jobs/{jid}")[1]
            )["state"] == DONE)
            status, raw = _http("GET", f"{base}/result/{jid}")
            assert status == 200
            payload = json.loads(raw)
            want = oracle.run(board, GameConfig(gen_limit=12))
            got = text_grid.decode(
                payload["grid"].encode("ascii"),
                payload["width"], payload["height"],
            )
            np.testing.assert_array_equal(np.asarray(got), want.grid)
            assert payload["generations"] == want.generations

        status, raw = _http("GET", f"{base}/metrics?format=json")
        snap = json.loads(raw)
        assert snap["counters"]["jobs_completed_total"] == 2
        assert "queue_latency_seconds" in snap["histograms"]
        assert "run_latency_seconds" in snap["histograms"]
        status, raw = _http("GET", f"{base}/metrics")
        text = raw.decode()
        assert "gol_serve_jobs_completed_total 2" in text
        assert 'gol_serve_run_latency_seconds{quantile="0.99"}' in text

        status, raw = _http("POST", f"{base}/drain", {})
        assert status == 200 and json.loads(raw)["drained"] is True
        # Draining servers refuse new work with 429.
        status, raw = _http("POST", f"{base}/jobs", {
            "width": 8, "height": 8,
            "cells": text_grid.encode(np.zeros((8, 8), np.uint8)).decode(),
        })
        assert status == 429

    def test_bad_requests(self, server):
        base = server.url
        assert _http("POST", f"{base}/jobs", {"width": 8})[0] == 400
        assert _http("GET", f"{base}/jobs/nope")[0] == 404
        assert _http("GET", f"{base}/result/nope")[0] == 404
        assert _http("POST", f"{base}/nope", {})[0] == 404

    def test_bad_field_types_rejected_not_queued(self, server):
        """Wrong JSON *types* (priority: null, gen_limit: "x") must be 400 at
        admission — an accepted-but-poisoned job would kill the worker
        thread at dispatch-key time and wedge the scheduler forever."""
        base = server.url
        cells = text_grid.encode(text_grid.generate(8, 8, seed=1)).decode()
        for bad in (
            {"priority": None}, {"priority": "high"},
            {"gen_limit": "x"}, {"similarity_frequency": None},
            {"deadline_s": "soon"}, {"check_similarity": "false"},
        ):
            body = {"width": 8, "height": 8, "cells": cells, **bad}
            status, raw = _http("POST", f"{base}/jobs", body)
            assert status == 400, (bad, raw)
        # The scheduler is still alive: a well-formed job completes.
        status, raw = _http("POST", f"{base}/jobs", {
            "width": 8, "height": 8, "cells": cells, "gen_limit": 5,
        })
        assert status == 202
        jid = json.loads(raw)["id"]
        assert _wait(lambda: json.loads(
            _http("GET", f"{base}/jobs/{jid}")[1]
        )["state"] == DONE)

    def test_cancel_endpoint(self, tmp_path):
        # flush_age 10s: the lone job sits QUEUED long enough to cancel.
        srv = GolServer(port=0, flush_age=10.0)
        srv.start()
        try:
            base = srv.url
            job = srv.scheduler.submit(new_job(8, 8, np.zeros((8, 8), np.uint8)))
            status, raw = _http("DELETE", f"{base}/jobs/{job.id}")
            assert status == 200 and json.loads(raw)["state"] == CANCELLED
            assert job.state == CANCELLED
            # Terminal job: no longer cancellable.
            assert _http("DELETE", f"{base}/jobs/{job.id}")[0] == 409
            assert _http("DELETE", f"{base}/jobs/unknown")[0] == 404
        finally:
            srv.shutdown()

    def test_worker_survives_journal_append_failure(self, tmp_path):
        """A journal I/O error on a terminal record must not kill the worker
        thread: the job stays DONE in-memory and later batches still run."""
        journal = JobJournal(str(tmp_path))
        real_done = JobJournal.record_done
        fail = {"armed": True}

        def flaky_done(self_j, job):
            if fail.pop("armed", False):
                raise OSError(28, "No space left on device")
            return real_done(self_j, job)

        sched = Scheduler(journal=journal, flush_age=0.0)
        try:
            JobJournal.record_done = flaky_done
            sched.start()
            j1 = sched.submit(new_job(8, 8, text_grid.generate(8, 8, seed=1),
                                      gen_limit=3))
            assert _wait(lambda: j1.state == DONE), j1.state
            # The worker is still alive: a second job completes and journals.
            j2 = sched.submit(new_job(8, 8, text_grid.generate(8, 8, seed=2),
                                      gen_limit=3))
            assert _wait(lambda: j2.state == DONE), j2.state
        finally:
            JobJournal.record_done = real_done
            sched.stop()
        assert sched.metrics.counter("journal_errors_total") == 1
        replay = JobJournal(str(tmp_path)).replay()
        # j1's done record was lost (it would re-run after restart, loudly
        # logged); j2's landed.
        assert j2.id in replay.results and j1.id in {j.id for j in replay.pending}

    def test_result_not_ready_conflict(self, tmp_path):
        # Scheduler intentionally not started: the job stays queued.
        srv = GolServer(port=0, flush_age=10.0)
        srv.httpd.server_close()
        job = srv.scheduler.submit(
            new_job(8, 8, np.zeros((8, 8), np.uint8))
        )
        code, payload = srv.result_json(job.id)
        assert code == 409 and payload["state"] == QUEUED

    def test_restart_replays_journal_exactly_once(self, tmp_path):
        journal_dir = str(tmp_path / "journal")
        board = text_grid.generate(32, 32, seed=21)
        # Server 1 accepts (journals) a job but is killed before running it:
        # its scheduler never starts.
        srv1 = GolServer(port=0, journal_dir=journal_dir, flush_age=0.01)
        srv1.httpd.server_close()  # simulate the crash: no drain, no stop
        job = srv1.scheduler.submit(
            new_job(32, 32, board, gen_limit=18)
        )
        srv1.scheduler.journal.close()

        # Server 2 replays: the accepted job runs to DONE exactly once.
        srv2 = GolServer(port=0, journal_dir=journal_dir, flush_age=0.01)
        assert srv2.replayed == 1
        srv2.start()
        try:
            assert _wait(
                lambda: (j := srv2.scheduler.job(job.id)) is not None
                and j.state == DONE
            )
        finally:
            srv2.shutdown()
        want = oracle.run(board, GameConfig(gen_limit=18))
        replayed_job = srv2.scheduler.job(job.id)
        assert np.array_equal(replayed_job.result.grid, want.grid)
        assert replayed_job.result.generations == want.generations

        # Exactly-once: one submit record, one done record for the id.
        with open(JobJournal(journal_dir).path, "rb") as f:
            events = [json.loads(line) for line in f.read().splitlines() if line]
        submits = [e for e in events if e["event"] == "submit"
                   and e["job"]["id"] == job.id]
        dones = [e for e in events if e["event"] == "done" and e["id"] == job.id]
        assert len(submits) == 1 and len(dones) == 1

        # Server 3 replays nothing (the job is terminal) but still serves
        # the result from the journal.
        srv3 = GolServer(port=0, journal_dir=journal_dir, flush_age=0.01)
        assert srv3.replayed == 0
        code, payload = srv3.result_json(job.id)
        assert code == 200 and payload["generations"] == want.generations
        srv3.httpd.server_close()
        srv3.scheduler.journal.close()

    def test_cancelled_job_survives_restart_as_410(self, tmp_path):
        journal_dir = str(tmp_path / "journal")
        srv1 = GolServer(port=0, journal_dir=journal_dir)
        srv1.httpd.server_close()
        job = srv1.scheduler.submit(new_job(8, 8, np.zeros((8, 8), np.uint8)))
        assert srv1.scheduler.cancel(job.id) is True
        srv1.scheduler.journal.close()

        srv2 = GolServer(port=0, journal_dir=journal_dir)
        assert srv2.replayed == 0  # cancelled is terminal: not re-run
        assert srv2.job_json(job.id)["state"] == CANCELLED
        code, payload = srv2.result_json(job.id)
        assert code == 410 and payload["state"] == CANCELLED
        srv2.httpd.server_close()
        srv2.scheduler.journal.close()
