"""gol_tpu/tune: plan cache durability, fingerprint invalidation, selection,
and — most load-bearing — the no-plan path staying byte-identical to the
hard-coded ladders for both conventions."""

import dataclasses
import json
import logging
import os

import numpy as np
import pytest

import jax

from gol_tpu import engine, oracle
from gol_tpu.config import GameConfig
from gol_tpu.ops import get_kernel, resolve_kernel, with_temporal_depth
from gol_tpu.parallel.mesh import SINGLE_DEVICE
from gol_tpu.serve import batcher
from gol_tpu.tune import measure, plans, select, space


@pytest.fixture
def plan_cache(tmp_path, monkeypatch):
    """A private, initially-absent plan cache; consult caches dropped on
    entry and exit so no other test sees this one's plans."""
    path = str(tmp_path / "plans.json")
    monkeypatch.setenv(plans.ENV_CACHE_PATH, path)
    select.reset()
    batcher._reset_plan()
    yield path
    select.reset()
    batcher._reset_plan()


def _grid(h=48, w=64, seed=11):
    return np.random.default_rng(seed).integers(0, 2, (h, w), dtype=np.uint8)


# ---------------------------------------------------------------------------
# Plan store: round-trip, crash tolerance, invalidation.
# ---------------------------------------------------------------------------


def test_store_round_trip(plan_cache):
    store = plans.PlanStore(plan_cache)
    fp = plans.fingerprint("engine", 48, 64, "c", "byte", (1, 1), "cpu")
    plan = {"kernel": "packed-jnp", "termination_block": 64}
    store.put(fp, plan, measured={"tuned_vs_default": 1.5})
    assert store.get(fp) == plan
    # A fresh store (fresh process) reads the same entry back.
    assert plans.PlanStore(plan_cache).get(fp) == plan
    # The commit left no staging litter.
    from gol_tpu.resilience import STAGING_SUFFIX

    leftovers = [f for f in os.listdir(os.path.dirname(plan_cache))
                 if f.endswith(STAGING_SUFFIX)]
    assert leftovers == []


def test_store_missing_file_is_empty(plan_cache):
    store = plans.PlanStore(plan_cache)
    assert store.get("anything") is None
    # Bundled defaults still resolve.
    assert store.get_default("serve")["pad_quantum"] == 32
    assert store.get_default("engine") == {}


@pytest.mark.parametrize("body", [
    "{\"schema\": 1, \"plans\": {\"k\": {\"pl",  # torn mid-write
    "not json at all",
    "{\"schema\": 1}",  # missing plans
    "{\"plans\": [1, 2]}",  # wrong container type
])
def test_store_torn_file_falls_back_loudly(plan_cache, body, caplog):
    with open(plan_cache, "w", encoding="utf-8") as f:
        f.write(body)
    store = plans.PlanStore(plan_cache)
    with caplog.at_level(logging.WARNING, logger="gol_tpu.tune.plans"):
        assert store.get("anything") is None
    assert any("unreadable" in rec.message for rec in caplog.records)
    # The runtime consult degrades to the built-in ladders, not an error.
    select.reset()
    assert select.serve_plan() == space.DEFAULT_SERVE_PLAN
    # And put() recovers the file: a torn cache is replaced, not appended to.
    store.put("fp", {"kernel": "lax"})
    assert plans.PlanStore(plan_cache).get("fp") == {"kernel": "lax"}


def test_fingerprint_jax_version_invalidates(plan_cache, monkeypatch):
    store = plans.PlanStore(plan_cache)
    config = GameConfig(gen_limit=30)
    fp = select.engine_fingerprint((48, 64), config)
    store.put(fp, {"kernel": "lax"})
    select.reset()
    assert select.engine_plan((48, 64), config).kernel == "lax"
    # A different jax version produces a different fingerprint: clean miss.
    monkeypatch.setattr(plans, "_jax_version", lambda: "999.0.0")
    select.reset()
    assert select.engine_plan((48, 64), config) is None


def test_fingerprint_schema_invalidates(plan_cache, monkeypatch):
    store = plans.PlanStore(plan_cache)
    config = GameConfig(gen_limit=30)
    fp = select.engine_fingerprint((48, 64), config)
    store.put(fp, {"kernel": "lax"})
    monkeypatch.setattr(plans, "SCHEMA_VERSION", 2)
    select.reset()
    assert select.engine_plan((48, 64), config) is None


def test_put_prunes_stale_entries(plan_cache, monkeypatch):
    store = plans.PlanStore(plan_cache)
    store.put("old-key", {"kernel": "lax"})
    monkeypatch.setattr(plans, "_jax_version", lambda: "999.0.0")
    fresh = plans.PlanStore(plan_cache)
    fresh.put("new-key", {"kernel": "packed"})
    body = json.load(open(plan_cache, encoding="utf-8"))
    # The stale-jax entry was swept on write; only the new one remains.
    assert set(body["plans"]) == {"new-key"}


# ---------------------------------------------------------------------------
# The no-plan path: byte-identical to the hard-coded ladders.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("convention", ["c", "cuda"])
def test_no_plan_engine_identical(plan_cache, convention):
    """With an absent cache the consult returns None, the auto ladder picks
    exactly what ops.resolve_kernel picks, and the output matches the
    oracle — the pre-tune contract for both conventions."""
    config = GameConfig(gen_limit=25, convention=convention)
    assert select.engine_plan((48, 64), config) is None
    grid = _grid()
    runner = engine._build_runner((48, 64), config, None, "auto",
                                  segmented=False, packed_state=False)
    expected_name = resolve_kernel("auto", 48, 64, SINGLE_DEVICE).name
    assert runner.kernel_name == expected_name
    final, gen = runner(jax.device_put(grid))
    expect = oracle.run(grid, config)
    assert np.array_equal(np.asarray(final), expect.grid)
    assert int(gen) == expect.generations


def test_no_plan_batcher_constants(plan_cache):
    """pad_dim/pad_batch under an absent cache are the original constants."""
    assert batcher.pad_dim(1) == 32
    assert batcher.pad_dim(33) == 64
    assert [batcher.pad_batch(n) for n in (1, 2, 3, 5, 9, 17, 33, 64)] == \
        [1, 2, 4, 8, 16, 32, 64, 64]


# ---------------------------------------------------------------------------
# Plan application.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("convention", ["c", "cuda"])
def test_planned_kernel_applied_and_exact(plan_cache, convention):
    config = GameConfig(gen_limit=27, convention=convention)
    store = plans.PlanStore(plan_cache)
    store.put(
        select.engine_fingerprint((48, 64), config),
        {"kernel": "packed-jnp", "temporal_depth": 2, "termination_block": 8},
    )
    select.reset()
    plan = select.engine_plan((48, 64), config)
    assert plan == space.EnginePlan(kernel="packed-jnp", temporal_depth=2,
                                    termination_block=8)
    runner = engine._build_runner((48, 64), config, None, "auto",
                                  segmented=False, packed_state=False)
    assert runner.kernel_name == "packed-jnp"
    grid = _grid()
    final, gen = runner(jax.device_put(grid))
    expect = oracle.run(grid, config)
    assert np.array_equal(np.asarray(final), expect.grid)
    assert int(gen) == expect.generations


def test_unsupported_plan_kernel_ignored_loudly(plan_cache, caplog):
    """A plan naming a kernel the shape can't run (stale hardware, hand
    edit) degrades to the default ladder with a warning, not a crash."""
    config = GameConfig(gen_limit=25)
    store = plans.PlanStore(plan_cache)
    # 48x50 does not pack (width % 32 != 0): the packed kernel is invalid.
    store.put(select.engine_fingerprint((48, 50), config),
              {"kernel": "packed"})
    select.reset()
    with caplog.at_level(logging.WARNING, logger="gol_tpu.engine"):
        runner = engine._build_runner((48, 50), config, None, "auto",
                                      segmented=False, packed_state=False)
    assert any("ignoring the plan" in rec.message for rec in caplog.records)
    grid = _grid(48, 50)
    final, gen = runner(jax.device_put(grid))
    expect = oracle.run(grid, config)
    assert np.array_equal(np.asarray(final), expect.grid)
    assert int(gen) == expect.generations


def test_packed_state_plan_rejects_byte_kernel(plan_cache, caplog):
    config = GameConfig(gen_limit=10)
    with caplog.at_level(logging.WARNING, logger="gol_tpu.engine"):
        runner = engine._build_runner(
            (48, 64), config, None, "packed", segmented=False,
            packed_state=True, plan=space.EnginePlan(kernel="lax"),
        )
    assert any("packed word state" in rec.message for rec in caplog.records)
    assert runner.kernel_name == "packed"


@pytest.mark.parametrize("convention", ["c", "cuda"])
@pytest.mark.parametrize("depth", [1, 2, 4, 8])
def test_temporal_depth_bit_exact(convention, depth):
    """Every depth is a pure performance knob: same grid, same count."""
    config = GameConfig(gen_limit=30, convention=convention)
    grid = _grid()
    plan = space.EnginePlan(kernel="packed-jnp", temporal_depth=depth)
    runner = engine._build_runner((48, 64), config, None, "packed-jnp",
                                  segmented=False, packed_state=False,
                                  plan=plan)
    final, gen = runner(jax.device_put(grid))
    expect = oracle.run(grid, config)
    assert np.array_equal(np.asarray(final), expect.grid)
    assert int(gen) == expect.generations


@pytest.mark.parametrize("convention", ["c", "cuda"])
@pytest.mark.parametrize("block", [8, 64])
def test_termination_block_bit_exact(convention, block):
    """Still-life early exit lands on the same generation at any block size
    (the blocked-loop exactness argument, now under a tuned block)."""
    config = GameConfig(gen_limit=200, convention=convention)
    grid = np.zeros((48, 64), np.uint8)
    grid[10:12, 10:12] = 1  # block still life -> similarity exit
    plan = space.EnginePlan(kernel="packed-jnp", termination_block=block)
    runner = engine._build_runner((48, 64), config, None, "packed-jnp",
                                  segmented=False, packed_state=False,
                                  plan=plan)
    final, gen = runner(jax.device_put(grid))
    expect = oracle.run(grid, config)
    assert np.array_equal(np.asarray(final), expect.grid)
    assert int(gen) == expect.generations


def test_with_temporal_depth_validity():
    lax = get_kernel("lax")
    assert with_temporal_depth(lax, 1) is lax
    with pytest.raises(ValueError, match="no fused pass"):
        with_temporal_depth(lax, 4)
    packed = get_kernel("packed")
    assert with_temporal_depth(packed, packed.multi_gens) is packed
    stripped = with_temporal_depth(packed, 1)
    assert stripped.fused_multi is None and stripped.multi_gens == 1
    composed = with_temporal_depth(packed, 4)
    assert composed.multi_gens == 4
    assert composed.supports_multi(48, 64, SINGLE_DEVICE) == \
        packed.supports(48, 64, SINGLE_DEVICE)


# ---------------------------------------------------------------------------
# Serve plan: batcher geometry consult.
# ---------------------------------------------------------------------------


def _put_serve(path, plan_dict):
    plans.PlanStore(path).put(select.serve_fingerprint(), plan_dict)
    select.reset()
    batcher._reset_plan()


def test_serve_plan_changes_geometry(plan_cache):
    _put_serve(plan_cache, {"pad_quantum": 64, "batch_ladder": [1, 8, 64]})
    assert batcher.pad_dim(1) == 64
    assert batcher.pad_dim(65) == 128
    assert [batcher.pad_batch(n) for n in (1, 2, 8, 9, 64)] == \
        [1, 8, 8, 64, 64]
    # Bucket routing composes: a 48x48 board pads to the tuned 64x64 canvas.
    from gol_tpu.serve.jobs import new_job

    key = batcher.bucket_for(new_job(48, 48, np.zeros((48, 48), np.uint8)))
    assert (key.height, key.width) == (64, 64)
    assert key.kernel == "masked"


@pytest.mark.parametrize("bad", [
    {"pad_quantum": 48, "batch_ladder": [1, 8, 64]},  # quantum % 32 != 0
    {"pad_quantum": 32, "batch_ladder": [1, 8, 32]},  # top rung != cap
    {"pad_quantum": 32, "batch_ladder": [2, 8, 64]},  # no rung 1
    {"pad_quantum": 32, "batch_ladder": [1, 8, 8, 64]},  # not ascending
])
def test_invalid_serve_plan_rejected_loudly(plan_cache, bad, caplog):
    with caplog.at_level(logging.WARNING, logger="gol_tpu.tune.select"):
        _put_serve(plan_cache, bad)
        assert batcher.pad_dim(1) == 32
        assert batcher.pad_batch(3) == 4
    assert any("bucket" in rec.message for rec in caplog.records)


def test_warm_actually_compiles(plan_cache):
    """batcher.warm must dispatch (jit is lazy): after warm, the first real
    batch of that bucket reuses the compiled program instead of tracing."""
    import time

    from gol_tpu.serve.jobs import new_job

    board = _grid(40, 40, seed=3)
    job = new_job(40, 40, board, gen_limit=5)
    key = batcher.bucket_for(job)
    batcher.warm(key, batch=1)
    t0 = time.perf_counter()
    first = batcher.run_batch(key, [job])
    first_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    batcher.run_batch(key, [job])
    second_s = time.perf_counter() - t0
    # A cold trace+compile is orders of magnitude above a warm dispatch;
    # 5x headroom keeps this robust to CI noise while still catching a
    # warm() that builds the callable without compiling it.
    assert first_s < max(5 * second_s, 0.25), (first_s, second_s)
    exp = oracle.run(board, GameConfig(gen_limit=5))
    assert np.array_equal(first[0].grid, exp.grid)


def test_warm_plans_survives_corrupt_entries(plan_cache, capsys):
    """A stale/hand-edited warm entry degrades loudly, never aborts boot."""
    from gol_tpu.cli import _warm_plans

    _put_serve(plan_cache, {
        "pad_quantum": 32, "batch_ladder": [1, 2, 4, 8, 16, 32, 64],
        "warm": [{"height": "big", "width": 48},
                 {"height": 48, "width": 48, "convention": "not-a-conv"},
                 {"height": 40, "width": 40, "convention": "c"}],
    })
    _warm_plans()  # must not raise
    err = capsys.readouterr().err
    assert err.count("failed") == 2
    assert "warmed bucket" in err


def test_warm_entries(plan_cache):
    _put_serve(plan_cache, {
        "pad_quantum": 32, "batch_ladder": [1, 2, 4, 8, 16, 32, 64],
        "warm": [{"height": 48, "width": 48, "convention": "c"},
                 {"bogus": True}],
    })
    entries = select.warm_entries()
    assert entries == [{"height": 48, "width": 48, "convention": "c"}]


# ---------------------------------------------------------------------------
# Measurement machinery.
# ---------------------------------------------------------------------------


def test_trimmed_median():
    assert measure.trimmed_median([3.0]) == 3.0
    assert measure.trimmed_median([1.0, 2.0]) == 1.5
    # The outlier (100) is trimmed before the median.
    assert measure.trimmed_median([1.0, 2.0, 3.0, 100.0]) == 2.5
    with pytest.raises(ValueError):
        measure.trimmed_median([])


def test_pick_winner_excludes_gate_failures(caplog):
    ok = measure.Trial("slow-ok", space.EnginePlan(kernel="lax"),
                       2.0, [2.0], "ok")
    cheat = measure.Trial("fast-wrong", space.EnginePlan(kernel="packed"),
                          None, [], "mismatch")
    with caplog.at_level(logging.WARNING, logger="gol_tpu.tune.measure"):
        winner = measure._pick_winner([ok, cheat], "slow-ok")
    assert winner is ok
    assert any("gate FAILED" in rec.message for rec in caplog.records)
    with pytest.raises(RuntimeError, match="no candidate passed"):
        measure._pick_winner([cheat], "fast-wrong")


def test_pick_winner_keeps_default_within_noise():
    default = measure.Trial("default", space.EnginePlan(kernel="packed"),
                            1.00, [1.0], "ok")
    rival = measure.Trial("rival", space.EnginePlan(kernel="lax"),
                          0.99, [0.99], "ok")
    assert measure._pick_winner([default, rival], "default") is default
    clear_win = measure.Trial("rival2", space.EnginePlan(kernel="lax"),
                              0.5, [0.5], "ok")
    assert measure._pick_winner([default, clear_win], "default") is clear_win


def test_engine_search_smoke(plan_cache):
    """Tiny end-to-end search: every candidate gated, winner >= default."""
    config = GameConfig(gen_limit=12)
    result = measure.run_engine_search(32, 32, config, quick=True,
                                       iters=2, warmup=1)
    assert result.default_label == result.trials[0].label
    assert all(t.gate == "ok" for t in result.trials)
    assert result.speedup >= 1.0
    # The winner round-trips through the store and the consult.
    store = plans.PlanStore(plan_cache)
    store.put(select.engine_fingerprint((32, 32), config),
              result.winner.to_dict())
    select.reset()
    got = select.engine_plan((32, 32), config)
    if result.winner == space.EnginePlan():
        assert got is None
    else:
        assert got == result.winner


def test_search_result_report():
    config = GameConfig(gen_limit=10)
    result = measure.run_engine_search(32, 32, config, quick=True,
                                       iters=2, warmup=1)
    text = measure.render_report([result])
    assert "winner" in text and result.winner.label() in text
    payload = result.to_dict()
    assert payload["gates_all_ok"] is True
    assert payload["tuned_vs_default"] >= 1.0


# ---------------------------------------------------------------------------
# Space sanity.
# ---------------------------------------------------------------------------


def test_engine_candidates_validity():
    ctx = space.TuneContext(height=48, width=64, convention="c",
                            packed_state=False)
    cands = space.engine_candidates(ctx)
    assert cands[0] == space.default_engine_plan(ctx)
    names = {c.kernel for c in cands}
    assert "lax" in names and "packed" in names
    assert "pallas" not in names  # TPU-only off TPU
    for cand in cands:
        if cand.kernel == "lax":
            assert cand.temporal_depth in (None, 1)
        assert cand.band_bytes is None  # TPU-only axis
    # An unpackable width drops the packed family entirely.
    odd = dataclasses.replace(ctx, width=50)
    assert {c.kernel for c in space.engine_candidates(odd)} == {"lax"}


def test_engine_plan_from_dict_tolerates_junk():
    plan = space.EnginePlan.from_dict(
        {"kernel": "packed", "temporal_depth": "4", "unknown_field": 7,
         "band_bytes": None}
    )
    assert plan == space.EnginePlan(kernel="packed", temporal_depth=4)


def test_serve_candidates_all_valid():
    cands = space.serve_candidates()
    assert cands[0] == space.DEFAULT_SERVE_PLAN
    assert all(space.valid_serve_plan(c, 64) for c in cands)
