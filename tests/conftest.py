"""Test harness: 8 virtual CPU devices so mesh/ppermute/psum paths run anywhere.

This is the reference's `mpiexec -n <x>` (README.md:54-57) without a cluster:
XLA hosts N fake devices on CPU, and the same shard_map code that rides ICI on
a pod runs unit-tested here.

Note: this environment preloads jax at interpreter start (sitecustomize), so
JAX_PLATFORMS in os.environ is already consumed; the platform must be forced
through jax.config instead. XLA_FLAGS is still honored because backends
initialize lazily, on the first jax.devices() call.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

if not os.environ.get("GOL_TPU_HW"):
    jax.config.update("jax_platforms", "cpu")
# else: hardware lane — leave the attached backend alone so
# tests/test_tpu_hw.py runs on the real chip:
#   GOL_TPU_HW=1 python -m pytest tests/test_tpu_hw.py -q
# (run only that module; the CPU-mesh suites would needlessly recompile
# everything for the TPU).

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
