"""Test harness: 8 virtual CPU devices so mesh/ppermute/psum paths run anywhere.

This is the reference's `mpiexec -n <x>` (README.md:54-57) without a cluster:
XLA hosts N fake devices on CPU, and the same shard_map code that rides ICI on
a pod runs unit-tested here. Must run before any jax import.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
