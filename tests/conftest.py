"""Test harness: 8 virtual CPU devices so mesh/ppermute/psum paths run anywhere.

This is the reference's `mpiexec -n <x>` (README.md:54-57) without a cluster:
XLA hosts N fake devices on CPU, and the same shard_map code that rides ICI on
a pod runs unit-tested here.

Note: this environment preloads jax at interpreter start (sitecustomize), so
JAX_PLATFORMS in os.environ is already consumed; the platform must be forced
through jax.config instead. XLA_FLAGS is still honored because backends
initialize lazily, on the first jax.devices() call.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

if not os.environ.get("GOL_TPU_HW"):
    # Align the ENV VAR with the forced platform, not just jax.config: the
    # CLI re-applies JAX_PLATFORMS from the environment at import time
    # (gol_tpu/platform_env.py), so a stale accelerator value there would
    # override this suite's CPU forcing the moment a test imports cli.
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
# else: hardware lane — leave the attached backend alone so
# tests/test_tpu_hw.py runs on the real chip:
#   GOL_TPU_HW=1 python -m pytest tests/test_tpu_hw.py -q
# (run only that module; the CPU-mesh suites would needlessly recompile
# everything for the TPU).

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Isolate the suite from any real autotune plan cache on this machine: the
# kernel-selection and batcher-geometry tests pin the DEFAULT ladders, and a
# developer's ~/.cache/gol_tpu/plans.json would silently reroute them. Tests
# that exercise plans point GOL_PLAN_CACHE at their own tmp files.
import tempfile as _tempfile

os.environ["GOL_PLAN_CACHE"] = os.path.join(
    _tempfile.mkdtemp(prefix="gol_test_plans_"), "plans.json"
)


# ---------------------------------------------------------------------------
# Hardware-lane evidence artifact: GOL_TPU_HW=1 runs record every hardware
# test's outcome to benchmarks/tpu_hw_r<N>.json so the "verified on v5e"
# claims in kernel comments are auditable files, not git-log prose.
_HW_ARTIFACT_ROUND = 5
_hw_results: list[dict] = []


def pytest_runtest_logreport(report):
    if not os.environ.get("GOL_TPU_HW"):
        return
    # Record calls AND setup/teardown errors — a fixture blow-up must show
    # as an error in the artifact, not vanish into an all-green payload.
    if report.when == "call":
        outcome = report.outcome
    elif report.failed:
        outcome = "error"
    else:
        return
    _hw_results.append(
        {
            "test": report.nodeid,
            "outcome": outcome,
            "duration_s": round(report.duration, 3),
        }
    )


def pytest_sessionfinish(session, exitstatus):
    if not os.environ.get("GOL_TPU_HW") or not _hw_results:
        return
    import json
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "benchmarks", f"tpu_hw_r{_HW_ARTIFACT_ROUND:02d}.json")
    # A partial run (pytest -k ...) must not clobber fuller evidence.
    if os.path.exists(path):
        try:
            with open(path) as f:
                prior = json.load(f)
            if len(prior.get("tests", [])) > len(_hw_results):
                path = path.replace(".json", "-partial.json")
        except (OSError, ValueError):
            pass
    payload = {
        "lane": "GOL_TPU_HW=1 pytest tests/test_tpu_hw.py",
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "exitstatus": int(exitstatus),
        "passed": sum(1 for r in _hw_results if r["outcome"] == "passed"),
        "failed": sum(
            1 for r in _hw_results if r["outcome"] in ("failed", "error")
        ),
        "tests": _hw_results,
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
