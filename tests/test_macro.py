"""Macrocell engine (gol_tpu/macro) tests.

The acceptance surface of ISSUE 17:

- hash-consing: two stamps of the same subtree are ONE object, and node
  identity is decomposition-independent;
- advance byte-identity vs the sparse engine at checkpointed generations
  for glider/gosper-gun/r-pentomino/acorn, BOTH conventions, including
  non-power-of-two generation counts;
- early-exit parity (empty and similar, the convention-specific
  accounting included) against the per-generation sparse loop;
- memo restart-hits through the DiskCAS tier, journal replay of macro
  jobs (the SIGKILL shape), and eviction under the `gol gc` budget.
"""

import json
import os
import time

import numpy as np
import pytest

from gol_tpu.cache import gc as cas_gc
from gol_tpu.config import Convention, GameConfig
from gol_tpu.macro import (
    MacroMemo,
    MacroPlaneError,
    NodeStore,
    MacroUniverse,
    auto_macro,
    simulate_macro,
)
from gol_tpu.serve.jobs import DONE, JobJournal, new_job
from gol_tpu.serve.scheduler import Scheduler
from gol_tpu.sparse import SparseBoard, simulate_sparse

PATTERNS_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "patterns")

CONVENTIONS = (Convention.C, Convention.CUDA)

GLIDER_RLE = "x = 3, y = 3, rule = B3/S23\nbob$2bo$3o!"
# An L-tromino: becomes a block at generation 1 and stays — the minimal
# nonempty SIMILAR-exit fixture.
PRE_BLOCK_RLE = "x = 2, y = 2, rule = B3/S23\n2o$ob!"


def _pattern(name: str) -> str:
    with open(os.path.join(PATTERNS_DIR, name + ".rle"),
              encoding="utf-8") as f:
        return f.read()


def _board(rle: str, size: int, tile: int, at: int) -> SparseBoard:
    return SparseBoard.from_rle(rle, size, size, tile, x=at, y=at)


def _assert_parity(rle, size, tile, at, config, checkpoints=()):
    """The byte-gate: macro vs the sparse per-generation loop — final
    cells, generation count, exit reason, and the exact state at every
    checkpointed generation."""
    seen = {}
    macro = simulate_macro(
        _board(rle, size, tile, at), config, checkpoints=checkpoints,
        on_checkpoint=lambda g, b: seen.__setitem__(g, b),
    )
    sparse = simulate_sparse(_board(rle, size, tile, at), config)
    assert macro.generations == sparse.generations
    assert macro.exit_reason == sparse.exit_reason
    assert macro.board == sparse.board
    for g in checkpoints:
        if g > config.gen_limit:
            assert g not in seen
            continue
        ref = simulate_sparse(_board(rle, size, tile, at),
                              GameConfig(gen_limit=g,
                                         convention=config.convention))
        assert seen[g] == ref.board, f"checkpoint {g} diverged"
    return macro


# ---------------------------------------------------------------------------
# Hash-consing
# ---------------------------------------------------------------------------


class TestNodeStore:
    def test_two_stamps_one_object(self):
        """The interning law: identical subtrees — built through different
        call sequences — are the same Python object at every level."""
        store = NodeStore(4)
        rng = np.random.default_rng(7)
        cells = (rng.random((4, 4)) < 0.5).astype(np.uint8)
        a = store.leaf(cells)
        b = store.leaf(cells.copy())
        assert a is b
        e = store.empty(0)
        n1 = store.node(a, e, e, a)
        n2 = store.node(b, store.leaf(np.zeros((4, 4), np.uint8)), e, b)
        assert n1 is n2
        assert store.node(n1, n1, n2, n2) is store.node(n2, n2, n1, n1)

    def test_interning_is_decomposition_independent(self):
        """A universe built from a board and one rebuilt from the dense
        flattening share every node — content decides identity, not the
        construction path."""
        store = NodeStore(4)
        board = SparseBoard.from_rle(_pattern("glider"), 32, 32, 4,
                                     x=13, y=9)
        u = MacroUniverse.from_board(store, board)
        rebuilt = store.from_dense(u.root.to_dense(4))
        assert rebuilt is u.root

    def test_empty_is_canonical_per_level(self):
        store = NodeStore(4)
        z = store.leaf(np.zeros((4, 4), np.uint8))
        assert z is store.empty(0)
        assert store.node(z, z, z, z) is store.empty(1)

    def test_board_round_trip(self):
        board = SparseBoard.from_rle(_pattern("gosper_gun"), 64, 64, 8,
                                     x=11, y=23)
        store = NodeStore(8)
        u = MacroUniverse.from_board(store, board)
        assert u.population() == board.population()
        assert u.to_board() == board

    def test_leaf_constraints(self):
        with pytest.raises(ValueError):
            NodeStore(2)  # below the board's MIN_TILE
        with pytest.raises(ValueError):
            NodeStore(5)  # odd leaves cannot split


# ---------------------------------------------------------------------------
# Byte-identity vs the sparse engine
# ---------------------------------------------------------------------------


class TestMacroParity:
    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_glider_checkpoints(self, convention):
        _assert_parity(
            GLIDER_RLE, 128, 8, 60,
            GameConfig(gen_limit=137, convention=convention),
            checkpoints=(1, 30, 64, 100, 137),
        )

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_gosper_gun_checkpoints(self, convention):
        """Non-power-of-two limit, non-power-of-two checkpoints, a
        growing population — the canonical deep-time fixture."""
        _assert_parity(
            _pattern("gosper_gun"), 512, 8, 200,
            GameConfig(gen_limit=210, convention=convention),
            checkpoints=(1, 31, 137, 209, 210, 1000),
        )

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_r_pentomino_checkpoints(self, convention):
        _assert_parity(
            _pattern("r_pentomino"), 512, 16, 220,
            GameConfig(gen_limit=300, convention=convention),
            checkpoints=(100, 255, 300),
        )

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_acorn_checkpoints(self, convention):
        _assert_parity(
            _pattern("acorn"), 512, 16, 200,
            GameConfig(gen_limit=250, convention=convention),
            checkpoints=(3, 97, 250),
        )

    @pytest.mark.parametrize("convention", CONVENTIONS)
    @pytest.mark.parametrize("gens", (0, 1, 5, 100))
    def test_tiny_generation_counts(self, convention, gens):
        _assert_parity(GLIDER_RLE, 64, 8, 30,
                       GameConfig(gen_limit=gens, convention=convention))


# ---------------------------------------------------------------------------
# Early-exit parity
# ---------------------------------------------------------------------------


class TestMacroExits:
    @pytest.mark.parametrize("convention", CONVENTIONS)
    @pytest.mark.parametrize("gens", (129, 130, 131, 400))
    def test_diehard_empty_exit(self, convention, gens):
        """Diehard dies at generation 130: the empty exit fires with the
        convention's own accounting (C reports the empty board at 130;
        CUDA reports the last NONEMPTY board at 129)."""
        macro = _assert_parity(
            _pattern("diehard"), 512, 8, 200,
            GameConfig(gen_limit=gens, convention=convention),
        )
        if gens >= 130:
            assert macro.exit_reason == "empty"

    @pytest.mark.parametrize("convention", CONVENTIONS)
    @pytest.mark.parametrize("frequency", (1, 2, 5, 7))
    def test_still_life_similar_exit(self, convention, frequency):
        for gens in (0, 1, 4, 5, 6, 60):
            _assert_parity(
                PRE_BLOCK_RLE, 64, 8, 30,
                GameConfig(gen_limit=gens, convention=convention,
                           similarity_frequency=frequency),
            )

    @pytest.mark.parametrize("convention", CONVENTIONS)
    def test_similarity_disabled(self, convention):
        macro = _assert_parity(
            PRE_BLOCK_RLE, 64, 8, 30,
            GameConfig(gen_limit=50, convention=convention,
                       check_similarity=False),
        )
        assert macro.exit_reason == "gen_limit"

    @pytest.mark.parametrize("convention", CONVENTIONS)
    @pytest.mark.parametrize("frequency", (1, 3))
    def test_initially_empty_universe(self, convention, frequency):
        for gens in (0, 1, 10):
            config = GameConfig(gen_limit=gens, convention=convention,
                                similarity_frequency=frequency)
            macro = simulate_macro(SparseBoard(64, 64, 8), config)
            sparse = simulate_sparse(SparseBoard(64, 64, 8), config)
            assert macro.generations == sparse.generations
            assert macro.exit_reason == sparse.exit_reason
            assert macro.board == sparse.board

    def test_plane_error_at_the_seam(self):
        """A pattern whose light cone reaches the universe edge raises the
        plane/torus divergence error instead of silently drifting from
        the (toroidal) sparse answer."""
        board = SparseBoard.from_rle(GLIDER_RLE, 32, 32, 4, x=1, y=1)
        with pytest.raises(MacroPlaneError, match="--engine sparse"):
            simulate_macro(board, GameConfig(gen_limit=200))


# ---------------------------------------------------------------------------
# Memo: DiskCAS restarts + `gol gc` eviction
# ---------------------------------------------------------------------------


class TestMacroMemo:
    def test_restart_hits_warm_cas(self, tmp_path):
        """A fresh process (new store, new memo — only the CAS directory
        survives) re-runs the same deep question on cache hits: the
        content tier IS the cross-restart knowledge base."""
        cas = str(tmp_path / "cas")
        config = GameConfig(gen_limit=210)
        board_spec = (_pattern("gosper_gun"), 512, 8, 200)

        memo1 = MacroMemo(NodeStore(8), cas_dir=cas)
        cold = simulate_macro(_board(*board_spec), config, memo1)
        assert cold.stats.cas_hits == 0
        assert os.listdir(cas)

        memo2 = MacroMemo(NodeStore(8), cas_dir=cas)  # "restart"
        warm = simulate_macro(_board(*board_spec), config, memo2)
        assert warm.board == cold.board
        assert warm.stats.cas_hits > 0
        assert warm.stats.leaf_gen_steps < cold.stats.leaf_gen_steps

    def test_gc_budget_evicts_macro_entries(self, tmp_path):
        """`gol gc` over a macro CAS directory: entries are evicted to
        budget with the standard report, and a post-GC run still answers
        correctly (recomputing what was evicted)."""
        cas = str(tmp_path / "cas")
        config = GameConfig(gen_limit=137)
        memo = MacroMemo(NodeStore(8), cas_dir=cas)
        ref = simulate_macro(_board(GLIDER_RLE, 128, 8, 60), config, memo)

        def entries():
            found = []
            for root, _dirs, names in os.walk(cas):
                found += [n for n in names if not n.startswith(".")]
            return found

        files = entries()
        assert len(files) > 1
        report = cas_gc.collect(cas, budget=1, apply=True)
        assert report.evicted
        assert len(entries()) < len(files)
        memo2 = MacroMemo(NodeStore(8), cas_dir=cas)
        again = simulate_macro(_board(GLIDER_RLE, 128, 8, 60), config,
                               memo2)
        assert again.board == ref.board

    def test_memo_keys_scoped_by_time_and_leaf(self):
        """The content key carries the jump size and the leaf edge: the
        same node advanced by different t must never collide."""
        memo = MacroMemo(NodeStore(8))
        board = SparseBoard.from_rle(GLIDER_RLE, 32, 32, 8, x=14, y=14)
        u = MacroUniverse.from_board(memo.store, board)
        k1 = memo.key(u.root, 1)
        k2 = memo.key(u.root, 2)
        assert k1 != k2
        assert k1.endswith("-8") and "-1-" in k1


# ---------------------------------------------------------------------------
# Serve lane: macro jobs, journal replay (the SIGKILL shape)
# ---------------------------------------------------------------------------


def _macro_job(**over):
    spec = dict(rle=GLIDER_RLE, place_x=30, place_y=30, tile=8,
                gen_limit=100, macro=True)
    spec.update(over)
    return new_job(128, 128, None, **spec)


def _await(jobs, timeout=60):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if all(j.state == DONE for j in jobs):
            return
        time.sleep(0.01)
    raise AssertionError(
        f"jobs stuck: {[(j.id, j.state, j.error) for j in jobs]}"
    )


class TestMacroServe:
    def test_macro_job_byte_identical_to_sparse_job(self):
        sched = Scheduler(flush_age=0.01)
        sched.start()
        try:
            macro = sched.submit(_macro_job())
            sparse = sched.submit(_macro_job(macro=False))
            _await([macro, sparse])
        finally:
            sched.stop()
        assert macro.result.rle == sparse.result.rle
        assert macro.result.generations == sparse.result.generations
        assert macro.result.exit_reason == sparse.result.exit_reason

    def test_macro_flag_validation(self):
        with pytest.raises(TypeError, match="JSON boolean"):
            _macro_job(macro="true")
        with pytest.raises(ValueError, match="sparse input form"):
            new_job(8, 8, np.zeros((8, 8), np.uint8), macro=True)

    def test_journal_replay_reruns_macro(self, tmp_path):
        """The SIGKILL-shaped auto-resume: a journaled-but-unfinished
        macro job replays from its spec — engine flag included — and
        re-runs to a byte-identical result on the next boot."""
        journal = JobJournal(str(tmp_path))
        sched = Scheduler(journal=journal, flush_age=0.01)  # never started
        job = sched.submit(_macro_job())
        journal.close()
        with open(journal.path, encoding="utf-8") as f:
            rec = json.loads(f.readline())
        assert rec["job"]["macro"] is True
        assert "cells" not in rec["job"]

        journal2 = JobJournal(str(tmp_path))
        replay = journal2.replay()
        assert [j.id for j in replay.pending] == [job.id]
        assert replay.pending[0].macro is True
        sched2 = Scheduler(journal=journal2, flush_age=0.01)
        sched2.resubmit_replayed(replay.pending)
        sched2.start()
        try:
            replayed = sched2.job(job.id)
            _await([replayed])
        finally:
            sched2.stop()
        journal2.close()
        direct = simulate_sparse(
            SparseBoard.from_rle(GLIDER_RLE, 128, 128, 8, x=30, y=30),
            GameConfig(gen_limit=100),
        )
        assert replayed.result.rle == direct.board.to_rle()
        assert replayed.result.generations == direct.generations


# ---------------------------------------------------------------------------
# Auto crossover
# ---------------------------------------------------------------------------


class TestAutoMacro:
    def test_deep_centered_run_upgrades(self):
        assert auto_macro(1 << 16, 1 << 16, 256, 20_000,
                          (30_000, 30_000, 30_100, 30_100))

    def test_shallow_run_stays_sparse(self):
        assert not auto_macro(1 << 16, 1 << 16, 256, 100,
                              (30_000, 30_000, 30_100, 30_100))

    def test_seam_risk_stays_sparse(self):
        # Margin (~2k cells) below the generation count: the run COULD
        # reach the torus seam, so auto must not pick a raising lane.
        assert not auto_macro(1 << 16, 1 << 16, 256, 20_000,
                              (2_000, 30_000, 2_100, 30_100))

    def test_odd_tile_and_unknown_bbox_stay_sparse(self):
        assert not auto_macro(1 << 16, 1 << 16, 255, 20_000,
                              (30_000, 30_000, 30_100, 30_100))
        assert not auto_macro(1 << 16, 1 << 16, 256, 20_000, None)
