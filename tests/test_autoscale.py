"""The elastic fleet (gol_tpu/fleet/autoscale.py + affinity.py): weighted
placement, scale-event disruption bounds, the autoscaler decision loop,
drain->retire, the tuned sparse auto threshold, and the shard-across
membership refresh.

The load-bearing pins:

- weighted HRW with EQUAL weights is byte-identical to plain HRW (it
  delegates — affinity off and affinity-on-with-no-weights are the same
  code path);
- a scale event moves ONLY the affected worker's buckets: adding a worker
  moves exactly the buckets it now owns, retiring one moves exactly its
  buckets, and the survivors' relative order never changes (the
  compile-budget story under autoscaling);
- scale-down NEVER loses a job: ``Fleet.retire`` aborts unless the drain
  completed, and the partition's journal keeps every done record.
"""

import json
import os
import threading
import types

import pytest

from gol_tpu.fleet import affinity, placement
from gol_tpu.fleet.autoscale import (
    DOWN, HOLD, UP, AutoscaleConfig, Autoscaler,
)
from gol_tpu.fleet.workers import Fleet, Worker
from gol_tpu.obs import history as obs_history
from gol_tpu.obs.registry import Registry


def _labels(n=40):
    return [f"{32 * i}x{32 * i}/c" for i in range(1, n + 1)]


class TestWeightedPlacement:
    def test_equal_weights_byte_identical_to_plain(self):
        """The --affinity pin: all-equal weights (any value) must rank
        exactly like plain HRW — rank_weighted delegates to rank."""
        ids = ["w0", "w1", "w2", "w3"]
        for value in (1.0, 2.5, 7):
            weights = {w: value for w in ids}
            for lbl in _labels():
                assert placement.rank_weighted(lbl, weights) == \
                    placement.rank(lbl, ids)

    def test_deterministic_and_complete(self):
        weights = {"w0": 1.0, "w1": 4.0, "w2": 2.0}
        for lbl in _labels(10):
            first = placement.rank_weighted(lbl, weights)
            assert first == placement.rank_weighted(lbl, weights)
            assert sorted(first) == sorted(weights)

    def test_weight_biases_ownership_proportionally(self):
        """An 8x-weight worker owns ~8x the buckets (the 2-core vs
        8-core slice story). Loose bounds — this is a hash distribution,
        not an exact split."""
        weights = {"w0": 1.0, "w1": 8.0, "w2": 1.0}
        owners = {w: 0 for w in weights}
        for lbl in _labels(400):
            owners[placement.rank_weighted(lbl, weights)[0]] += 1
        assert owners["w1"] > 4 * owners["w0"]
        assert owners["w1"] > 4 * owners["w2"]
        assert owners["w0"] > 0 and owners["w2"] > 0

    def test_non_positive_weights_default(self):
        """A zero/negative/garbage weight is the 1.0 default, not a
        crash and not never-place-here (membership's job)."""
        got = placement.rank_weighted("64x64/c", {"w0": 0.0, "w1": -3.0})
        assert sorted(got) == ["w0", "w1"]
        # All non-positive -> all default -> the plain-HRW delegation.
        assert got == placement.rank("64x64/c", ["w0", "w1"])


class TestScaleEventDisruption:
    """The ISSUE's placement-disruption contract: every scale event moves
    only the affected buckets, for BOTH the plain and weighted layers."""

    def _assert_only_victims_move(self, rank_before, rank_after, added=None,
                                  removed=None):
        moved = []
        for lbl in _labels():
            before, after = rank_before(lbl), rank_after(lbl)
            if removed is not None:
                # Survivors keep their relative order in full.
                assert after == [w for w in before if w != removed], lbl
                if before[0] == removed:
                    moved.append(lbl)
            if added is not None:
                assert [w for w in after if w != added] == before, lbl
                if after[0] == added:
                    moved.append(lbl)
        # A scale event that moves nothing at all would be suspicious too:
        # the hash must actually hand the new/removed worker some buckets.
        assert moved

    def test_add_worker_moves_only_its_buckets_plain(self):
        ids = ["w0", "w1", "w2"]
        self._assert_only_victims_move(
            lambda lbl: placement.rank(lbl, ids),
            lambda lbl: placement.rank(lbl, ids + ["w3"]),
            added="w3",
        )

    def test_retire_worker_moves_only_its_buckets_plain(self):
        ids = ["w0", "w1", "w2", "w3"]
        self._assert_only_victims_move(
            lambda lbl: placement.rank(lbl, ids),
            lambda lbl: placement.rank(lbl, [w for w in ids if w != "w1"]),
            removed="w1",
        )

    def test_add_worker_moves_only_its_buckets_weighted(self):
        weights = {"w0": 2.0, "w1": 4.0, "w2": 1.0}
        grown = {**weights, "w3": 4.0}
        self._assert_only_victims_move(
            lambda lbl: placement.rank_weighted(lbl, weights),
            lambda lbl: placement.rank_weighted(lbl, grown),
            added="w3",
        )

    def test_retire_worker_moves_only_its_buckets_weighted(self):
        weights = {"w0": 2.0, "w1": 4.0, "w2": 1.0, "w3": 3.0}
        shrunk = {w: v for w, v in weights.items() if w != "w2"}
        self._assert_only_victims_move(
            lambda lbl: placement.rank_weighted(lbl, weights),
            lambda lbl: placement.rank_weighted(lbl, shrunk),
            removed="w2",
        )

    def test_reweighting_one_worker_never_reshuffles_third_parties(self):
        """Adopting an advertised weight for one worker must not move a
        bucket between two OTHER workers (the weighted-rendezvous analog
        of minimal disruption)."""
        weights = {"w0": 2.0, "w1": 4.0, "w2": 3.0}
        bumped = {**weights, "w1": 8.0}
        for lbl in _labels():
            before = placement.rank_weighted(lbl, weights)
            after = placement.rank_weighted(lbl, bumped)
            assert [w for w in after if w != "w1"] == \
                [w for w in before if w != "w1"], lbl


class TestAffinityWeights:
    def test_pinned_weight_wins_and_suppresses_advertised(self):
        """Cores and cells/s are different units: one pinned weight in
        the pool switches the WHOLE pool to pinned-or-default."""
        pool = [
            Worker(id="w0", weight=8.0, advertised_weight=1e8),
            Worker(id="w1", advertised_weight=5e7),
            Worker(id="w2"),
        ]
        assert affinity.weights_for(pool) == {
            "w0": 8.0, "w1": affinity.DEFAULT_WEIGHT,
            "w2": affinity.DEFAULT_WEIGHT,
        }

    def test_advertised_weights_used_when_nothing_pinned(self):
        pool = [
            Worker(id="w0", advertised_weight=2e8),
            Worker(id="w1", advertised_weight=1e8),
            Worker(id="w2"),
        ]
        assert affinity.weights_for(pool) == {
            "w0": 2e8, "w1": 1e8, "w2": affinity.DEFAULT_WEIGHT,
        }

    def test_all_default_is_plain_hrw(self):
        pool = [Worker(id="w0"), Worker(id="w1"), Worker(id="w2")]
        weights = affinity.weights_for(pool)
        for lbl in _labels(10):
            assert placement.rank_weighted(lbl, weights) == \
                placement.rank(lbl, ["w0", "w1", "w2"])

    def test_garbage_weights_degrade_to_default(self):
        pool = [Worker(id="w0", weight=float("nan") if False else None,
                       advertised_weight="fast")]
        assert affinity.weights_for(pool) == {"w0": affinity.DEFAULT_WEIGHT}


# -- autoscaler unit rig ----------------------------------------------------

class _StubFleet:
    """Just enough Fleet for the decision loop: live workers + recording
    actuators whose behavior the test scripts."""

    def __init__(self, n=1):
        self._workers = [Worker(id=f"w{i}", url=f"http://w{i}")
                         for i in range(n)]
        self.spawned = 0
        self.retired = []
        self.retire_ok = True
        self.spawn_error = None

    def workers(self):
        return list(self._workers)

    def spawn(self, *a, **k):
        if self.spawn_error is not None:
            raise self.spawn_error
        self.spawned += 1
        worker = Worker(id=f"w{len(self._workers)}", url="http://new")
        self._workers.append(worker)
        return worker

    def retire(self, worker_id, drain_timeout=600.0):
        if not self.retire_ok:
            return False
        self.retired.append(worker_id)
        self._workers = [w for w in self._workers if w.id != worker_id]
        return True


class _StubRouter:
    """Signals come from per-worker snapshot gauges (what the scoped
    ``Autoscaler.signals`` sums): ``queued``/``inflight`` land on the
    first worker unless ``per_worker`` spells out a distribution."""

    def __init__(self, fleet):
        self.fleet = fleet
        self.registry = Registry(prefix="gol_fleet")
        self._draining = False
        self.queued = 0.0
        self.inflight = 0.0
        self.per_worker = {}

    def _merged_snapshot(self):
        snaps = {}
        for i, w in enumerate(self.fleet.workers()):
            q = self.per_worker.get(w.id)
            if q is None:
                q = self.queued if i == 0 else 0.0
            snaps[w.id] = {"gauges": {
                "queue_depth": q,
                "inflight_batches": self.inflight if i == 0 else 0.0,
            }}
        return snaps, {"gauges": {}}


class _Clock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


def _rig(n=1, history=None, **cfg):
    config = AutoscaleConfig(**{
        "min_workers": 1, "max_workers": 4, "up_sustain": 2,
        "down_sustain": 3, "cooldown_s": 10.0, **cfg,
    })
    fleet = _StubFleet(n)
    router = _StubRouter(fleet)
    clock = _Clock()
    scaler = Autoscaler(fleet, router, config, queue_capacity=100,
                        history=history, clock=clock, sync_actions=True)
    return types.SimpleNamespace(fleet=fleet, router=router, clock=clock,
                                 scaler=scaler, config=config)


class TestAutoscalerDecisions:
    def test_saturation_scales_up_after_sustain(self):
        rig = _rig(n=1)
        rig.router.queued = 90.0  # 0.9 of the 100-cap, n=1
        first = rig.scaler.tick()
        assert first["action"] == HOLD and rig.fleet.spawned == 0
        second = rig.scaler.tick()
        assert second["action"] == UP
        assert rig.fleet.spawned == 1
        assert "saturation" in second["reason"]
        assert rig.router.registry.counter("autoscaler_scale_ups_total") == 1

    def test_blip_does_not_scale(self):
        """One saturated tick then recovery: the sustain window holds."""
        rig = _rig(n=1)
        rig.router.queued = 95.0
        rig.scaler.tick()
        rig.router.queued = 10.0
        rig.scaler.tick()
        rig.router.queued = 95.0
        rig.scaler.tick()
        assert rig.fleet.spawned == 0

    def test_cooldown_blocks_consecutive_events(self):
        rig = _rig(n=1)
        rig.router.queued = 95.0
        rig.scaler.tick()
        rig.scaler.tick()
        assert rig.fleet.spawned == 1
        # Still saturated (each worker adds 100 of cap; queue split): the
        # cooldown must hold the second spawn until the clock passes it.
        rig.router.queued = 190.0
        rig.scaler.tick()
        rig.scaler.tick()
        rig.scaler.tick()
        assert rig.fleet.spawned == 1
        rig.clock.now += 11.0  # past cooldown_s=10
        rig.scaler.tick()
        rig.scaler.tick()
        assert rig.fleet.spawned == 2

    def test_slo_critical_burn_scales_up_without_saturation(self):
        rig = _rig(n=1)
        rig.fleet._workers[0].slo = {
            "status": "critical",
            "objectives": [{"name": "latency_p99_normal",
                            "status": "critical", "burn": 3.2}],
        }
        rig.scaler.tick()
        decision = rig.scaler.tick()
        assert decision["action"] == UP
        assert "slo critical" in decision["reason"]
        assert "w0:latency_p99_normal" in decision["reason"]

    def test_max_workers_clamps(self):
        rig = _rig(n=4)
        rig.router.queued = 400.0
        rig.scaler.tick()
        decision = rig.scaler.tick()
        assert decision["action"] == HOLD
        assert "max_workers" in decision["reason"]
        assert rig.fleet.spawned == 0

    def test_idle_scales_down_to_emptiest_after_sustain(self):
        rig = _rig(n=3)
        rig.router.queued = 0.0
        rig.router.per_worker = {"w0": 4.0, "w1": 0.0, "w2": 2.0}
        for _ in range(2):
            assert rig.scaler.tick()["action"] == HOLD
        decision = rig.scaler.tick()
        assert decision["action"] == DOWN
        assert decision["victim"] == "w1"  # the emptiest
        assert rig.fleet.retired == ["w1"]
        assert rig.router.registry.counter(
            "autoscaler_scale_downs_total") == 1

    def test_min_workers_floor(self):
        rig = _rig(n=1)
        for _ in range(5):
            decision = rig.scaler.tick()
        assert decision["action"] == HOLD
        assert rig.fleet.retired == []

    def test_burn_blocks_scale_down(self):
        """An idle queue with a burning SLO is not idle capacity — a
        drain would amplify exactly the burn being measured."""
        rig = _rig(n=2)
        rig.fleet._workers[0].slo = {
            "status": "warning",
            "objectives": [{"name": "x", "status": "warning", "burn": 1.4}],
        }
        for _ in range(5):
            rig.scaler.tick()
        assert rig.fleet.retired == []

    def test_failed_spawn_counts_and_cooldown_still_applies(self):
        rig = _rig(n=1)
        rig.fleet.spawn_error = RuntimeError("boot died")
        rig.router.queued = 95.0
        rig.scaler.tick()
        rig.scaler.tick()
        assert rig.router.registry.counter(
            "autoscaler_scale_failures_total") == 1
        # The failure still starts the cooldown (retry pacing, not a
        # tight respawn loop against a broken image).
        rig.scaler.tick()
        assert rig.fleet.spawned == 0

    def test_failed_retire_counts_and_keeps_worker(self):
        rig = _rig(n=2)
        rig.fleet.retire_ok = False
        for _ in range(4):
            rig.scaler.tick()
        assert rig.router.registry.counter(
            "autoscaler_scale_failures_total") == 1
        assert len(rig.fleet.workers()) == 2

    def test_draining_router_freezes_decisions(self):
        rig = _rig(n=1)
        rig.router.queued = 95.0
        rig.router._draining = True
        assert rig.scaler.tick() is None
        assert rig.fleet.spawned == 0

    def test_gauges_exported_per_tick(self):
        rig = _rig(n=2)
        rig.router.queued = 50.0
        rig.scaler.tick()
        snap = rig.router.registry.snapshot()
        assert snap["gauges"]["autoscaler_workers"] == 2
        assert snap["gauges"]["autoscaler_queue_saturation"] == \
            pytest.approx(0.25)
        assert snap["counters"]["autoscaler_ticks_total"] == 1

    def test_decisions_land_in_the_history_ring(self, tmp_path):
        """Every tick is a durable record; scale events carry their
        outcome — the series `gol history-report` and the bench suite
        replay to answer WHY the fleet grew."""
        writer = obs_history.HistoryWriter(str(tmp_path / "ring"),
                                           source="autoscaler")
        rig = _rig(n=1, history=writer)
        rig.router.queued = 95.0
        rig.scaler.tick()
        rig.scaler.tick()
        writer.close()
        records = [r for r in obs_history.read_records(str(tmp_path / "ring"))
                   if "autoscaler" in r]
        assert len(records) == 3  # two decision ticks + one scale outcome
        actions = [r["autoscaler"].get("action") for r in records]
        assert actions.count(UP) == 2  # the decision AND its outcome record
        outcome = next(r["autoscaler"] for r in records
                       if r["autoscaler"].get("record_kind") == "scale")
        assert outcome["ok"] is True

    def test_down_demotes_to_hold_consistently(self):
        """A DOWN with no retireable victim must read HOLD on EVERY
        surface — gauges, the panel, and the durable ring never
        disagree about what a tick decided."""
        rig = _rig(n=2, down_sustain=1)
        rig.scaler._pick_victim = lambda signals: None
        decision = rig.scaler.tick()
        assert decision["action"] == HOLD
        assert decision["reason"] == "no retireable worker"
        assert decision["target"] == 2
        snap = rig.router.registry.snapshot()
        assert snap["gauges"]["autoscaler_target_workers"] == 2
        assert rig.scaler.public()["last_decision"]["action"] == HOLD

    def test_big_and_retiring_workers_scoped_out_of_signals(self):
        """Big-lane queues/burn cannot be absorbed by spawning normal
        workers, and a retiring worker's stored /slo is frozen — neither
        may drive (or veto) a decision about the normal pool."""
        rig = _rig(n=2)
        big = Worker(id="big0", url="http://big0", big=True,
                     slo={"status": "critical",
                          "objectives": [{"name": "x", "status": "critical",
                                          "burn": 9.9}]})
        rig.fleet._workers.append(big)
        rig.router.per_worker = {"big0": 5000.0, "w0": 0.0, "w1": 0.0}
        signals = rig.scaler.signals()
        assert signals["queued"] == 0.0
        assert signals["burn"] == 0.0 and signals["critical"] == []
        # A retiring normal leaves both the capacity denominator and the
        # burn signal.
        rig.fleet._workers[0].retiring = True
        rig.fleet._workers[0].slo = {
            "status": "critical",
            "objectives": [{"name": "y", "status": "critical", "burn": 5.0}],
        }
        signals = rig.scaler.signals()
        assert signals["pool"] == 1
        assert signals["critical"] == []

    def test_public_shape(self):
        rig = _rig(n=1)
        rig.scaler.tick()
        pub = rig.scaler.public()
        assert pub["enabled"] is True
        assert pub["min"] == 1 and pub["max"] == 4
        assert pub["workers"] == 1
        assert pub["last_decision"]["action"] == HOLD

    def test_config_validation(self):
        with pytest.raises(ValueError):
            AutoscaleConfig(min_workers=0)
        with pytest.raises(ValueError):
            AutoscaleConfig(min_workers=3, max_workers=2)
        with pytest.raises(ValueError):
            AutoscaleConfig(up_saturation=0.3, down_occupancy=0.4)
        with pytest.raises(ValueError):
            AutoscaleConfig(cooldown_s=-1)
        with pytest.raises(ValueError):
            AutoscaleConfig(up_sustain=0)


class TestFleetRetire:
    def _fleet(self, tmp_path, http):
        fleet = Fleet(str(tmp_path / "fleet"),
                      probe=lambda *a, **k: None, http=http)
        worker = Worker(id="w0", url="http://w0",
                        journal_dir=str(tmp_path / "fleet" / "w0"))
        fleet._add(worker)
        other = Worker(id="w1", url="http://w1",
                       journal_dir=str(tmp_path / "fleet" / "w1"))
        fleet._add(other)
        return fleet, worker

    def test_retire_drains_then_removes_from_membership(self, tmp_path):
        calls = []

        def http(method, url, body=None, timeout=0):
            calls.append((method, url))
            return 200, {"drained": True}

        fleet, worker = self._fleet(tmp_path, http)
        assert fleet.retire("w0") is True
        assert calls == [("POST", "http://w0/drain")]
        assert fleet.worker("w0") is None
        assert fleet.worker("w1") is not None
        with open(fleet.manifest_path) as f:
            manifest = json.load(f)
        assert [p["id"] for p in manifest["partitions"]] == ["w1"]

    def test_failed_drain_aborts_the_retire_via_respawn(self, tmp_path):
        """A failed drain may still have LANDED — and a draining
        scheduler refuses work forever, so the abort path must respawn
        the worker on its partition, never hand the old process back."""
        def http(method, url, body=None, timeout=0):
            return 200, {"drained": False}

        fleet, worker = self._fleet(tmp_path, http)
        respawned = []
        fleet._respawn = lambda w: respawned.append(w.id)
        assert fleet.retire("w0") is False
        assert fleet.worker("w0") is not None  # still a member
        assert respawned == ["w0"]
        assert worker.retiring is False  # back under health supervision

    def test_unreachable_drain_aborts_the_retire(self, tmp_path):
        def http(method, url, body=None, timeout=0):
            raise OSError("connection refused")

        fleet, worker = self._fleet(tmp_path, http)
        respawned = []
        fleet._respawn = lambda w: respawned.append(w.id)
        assert fleet.retire("w0") is False
        assert respawned == ["w0"]
        assert worker.retiring is False

    def test_failed_spawn_rolls_back_membership(self, tmp_path, monkeypatch):
        """A boot that never becomes ready must not leave a zombie in
        membership: the health loop would respawn the same broken image
        every tick, bypassing the autoscaler's cooldown pacing."""
        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)

        class _Proc:
            killed = False

            def poll(self):
                return None if not self.killed else 1

            def kill(self):
                self.killed = True

            def wait(self, timeout=None):
                return 1

        proc = _Proc()

        def fake_launch(worker):
            worker.proc = proc
            worker.pid = 999999
            return worker

        monkeypatch.setattr(fleet, "_launch", fake_launch)

        def never_ready(worker):
            raise RuntimeError("boot died")

        monkeypatch.setattr(fleet, "_await_ready", never_ready)
        with pytest.raises(RuntimeError):
            fleet.spawn()
        assert fleet.workers() == []
        assert proc.killed
        with open(fleet.manifest_path) as f:
            assert json.load(f)["partitions"] == []

    def test_attached_and_big_and_unknown_refused(self, tmp_path):
        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        fleet._add(Worker(id="a0", url="http://a0", attached=True))
        fleet._add(Worker(id="big0", url="http://b0", big=True))
        assert fleet.retire("a0") is False
        assert fleet.retire("big0") is False
        assert fleet.retire("nope") is False

    def test_health_tick_skips_retiring_workers(self, tmp_path):
        probes = []

        def probe(url, path="/healthz", **k):
            probes.append((url, path))
            return {"ok": True}

        fleet = Fleet(str(tmp_path / "fleet"), probe=probe)
        worker = Worker(id="w0", url="http://w0", retiring=True)
        fleet._add(worker)
        fleet.health_tick()
        assert probes == []  # mid-retire: the retire thread owns it

    def test_tick_hooks_ride_the_health_tick(self, tmp_path):
        fleet = Fleet(str(tmp_path / "fleet"),
                      probe=lambda *a, **k: {"ok": True})
        seen = []
        fleet.add_tick_hook(lambda: seen.append(1))
        fleet.health_tick()
        fleet.health_tick()
        assert seen == [1, 1]

    def test_health_tick_adopts_advertised_weight(self, tmp_path):
        def probe(url, path="/healthz", **k):
            if path == "/healthz":
                return {"ok": True, "weight": 2.5e8}
            return {"status": "ok"}

        fleet = Fleet(str(tmp_path / "fleet"), probe=probe)
        worker = Worker(id="w0", url="http://w0")
        fleet._add(worker)
        fleet.health_tick()
        assert worker.advertised_weight == 2.5e8
        assert worker.slo == {"status": "ok"}
        # A pinned weight is never overwritten by advertisement.
        pinned = Worker(id="w1", url="http://w1", weight=4.0)
        fleet._add(pinned)
        fleet.health_tick()
        assert pinned.weight == 4.0
        assert pinned.advertised_weight is None

    def test_manifest_round_trips_weight(self, tmp_path):
        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        fleet._add(Worker(id="w0", url="http://w0", attached=True,
                          weight=6.0))
        fresh = Fleet(str(tmp_path / "fleet"),
                      probe=lambda *a, **k: {"ok": True})
        fresh.load()
        assert fresh.worker("w0").weight == 6.0


class TestRouterIntegration:
    """Router-level affinity + retiring semantics, over a stub fleet (no
    HTTP to workers; the router's own server binds a real port)."""

    def _router(self, tmp_path, workers, **kwargs):
        from gol_tpu.fleet.router import RouterServer

        fleet = Fleet(str(tmp_path / "fleet"), probe=lambda *a, **k: None)
        for worker in workers:
            fleet._add(worker)
        router = RouterServer(fleet, port=0, **kwargs)
        return router

    def test_affinity_off_and_equal_weights_byte_identical(self, tmp_path):
        workers = [Worker(id=f"w{i}", url=f"http://w{i}") for i in range(3)]
        plain = self._router(tmp_path, workers)
        weighted = self._router(tmp_path / "b", [
            Worker(id=f"w{i}", url=f"http://w{i}") for i in range(3)
        ], affinity_route=True)
        try:
            for i in range(1, 20):
                key = placement.key_for({"width": 32 * i, "height": 32 * i})
                assert [w.id for w in plain.candidates(key)] == \
                    [w.id for w in weighted.candidates(key)]
        finally:
            plain.httpd.server_close()
            weighted.httpd.server_close()

    def test_affinity_weights_change_ownership(self, tmp_path):
        heavy = [
            Worker(id="w0", url="http://w0", weight=1.0),
            Worker(id="w1", url="http://w1", weight=16.0),
            Worker(id="w2", url="http://w2", weight=1.0),
        ]
        router = self._router(tmp_path, heavy, affinity_route=True)
        try:
            owners = {}
            for i in range(1, 60):
                key = placement.key_for({"width": 32 * i, "height": 32 * i})
                owner = router.candidates(key)[0].id
                owners[owner] = owners.get(owner, 0) + 1
            assert owners.get("w1", 0) > owners.get("w0", 0)
            assert owners.get("w1", 0) > owners.get("w2", 0)
        finally:
            router.httpd.server_close()

    def test_retiring_worker_excluded_from_submits_not_lookups(self, tmp_path):
        workers = [
            Worker(id="w0", url="http://w0"),
            Worker(id="w1", url="http://w1", retiring=True),
        ]
        router = self._router(tmp_path, workers)
        try:
            key = placement.key_for({"width": 64, "height": 64})
            assert [w.id for w in router.candidates(key)] == ["w0"]

            # forward_job still reaches the retiring worker: its drain is
            # finishing jobs whose results clients are polling for.
            seen = []

            def http(method, url, body=None, timeout=0, **k):
                seen.append(url)
                if "w1" in url:
                    return 200, {"state": "done"}
                return 404, {"error": "nope"}

            router.http = http
            status, payload = router.forward_job("GET", "job-1")
            assert status == 200
            assert any("w1" in url for url in seen)
        finally:
            router.httpd.server_close()

    def test_metrics_and_fleet_carry_autoscaler_panel(self, tmp_path):
        workers = [Worker(id="w0", url="http://w0")]
        router = self._router(tmp_path, workers)
        try:
            # No autoscaler: no section (old payload shape pinned).
            assert "autoscaler" not in router.fleet_json()
            scaler = Autoscaler(
                _StubFleet(1), _StubRouter(_StubFleet(1)),
                AutoscaleConfig(), sync_actions=True,
            )
            router.autoscaler = scaler
            assert router.fleet_json()["autoscaler"]["enabled"] is True
            router.http = lambda *a, **k: (200, {"counters": {},
                                                 "gauges": {},
                                                 "histograms": {}})
            merged = router.metrics_json()
            assert merged["fleet"]["autoscaler"]["min"] == 1
        finally:
            router.httpd.server_close()


class TestTopPanel:
    def test_autoscaler_line_renders(self):
        from gol_tpu.obs import top

        frame = top.render_frame({
            "counters": {}, "gauges": {}, "histograms": {},
            "fleet": {
                "workers": 3, "healthy": 3, "backpressured": 0,
                "restarts": 0, "retiring": 1,
                "autoscaler": {
                    "enabled": True, "min": 1, "max": 4, "workers": 3,
                    "target": 4, "scaling": True,
                    "last_decision": {
                        "action": "up", "reason": "queue saturation "
                        "0.93 >= 0.80", "saturation": 0.93,
                        "occupancy": 0.95, "burn": 2.1,
                    },
                },
            },
        }, None, ansi=False)
        assert "autoscale: 3 workers (target 4, min 1 max 4)" in frame
        assert "SCALING" in frame
        assert "last: up (queue saturation 0.93 >= 0.80)" in frame
        assert "1 retiring" in frame

    def test_no_autoscaler_no_line(self):
        from gol_tpu.obs import top

        frame = top.render_frame({
            "counters": {}, "gauges": {}, "histograms": {},
            "fleet": {"workers": 2, "healthy": 2, "backpressured": 0,
                      "restarts": 0},
        }, None, ansi=False)
        assert "autoscale:" not in frame


class TestShardTargets:
    def _targets(self, payloads, enabled=True, refresh_s=5.0):
        from gol_tpu.cli import _ShardTargets

        clock = _Clock()
        calls = []

        def fetch(url):
            calls.append(url)
            return payloads[min(len(calls) - 1, len(payloads) - 1)]

        t = _ShardTargets("http://router", enabled, refresh_s=refresh_s,
                          fetch=fetch, clock=clock)
        return t, clock, calls

    def _fleet_payload(self, n):
        return {"workers": [
            {"id": f"w{i}", "url": f"http://w{i}", "healthy": True}
            for i in range(n)
        ]}

    def test_round_robin_over_current_membership(self):
        t, clock, calls = self._targets([self._fleet_payload(2)])
        t.refresh(force=True)
        assert [t.next() for _ in range(4)] == \
            ["http://w0", "http://w1", "http://w0", "http://w1"]
        assert len(calls) == 1  # interval-gated: no refetch per next()

    def test_interval_refetch_sees_autoscaled_workers(self):
        t, clock, calls = self._targets(
            [self._fleet_payload(1), self._fleet_payload(3)],
        )
        t.refresh(force=True)
        assert t.next() == "http://w0"
        clock.now += 6.0  # past refresh_s
        got = {t.next() for _ in range(3)}
        assert got == {"http://w0", "http://w1", "http://w2"}
        assert len(calls) == 2

    def test_429_forces_refetch(self):
        t, clock, calls = self._targets(
            [self._fleet_payload(1), self._fleet_payload(2)],
        )
        t.refresh(force=True)
        t.on_429()  # no clock advance: still refetches
        assert len(calls) == 2
        assert t.targets == ["http://w0", "http://w1"]

    def test_single_server_stays_noop(self):
        t, clock, calls = self._targets([{}])
        t.refresh(force=True)
        assert t.targets == ["http://router"]
        assert t.next() == "http://router"

    def test_disabled_never_fetches(self):
        t, clock, calls = self._targets([self._fleet_payload(3)],
                                        enabled=False)
        t.refresh(force=True)
        assert calls == []
        assert t.next() == "http://router"

    def test_unreachable_refetch_keeps_current_targets(self):
        t, clock, calls = self._targets([self._fleet_payload(2), {}])
        t.refresh(force=True)
        clock.now += 6.0
        t.refresh()
        assert t.targets == ["http://w0", "http://w1"]

    def test_retiring_and_big_workers_excluded(self):
        payload = {"workers": [
            {"id": "w0", "url": "http://w0", "healthy": True},
            {"id": "w1", "url": "http://w1", "healthy": True,
             "retiring": True},
            {"id": "big0", "url": "http://b0", "healthy": True, "big": True},
        ]}
        t, clock, calls = self._targets([payload])
        t.refresh(force=True)
        assert t.targets == ["http://w0"]


class TestSparseAutoThreshold:
    def test_bundled_default_is_the_measured_crossover(self):
        from gol_tpu.sparse.engine import SPARSE_AUTO_AREA
        from gol_tpu.tune import select

        assert SPARSE_AUTO_AREA == 1 << 25
        # conftest points GOL_PLAN_CACHE at an empty tmp file, so this
        # reads the bundled default entry — pinned equal to the constant.
        assert select.sparse_auto_area(SPARSE_AUTO_AREA) == 1 << 25

    def test_cached_value_consulted(self, tmp_path, monkeypatch):
        from gol_tpu.tune import plans, select

        monkeypatch.setenv(plans.ENV_CACHE_PATH,
                           str(tmp_path / "plans.json"))
        select.reset()
        try:
            store = plans.PlanStore()
            store.put(select.sparse_fingerprint(), {"auto_area": 1 << 22})
            select.reset()
            assert select.sparse_auto_area(1 << 25) == 1 << 22
        finally:
            select.reset()

    def test_invalid_cached_value_degrades_loudly(self, tmp_path,
                                                  monkeypatch, caplog):
        from gol_tpu.tune import plans, select

        monkeypatch.setenv(plans.ENV_CACHE_PATH,
                           str(tmp_path / "plans.json"))
        select.reset()
        try:
            store = plans.PlanStore()
            store.put(select.sparse_fingerprint(), {"auto_area": 64})
            select.reset()
            with caplog.at_level("WARNING", logger="gol_tpu.tune.select"):
                assert select.sparse_auto_area(1 << 25) == 1 << 25
            assert any("sparse crossover" in r.message
                       for r in caplog.records)
        finally:
            select.reset()

    def test_auto_engine_respects_threshold(self):
        from gol_tpu.sparse.engine import auto_engine

        assert auto_engine(2048, 2048, 256,
                           area_threshold=1 << 22) == "sparse"
        assert auto_engine(2048, 2048, 256,
                           area_threshold=1 << 23) == "dense"
        # Uneven tiling always stays dense, threshold notwithstanding.
        assert auto_engine(2048 + 1, 2048, 256,
                           area_threshold=1 << 20) == "dense"

    def test_auto_engine_consults_plan_cache(self, tmp_path, monkeypatch):
        from gol_tpu.sparse.engine import auto_engine
        from gol_tpu.tune import plans, select

        monkeypatch.setenv(plans.ENV_CACHE_PATH,
                           str(tmp_path / "plans.json"))
        select.reset()
        try:
            assert auto_engine(2048, 2048, 256) == "dense"  # 2^22 < default
            store = plans.PlanStore()
            store.put(select.sparse_fingerprint(), {"auto_area": 1 << 21})
            select.reset()
            assert auto_engine(2048, 2048, 256) == "sparse"
        finally:
            select.reset()


class TestCrossoverFit:
    def test_linear_fit_solves_the_crossover(self):
        from gol_tpu.tune.measure import fit_crossover

        # dense(area) = 1e-9 * area (no intercept), sparse flat at 4 ms:
        # crossover at 4e6 cells.
        dense = [(1 << 20, 1e-9 * (1 << 20)), (1 << 22, 1e-9 * (1 << 22))]
        got = fit_crossover(dense, 4e-3)
        assert got == pytest.approx(4_000_000, rel=0.01)

    def test_intercept_respected(self):
        from gol_tpu.tune.measure import fit_crossover

        # dense = 2e-9 * area + 1ms, sparse 5ms -> area = 2e6
        dense = [(10 ** 6, 3e-3), (2 * 10 ** 6, 5e-3), (3 * 10 ** 6, 7e-3)]
        assert fit_crossover(dense, 5e-3) == pytest.approx(2e6, rel=0.01)

    def test_clamped_to_band(self):
        from gol_tpu.tune.measure import fit_crossover

        dense = [(1 << 20, 1e-9 * (1 << 20)), (1 << 22, 1e-9 * (1 << 22))]
        assert fit_crossover(dense, 1e-9) == 1 << 16  # floor
        assert fit_crossover(dense, 1e9) == 1 << 36  # ceiling

    def test_flat_dense_measurement_raises(self):
        from gol_tpu.tune.measure import fit_crossover

        with pytest.raises(ValueError):
            fit_crossover([(1 << 20, 1e-3), (1 << 22, 1e-3)], 4e-3)
        with pytest.raises(ValueError):
            fit_crossover([(1 << 20, 1e-3)], 4e-3)
        with pytest.raises(ValueError):
            fit_crossover([(1 << 20, 1e-3), (1 << 22, 2e-3)], 0.0)
